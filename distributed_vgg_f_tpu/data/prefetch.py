"""Device prefetch: overlap host→device batch transfer with device compute.

Reference equivalent (SURVEY.md §1 data layer / §7 hard parts): the reference's
input pipeline hides host work behind device compute with queue runners /
``tf.data`` prefetch. On TPU the analogue has two halves:

1. host-side prefetch — already done inside the dataset iterators (tf.data
   prefetch / the native C++ double-buffered loader);
2. **device-side prefetch** — this module: a bounded background thread that
   pulls the next process-local numpy batch and immediately lands it on the
   mesh (sharded over the data axis) while the current jitted step is still
   executing. The trainer then never blocks on a H2D copy at step start: JAX's
   async dispatch overlaps the copy with the previous step's device work.

The buffer is deliberately small (default 2): each slot holds a full on-device
batch in HBM, and deeper queues add memory pressure without latency benefit.

Resilience (train.data_timeout_s; resilience layer): the consumer side is
also the **data watchdog**. A loader that stalls (hung NFS/GCS read, wedged
decode worker, remote shard server gone) used to hang `next()` forever — the
step loop just stopped, indistinguishable from slow compute. With a timeout
configured, `__next__` waits `data_timeout_s`, then retries with exponential
backoff (bounded by `timeout_retries`), then raises a typed
:class:`DataStallError` carrying how long it waited and how many batches had
been delivered. Independently of the timeout, a prefetch worker thread that
dies without delivering a batch or an error is detected (thread liveness
checked while waiting) and surfaces as `DataStallError` too, instead of the
consumer blocking on a queue nothing will ever fill.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Iterator, Mapping

import numpy as np

from distributed_vgg_f_tpu import telemetry
from distributed_vgg_f_tpu.parallel.mesh import shard_host_batch
from distributed_vgg_f_tpu.resilience.errors import DataStallError


class _WaitTimeout(Exception):
    """Internal: one bounded wait elapsed (distinct from the public,
    retries-exhausted DataStallError)."""


class DevicePrefetchIterator:
    """Wraps a host-batch iterator; yields mesh-sharded device batches.

    A daemon thread runs ``shard_host_batch`` (device_put) ahead of the
    consumer, keeping up to ``buffer_size`` batches resident on device.
    Exceptions from the source iterator (including exhaustion) propagate to
    the consumer at the matching ``next()`` call, preserving iterator
    semantics. ``close()`` stops the thread and drops buffered batches.

    ``batch_timeout_s`` > 0 arms the watchdog: each ``next()`` waits at most
    ``batch_timeout_s``, retried ``timeout_retries`` times with the wait
    doubling per attempt (worst case ``batch_timeout_s * (2^(retries+1)-1)``
    total), then raises :class:`DataStallError`. A dead worker thread is
    detected regardless of the timeout setting.
    """

    _STOP = object()
    _POLL_S = 0.1  # liveness-check granularity while blocked on the queue

    def __init__(self, source: Iterator[Mapping[str, np.ndarray]], mesh,
                 data_axis: str = "data", buffer_size: int = 2,
                 batch_timeout_s: float = 0.0, timeout_retries: int = 2):
        if buffer_size < 1:
            raise ValueError(f"buffer_size must be >= 1, got {buffer_size}")
        # Buffer-ownership contract: a source that recycles its output
        # arrays (native_jpeg.enable_output_buffer_reuse — bench-only)
        # would have its batch overwritten while device_put may still be
        # reading (or aliasing) the host memory. Refuse loudly instead of
        # corrupting training data.
        if getattr(source, "reuses_output_buffers", False):
            raise ValueError(
                "device prefetch requires caller-owned batches, but this "
                "iterator recycles its output buffers "
                "(enable_output_buffer_reuse is for synchronous bench "
                "loops only) — construct the iterator without buffer "
                "reuse for training")
        if batch_timeout_s < 0 or timeout_retries < 0:
            raise ValueError(
                f"batch_timeout_s/timeout_retries must be >= 0, got "
                f"{batch_timeout_s}/{timeout_retries}")
        self._source = source
        self._mesh = mesh
        self._data_axis = data_axis
        self._batch_timeout = batch_timeout_s
        self._timeout_retries = timeout_retries
        self._batches_delivered = 0
        self._queue: queue.Queue = queue.Queue(maxsize=buffer_size)
        self._closed = threading.Event()
        # Telemetry (telemetry/registry.py namespace "prefetch/"): pre-create
        # the counters so a zero reads as "instrumented, nothing happened"
        # in every snapshot; the queue-depth gauge is the stall attributor's
        # corroborating signal (depth pinned at 0 <=> infeed-bound).
        reg = telemetry.get_registry()
        for name in ("prefetch/batches", "prefetch/wait_ns",
                     "prefetch/timeouts", "prefetch/dead_workers",
                     "prefetch/source_batches", "prefetch/device_put_bytes"):
            reg.counter(name)
        reg.set_gauge("prefetch/queue_depth", 0)
        # bytes_in_flight: HBM resident in queued (undelivered) batches —
        # with device_put_bytes this makes wire-format wins (u8 vs bf16 vs
        # f32, data.wire) directly visible in stall-attribution receipts.
        reg.set_gauge("prefetch/bytes_in_flight", 0)
        self._bytes_lock = threading.Lock()
        self._bytes_in_flight = 0
        self._thread = threading.Thread(target=self._worker, daemon=True,
                                        name="device-prefetch")
        self._thread.start()

    def _worker(self) -> None:
        rec = telemetry.get_recorder()
        reg = telemetry.get_registry()
        try:
            source = iter(self._source)
            while True:
                # the worker's own source wait is "infeed_source": it shows
                # WHERE the pipeline starves (host loader vs H2D) without
                # double-counting against the consumer-side "infeed" spans
                t0 = time.monotonic_ns()
                try:
                    host_batch = next(source)
                except StopIteration:
                    break
                rec.record("source_next", "infeed_source", t0,
                           time.monotonic_ns() - t0)
                reg.inc("prefetch/source_batches")
                if self._closed.is_set():
                    return
                # wire-format receipt: bytes the host actually ships through
                # device_put for this batch (1 B/px on the u8 wire vs 2/4 on
                # host_bf16/host_f32 — the counter the bench's bytes/img
                # columns corroborate against)
                nbytes = sum(int(np.asarray(v).nbytes)
                             for v in host_batch.values())
                t0 = time.monotonic_ns()
                device_batch = shard_host_batch(host_batch, self._mesh,
                                                self._data_axis)
                rec.record("device_put", "infeed_source", t0,
                           time.monotonic_ns() - t0)
                reg.inc("prefetch/device_put_bytes", nbytes)
                # count the bytes BEFORE the queue put: the consumer may
                # dequeue (and decrement) the instant the put lands, and a
                # decrement-first interleaving would publish a negative
                # "HBM resident" gauge
                with self._bytes_lock:
                    self._bytes_in_flight += nbytes
                    reg.set_gauge("prefetch/bytes_in_flight",
                                  self._bytes_in_flight)
                if not self._put(("batch", device_batch, nbytes)):
                    # clamp: close() may have zeroed the count while this
                    # worker was blocked in _put — compensating below zero
                    # would publish a negative "HBM resident" gauge
                    with self._bytes_lock:
                        self._bytes_in_flight = max(
                            0, self._bytes_in_flight - nbytes)
                        reg.set_gauge("prefetch/bytes_in_flight",
                                      self._bytes_in_flight)
                    return
                reg.set_gauge("prefetch/queue_depth", self._queue.qsize())
            self._put(("stop", StopIteration()))
        except BaseException as exc:  # noqa: BLE001 — relayed to consumer
            self._put(("error", exc))

    def _put(self, item) -> bool:
        """Put with periodic close checks; False if closed before it landed."""
        while not self._closed.is_set():
            try:
                self._queue.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def __iter__(self) -> "DevicePrefetchIterator":
        return self

    def _get(self, timeout: float | None):
        """One bounded queue wait in liveness-checking slices: raises
        DataStallError the moment the worker is dead with nothing queued
        (nothing will EVER arrive — waiting longer is a hang), _WaitTimeout
        when `timeout` elapses."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            try:
                return self._queue.get(timeout=self._POLL_S)
            except queue.Empty:
                if not self._thread.is_alive() and self._queue.empty():
                    telemetry.inc("prefetch/dead_workers")
                    telemetry.inc("resilience/data_stall_errors")
                    from distributed_vgg_f_tpu.telemetry import flight
                    flight.note_crash(
                        "data_stall",
                        f"prefetch worker died after "
                        f"{self._batches_delivered} batches")
                    raise DataStallError(
                        f"device-prefetch worker thread died without "
                        f"delivering a batch or an error (after "
                        f"{self._batches_delivered} batches) — the host "
                        f"loader is gone; restart the run or check the "
                        f"input pipeline") from None
                if deadline is not None and time.monotonic() >= deadline:
                    raise _WaitTimeout from None

    def __next__(self):
        if self._closed.is_set():
            raise StopIteration
        t_wait = time.monotonic_ns()
        if self._batch_timeout <= 0:
            item = self._get(None)
        else:
            timeout, waited = self._batch_timeout, 0.0
            for attempt in range(self._timeout_retries + 1):
                try:
                    item = self._get(timeout)
                    break
                except _WaitTimeout:
                    telemetry.inc("prefetch/timeouts")
                    waited += timeout
                    timeout *= 2  # exponential backoff between retries
            else:
                telemetry.inc("resilience/data_stall_errors")
                from distributed_vgg_f_tpu.telemetry import flight
                flight.note_crash(
                    "data_stall",
                    f"watchdog timeout: no batch within {waited:.1f}s "
                    f"across {self._timeout_retries + 1} attempts "
                    f"({self._batches_delivered} batches delivered)")
                raise DataStallError(
                    f"input pipeline stalled: no batch within {waited:.1f}s "
                    f"across {self._timeout_retries + 1} watchdog attempts "
                    f"(train.data_timeout_s={self._batch_timeout}, "
                    f"exponential backoff; {self._batches_delivered} batches "
                    f"delivered before the stall). The host loader is hung "
                    f"or severely underprovisioned — check storage/decode "
                    f"workers, or raise train.data_timeout_s if this "
                    f"pipeline is legitimately this slow.") from None
        kind, payload = item[0], item[1]
        if kind == "batch":
            self._batches_delivered += 1
            # "infeed" category = time the CONSUMER was blocked here — the
            # direct input to the stall attributor's infeed_fraction
            dt = time.monotonic_ns() - t_wait
            telemetry.record("prefetch_wait", "infeed", t_wait, dt)
            reg = telemetry.get_registry()
            reg.inc("prefetch/batches")
            reg.inc("prefetch/wait_ns", dt)
            reg.set_gauge("prefetch/queue_depth", self._queue.qsize())
            # clamped like the producer's rollback: a concurrent close()
            # (teardown, watchdog, __del__) may already have zeroed the
            # count, and going below zero would publish a negative gauge
            with self._bytes_lock:
                self._bytes_in_flight = max(0, self._bytes_in_flight
                                            - item[2])
                reg.set_gauge("prefetch/bytes_in_flight",
                              self._bytes_in_flight)
            return payload
        self.close()
        if kind == "stop":
            raise StopIteration
        raise payload

    @property
    def buffer_size(self) -> int:
        return self._queue.maxsize

    def set_buffer_size(self, n: int) -> int:
        """Runtime-resize the device ring (r11 — the ingest autotuner's
        `prefetch_to_device` knob). Growing takes effect at the producer's
        next put (its bounded put loop re-checks the limit every 100 ms);
        shrinking never drops queued batches — the queue simply refuses new
        puts until the consumer drains below the new bound, so HBM
        occupancy decays to the target instead of discarding work. Returns
        the now-active bound."""
        n = max(1, int(n))
        with self._queue.mutex:
            self._queue.maxsize = n
            self._queue.not_full.notify_all()
        return n

    def close(self) -> None:
        self._closed.set()
        # Drain so a blocked producer can observe the closed flag and exit.
        while True:
            try:
                self._queue.get_nowait()
            except queue.Empty:
                break
        # dropped buffered batches are no longer in flight; publish the
        # zero under the lock so it cannot stomp a concurrent update
        with self._bytes_lock:
            self._bytes_in_flight = 0
            telemetry.get_registry().set_gauge("prefetch/bytes_in_flight", 0)

    def __del__(self):  # pragma: no cover — best-effort cleanup
        try:
            self.close()
        except Exception:  # interpreter-shutdown teardown order
            pass


class HostPrefetchIterator:
    """Bounded host-side read-ahead stage: a daemon thread pulls host
    batches from `source` into a queue of numpy batches (no device work),
    decoupling decode jitter from the consumer — typically the
    device-prefetch worker, whose single-threaded pull otherwise exposes
    every source hiccup directly to `device_put` cadence.

    Built for the closed-loop ingest autotuner (data/autotune.py): `depth`
    is runtime-resizable via `set_depth` (the `data.prefetch` knob), so the
    controller can deepen the buffer when the stall attributor names the
    host pipeline. Only installed when autotuning is active — with the
    controller absent (config off or DVGGF_AUTOTUNE=0) the feed path is
    byte-identical to pre-r11 behavior, wrapper included.

    Ownership contract: queued batches are caller-owned references, so a
    source that recycles its output arrays (enable_output_buffer_reuse) is
    refused — same rule as device prefetch. Exceptions (and exhaustion)
    propagate to the consumer at the matching `next()`; `close()` stops the
    worker and drops buffered batches.
    """

    def __init__(self, source, depth: int = 2):
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        if getattr(source, "reuses_output_buffers", False):
            raise ValueError(
                "host prefetch requires caller-owned batches, but this "
                "iterator recycles its output buffers "
                "(enable_output_buffer_reuse is for synchronous bench "
                "loops only)")
        self._source = source
        self._queue: queue.Queue = queue.Queue(maxsize=depth)
        self._closed = threading.Event()
        reg = telemetry.get_registry()
        reg.counter("prefetch/host_batches")
        reg.set_gauge("prefetch/host_queue_depth", 0)
        self._thread = threading.Thread(target=self._worker, daemon=True,
                                        name="host-prefetch")
        self._thread.start()

    @property
    def depth(self) -> int:
        return self._queue.maxsize

    def set_depth(self, n: int) -> int:
        """Runtime-resize the read-ahead bound (same contract as
        DevicePrefetchIterator.set_buffer_size: grow engages within the
        producer's next put poll, shrink decays without dropping)."""
        n = max(1, int(n))
        with self._queue.mutex:
            self._queue.maxsize = n
            self._queue.not_full.notify_all()
        return n

    def decode_errors(self):
        """Forward the wrapped loader's corrupt-image counter (the trainer
        binds it before wrapping, but bench consumers read it here)."""
        fn = getattr(self._source, "decode_errors", None)
        return fn() if callable(fn) else 0

    def _worker(self) -> None:
        rec = telemetry.get_recorder()
        reg = telemetry.get_registry()
        try:
            source = iter(self._source)
            while not self._closed.is_set():
                t0 = time.monotonic_ns()
                try:
                    batch = next(source)
                except StopIteration:
                    break
                rec.record("host_prefetch_next", "infeed_source", t0,
                           time.monotonic_ns() - t0)
                reg.inc("prefetch/host_batches")
                if not self._put(("batch", batch)):
                    return
                reg.set_gauge("prefetch/host_queue_depth",
                              self._queue.qsize())
            self._put(("stop", StopIteration()))
        except BaseException as exc:  # noqa: BLE001 — relayed to consumer
            self._put(("error", exc))

    def _put(self, item) -> bool:
        while not self._closed.is_set():
            try:
                self._queue.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def __iter__(self):
        return self

    def __next__(self):
        if self._closed.is_set():
            raise StopIteration
        while True:
            try:
                item = self._queue.get(timeout=0.1)
                break
            except queue.Empty:
                if self._closed.is_set():
                    # a concurrent close() drained the queue (stop marker
                    # included) — this is shutdown, not a dead worker;
                    # raising the watchdog error here would stamp every
                    # clean teardown race as a data stall
                    raise StopIteration from None
                if not self._thread.is_alive() and self._queue.empty():
                    # mirror the device-prefetch dead-worker contract: a
                    # silently dead read-ahead thread must surface as a
                    # typed stall, never an indefinite hang (the DEVICE
                    # prefetch watchdog downstream usually fires first)
                    telemetry.inc("prefetch/dead_workers")
                    raise DataStallError(
                        "host-prefetch worker thread died without "
                        "delivering a batch or an error") from None
        kind, payload = item
        if kind == "batch":
            telemetry.set_gauge("prefetch/host_queue_depth",
                                self._queue.qsize())
            return payload
        self.close()
        if kind == "stop":
            raise StopIteration
        raise payload

    def close(self) -> None:
        self._closed.set()
        # drain so a producer blocked in put() can observe the closed flag
        while True:
            try:
                self._queue.get_nowait()
            except queue.Empty:
                break
        # JOIN the worker BEFORE touching the source: closing the inner
        # loader while the worker is still inside next(source) would
        # destroy native decode state under a live call (use-after-free —
        # observed as a wedged teardown in the bench's wire-rebuild hook)
        if self._thread.is_alive() \
                and threading.current_thread() is not self._thread:
            self._thread.join(timeout=10)
        while True:  # anything the worker put while we were joining
            try:
                self._queue.get_nowait()
            except queue.Empty:
                break
        telemetry.set_gauge("prefetch/host_queue_depth", 0)
        if self._thread.is_alive() \
                and threading.current_thread() is not self._thread:
            # join timed out: the worker is wedged INSIDE next(source)
            # (hung storage read). Closing the source now would be the
            # exact use-after-free the join exists to prevent — leak the
            # handles instead (the daemon thread dies with the process)
            # and leave a receipt.
            telemetry.inc("prefetch/dead_workers")
            return
        src_close = getattr(self._source, "close", None)
        if callable(src_close):
            src_close()

    def __del__(self):  # pragma: no cover — best-effort cleanup
        try:
            self.close()
        except Exception:
            pass


def maybe_prefetch(source, mesh, data_axis: str = "data", buffer_size: int = 2,
                   batch_timeout_s: float = 0.0, timeout_retries: int = 2):
    """Wrap `source` in device prefetch when buffer_size > 0, else return a
    generator that shards synchronously (the non-overlapped fallback — the
    watchdog needs the prefetch thread to time-bound, so timeouts only apply
    to the threaded path)."""
    if buffer_size > 0:
        return DevicePrefetchIterator(source, mesh, data_axis, buffer_size,
                                      batch_timeout_s=batch_timeout_s,
                                      timeout_retries=timeout_retries)

    def _sync():
        for host_batch in source:
            yield shard_host_batch(host_batch, mesh, data_axis)

    return _sync()
