"""Device prefetch: overlap host→device batch transfer with device compute.

Reference equivalent (SURVEY.md §1 data layer / §7 hard parts): the reference's
input pipeline hides host work behind device compute with queue runners /
``tf.data`` prefetch. On TPU the analogue has two halves:

1. host-side prefetch — already done inside the dataset iterators (tf.data
   prefetch / the native C++ double-buffered loader);
2. **device-side prefetch** — this module: a bounded background thread that
   pulls the next process-local numpy batch and immediately lands it on the
   mesh (sharded over the data axis) while the current jitted step is still
   executing. The trainer then never blocks on a H2D copy at step start: JAX's
   async dispatch overlaps the copy with the previous step's device work.

The buffer is deliberately small (default 2): each slot holds a full on-device
batch in HBM, and deeper queues add memory pressure without latency benefit.
"""

from __future__ import annotations

import queue
import threading
from typing import Iterator, Mapping

import numpy as np

from distributed_vgg_f_tpu.parallel.mesh import shard_host_batch


class DevicePrefetchIterator:
    """Wraps a host-batch iterator; yields mesh-sharded device batches.

    A daemon thread runs ``shard_host_batch`` (device_put) ahead of the
    consumer, keeping up to ``buffer_size`` batches resident on device.
    Exceptions from the source iterator (including exhaustion) propagate to
    the consumer at the matching ``next()`` call, preserving iterator
    semantics. ``close()`` stops the thread and drops buffered batches.
    """

    _STOP = object()

    def __init__(self, source: Iterator[Mapping[str, np.ndarray]], mesh,
                 data_axis: str = "data", buffer_size: int = 2):
        if buffer_size < 1:
            raise ValueError(f"buffer_size must be >= 1, got {buffer_size}")
        self._source = source
        self._mesh = mesh
        self._data_axis = data_axis
        self._queue: queue.Queue = queue.Queue(maxsize=buffer_size)
        self._closed = threading.Event()
        self._thread = threading.Thread(target=self._worker, daemon=True,
                                        name="device-prefetch")
        self._thread.start()

    def _worker(self) -> None:
        try:
            for host_batch in self._source:
                if self._closed.is_set():
                    return
                device_batch = shard_host_batch(host_batch, self._mesh,
                                                self._data_axis)
                if not self._put(("batch", device_batch)):
                    return
            self._put(("stop", StopIteration()))
        except BaseException as exc:  # noqa: BLE001 — relayed to consumer
            self._put(("error", exc))

    def _put(self, item) -> bool:
        """Put with periodic close checks; False if closed before it landed."""
        while not self._closed.is_set():
            try:
                self._queue.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def __iter__(self) -> "DevicePrefetchIterator":
        return self

    def __next__(self):
        if self._closed.is_set():
            raise StopIteration
        kind, payload = self._queue.get()
        if kind == "batch":
            return payload
        self.close()
        if kind == "stop":
            raise StopIteration
        raise payload

    def close(self) -> None:
        self._closed.set()
        # Drain so a blocked producer can observe the closed flag and exit.
        while True:
            try:
                self._queue.get_nowait()
            except queue.Empty:
                break

    def __del__(self):  # pragma: no cover — best-effort cleanup
        self.close()


def maybe_prefetch(source, mesh, data_axis: str = "data", buffer_size: int = 2):
    """Wrap `source` in device prefetch when buffer_size > 0, else return a
    generator that shards synchronously (the non-overlapped fallback)."""
    if buffer_size > 0:
        return DevicePrefetchIterator(source, mesh, data_axis, buffer_size)

    def _sync():
        for host_batch in source:
            yield shard_host_batch(host_batch, mesh, data_axis)

    return _sync()
