"""Decoded-crop snapshot cache behind the native train iterator (r9).

The tf.data paper's cache/snapshot move (arXiv 2101.12127), applied at the
point PR 3's profile says it pays: libjpeg Huffman entropy decode is 85-93 %
of host ingest cost and unskippable per decode — so the biggest lever after
restart-marker excerpting is to not decode at all. The first pass over the
dataset runs the normal native pipeline and writes each item's post-decode
crop — exactly the bytes the loader shipped: raw uint8 HWC on the flagship
u8 wire, normalized f32/bf16 (packed or not) on the host wires — into a
bounded on-disk store keyed by (source fingerprint, decode params, native
ABI). Once every item is present the iterator flips to WARM serving:
batches are assembled straight from the store (numpy reads + a fresh
per-epoch horizontal flip) and libjpeg never runs; a store left complete by
a previous run serves warm from batch 0.

Flip ownership (r13): the "fresh per-epoch horizontal flip" above holds
only while the HOST owns flips. With the fused on-device augmentation
stage enabled (`data.augment.hflip`, AugmentConfig.owns_hflip) the inner
loader captures UNFLIPPED crops (ABI v9), warm serving never redraws the
flip, the repair path decodes flips-disabled, and the store generation is
keyed on the flip state — one switch, no path left that could double-flip
(grid-pinned in tests/test_augment.py).

Order contract: warm batches follow the SAME per-epoch shuffle as the
native stream — `shuffle_indices` below is an exact mirror of the
SplitMix64 shuffle in native/jpeg_loader.cc, pinned against native batch
labels by tests/test_snapshot_cache.py — so the stream stays a pure
function of (seed, position) and `restore_state(step)` stays an O(1) seek.
What warm epochs change is the PIXELS: every epoch re-serves the first
pass's crop geometry with only the flip re-drawn (the documented
cache-after-augment trade the tf.data paper names); training curves are
therefore not bit-comparable to the uncached stream, which is why the
cache is opt-in (`data.snapshot_cache.enabled`).

Degradation contract (mirrors the r9 corrupt-image rules): a warm item
whose payload fails its crc32, whose source file stat drifted (a
re-encoded/replaced file under a live cache), or which was evicted,
degrades to a sequential native decode of the SAME epoch-0 crop
(`decode_single_image` seeded with the mirrored item RNG — the repaired
entry is written back), and to the wire's corrupt-image fill (mean on u8,
zeros on host wires) only when that decode also fails. Never stale pixels.

Telemetry: `prefetch/snapshot_hits`, `prefetch/snapshot_misses`,
`prefetch/snapshot_bytes` (payload bytes served from the store) feed the
PR 4 stall attributor's counter namespace.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import zlib
from typing import Optional, Sequence

import numpy as np

log = logging.getLogger(__name__)

_MASK = (1 << 64) - 1


# --------------------------------------------------------------- RNG mirror
#
# Exact mirrors of the native stream's RNG (native/jpeg_loader.cc
# SplitMix64 / mix / shuffle_indices). The warm path NEEDS the epoch
# shuffle to match the native order bit-for-bit (labels and cache keys are
# joined on it); the mirror is pinned by test_snapshot_cache.py against
# labels decoded by the native loader itself.

class SplitMix64:
    __slots__ = ("s",)

    def __init__(self, seed: int):
        self.s = seed & _MASK

    def next(self) -> int:
        self.s = (self.s + 0x9E3779B97F4A7C15) & _MASK
        z = self.s
        z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & _MASK
        z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & _MASK
        return z ^ (z >> 31)


def mix(a: int, b: int) -> int:
    r = SplitMix64((a * 0x9E3779B97F4A7C15 + b) & _MASK)
    r.next()
    return r.next()


def shuffle_indices(n: int, seed: int, epoch: int) -> np.ndarray:
    """The native loader's epoch shuffle, index-for-index."""
    idx = np.arange(n, dtype=np.int64)
    r = SplitMix64(mix(seed, (0x5EED + epoch) & _MASK))
    for i in range(n - 1, 0, -1):
        j = r.next() % (i + 1)
        idx[i], idx[j] = idx[j], idx[i]
    return idx


def item_rng_seed(seed: int, g: int) -> int:
    """Per-item decode RNG seed for global item index g — what the native
    worker hands decode_one, and what the degraded-path decode_single call
    must use to reproduce the exact cached crop."""
    return mix(seed, (0xA0A0 + g) & _MASK)


def _flip_bit(seed: int, g: int) -> bool:
    """Fresh per-(epoch, position) horizontal-flip draw for warm serving —
    its own tag so it can never collide with the native crop RNG stream."""
    return bool(mix(seed, (0xF11F00 + g) & _MASK) & 1)


# ----------------------------------------------------- shared item source
#
# One copy of the item-level source plumbing — byte-range reads, the
# per-epoch stat-memo fingerprint, and the r9 corrupt-image fill — shared
# by the warm cache iterator below AND the disaggregated-ingest worker
# (data/ingest_service.py PositionKeyedProducer): both reconstruct the
# same stream, so a contract fix applied to one path only would silently
# break their byte-identity.

def read_item_bytes(files: Sequence[str], path_idx, offsets, lengths,
                    idx: int) -> Optional[bytes]:
    """Item idx's source bytes (offset < 0 = the whole file), or None on
    any I/O failure — callers degrade per the corrupt-image contract."""
    try:
        with open(files[int(path_idx[idx])], "rb") as f:
            off = int(offsets[idx])
            if off < 0:
                return f.read()
            f.seek(off)
            return f.read(int(lengths[idx]))
    except OSError:
        return None


def corrupt_fill(out: np.ndarray, image_dtype: str, mean) -> None:
    """The r9 corrupt-image contract, per wire: mean-fill on u8 (reads as
    ~zero after the device finish), zero-fill on host wires (mirrors
    native fill_failed_item)."""
    if image_dtype == "uint8":
        out[...] = np.clip(np.round(np.asarray(mean, np.float32)), 0, 255) \
            .astype(np.uint8).reshape(1, 1, 3)
    else:
        out[...] = 0


class SourceStatMemo:
    """(file size, mtime_ns, offset, length) fingerprints with a per-epoch
    stat memo: warm/worker batches don't stat the same container file
    `batch` times, while a payload swapped on disk is still noticed at the
    next epoch boundary."""

    def __init__(self, files: Sequence[str], path_idx, offsets, lengths):
        self._files = files
        self._path_idx = path_idx
        self._offsets = offsets
        self._lengths = lengths
        self._memo: dict = {}
        self._epoch = -1

    def fingerprint(self, idx: int, epoch: int) -> tuple:
        if epoch != self._epoch:
            self._memo.clear()
            self._epoch = epoch
        p = int(self._path_idx[idx])
        st = self._memo.get(p)
        if st is None:
            try:
                s = os.stat(self._files[p])
                st = (s.st_size, s.st_mtime_ns)
            except OSError:
                st = (-1, -1)
            self._memo[p] = st
        return (st[0], st[1], int(self._offsets[idx]),
                int(self._lengths[idx]))

    @property
    def epoch(self) -> int:
        return self._epoch


# ------------------------------------------------------------------- store

def _dtype_name(dt: np.dtype) -> str:
    return np.dtype(dt).name  # 'float32' / 'uint8' / 'bfloat16' (ml_dtypes)


def _resolve_dtype(name: str) -> np.dtype:
    if name == "bfloat16":
        import ml_dtypes
        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(name)


class SnapshotStore:
    """One generation of the on-disk snapshot: <root>/<key>/data.pack (all
    payloads, append-only) + <root>/<key>/index.json (per-item offset,
    length, crc32, dtype/shape, source fingerprint). The pack layout is a
    WARM-PATH design decision: serving an item costs one os.pread + one
    crc pass — no per-item open(), no per-item header parse (both profiled
    at ~100 us each on the r10 box with a file-per-item layout, half the
    warm budget). <key> hashes the full decode-parameter tuple + native
    ABI + a source-set fingerprint — any drift in how pixels would be
    produced lands in a fresh generation, and stale generations are the
    FIRST thing eviction removes. Eviction of a single item drops its
    index entry (the orphaned pack bytes stay inside the capacity
    accounting until the generation is rebuilt — bounded, never reused).
    The index is persisted atomically every `_FLUSH_EVERY` admissions and
    on flush(); a crash leaves a valid prefix index (missing items are
    re-captured on the next cold pass)."""

    _FLUSH_EVERY = 256

    def __init__(self, root: str, key: str, capacity_bytes: int,
                 n_items: int, *, validate: bool = True):
        self.root = root
        self.key = key
        self.capacity_bytes = int(capacity_bytes)
        self.n_items = int(n_items)
        self.validate = bool(validate)
        self.rejected_writes = 0
        self._dir = os.path.join(root, key)
        os.makedirs(self._dir, exist_ok=True)
        self._pack_path = os.path.join(self._dir, "data.pack")
        self._index_path = os.path.join(self._dir, "index.json")
        # entry: [off, len, crc, dtype, shape, src_fp]
        self._entries: dict[int, list] = {}
        self._pack_end = 0
        self._dirty = 0
        self._append_f = None
        self._read_fd = -1
        self._load_index()
        self._evict_stale_generations()

    def _load_index(self) -> None:
        try:
            pack_size = os.path.getsize(self._pack_path)
            with open(self._index_path) as f:
                raw = json.load(f)
        except (OSError, ValueError):
            return
        for k, e in raw.get("entries", {}).items():
            # only trust records fully inside the pack (crash-truncation)
            if e[0] + e[1] <= pack_size:
                self._entries[int(k)] = e
        self._pack_end = pack_size

    def _persist_index(self) -> None:
        tmp = f"{self._index_path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                json.dump({"entries": {str(k): v for k, v
                                       in self._entries.items()}}, f)
            os.replace(tmp, self._index_path)
        except OSError as e:
            log.warning("snapshot cache index persist failed: %s", e)
        self._dirty = 0

    def flush(self) -> None:
        if self._append_f is not None:
            try:
                self._append_f.flush()
            except OSError:
                pass
        if self._dirty:
            self._persist_index()

    def close(self) -> None:
        self.flush()
        if self._append_f is not None:
            try:
                self._append_f.close()
            except OSError:
                pass
            self._append_f = None
        if self._read_fd >= 0:
            try:
                os.close(self._read_fd)
            except OSError:
                pass
            self._read_fd = -1

    def __del__(self):  # pragma: no cover — best-effort cleanup
        try:
            self.close()
        except Exception:
            pass

    # -- capacity -----------------------------------------------------------
    @property
    def bytes_used(self) -> int:
        return self._pack_end

    @property
    def complete(self) -> bool:
        return len(self._entries) >= self.n_items

    #: Foreign generations younger than this survive eviction: under a
    #: SHARED root (multi-host training over the same data_dir, or two jobs
    #: with different params) every store hashes to its own key, and
    #: unconditional eviction would have the stores rmtree each other's
    #: live caches on startup. Each store touches its own dir at open (and
    #: refreshes mtime on every index flush), so "older than the grace
    #: window" means no store has opened or written it for a day — truly
    #: dead parameter generations, the original target.
    _EVICT_GRACE_S = 24 * 3600

    def _evict_stale_generations(self) -> None:
        """Other parameter generations under the same root are dead weight
        once no live store claims them — evict the ones whose directories
        have not been touched within the grace window, oldest-first."""
        import time
        try:
            os.utime(self._dir)  # claim our generation as live
        except OSError:
            pass
        cutoff = time.time() - self._EVICT_GRACE_S
        try:
            with os.scandir(self.root) as it:
                stale = sorted(
                    (e.stat().st_mtime, e.path) for e in it
                    if e.is_dir() and e.name != self.key
                    and e.stat().st_mtime < cutoff)
        except OSError:
            return
        import shutil
        for _, path in stale:
            log.info("snapshot cache: evicting stale generation %s", path)
            shutil.rmtree(path, ignore_errors=True)

    def has(self, i: int) -> bool:
        return i in self._entries

    def evict(self, i: int) -> None:
        if self._entries.pop(i, None) is not None:
            self._dirty += 1

    # -- io -----------------------------------------------------------------
    def write(self, i: int, arr: np.ndarray, src_fp: Sequence[int]) -> bool:
        """Admit item i (append + index update; a re-write orphans the old
        record). Returns False — and counts the rejection — when the
        append would exceed the capacity budget: the cache stays bounded
        and simply never turns warm."""
        arr = np.ascontiguousarray(arr)
        nbytes = arr.nbytes
        if self._pack_end + nbytes > self.capacity_bytes:
            self.rejected_writes += 1
            return False
        # zero-copy byte view — extension dtypes (ml_dtypes bfloat16) don't
        # export a buffer-protocol format of their own
        raw = arr.view(np.uint8).reshape(-1)
        try:
            if self._append_f is None:
                self._append_f = open(self._pack_path, "ab")
            off = self._append_f.tell()
            self._append_f.write(raw.data)
        except (OSError, ValueError) as e:
            log.warning("snapshot cache write failed for item %d: %s", i, e)
            return False
        self._entries[i] = [off, nbytes, zlib.crc32(raw.data),
                            _dtype_name(arr.dtype), list(arr.shape),
                            list(src_fp)]
        self._pack_end = off + nbytes
        self._dirty += 1
        if self._dirty >= self._FLUSH_EVERY or self.complete:
            self.flush()
        return True

    def read(self, i: int,
             src_fp: Optional[Sequence[int]] = None) -> Optional[np.ndarray]:
        """Item i's crop, or None (and the entry evicted) when it is
        missing, fails validation, or its recorded source fingerprint
        doesn't match `src_fp` — the changed-payload-under-the-cache case
        must degrade to a real decode, never serve stale pixels."""
        e = self._entries.get(i)
        if e is None:
            return None
        off, nbytes, crc, dtype, shape, src = e
        if src_fp is not None and list(src_fp) != list(src):
            log.warning("snapshot cache: invalidating item %d "
                        "(source fingerprint drift)", i)
            self.evict(i)
            return None
        try:
            if self._append_f is not None:
                # every read, not just the fd open: a warm-path repair may
                # have appended SINCE — pread past the buffered writer's
                # flushed EOF would short-read and evict the fresh entry
                self._append_f.flush()
            if self._read_fd < 0:
                self._read_fd = os.open(self._pack_path, os.O_RDONLY)
            payload = os.pread(self._read_fd, nbytes, off)
            if len(payload) != nbytes:
                raise ValueError("short pack read")
            if self.validate and zlib.crc32(payload) != crc:
                raise ValueError("payload crc mismatch")
            return np.frombuffer(payload, _resolve_dtype(dtype)) \
                .reshape(shape)
        except (OSError, ValueError) as err:
            log.warning("snapshot cache: invalidating item %d (%s)", i, err)
            self.evict(i)
            return None


def params_key(*, n_items: int, files: Sequence[str], image_size: int,
               image_dtype: str, pack4: bool, mean, std, area_range,
               seed: int, hflip: bool = True) -> str:
    """Generation key: decode params + native ABI + a (path, size) source
    fingerprint. Anything that would change the produced pixels changes
    the key, so a parameter tweak can never read another config's crops.
    `hflip` (flip ownership, r13) is part of the key: a flips-on cache
    holds flipped cold-pass captures a flips-off run must never serve.
    (Pre-r13 stores are unreachable regardless — the ABI field below
    moved 8→9 in the same round.)"""
    from distributed_vgg_f_tpu.data.native_jpeg import JPEG_ABI_VERSION
    fp = hashlib.sha1()
    for p in files:
        try:
            fp.update(f"{p}:{os.path.getsize(p)}\n".encode())
        except OSError:
            fp.update(f"{p}:?\n".encode())
    spec = {
        "abi": JPEG_ABI_VERSION, "n": int(n_items),
        "files": fp.hexdigest(), "image_size": int(image_size),
        "image_dtype": image_dtype, "pack4": bool(pack4),
        "mean": [float(v) for v in mean], "std": [float(v) for v in std],
        "area_range": [float(v) for v in area_range], "seed": int(seed),
        "hflip": bool(hflip),
    }
    return hashlib.sha1(json.dumps(spec, sort_keys=True).encode()) \
        .hexdigest()[:16]


# ---------------------------------------------------------------- iterator

def _hflip(arr: np.ndarray, image_size: int, pack4: bool) -> np.ndarray:
    """Horizontal flip in whatever layout the wire ships: HWC directly, or
    through the 4x4 space-to-depth block structure (by, bx, dy, dx, c) for
    packed host-wire batches."""
    if not pack4:
        return arr[:, ::-1, :]
    s4 = image_size // 4
    return arr.reshape(s4, s4, 4, 4, 3)[:, ::-1, :, ::-1, :] \
        .reshape(arr.shape)


class SnapshotCachingTrainIterator:
    """Wraps a NativeJpegTrainIterator: passthrough-and-capture until the
    store holds every item, then warm-serve forever (the inner iterator is
    closed at the switch — all later item-level repairs go through the
    stateless decode_single path). Stream order mirrors the native shuffle
    exactly; `restore_state(step)` stays an O(1) exact seek either way."""

    supports_state = True

    def __init__(self, inner, store: SnapshotStore, *, n_items: int,
                 seed: int, labels, files: Sequence[str], path_idx, offsets,
                 lengths, mean, std, image_dtype: str, pack4: bool,
                 image_size: int, area_range=(0.08, 1.0),
                 hflip: bool = True):
        self._inner = inner
        # Flip ownership (r13): False = the fused on-device augmentation
        # stage owns the horizontal flip — the cold pass captured UNFLIPPED
        # crops (the inner loader's ABI v9 switch), warm serving must NOT
        # redraw flips, and the repair path must reproduce flips-disabled
        # crops. One flag covers all three, keyed into the store generation
        # (params_key) so a flips-on cache is never served to a flips-off
        # run.
        self._hflip = bool(hflip)
        self._store = store
        self._n = int(n_items)
        self._seed = int(seed)
        self._labels = np.ascontiguousarray(labels, np.int32)
        self._files = [str(f) for f in files]
        self._path_idx = np.ascontiguousarray(path_idx, np.int32)
        self._offsets = np.ascontiguousarray(offsets, np.int64)
        self._lengths = np.ascontiguousarray(lengths, np.int64)
        self._mean = np.ascontiguousarray(mean, np.float32)
        self._std = np.ascontiguousarray(std, np.float32)
        self._area_range = (float(area_range[0]), float(area_range[1]))
        self._pack4 = bool(pack4)
        self.batch = int(inner.batch)
        self.image_size = int(image_size)
        self.image_dtype = image_dtype
        if self._pack4:
            self._out_shape = (image_size // 4, image_size // 4, 48)
        else:
            self._out_shape = (image_size, image_size, 3)
        self._np_dtype = _resolve_dtype(image_dtype)
        self._pos = 0
        self._started = False
        self._warm = False
        self._inner_open = True
        self._inner_errors = 0
        self._orders: dict[int, np.ndarray] = {}
        self._inv0: Optional[np.ndarray] = None
        self._stats = SourceStatMemo(self._files, self._path_idx,
                                     self._offsets, self._lengths)
        self._fill_failures = 0
        self._buf_ring: list = []
        self._buf_i = 0

    # -- iterator surface ---------------------------------------------------
    def __iter__(self):
        return self

    @property
    def reuses_output_buffers(self) -> bool:
        return bool(self._buf_ring) or getattr(
            self._inner, "reuses_output_buffers", False)

    def enable_output_buffer_reuse(self, depth: int = 3) -> None:
        """Bench-only ring, mirroring the native iterators' ownership
        contract (the wrapper arms BOTH halves: the inner loader's ring for
        cold batches and its own for warm assembly)."""
        if depth < 2:
            raise ValueError(f"ring depth must be >= 2, got {depth}")
        if self._inner_open:
            self._inner.enable_output_buffer_reuse(depth)
        self._buf_ring = [
            (np.empty((self.batch,) + self._out_shape, self._np_dtype),
             np.empty((self.batch,), np.int32))
            for _ in range(depth)]
        self._buf_i = 0

    def restore_state(self, step: int) -> bool:
        if self._started:
            return False
        self._pos = int(step)
        if not self._store.complete and self._inner_open:
            return self._inner.restore_state(step)
        return True

    def decode_errors(self) -> int:
        inner = (self._inner.decode_errors() if self._inner_open
                 else self._inner_errors)
        return inner + self._fill_failures

    def set_num_threads(self, n: int):
        """Forward the autotuner's decode-worker knob (r11) to the inner
        native loader while the cold pass is still decoding; once warm the
        store serves batches with no decode pool at all, so the knob
        reports unavailable (None) and the controller stops steering it."""
        if not self._inner_open:
            return None
        fn = getattr(self._inner, "set_num_threads", None)
        return fn(n) if callable(fn) else None

    def num_threads(self):
        if not self._inner_open:
            return None
        fn = getattr(self._inner, "num_threads", None)
        return fn() if callable(fn) else None

    def close(self) -> None:
        if self._inner_open:
            # snapshot before closing: the counter must never go backwards
            # across the warm switch (cold-pass corruption stays in receipts)
            self._inner_errors = self._inner.decode_errors()
            self._inner.close()
            self._inner_open = False
        self._store.flush()

    def __del__(self):  # pragma: no cover — best-effort cleanup
        try:
            self.close()
        except Exception:
            pass

    # -- internals ----------------------------------------------------------
    def _order(self, epoch: int) -> np.ndarray:
        order = self._orders.get(epoch)
        if order is None:
            order = shuffle_indices(self._n, self._seed, epoch)
            self._orders[epoch] = order
            while len(self._orders) > 2:  # batches straddle epoch edges:
                self._orders.pop(min(self._orders))  # keep two live epochs
        return order

    def _src_fp(self, idx: int, epoch: int) -> tuple:
        """Item idx's source fingerprint (shared SourceStatMemo — one
        implementation with the disaggregated-ingest worker)."""
        return self._stats.fingerprint(idx, epoch)

    def _read_source(self, idx: int) -> Optional[bytes]:
        return read_item_bytes(self._files, self._path_idx, self._offsets,
                               self._lengths, idx)

    def _fallback_decode(self, idx: int) -> Optional[np.ndarray]:
        """Degrade to the sequential path: re-decode the EXACT epoch-0 crop
        (the mirrored item RNG seed) through the stateless single-image
        decoder, and repair the store entry."""
        from distributed_vgg_f_tpu.data.native_jpeg import decode_single_image
        if self._inv0 is None:
            order0 = shuffle_indices(self._n, self._seed, 0)
            self._inv0 = np.empty_like(order0)
            self._inv0[order0] = np.arange(self._n, dtype=np.int64)
        data = self._read_source(idx)
        if not data:
            return None
        try:
            arr = decode_single_image(
                data, self.image_size, self._mean, self._std,
                image_dtype=self.image_dtype, pack4=self._pack4,
                eval_mode=False, area_range=self._area_range,
                rng_seed=item_rng_seed(self._seed, int(self._inv0[idx])),
                hflip=self._hflip)
        except RuntimeError:
            return None
        if arr is not None:
            self._store.write(int(idx), arr, self._src_fp(idx,
                                                          self._stats.epoch))
        return arr

    def _fill_failed(self, out: np.ndarray) -> None:
        """The r9 corrupt-image contract (shared corrupt_fill)."""
        self._fill_failures += 1
        corrupt_fill(out, self.image_dtype, self._mean)

    def _capture(self, batch: dict, b: int) -> None:
        """Cold passthrough: write every not-yet-present item of native
        batch b into the store (any epoch — a resumed run back-fills the
        items its cold pass missed)."""
        images = batch["image"]
        for j in range(self.batch):
            g = b * self.batch + j
            epoch, pos = divmod(g, self._n)
            idx = int(self._order(epoch)[pos])
            if self._store.has(idx):
                continue
            self._store.write(idx, np.ascontiguousarray(images[j]),
                              self._src_fp(idx, epoch))

    def _assemble_warm(self, b: int) -> dict:
        from distributed_vgg_f_tpu import telemetry
        if self._buf_ring:
            images, labels = self._buf_ring[self._buf_i % len(self._buf_ring)]
            self._buf_i += 1
        else:
            images = np.empty((self.batch,) + self._out_shape, self._np_dtype)
            labels = np.empty((self.batch,), np.int32)
        hits = misses = nbytes = 0
        for j in range(self.batch):
            g = b * self.batch + j
            epoch, pos = divmod(g, self._n)
            idx = int(self._order(epoch)[pos])
            arr = self._store.read(idx, self._src_fp(idx, epoch))
            if arr is not None and (tuple(arr.shape) != self._out_shape
                                    or arr.dtype != self._np_dtype):
                self._store.evict(idx)  # stale layout: treat as a miss
                arr = None
            if arr is None:
                misses += 1
                arr = self._fallback_decode(idx)
            else:
                hits += 1
                nbytes += arr.nbytes
            if arr is None:
                self._fill_failed(images[j])
            else:
                # fresh per-epoch flips ONLY while the host owns flips:
                # with device-side augmentation the warm path serves the
                # stored (unflipped) crop untouched — the device flips once
                if self._hflip and _flip_bit(self._seed, g):
                    arr = _hflip(arr, self.image_size, self._pack4)
                images[j] = arr
            labels[j] = self._labels[idx]
        reg = telemetry.get_registry()
        reg.inc("prefetch/snapshot_hits", hits)
        reg.inc("prefetch/snapshot_misses", misses)
        reg.inc("prefetch/snapshot_bytes", nbytes)
        return {"image": images, "label": labels}

    def __next__(self):
        self._started = True
        b = self._pos
        self._pos += 1
        if not self._warm and self._store.complete:
            # latch warm: item repairs ride decode_single from here on, so
            # the inner loader's worker threads and ring buffers can go
            self._warm = True
            self.close()
        if self._warm:
            return self._assemble_warm(b)
        batch = next(self._inner)
        self._capture(batch, b)
        return batch


def wrap_train_iterator(inner, cfg, *, seed: int, files: Sequence[str],
                        labels, ranges=None):
    """Wrap a freshly built NativeJpegTrainIterator per
    `cfg.snapshot_cache` (data/imagenet.py calls this for both layouts).
    Returns `inner` unchanged when the cache is disabled."""
    sc = getattr(cfg, "snapshot_cache", None)
    if sc is None or not sc.enabled:
        return inner
    if ranges is None:
        from distributed_vgg_f_tpu.data.native_jpeg import _whole_file_ranges
        path_idx, offsets, lengths = _whole_file_ranges(len(files))
    else:
        path_idx, offsets, lengths = ranges
    root = sc.dir or os.path.join(cfg.data_dir or ".", ".dvggf_snapshot")
    pack4 = bool(getattr(inner, "_pack4", False))
    # flip ownership rides the INNER loader's state (r13): an hflip=False
    # loader captured unflipped crops, so the cache generation, the warm
    # redraw, and the repair path all follow it
    hflip = bool(getattr(inner, "hflip", True))
    key = params_key(
        n_items=len(labels), files=files, image_size=cfg.image_size,
        image_dtype=inner.image_dtype, pack4=pack4, mean=cfg.mean_rgb,
        std=cfg.stddev_rgb, area_range=(0.08, 1.0), seed=seed, hflip=hflip)
    try:
        store = SnapshotStore(root, key, sc.capacity_bytes, len(labels),
                              validate=sc.validate)
    except OSError as e:
        # Fault isolation: an unwritable store root (the default lives
        # under data_dir — often a read-only dataset mount) must cost the
        # CACHE, never the native iterator. Left to propagate, the
        # imagefolder path's backend fallback would silently downgrade the
        # whole ingest stack to tf.data.
        log.warning("snapshot cache disabled: store root %s unusable (%s)",
                    root, e)
        return inner
    return SnapshotCachingTrainIterator(
        inner, store, n_items=len(labels), seed=seed, labels=labels,
        files=files, path_idx=path_idx, offsets=offsets, lengths=lengths,
        mean=cfg.mean_rgb, std=cfg.stddev_rgb,
        image_dtype=inner.image_dtype, pack4=pack4,
        image_size=cfg.image_size, hflip=hflip)
