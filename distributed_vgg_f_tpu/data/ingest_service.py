"""Disaggregated ingest — the worker half of the multi-host u8 data service
(r16; ROADMAP item 4, the tf.data-service split of arXiv 2101.12127 over the
training/serving-split architecture of arXiv 1605.08695).

Host decode is a per-host ceiling (~1229 img/s/core at the r9 pin,
autotuner-steered since r11); a pod slice training at the committed device
rate starves the moment resolution rises. The fix is to split ingest from
training: decode-worker processes run the full native stack and serve READY
crops — exactly the bytes the local loader would have shipped, 1 B/px on the
u8 wire — over length-prefixed sockets, and the training host runs a thin
fetch-and-device_put client (data/service_client.py) that drops into the
existing HostPrefetchIterator/DevicePrefetchIterator chain.

Why this can be byte-identical to local ingest: the native train stream is a
pure function of (seed, position). Batch cursor b consists of global stream
items g = b*B..b*B+B-1; item g maps to dataset index `order_epoch[g % n]`
through the SplitMix64 epoch shuffle, and its crop/flip RNG is seeded
`mix(seed, 0xA0A0 + g)` — keyed on g alone (native/jpeg_loader.cc
produce_item). The python mirrors in data/snapshot_cache.py reproduce both,
and `decode_single_image(..., rng_seed=item_rng_seed(seed, g))` runs the
SAME native crop/resample math the batch loader runs (the snapshot cache's
repair path is built on this and pinned byte-identical). So ANY worker can
reconstruct ANY batch statelessly — which is what makes both the static
shard split and failover-by-reassignment exact, with no mid-stream handoff
protocol needed.

Ownership (`shard_owner`): batch cursors are split across workers by an
epoch-keyed SplitMix64 permutation of the worker set — static within an
epoch (no handoff), re-drawn per epoch (a slow box is not pinned to the
same residue class forever — the heterogeneous-fleet story). Ownership is
ROUTING only: every worker serves any cursor it is asked for, which is the
whole failover contract.

Self-sizing: each worker runs its own PR 8 controller (data/autotune.py
IngestAutotuner) over a one-knob surface — its decode thread pool — fed by
per-window busy-fraction verdicts (`infeed_bound` when the worker's decode
occupies most of its request-handling wall clock, i.e. clients are waiting
on it). A heterogeneous fleet sizes each box independently; no shared pins.

Shared warm snapshot tier: when `data.snapshot_cache.enabled`, workers
read/write the SAME on-disk store generation the local cache would use
(data/snapshot_cache.py SnapshotStore, keyed by decode params + native ABI
+ source fingerprint), inheriting its 24h-grace eviction, crc validation,
and repair-by-re-decode contracts. A warm item skips libjpeg entirely;
flips are re-drawn per (epoch, position) exactly as the local warm path
does. The tier changes pixels the same documented way the local cache does
(epoch-0 geometry re-served), so parity gates run with the store off.

Kill-switch discipline (r6–r14): `data.service.enabled=false` (the default)
never touches this module — `build_dataset` returns the local pipeline
object unchanged, pinned byte-identical in tests/test_ingest_service.py.

Protocol (version 1, little machinery on purpose — the u8 wire IS the
payload format; the service adds framing only):

    frame    := u64_be(total_len) u32_be(header_len) header_json blobs
    request  := {"op": "hello" | "get" | "stats" | "shutdown", ...}
    response := {"ok": true, ...} | {"ok": false, "error": str}

Batch responses describe their arrays in `header["arrays"]`
([{key, dtype, shape, nbytes, adler32}]) followed by the raw bytes, one
checksum per blob — the snapshot store's integrity discipline. adler32,
not crc32, deliberately: at batch 64 the payload is ~9.6 MB and crc32
costs ~9 ms per side per batch (a quarter of the single-worker produce
budget) where adler32 costs ~3.5 ms for the same torn-frame/corruption
coverage class; the receive path additionally streams blobs straight into
their destination arrays (recv_into) instead of materializing the frame.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import socket
import struct
import threading
import time
import zlib
from typing import Callable, Dict, Optional, Sequence

import numpy as np

from distributed_vgg_f_tpu import telemetry
from distributed_vgg_f_tpu.data.snapshot_cache import (
    SnapshotStore, SourceStatMemo, _dtype_name, _flip_bit, _hflip,
    _resolve_dtype, corrupt_fill, item_rng_seed, mix, params_key,
    read_item_bytes, shuffle_indices)

log = logging.getLogger(__name__)

PROTOCOL_VERSION = 1

#: Tag mixed into the ownership permutation's seed so the worker split can
#: never collide with the item-shuffle or crop-RNG streams (same idiom as
#: the 0xA0A0 / 0xF11F00 tags in the native loader and snapshot cache).
_OWNER_TAG = 0x51AB0B

_LEN = struct.Struct(">Q")
_HDR = struct.Struct(">I")

#: One frame is a batch plus a small header; anything larger is a corrupt
#: or hostile length prefix, not a legitimate message.
MAX_FRAME_BYTES = 1 << 31


class ServiceProtocolError(RuntimeError):
    """Framing/shape violation on the service socket (truncated frame,
    crc mismatch, oversized length prefix). The client treats it exactly
    like a dead worker: fail over, never deliver suspect bytes."""


# --------------------------------------------------------------- framing

def _apply_deadline(sock: socket.socket,
                    deadline: Optional[float]) -> None:
    """Per-REQUEST deadline, not per-recv: a socket timeout alone bounds
    each individual recv, so a worker trickling one byte per timeout
    window keeps a single get alive for many minutes — the config
    contract ('a worker slower than request_timeout_s is treated as
    dead') needs the remaining budget re-armed before every recv."""
    if deadline is None:
        return
    remaining = deadline - time.monotonic()
    if remaining <= 0:
        raise socket.timeout("request deadline exceeded")
    sock.settimeout(remaining)


def _recv_exact(sock: socket.socket, n: int,
                deadline: Optional[float] = None) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        _apply_deadline(sock, deadline)
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            raise ServiceProtocolError(
                f"connection closed mid-frame ({len(buf)}/{n} bytes)")
        buf.extend(chunk)
    return bytes(buf)


def send_message(sock: socket.socket, header: Dict,
                 arrays: Optional[Dict[str, np.ndarray]] = None) -> None:
    """One frame: header JSON plus the raw bytes of `arrays`, each
    described (dtype/shape/adler32) in the header so the receiver can
    reconstruct and validate without trusting the payload."""
    blobs = []
    descr = []
    for key, arr in (arrays or {}).items():
        raw = np.ascontiguousarray(arr)
        flat = raw.view(np.uint8).reshape(-1)
        blobs.append(flat)
        descr.append({"key": key, "dtype": _dtype_name(raw.dtype),
                      "shape": list(raw.shape), "nbytes": int(flat.nbytes),
                      "adler32": zlib.adler32(flat)})
    if descr:
        header = dict(header, arrays=descr)
    hdr = json.dumps(header).encode()
    total = _HDR.size + len(hdr) + sum(b.nbytes for b in blobs)
    sock.sendall(_LEN.pack(total) + _HDR.pack(len(hdr)) + hdr)
    for b in blobs:
        sock.sendall(memoryview(b))


def _recv_into(sock: socket.socket, view: memoryview, key,
               deadline: Optional[float] = None) -> None:
    filled = 0
    n = len(view)
    while filled < n:
        _apply_deadline(sock, deadline)
        got = sock.recv_into(view[filled:])
        if got == 0:
            raise ServiceProtocolError(
                f"connection closed mid-blob {key!r} ({filled}/{n} bytes)")
        filled += got


def recv_message(sock: socket.socket, deadline: Optional[float] = None):
    """(header, {key: array}) for one frame; raises ServiceProtocolError
    on truncation, oversized frames, or blob checksum mismatch (and
    socket.timeout once `deadline` — a monotonic instant bounding the
    WHOLE message — passes). Blob bytes stream DIRECTLY into their
    destination arrays — the frame is never materialized as one buffer
    (at batch 64 that would be two extra ~9.6 MB copies per batch)."""
    total = _LEN.unpack(_recv_exact(sock, _LEN.size, deadline))[0]
    if total > MAX_FRAME_BYTES or total < _HDR.size:
        raise ServiceProtocolError(f"implausible frame length {total}")
    hdr_len = _HDR.unpack(_recv_exact(sock, _HDR.size, deadline))[0]
    if _HDR.size + hdr_len > total:
        raise ServiceProtocolError("header length exceeds frame")
    try:
        header = json.loads(_recv_exact(sock, hdr_len, deadline))
    except ValueError as e:
        raise ServiceProtocolError(f"unparseable header: {e}") from None
    arrays: Dict[str, np.ndarray] = {}
    consumed = _HDR.size + hdr_len
    for d in header.get("arrays", ()):
        nbytes = int(d["nbytes"])
        if nbytes < 0 or consumed + nbytes > total:
            raise ServiceProtocolError(
                f"blob {d.get('key')!r} exceeds frame "
                f"({consumed}+{nbytes}/{total})")
        buf = np.empty(nbytes, np.uint8)
        _recv_into(sock, memoryview(buf), d.get("key"), deadline)
        if zlib.adler32(buf) != d.get("adler32"):
            raise ServiceProtocolError(
                f"blob {d.get('key')!r} checksum mismatch")
        arrays[d["key"]] = buf.view(
            _resolve_dtype(d["dtype"])).reshape(d["shape"])
        consumed += nbytes
    if consumed != total:
        raise ServiceProtocolError(
            f"frame length mismatch ({consumed} consumed of {total})")
    return header, arrays


# ------------------------------------------------------------- ownership

def shard_owner(cursor: int, num_workers: int, seed: int,
                batches_per_epoch: int) -> int:
    """Which worker OWNS batch cursor `cursor` — an epoch-keyed SplitMix64
    permutation of the worker set over the cursor's residue class. Static
    within an epoch (no mid-stream handoff), re-drawn at epoch boundaries
    so no worker is pinned to one residue class across the run. Pure
    function of its arguments: client and any observer reconstruct it
    independently, the same reconstructibility argument as the snapshot
    cache's shuffle mirror."""
    if num_workers <= 1:
        return 0
    # THE shared cursor→epoch map (r18, data/iterator_state.epoch_of):
    # next-item-to-emit semantics, so cursor k*N re-draws the ownership
    # permutation for epoch k — the same off-by-one the checkpoint blob
    # and the client's blob restore use, pinned cross-implementation in
    # tests/test_iterator_state.py.
    from distributed_vgg_f_tpu.data.iterator_state import epoch_of
    epoch = epoch_of(cursor, batches_per_epoch)
    perm = shuffle_indices(num_workers, mix(int(seed), _OWNER_TAG), epoch)
    return int(perm[int(cursor) % num_workers])


def ingest_label(num_workers: int, enabled: bool = True) -> str:
    """The ingest basis label — `local` or `service_<N>w` — used by the
    trainer start record, the bench rows, and the regression sentinel's
    Basis key (telemetry/regress.py)."""
    return f"service_{int(num_workers)}w" if enabled else "local"


# ------------------------------------------------------------- producers

class PositionKeyedProducer:
    """Reconstruct batch `cursor` of the native train stream statelessly:
    per item, mirror the epoch shuffle + per-item RNG seed in python
    (data/snapshot_cache.py pins the mirrors against native labels) and
    decode through `decode_single_image` — the SAME native crop/resample
    math as the batch loader, byte-identical (the snapshot repair path's
    contract). Decode fans out over an internal thread pool; the pool size
    is the worker's one autotuner knob (`set_num_threads`/`num_threads`,
    the surface data/autotune.thread_knob binds to).

    `store` (optional) is the shared warm tier: a hit skips libjpeg and —
    when the host owns flips — re-draws the per-(epoch, position) flip
    exactly as the local warm path does; a miss decodes the exact
    position-keyed crop and repairs the store. Store access stays on the
    produce() caller thread (the store's documented single-owner contract);
    only the stateless decodes fan out. `store_writable=False` makes the
    tier read-only for this producer: SnapshotStore is a SINGLE-WRITER
    design (private append offsets, whole-file index replace), so when
    several worker processes share one generation exactly one — the
    holder of the generation's flock, see `_native_position_producer` —
    may write; the rest serve hits and decode misses without repairing."""

    def __init__(self, *, files: Sequence[str], labels, batch: int,
                 image_size: int, seed: int, mean, std,
                 image_dtype: str = "float32", pack4: bool = False,
                 hflip: bool = True, area_range=(0.08, 1.0), ranges=None,
                 threads: int = 1, store: Optional[SnapshotStore] = None,
                 store_writable: bool = True):
        if pack4 and image_dtype == "uint8":
            raise ValueError("the u8 wire never packs on the host")
        self._files = [str(f) for f in files]
        self._labels = np.ascontiguousarray(labels, np.int32)
        self._n = int(len(self._labels))
        if ranges is None:
            from distributed_vgg_f_tpu.data.native_jpeg import (
                _whole_file_ranges)
            ranges = _whole_file_ranges(self._n)
        self._path_idx = np.ascontiguousarray(ranges[0], np.int32)
        self._offsets = np.ascontiguousarray(ranges[1], np.int64)
        self._lengths = np.ascontiguousarray(ranges[2], np.int64)
        self.batch = int(batch)
        self.image_size = int(image_size)
        self.image_dtype = image_dtype
        self._seed = int(seed)
        self._mean = np.ascontiguousarray(mean, np.float32)
        self._std = np.ascontiguousarray(std, np.float32)
        self._pack4 = bool(pack4)
        self._hflip = bool(hflip)
        self._area = (float(area_range[0]), float(area_range[1]))
        self._store = store
        self._store_writable = bool(store_writable)
        self._np_dtype = _resolve_dtype(image_dtype)
        if self._pack4:
            self._out_shape = (image_size // 4, image_size // 4, 48)
        else:
            self._out_shape = (image_size, image_size, 3)
        self._orders: Dict[int, np.ndarray] = {}
        self._stats = SourceStatMemo(self._files, self._path_idx,
                                     self._offsets, self._lengths)
        self._decode_errors = 0
        self._lock = threading.Lock()
        self._threads = max(1, int(threads))
        import concurrent.futures
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=self._threads, thread_name_prefix="svc-decode")
        # source reads ride a tiny dedicated I/O pool so they overlap the
        # decode threads: open()+read() costs ~170 us/item on overlay
        # filesystems (~15% of the u8 produce budget at 224 px) and is
        # syscall-bound, not decode CPU — the read-ahead hides it entirely
        self._io_pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=2, thread_name_prefix="svc-io")

    # -- the autotuner's knob surface (data/autotune.thread_knob) ----------
    def num_threads(self) -> Optional[int]:
        return self._threads

    def set_num_threads(self, n: int) -> Optional[int]:
        n = max(1, int(n))
        with self._lock:
            if n != self._threads:
                import concurrent.futures
                old, self._pool = self._pool, \
                    concurrent.futures.ThreadPoolExecutor(
                        max_workers=n, thread_name_prefix="svc-decode")
                self._threads = n
                old.shutdown(wait=False)
        return self._threads

    def decode_errors(self) -> int:
        return self._decode_errors

    def close(self) -> None:
        self._pool.shutdown(wait=True)
        self._io_pool.shutdown(wait=True)
        if self._store is not None:
            self._store.flush()

    # -- internals ----------------------------------------------------------
    def _order(self, epoch: int) -> np.ndarray:
        order = self._orders.get(epoch)
        if order is None:
            order = shuffle_indices(self._n, self._seed, epoch)
            self._orders[epoch] = order
            while len(self._orders) > 2:  # batches straddle epoch edges
                self._orders.pop(min(self._orders))
        return order

    def _src_fp(self, idx: int, epoch: int) -> tuple:
        # the cache's SourceStatMemo, shared: payload swaps are noticed at
        # the next epoch boundary without a stat per item
        return self._stats.fingerprint(idx, epoch)

    def _read_source(self, idx: int) -> Optional[bytes]:
        return read_item_bytes(self._files, self._path_idx, self._offsets,
                               self._lengths, idx)

    def _decode(self, g: int, data: Optional[bytes],
                out: Optional[np.ndarray] = None) -> Optional[np.ndarray]:
        from distributed_vgg_f_tpu.data.native_jpeg import decode_single_image
        if not data:
            return None
        try:
            return decode_single_image(
                data, self.image_size, self._mean, self._std,
                image_dtype=self.image_dtype, pack4=self._pack4,
                eval_mode=False, area_range=self._area,
                rng_seed=item_rng_seed(self._seed, g), hflip=self._hflip,
                out=out)
        except RuntimeError:
            return None

    def _fill_failed(self, out: np.ndarray) -> None:
        # the r9 corrupt-image contract (shared corrupt_fill)
        self._decode_errors += 1
        corrupt_fill(out, self.image_dtype, self._mean)

    def produce(self, cursor: int) -> Dict[str, np.ndarray]:
        b = int(cursor)
        images = np.empty((self.batch,) + self._out_shape, self._np_dtype)
        labels = np.empty((self.batch,), np.int32)
        jobs = []
        for j in range(self.batch):
            g = b * self.batch + j
            epoch, pos = divmod(g, self._n)
            idx = int(self._order(epoch)[pos])
            labels[j] = self._labels[idx]
            served = None
            if self._store is not None:
                served = self._store.read(idx, self._src_fp(idx, epoch))
                if served is not None and (
                        tuple(served.shape) != self._out_shape
                        or served.dtype != self._np_dtype):
                    self._store.evict(idx)
                    served = None
                if served is not None:
                    telemetry.inc("ingest_service/store_hits")
                    # warm semantics mirror the local cache: the stored
                    # crop with a fresh per-(epoch, position) flip while
                    # the host owns flips; untouched when the device does
                    if self._hflip and _flip_bit(self._seed, g):
                        served = _hflip(served, self.image_size, self._pack4)
                    images[j] = served
            if served is None:
                jobs.append((j, g, idx, epoch))
        reads = {j: self._io_pool.submit(self._read_source, idx)
                 for j, g, idx, epoch in jobs}

        def run_chunk(chunk):
            # decode straight into the batch slices (no temp + copy), one
            # contiguous chunk per pool thread (64 per-item submissions
            # cost ~3 ms of executor overhead per batch otherwise); the
            # source bytes arrive from the I/O read-ahead pool
            out = []
            for j, g, idx, epoch in chunk:
                out.append(self._decode(g, reads[j].result(),
                                        out=images[j]) is not None)
            return out

        while True:
            with self._lock:
                pool, threads = self._pool, self._threads
            step = max(1, -(-len(jobs) // max(1, threads)))
            chunks = [jobs[i:i + step] for i in range(0, len(jobs), step)]
            try:
                results = list(pool.map(run_chunk, chunks))
                break
            except RuntimeError:
                # a concurrent set_num_threads (the per-worker autotuner,
                # actuating from another connection's window) swapped and
                # shut down the pool between our capture and the map —
                # re-capture the fresh pool and retry; never surface a
                # knob actuation as a failed request
                with self._lock:
                    if pool is self._pool:
                        raise  # genuinely shut down (close()), not a swap
        for chunk, oks in zip(chunks, results):
            for (j, g, idx, epoch), ok in zip(chunk, oks):
                if not ok:
                    self._fill_failed(images[j])
                    continue
                if self._store is not None:
                    telemetry.inc("ingest_service/store_misses")
                    if self._store_writable:
                        self._store.write(idx,
                                          np.ascontiguousarray(images[j]),
                                          self._src_fp(idx, epoch))
        return {"image": images, "label": labels}


class SequentialReplayProducer:
    """Position-keyed serving over any deterministic pure-(seed, position)
    iterator factory — the non-native fallback (synthetic/cifar10/teacher,
    or a native-less box). Serves cursor b by advancing a sequential
    replica of the local stream, discarding batches other workers own (the
    documented cost of not having random access; the native path never
    pays it). A rewind rebuilds the iterator from the factory."""

    def __init__(self, factory: Callable[[], object]):
        self._factory = factory
        self._it = None
        self._pos = 0
        self._lock = threading.Lock()

    def produce(self, cursor: int) -> Dict[str, np.ndarray]:
        cursor = int(cursor)
        with self._lock:
            if self._it is None or cursor < self._pos:
                close = getattr(self._it, "close", None)
                if callable(close):
                    close()
                self._it = iter(self._factory())
                self._pos = 0
                if cursor and getattr(self._it, "supports_state", False) \
                        and self._it.restore_state(cursor):
                    self._pos = cursor
            while self._pos < cursor:
                next(self._it)
                self._pos += 1
            batch = next(self._it)
            self._pos += 1
            # a private copy: the source may recycle or mutate its arrays
            return {k: np.array(v, copy=True) for k, v in batch.items()}

    def decode_errors(self) -> int:
        fn = getattr(self._it, "decode_errors", None)
        return int(fn()) if callable(fn) else 0

    def close(self) -> None:
        close = getattr(self._it, "close", None)
        if callable(close):
            close()


# ---------------------------------------------------------------- worker

class IngestWorker:
    """One decode-worker process's serving plane: a TCP listener whose
    connection handlers answer hello/get/stats/shutdown, a produce() call
    into the wrapped producer per get, and (optionally) a per-worker PR 8
    controller sizing the producer's thread pool from busy-fraction
    verdicts. Ownership is advisory — any cursor is served on request,
    which is what makes client-side failover exact."""

    def __init__(self, producer, *, host: str = "127.0.0.1", port: int = 0,
                 worker_index: int = 0, num_workers: int = 1,
                 receipt: Optional[Dict] = None, autotune_cfg=None,
                 window_requests: int = 16, recorder=None):
        self._producer = producer
        # span destination for the decode spans that anchor cross-process
        # flow arrows (telemetry/stitch.py). Defaults to the process-global
        # ring; in-process multi-worker rigs (tests, the fleet bench) pass
        # per-worker recorders so each worker exports its OWN trace
        self._recorder = recorder if recorder is not None \
            else telemetry.get_recorder()
        self.worker_index = int(worker_index)
        self.num_workers = int(num_workers)
        self._receipt = dict(receipt or {})
        self._closed = threading.Event()
        self._conns: set = set()
        self._conn_lock = threading.Lock()
        self._produce_lock = threading.Lock()
        self._batches_served = 0
        self._bytes_served = 0
        # per-window self-sizing state (busy-fraction verdicts)
        self._window_requests = max(1, int(window_requests))
        self._win_start = time.monotonic()
        self._win_busy_s = 0.0
        self._win_count = 0
        self._tuner = None
        reg = telemetry.get_registry()
        reg.counter("ingest_service/requests")
        reg.counter("ingest_service/batches_served")
        reg.counter("ingest_service/bytes_served")
        reg.set_gauge("ingest_service/worker_threads",
                      (producer.num_threads() or 0)
                      if hasattr(producer, "num_threads") else 0)
        if autotune_cfg is not None:
            from distributed_vgg_f_tpu.data import autotune as _at
            if _at.autotune_active(autotune_cfg):
                max_threads = autotune_cfg.max_threads or max(
                    autotune_cfg.min_threads,
                    min(16, os.cpu_count() or 1))
                knob = _at.thread_knob(producer,
                                       min_value=autotune_cfg.min_threads,
                                       max_value=max_threads)
                if knob is not None:
                    self._tuner = _at.IngestAutotuner(autotune_cfg, [knob])
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, int(port)))
        self._sock.listen(32)
        # latch the bound address NOW: endpoint/port must stay readable
        # after close() (the chaos tests name the worker they just killed)
        self._bound = self._sock.getsockname()[:2]
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name=f"ingest-worker-{worker_index}")
        self._accept_thread.start()

    @property
    def port(self) -> int:
        return self._bound[1]

    @property
    def endpoint(self) -> str:
        return f"{self._bound[0]}:{self._bound[1]}"

    # ------------------------------------------------------------- serving
    def _accept_loop(self) -> None:
        while not self._closed.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return  # listener closed
            with self._conn_lock:
                self._conns.add(conn)
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True, name="ingest-worker-conn").start()

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            while not self._closed.is_set():
                try:
                    header, _ = recv_message(conn)
                except (ServiceProtocolError, OSError):
                    return
                telemetry.inc("ingest_service/requests")
                op = header.get("op")
                try:
                    if op == "hello":
                        send_message(conn, {"ok": True, **self.hello()})
                    elif op == "get":
                        self._serve_get(conn, header)
                    elif op == "stats":
                        send_message(conn, {"ok": True, **self.stats()})
                    elif op == "shutdown":
                        send_message(conn, {"ok": True})
                        # chaos/ops path: die like a preempted box — close
                        # the listener AND every live connection so
                        # in-flight client reads see EOF, not a hang
                        self.close()
                        return
                    else:
                        send_message(conn, {
                            "ok": False, "error": f"unknown op {op!r}"})
                except (BrokenPipeError, ConnectionError, OSError):
                    return
                except Exception as e:  # noqa: BLE001 — reply, don't die
                    try:
                        send_message(conn, {"ok": False, "error": repr(e)})
                    except OSError:
                        return
        finally:
            with self._conn_lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    def _serve_get(self, conn: socket.socket, header: Dict) -> None:
        cursor = int(header.get("cursor", -1))
        if cursor < 0:
            send_message(conn, {"ok": False, "error": "bad cursor"})
            return
        # wire-tolerant correlation id: clients that send one get their
        # decode span linked across processes (telemetry/stitch.py); an
        # absent id is exactly the pre-r22 protocol
        trace_id = header.get("trace_id")
        t0_ns = time.monotonic_ns()
        with self._produce_lock:
            batch = self._producer.produce(cursor)
        dur_ns = time.monotonic_ns() - t0_ns
        busy = dur_ns / 1e9
        self._recorder.record(
            "service_decode", "infeed_source", t0_ns, dur_ns,
            {"trace_id": trace_id, "flow": "in", "cursor": cursor,
             "worker": self.worker_index}
            if isinstance(trace_id, str) and trace_id else None)
        nbytes = sum(int(np.asarray(v).nbytes) for v in batch.values())
        self._batches_served += 1
        self._bytes_served += nbytes
        reg = telemetry.get_registry()
        reg.inc("ingest_service/batches_served")
        reg.inc("ingest_service/bytes_served", nbytes)
        self._observe_window(busy)
        errs = getattr(self._producer, "decode_errors", None)
        send_message(conn, {
            "ok": True, "cursor": cursor,
            "decode_errors": int(errs()) if callable(errs) else 0,
        }, arrays=batch)

    def _observe_window(self, busy_s: float) -> None:
        """Per-window self-sizing: when decode occupies most of the wall
        clock between requests, clients are waiting on THIS worker — the
        worker-local analogue of infeed_bound — and the controller may
        grow the pool (hysteresis/rails/oscillation-guard all inherited
        from data/autotune.py)."""
        self._win_busy_s += busy_s
        self._win_count += 1
        if self._win_count < self._window_requests:
            return
        wall = max(1e-9, time.monotonic() - self._win_start)
        busy_frac = min(1.0, self._win_busy_s / wall)
        verdict = "infeed_bound" if busy_frac >= 0.75 else "compute_bound"
        self._win_start = time.monotonic()
        self._win_busy_s = 0.0
        self._win_count = 0
        if self._tuner is not None:
            self._tuner.observe({"verdict": verdict,
                                 "infeed_fraction": round(busy_frac, 4)})
            nt = getattr(self._producer, "num_threads", None)
            if callable(nt) and nt() is not None:
                telemetry.set_gauge("ingest_service/worker_threads", nt())

    # ------------------------------------------------------------ receipts
    def hello(self) -> Dict:
        out = {"protocol": PROTOCOL_VERSION,
               "worker_index": self.worker_index,
               "num_workers": self.num_workers}
        # identity fields the producer knows about itself; a producer that
        # cannot state one (the sequential-replay fallback) omits it and
        # the client skips the comparison rather than failing on a 0
        for field in ("batch", "image_size", "image_dtype"):
            v = getattr(self._producer, field, None)
            if v is not None:
                out[field] = v
        out.update(self._receipt)
        return out

    def stats(self) -> Dict:
        errs = getattr(self._producer, "decode_errors", None)
        nt = getattr(self._producer, "num_threads", None)
        out = {"batches_served": self._batches_served,
               "bytes_served": self._bytes_served,
               "decode_errors": int(errs()) if callable(errs) else 0,
               "threads": nt() if callable(nt) else None}
        if self._tuner is not None:
            d = self._tuner.describe()
            d.pop("history", None)
            out["autotune"] = d
        return out

    def close(self) -> None:
        if self._closed.is_set():
            return
        self._closed.set()
        try:
            self._sock.close()
        except OSError:
            pass
        with self._conn_lock:
            conns, self._conns = set(self._conns), set()
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        close = getattr(self._producer, "close", None)
        if callable(close):
            close()

    def __del__(self):  # pragma: no cover — best-effort cleanup
        try:
            self.close()
        except Exception:
            pass


# ------------------------------------------------------- config plumbing

def build_worker_producer(data_cfg, local_batch: int, *, seed: int,
                          num_shards: int = 1, shard_index: int = 0,
                          num_classes: Optional[int] = None,
                          threads: int = 1):
    """The producer a worker process serves from, derived from the SAME
    config the training host runs — imagenet on the native stack gets the
    position-keyed stateless decoder (plus the shared warm tier when the
    snapshot cache is on); everything else replays the local builder
    sequentially."""
    import dataclasses
    svc_off = dataclasses.replace(
        data_cfg, service=dataclasses.replace(data_cfg.service,
                                              enabled=False))
    # the position-keyed reconstruction is only valid when the LOCAL
    # builder would run the native stream (the byte-identity baseline and
    # the all-workers-dead fallback both honor cfg.backend): a tfdata or
    # grain config must replay its own builder, or a mid-run fallback
    # would splice two differently-ordered streams
    from distributed_vgg_f_tpu.data.imagenet import _use_native
    if data_cfg.name == "imagenet" \
            and data_cfg.backend in ("auto", "native") \
            and _use_native(data_cfg, True):
        try:
            return _native_position_producer(
                svc_off, local_batch, seed=seed, num_shards=num_shards,
                shard_index=shard_index, threads=threads)
        except (RuntimeError, OSError, ValueError) as e:
            log.warning("ingest worker: native position-keyed producer "
                        "unavailable (%s); replaying the local builder "
                        "sequentially", e)
    from distributed_vgg_f_tpu.data import build_dataset

    def factory():
        return build_dataset(svc_off, "train", seed=seed,
                             num_shards=num_shards, shard_index=shard_index,
                             num_classes=num_classes)

    return SequentialReplayProducer(factory)


def _native_position_producer(cfg, local_batch: int, *, seed: int,
                              num_shards: int, shard_index: int,
                              threads: int) -> PositionKeyedProducer:
    from distributed_vgg_f_tpu.data.imagenet import (
        _resolve_wire, _wire_u8_active, native_train_items)
    cfg = _resolve_wire(cfg)
    files, labels, ranges = native_train_items(
        cfg, seed=seed, num_shards=num_shards, shard_index=shard_index)
    u8 = _wire_u8_active(cfg, True)
    image_dtype = "uint8" if u8 else cfg.image_dtype
    pack4 = cfg.host_space_to_depth and not u8
    hflip = not cfg.augment.owns_hflip
    store = None
    store_writable = False
    sc = cfg.snapshot_cache
    if sc.enabled:
        root = sc.dir or os.path.join(cfg.data_dir or ".", ".dvggf_snapshot")
        key = params_key(
            n_items=len(labels), files=files, image_size=cfg.image_size,
            image_dtype=image_dtype, pack4=pack4, mean=cfg.mean_rgb,
            std=cfg.stddev_rgb, area_range=(0.08, 1.0), seed=seed,
            hflip=hflip)
        try:
            store = SnapshotStore(root, key, sc.capacity_bytes, len(labels),
                                  validate=sc.validate)
            store_writable = _claim_store_writer(os.path.join(root, key))
            if not store_writable:
                log.info("ingest worker: another process holds the shared "
                         "snapshot tier's writer lock — serving read-only "
                         "(SnapshotStore is single-writer; concurrent "
                         "appends would corrupt pack offsets)")
        except OSError as e:
            log.warning("ingest worker: shared snapshot tier unusable "
                        "(%s) — serving without it", e)
            store = None
    # probe the native library NOW so an unusable box falls back loudly at
    # build time instead of per request
    from distributed_vgg_f_tpu.data.native_jpeg import load_native_jpeg
    if load_native_jpeg() is None:
        raise RuntimeError("native jpeg loader unavailable")
    return PositionKeyedProducer(
        files=files, labels=labels, batch=local_batch,
        image_size=cfg.image_size, seed=seed, mean=cfg.mean_rgb,
        std=cfg.stddev_rgb, image_dtype=image_dtype, pack4=pack4,
        hflip=hflip, ranges=ranges, threads=threads, store=store,
        store_writable=store_writable)


#: generation-dir -> held lock fd; held for the process lifetime (flock
#: auto-releases on process death, so a crashed writer never bricks the
#: generation — the next worker to open it wins the election).
_writer_locks: Dict[str, int] = {}


def _claim_store_writer(gen_dir: str) -> bool:
    """True when THIS process holds the generation's exclusive writer
    flock. SnapshotStore is single-writer by design (private append
    offsets + whole-file index replace); several workers sharing one
    generation elect exactly one writer, and the rest serve read-only."""
    if gen_dir in _writer_locks:
        # a producer in THIS process already claimed the generation — the
        # flock below would trivially succeed (flock is per-process), but
        # two writers in one process are exactly as unsafe as two
        # processes, so later claimants serve read-only
        return False
    import fcntl
    try:
        fd = os.open(os.path.join(gen_dir, ".writer.lock"),
                     os.O_CREAT | os.O_RDWR, 0o644)
        fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
    except OSError:
        try:
            os.close(fd)
        except (OSError, UnboundLocalError):
            pass
        return False
    _writer_locks[gen_dir] = fd
    return True


def serve_from_config(cfg, *, port: int = 0, host: str = "127.0.0.1",
                      worker_index: int = 0, num_workers: int = 1,
                      shard_index: int = 0, num_shards: int = 1,
                      threads: int = 1) -> IngestWorker:
    """Build the worker an `ExperimentConfig` describes (the CLI below and
    the bench harness both go through here). The hello receipt carries the
    stream-identity fields the client validates — a worker serving a
    different stream than the trainer expects must fail the handshake, not
    corrupt training."""
    local_batch = cfg.data.global_batch_size // max(1, num_shards)
    producer = build_worker_producer(
        cfg.data, local_batch, seed=cfg.train.seed, num_shards=num_shards,
        shard_index=shard_index, num_classes=cfg.model.num_classes,
        threads=threads)
    receipt = {"seed": int(cfg.train.seed), "shard_index": int(shard_index),
               "num_shards": int(num_shards), "dataset": cfg.data.name,
               "config": cfg.name}
    return IngestWorker(producer, host=host, port=port,
                        worker_index=worker_index, num_workers=num_workers,
                        receipt=receipt, autotune_cfg=cfg.data.autotune)


def main(argv: Optional[Sequence[str]] = None) -> int:
    """`python -m distributed_vgg_f_tpu.data.ingest_service --config X
    --set data.data_dir=... --port 7001 --worker-index 0 --num-workers 4`
    — one decode-worker process. Run one per decode host (or several per
    box for the CPU scaling receipt), then point the training host at them
    with `data.service.enabled=true data.service.workers=h1:p1,h2:p2,...`.
    """
    from distributed_vgg_f_tpu.config import (apply_overrides,
                                              fold_override_items,
                                              get_config)
    parser = argparse.ArgumentParser(
        description="distributed_vgg_f_tpu ingest-service decode worker")
    parser.add_argument("--config", default="vggf_imagenet_dp")
    parser.add_argument("--set", action="append", default=[],
                        metavar="KEY=VALUE")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--worker-index", type=int, default=0)
    parser.add_argument("--num-workers", type=int, default=1)
    parser.add_argument("--shard-index", type=int, default=0)
    parser.add_argument("--num-shards", type=int, default=1)
    parser.add_argument("--threads", type=int, default=1)
    args = parser.parse_args(argv)
    cfg = apply_overrides(get_config(args.config),
                          fold_override_items(args.set))
    worker = serve_from_config(
        cfg, port=args.port, host=args.host,
        worker_index=args.worker_index, num_workers=args.num_workers,
        shard_index=args.shard_index, num_shards=args.num_shards,
        threads=args.threads)
    # the launcher scrapes this line for the bound port (port 0 contract,
    # same as the telemetry exporter's sidecar discipline)
    print(f"ingest_service worker {args.worker_index}/{args.num_workers} "
          f"serving on {worker.endpoint}", flush=True)
    try:
        while not worker._closed.wait(1.0):
            pass
    except KeyboardInterrupt:
        pass
    worker.close()
    return 0


if __name__ == "__main__":  # pragma: no cover — process entry point
    raise SystemExit(main())
