"""Shared snapshot-file resume protocol for checkpointable train iterators.

One definition of the cadence/rotation/restore skeleton used by both the
tf.data iterator (data/imagenet.py CheckpointableTfIterator) and the grain
iterator (data/grain_imagenet.py GrainTrainIterator), so the two backends
cannot drift:

- a snapshot tagged D is written immediately after drawing batch D-1 — i.e.
  "the next draw is batch D", exactly the state a run restored at train step
  D needs, independent of how far ahead the device prefetcher has read;
- draws == 1 also snapshots, matching Orbax's initial save (its first save
  ignores save_interval_steps), so every durable checkpoint step has a
  matching iterator snapshot;
- only the newest `keep` snapshots are retained;
- `restore_state(D)`: D == 0 is trivially satisfied; a missing or corrupt
  snapshot returns False (caller falls back to replay or a fresh stream).

Subclasses implement the storage format: `_write_snapshot(draws)` (must be
atomic — a SIGKILL mid-write must not leave a trusted half-snapshot),
`_snapshot_exists(draws)`, `_read_snapshot(draws)` (raise on failure),
`_remove_snapshot(draws)`, and `_list_stamps()`.
"""

from __future__ import annotations

import os


class SnapshotResumableIterator:
    """Base: draw counting + snapshot cadence + rotation + restore skeleton."""

    supports_state = True

    def __init__(self, *, snapshot_dir: str = "", snapshot_every: int = 0,
                 keep: int = 4):
        self._draws = 0
        self._dir = snapshot_dir
        self._every = int(snapshot_every)
        self._keep = keep
        if self._dir:
            os.makedirs(self._dir, exist_ok=True)

    def __iter__(self):
        return self

    # ------------------------------------------------------------- protocol
    def _after_draw(self) -> None:
        """Call once per successful __next__ draw."""
        self._draws += 1
        if self._dir and self._every > 0 and (
                self._draws == 1 or self._draws % self._every == 0):
            self._write_snapshot(self._draws)
            for old in sorted(self._list_stamps())[:-self._keep]:
                self._remove_snapshot(old)

    def restore_state(self, draws: int) -> bool:
        """Restore to "next draw is batch `draws`". False if no usable
        snapshot exists (caller falls back to replay or a fresh stream)."""
        if draws == 0:
            return True
        if not self._dir or not self._snapshot_exists(draws):
            return False
        try:
            self._read_snapshot(draws)
        except Exception:
            # e.g. snapshot corrupted by a crash — fall back, don't die
            return False
        self._draws = draws
        return True

    # ------------------------------------------------------- subclass hooks
    def _write_snapshot(self, draws: int) -> None:
        raise NotImplementedError

    def _snapshot_exists(self, draws: int) -> bool:
        raise NotImplementedError

    def _read_snapshot(self, draws: int) -> None:
        raise NotImplementedError

    def _remove_snapshot(self, draws: int) -> None:
        raise NotImplementedError

    def _list_stamps(self) -> list[int]:
        raise NotImplementedError
