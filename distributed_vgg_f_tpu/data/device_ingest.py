"""Device-finish prologue for the uint8 ingest wire (r8).

The host input path historically finished every batch on the CPU —
``(pixel - mean) / std`` in f32, optional bf16 round, optional 4x4
space-to-depth — and shipped 2-4 bytes/pixel into ``device_put``. The u8
wire (native/jpeg_loader.cc out_kind=2, ``data.wire='u8'``) ships the raw
resampled uint8 pixels instead (1 byte/pixel, a 4x wire/ring reduction vs
f32) and performs that elementwise finishing math HERE, on the
accelerator: ``make_device_finish`` returns a pure function the jitted
train/eval steps apply to the batch's images INSIDE the ``shard_map`` body
(train/step.py), so XLA fuses normalize + cast + relayout into the step
for free — the tf.data-paper move (PAPERS.md arxiv 2101.12127) of pushing
cheap elementwise work to the device whose FLOPs are not the bottleneck.

Single-normalization contract: the finish dispatches on DTYPE — uint8
batches are normalized exactly once; float batches (the host-normalize
wires ``host_f32``/``host_bf16``, every non-native backend, and all eval
parity paths) pass through UNTOUCHED. Feeding the finish its own output is
therefore a no-op, which is what makes it safe to install unconditionally
in train, eval, and predict (the double-normalize hazard is structurally
impossible; tests/test_wire_u8.py pins it with a sentinel batch).

Numerics: the host path computes ``(v - mean) * (1/std)`` in f32 (with a
reciprocal multiply — jpeg_loader.cc inv_std); the finish performs the
SAME single-rounded f32 ops, so for identical u8 pixels the two wires
produce bit-identical normalized values (the CPU loss-trajectory
equivalence gate). The u8 pixels themselves differ from the float-path
bilinear by at most one intensity level (the fixed-point kernels' pinned
quantization bound).
"""

from __future__ import annotations

from typing import Callable, Sequence

import jax.numpy as jnp


def space_to_depth_batch(x: jnp.ndarray, block: int = 4) -> jnp.ndarray:
    """(B, H, W, C) -> (B, H/b, W/b, b*b*C) in tf.nn.space_to_depth's
    (dy, dx, c) channel order — the same layout the native host packer and
    the VGG-F stem contract use (models/vggf.py Conv1SpaceToDepth)."""
    b, h, w, c = x.shape
    x = x.reshape(b, h // block, block, w // block, block, c)
    return x.transpose(0, 1, 3, 2, 4, 5).reshape(
        b, h // block, w // block, block * block * c)


def make_device_finish(mean_rgb: Sequence[float], stddev_rgb: Sequence[float],
                       *, image_dtype: str = "float32",
                       space_to_depth: bool = False) -> Callable:
    """Build the jit-safe finish fn: uint8 batches get normalize → cast →
    (optional) space-to-depth; anything else passes through untouched.

    `image_dtype` is the dtype the equivalent HOST wire would have shipped
    ('float32' | 'bfloat16') — the model's own compute-dtype cast happens
    downstream either way. `space_to_depth` packs 4x4 blocks when the
    batch arrives unpacked with a %4 spatial size (the u8 wire never packs
    on the host); eval/predict callers leave it False, matching the
    host-path convention that eval batches stay (S, S, 3).

    Ordering under the fused augmentation stage (r13, data/augment.py):
    with `data.augment.enabled` the trainer builds THIS finish with
    `space_to_depth=False` and the augment closure performs the relayout
    AFTER the geometric augments (flipping a packed block layout would
    have to permute channels per block) — the host skips packing by the
    same predicate (DataConfig.host_space_to_depth), so the pack happens
    exactly once in every configuration.
    """
    mean = jnp.asarray(mean_rgb, jnp.float32)
    # reciprocal-multiply, NOT divide: mirrors the native kernels'
    # `inv_std` so host-normalize and device-finish are the same
    # single-rounded f32 ops for identical u8 inputs
    inv_std = (jnp.float32(1.0)
               / jnp.asarray(stddev_rgb, jnp.float32))
    out_dtype = jnp.bfloat16 if image_dtype == "bfloat16" else jnp.float32

    def finish(images: jnp.ndarray) -> jnp.ndarray:
        if images.dtype != jnp.uint8:
            return images  # host-normalized already — never touch twice
        x = (images.astype(jnp.float32) - mean) * inv_std
        if out_dtype != jnp.float32:
            x = x.astype(out_dtype)
        if space_to_depth and x.ndim == 4 and x.shape[-1] == 3 \
                and x.shape[1] % 4 == 0 and x.shape[2] % 4 == 0:
            x = space_to_depth_batch(x)
        return x

    return finish
