"""Shared image-dtype resolution for the input pipelines. numpy reaches
bfloat16 through ml_dtypes (a jax dependency)."""

from __future__ import annotations

import numpy as np


def resolve_image_dtype(name: str) -> np.dtype:
    if name == "bfloat16":
        import ml_dtypes
        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(name)
