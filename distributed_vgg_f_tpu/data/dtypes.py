"""Shared image-dtype and ingest-wire resolution for the input pipelines.
numpy reaches bfloat16 through ml_dtypes (a jax dependency)."""

from __future__ import annotations

import numpy as np

#: Legal values of DataConfig.wire (the host→device ingest wire format):
#:   auto      — keep the pre-r8 behavior: host-normalized batches in
#:               data.image_dtype (the eval-parity / non-native default);
#:   host_f32  — force host-normalized float32 batches;
#:   host_bf16 — force host-normalized bfloat16 batches;
#:   u8        — the uint8 wire: raw resampled pixels from the native
#:               loader, finished on device (data/device_ingest.py).
#:               Falls back to `auto` (with a logged warning) when the
#:               native u8 wire is unavailable, kill-switched
#:               (DVGGF_WIRE_U8=0), compiled out, or the backend is not
#:               the native loader.
WIRE_FORMATS = ("auto", "host_f32", "host_bf16", "u8")


def resolve_wire_dtype(wire: str, image_dtype: str) -> str:
    """Host-batch dtype a wire setting implies for HOST-normalize paths
    (u8 resolves per-pipeline — only the native train loader can ship it,
    so its resolution lives next to the loader construction)."""
    if wire == "host_f32":
        return "float32"
    if wire == "host_bf16":
        return "bfloat16"
    return image_dtype


def wire_bytes_per_pixel(wire: str, image_dtype: str) -> int:
    """device_put wire cost of one RGB pixel (3 channels) — the number the
    bench's bytes/img columns and the README wire-format table derive
    from."""
    dtype = ("uint8" if wire == "u8"
             else resolve_wire_dtype(wire, image_dtype))
    return 3 * {"float32": 4, "bfloat16": 2, "uint8": 1}[dtype]


def resolve_image_dtype(name: str) -> np.dtype:
    if name == "bfloat16":
        import ml_dtypes
        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(name)
