"""CIFAR-10 input pipeline — BASELINE config #1 (smoke test).

Reads the standard python-pickle batch files (`data_batch_1..5`, `test_batch`)
from `data_dir` when present; otherwise falls back to a deterministic synthetic
stand-in with CIFAR shapes so the smoke config runs on a bare machine (no
network on this box — SURVEY.md §0).

Augmentation (train): pad-4 reflect → random 32x32 crop → random horizontal flip
→ per-channel mean/std normalize. Eval: normalize only. Pure numpy — CIFAR is
tiny and the trainer overlaps host prep with device steps.
"""

from __future__ import annotations

import os
import pickle
from typing import Iterator, Mapping

import numpy as np

from distributed_vgg_f_tpu.config import DataConfig


def _load_cifar10_arrays(data_dir: str, split: str):
    """Returns (images uint8 NHWC, labels int32) or None if files absent."""
    # tolerate both data_dir/ and data_dir/cifar-10-batches-py/
    candidates = [data_dir, os.path.join(data_dir, "cifar-10-batches-py")]
    base = next((c for c in candidates
                 if c and os.path.exists(os.path.join(c, "data_batch_1"))), None)
    if base is None:
        return None
    files = ([f"data_batch_{i}" for i in range(1, 6)] if split == "train"
             else ["test_batch"])
    images, labels = [], []
    for fname in files:
        with open(os.path.join(base, fname), "rb") as f:
            d = pickle.load(f, encoding="bytes")
        images.append(d[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1))
        labels.extend(d[b"labels"])
    return np.concatenate(images), np.asarray(labels, np.int32)


def _synthetic_cifar_arrays(split: str, seed: int = 0):
    """Deterministic class-separable stand-in (class-dependent mean shift) so
    smoke training can still demonstrably learn."""
    rng = np.random.default_rng(seed + (0 if split == "train" else 1))
    n = 50_000 if split == "train" else 10_000
    labels = rng.integers(0, 10, size=(n,), dtype=np.int32)
    images = rng.integers(0, 256, size=(n, 32, 32, 3)).astype(np.uint8)
    # shift each class's red channel mean so the task is learnable
    images[..., 0] = np.clip(
        images[..., 0].astype(np.int32) + (labels * 12)[:, None, None] - 60,
        0, 255).astype(np.uint8)
    return images, labels


class Cifar10Iterator:
    def __init__(self, images: np.ndarray, labels: np.ndarray, batch_size: int,
                 *, train: bool, seed: int, mean: np.ndarray, std: np.ndarray,
                 hflip: bool = True):
        self.images, self.labels = images, labels
        self.batch_size = batch_size
        self.train = train
        self.mean, self.std = mean, std
        # Flip ownership (r13): False when the fused on-device augmentation
        # stage owns the horizontal flip — the host then only crops. The
        # flip draw still consumes the RNG so crops are identical either
        # way (same contract as the native loader's ABI v9 switch).
        self.hflip = bool(hflip)
        self._rng = np.random.default_rng(seed)
        self._order = np.arange(len(images))
        self._pos = len(images)  # trigger shuffle on first batch

    def _next_indices(self) -> np.ndarray:
        if self._pos + self.batch_size > len(self._order):
            if self.train:
                self._rng.shuffle(self._order)
            self._pos = 0
        idx = self._order[self._pos:self._pos + self.batch_size]
        self._pos += self.batch_size
        return idx

    def _augment(self, imgs: np.ndarray) -> np.ndarray:
        n, h, w, _ = imgs.shape
        padded = np.pad(imgs, ((0, 0), (4, 4), (4, 4), (0, 0)), mode="reflect")
        ys = self._rng.integers(0, 9, size=n)
        xs = self._rng.integers(0, 9, size=n)
        out = np.empty_like(imgs)
        for i in range(n):  # small batches; vectorizing not worth complexity
            out[i] = padded[i, ys[i]:ys[i] + h, xs[i]:xs[i] + w]
        flip = self._rng.random(n) < 0.5
        if self.hflip:
            out[flip] = out[flip, :, ::-1]
        return out

    def __iter__(self):
        return self

    def __next__(self) -> Mapping[str, np.ndarray]:
        idx = self._next_indices()
        imgs = self.images[idx]
        if self.train:
            imgs = self._augment(imgs)
        imgs = (imgs.astype(np.float32) - self.mean) / self.std
        return {"image": imgs, "label": self.labels[idx]}


def _cast_batches(it: Iterator, image_dtype: str) -> Iterator:
    if image_dtype == "float32":
        return it
    from distributed_vgg_f_tpu.data.dtypes import resolve_image_dtype
    dtype = resolve_image_dtype(image_dtype)

    def gen():
        for batch in it:
            yield {**batch, "image": batch["image"].astype(dtype)}

    return gen()


def build_cifar10(cfg: DataConfig, split: str, local_batch: int, *,
                  seed: int = 0, num_shards: int = 1,
                  shard_index: int = 0, use_native: bool = True) -> Iterator:
    loaded = _load_cifar10_arrays(cfg.data_dir, split) if cfg.data_dir else None
    if loaded is None:
        loaded = _synthetic_cifar_arrays(split, seed)
    images, labels = loaded
    # per-host shard (SURVEY.md §1 data layer): strided split by host index
    images = images[shard_index::num_shards]
    labels = labels[shard_index::num_shards]
    mean = np.asarray(cfg.mean_rgb, np.float32)
    std = np.asarray(cfg.stddev_rgb, np.float32)
    train = split == "train"
    if not train:
        # Exact eval: finite re-iterable pass over this host's shard, final
        # partial batch pad-and-masked (data/eval_pad.py) — every example
        # scored exactly once, none re-scored.
        from distributed_vgg_f_tpu.data.dtypes import resolve_image_dtype
        from distributed_vgg_f_tpu.data.eval_pad import FiniteEvalIterable
        dtype = resolve_image_dtype(cfg.image_dtype)

        def epoch():
            for i in range(0, len(images), local_batch):
                imgs = (images[i:i + local_batch].astype(np.float32)
                        - mean) / std
                yield {"image": imgs.astype(dtype),
                       "label": labels[i:i + local_batch]}

        return FiniteEvalIterable(epoch, local_batch,
                                  images.shape[1:], dtype)
    # Flip ownership (r13): with the fused on-device augmentation stage
    # owning flips, the host must not flip. The native batch assembler
    # (native/dataloader.cc) bakes its flip in, so it is bypassed for the
    # python iterator with flips off — cifar is the smoke path; the
    # throughput-critical native decoders take the ABI v9 per-loader
    # switch instead.
    device_flips = cfg.augment.owns_hflip
    if use_native and not device_flips:
        # C++ double-buffered assembler (native/dataloader.cc) — overlaps
        # augmentation with device steps; falls back silently when unbuilt.
        try:
            from distributed_vgg_f_tpu.data.native_loader import (
                NativeBatchIterator)
            return _cast_batches(NativeBatchIterator(
                images, labels, local_batch, train=train,
                seed=seed + 1000 * shard_index, mean=mean, std=std, pad=4),
                cfg.image_dtype)
        except (RuntimeError, OSError):
            pass
    return _cast_batches(
        Cifar10Iterator(images, labels, local_batch, train=train,
                        seed=seed + 1000 * shard_index, mean=mean, std=std,
                        hflip=not device_flips),
        cfg.image_dtype)
