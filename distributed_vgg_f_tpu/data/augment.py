"""Fused on-device augmentation stage (r13) — diversity at zero host cost.

The device-finish prologue (data/device_ingest.py, r8) proved elementwise
finishing is free inside the jitted step: XLA fuses normalize/cast/relayout
into the step's first kernels and the host ships raw u8 pixels. This module
extends that prologue into a full augmentation stage — horizontal flip,
translation (crop) jitter, mixup/cutmix, and a RandAugment-lite elementwise
subset — implemented as a PURE function of (train PRNG, batch) and applied
INSIDE the `shard_map` step body (train/step.py), so:

- the host wire stays raw u8 (bytes/image unchanged, receipted) and every
  host-side flip is deleted — the large-distributed-CNN study's
  host-offload argument (arXiv 1711.00705) applied to augmentation;
- every augmentation decision is reproducible from (seed, step, replica):
  the step folds the train PRNG as `fold_in(fold_in(base_rng, step),
  axis_index)` and this stage folds ONE more constant off that key, so the
  dropout stream is untouched and a checkpoint-resumed step re-draws the
  exact augmentations (mixup pairings included) the uninterrupted run
  would have — pinned by test;
- eval/predict are structurally untouched: only `build_train_step` takes a
  `device_augment`; the eval step's jaxpr is bit-identical augment-on vs
  off (sentinel test).

Ordering contract: finish (normalize/cast, NO pack) → augment (geometric →
photometric → mix) → space-to-depth pack. Packing moves AFTER the
geometric augments — flipping a 4x4-packed (S/4, S/4, 48) block layout
would have to permute channels per block — so when augmentation is
enabled the host never packs either (`DataConfig.host_space_to_depth`) and
this stage performs the relayout for BOTH wires, exactly as the u8 finish
always did.

Wire parity: the stage runs on the post-finish float batch. The u8 and
host wires produce bit-identical normalized values for identical pixels
(the r8 contract), and identical inputs through identical jitted ops give
identical outputs — so the per-model CPU loss-trajectory equality gates
(u8 ≡ host) hold with augmentation on, unchanged.

Flip ownership: `AugmentConfig.owns_hflip` is the single predicate. When
this stage owns the flip, the native decoder (ABI v9 per-loader switch),
tf.data, grain, cifar10, and the snapshot cache's warm-path redraw are ALL
disabled by it — exactly one side of the host/device boundary ever holds
the flip flag, so double-flip is structurally impossible (grid-pinned in
tests/test_augment.py).
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from distributed_vgg_f_tpu.data.device_ingest import space_to_depth_batch

#: fold_in constant deriving the augment key off the step's per-replica
#: train key — distinct from dropout (which uses the key directly) and from
#: the grad-accum micro-batch folds (small non-negative ints).
AUGMENT_RNG_FOLD = 0xA06

#: RandAugment-lite op table (op 0 = identity). Elementwise only — the
#: whole point is ops XLA fuses into the step for free.
RAND_OPS = ("identity", "brightness", "contrast", "posterize")

#: Maximum brightness shift at magnitude 1.0, in 0..255 intensity levels.
_BRIGHTNESS_MAX_LEVELS = 64.0
#: Maximum contrast factor deviation at magnitude 1.0 (factor in 1 ± this).
_CONTRAST_MAX_DELTA = 0.8
#: Maximum posterize coarsening at magnitude 1.0: quantization step 2^k,
#: k in [0, 3] — keeps >= 5 effective bits, the RandAugment-paper range.
_POSTERIZE_MAX_SHIFT = 3.0


def _hflip(key: jax.Array, x: jnp.ndarray) -> jnp.ndarray:
    """Per-image 50% horizontal flip: reverse W and select per image."""
    bits = jax.random.bernoulli(key, 0.5, (x.shape[0],))
    return jnp.where(bits[:, None, None, None], x[:, :, ::-1, :], x)


def _crop_jitter(key: jax.Array, x: jnp.ndarray, max_px: int) -> jnp.ndarray:
    """Per-image translation by (dy, dx) ∈ [-max_px, max_px]^2 with edge
    replication (clipped gather indices) — the cheap device-side stand-in
    for re-sampling the crop window, which only the host decoder could do."""
    b, h, w, _ = x.shape
    ky, kx = jax.random.split(key)
    dy = jax.random.randint(ky, (b,), -max_px, max_px + 1)
    dx = jax.random.randint(kx, (b,), -max_px, max_px + 1)
    rows = jnp.clip(jnp.arange(h)[None, :] + dy[:, None], 0, h - 1)
    x = jnp.take_along_axis(x, rows[:, :, None, None], axis=1)
    cols = jnp.clip(jnp.arange(w)[None, :] + dx[:, None], 0, w - 1)
    return jnp.take_along_axis(x, cols[:, None, :, None], axis=2)


def _rand_ops(key: jax.Array, x: jnp.ndarray, mean: jnp.ndarray,
              inv_std: jnp.ndarray, n_ops: int,
              magnitude: float) -> jnp.ndarray:
    """RandAugment-lite: `n_ops` independent draws per image from RAND_OPS,
    each at a per-image random strength up to `magnitude`. All elementwise
    (every candidate is computed and the per-image draw selects — 3 extra
    elementwise passes beat a data-dependent branch inside shard_map).
    Works on the 0..255 pixel scale — de-normalize, op, clip, re-normalize
    with the SAME single-rounded constants the finish used."""
    std = 1.0 / inv_std
    for i in range(n_ops):
        k_op, k_mag, key = jax.random.split(jax.random.fold_in(key, i), 3)
        b = x.shape[0]
        op = jax.random.randint(k_op, (b,), 0, len(RAND_OPS))
        u = jax.random.uniform(k_mag, (b,), minval=-1.0, maxval=1.0)
        sel = lambda k: (op == k)[:, None, None, None]
        p = x * std + mean  # back to the 0..255 pixel scale
        # brightness: additive shift, up to ±64 levels at magnitude 1
        bright = p + (u * magnitude * _BRIGHTNESS_MAX_LEVELS)[
            :, None, None, None]
        # contrast: scale around the per-image per-channel mean
        pivot = jnp.mean(p, axis=(1, 2), keepdims=True)
        factor = (1.0 + u * magnitude * _CONTRAST_MAX_DELTA)[
            :, None, None, None]
        contrast = (p - pivot) * factor + pivot
        # posterize: quantize to a 2^k-level grid, k in [0, 3] (|u| — the
        # op has no meaningful sign)
        step = jnp.exp2(jnp.round(
            jnp.abs(u) * magnitude * _POSTERIZE_MAX_SHIFT))[
            :, None, None, None]
        poster = jnp.floor(p / step) * step
        p = jnp.where(sel(1), bright,
                      jnp.where(sel(2), contrast,
                                jnp.where(sel(3), poster, p)))
        p = jnp.clip(p, 0.0, 255.0)
        x = (p - mean) * inv_std
    return x


def _mix(key: jax.Array, x: jnp.ndarray, labels: jnp.ndarray,
         mixup_alpha: float, cutmix_alpha: float
         ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Mixup (arXiv 1710.09412) / cutmix (arXiv 1905.04899) over the LOCAL
    shard: one Beta-drawn lam and one permutation per step (the standard
    batchwise formulation). Returns (x, labels[perm], lam) — integer labels
    stay integer; the loss mixes as lam*CE(y) + (1-lam)*CE(y[perm])."""
    b, h, w, _ = x.shape
    k_perm, k_lam, k_box, k_choice = jax.random.split(key, 4)
    perm = jax.random.permutation(k_perm, b)

    def do_mixup(args):
        x, lam0 = args
        lam = lam0.astype(x.dtype)
        return lam0, x * lam + x[perm] * (1.0 - lam)

    def do_cutmix(args):
        x, lam0 = args
        # box with area fraction (1 - lam0), centered uniformly; lam is
        # re-derived from the CLIPPED box so the label mix matches the
        # pixels actually pasted
        ratio = jnp.sqrt(1.0 - lam0)
        bh = jnp.round(ratio * h).astype(jnp.int32)
        bw = jnp.round(ratio * w).astype(jnp.int32)
        cy = jax.random.randint(k_box, (), 0, h)
        cx = jax.random.randint(jax.random.fold_in(k_box, 1), (), 0, w)
        y0 = jnp.clip(cy - bh // 2, 0, h)
        y1 = jnp.clip(cy + (bh + 1) // 2, 0, h)
        x0 = jnp.clip(cx - bw // 2, 0, w)
        x1 = jnp.clip(cx + (bw + 1) // 2, 0, w)
        in_rows = (jnp.arange(h) >= y0) & (jnp.arange(h) < y1)
        in_cols = (jnp.arange(w) >= x0) & (jnp.arange(w) < x1)
        mask = (in_rows[:, None] & in_cols[None, :])[None, :, :, None]
        lam = 1.0 - ((y1 - y0) * (x1 - x0)).astype(jnp.float32) / (h * w)
        return lam, jnp.where(mask, x[perm], x)

    if mixup_alpha > 0 and cutmix_alpha > 0:
        lam_mix = jax.random.beta(k_lam, mixup_alpha, mixup_alpha)
        lam_cut = jax.random.beta(jax.random.fold_in(k_lam, 1),
                                  cutmix_alpha, cutmix_alpha)
        use_cut = jax.random.bernoulli(k_choice, 0.5)
        lam, x = jax.lax.cond(use_cut, do_cutmix, do_mixup,
                              (x, jnp.where(use_cut, lam_cut, lam_mix)))
    elif cutmix_alpha > 0:
        lam0 = jax.random.beta(k_lam, cutmix_alpha, cutmix_alpha)
        lam, x = do_cutmix((x, lam0))
    else:
        lam0 = jax.random.beta(k_lam, mixup_alpha, mixup_alpha)
        lam, x = do_mixup((x, lam0))
    return x, labels[perm], lam.astype(jnp.float32)


def make_device_augment(aug_cfg, mean_rgb: Sequence[float],
                        stddev_rgb: Sequence[float], *,
                        space_to_depth: bool = False) -> Optional[Callable]:
    """Build the fused augmentation stage for the train step, or None when
    `aug_cfg.enabled` is false — the kill-switch contract is STRUCTURAL
    absence: a disabled stage contributes zero jaxpr equations, so the
    augment-off step is byte-identical to a pre-r13 build (pinned by test).

    The returned `augment(rng, images, labels) -> (images, mix_labels,
    mix_lam)` expects the POST-finish batch: float dtype, UNPACKED
    (B, S, S, 3). `mix_labels`/`mix_lam` are None unless mixup/cutmix is
    configured; the step's loss then mixes integer-label CE terms. When
    `space_to_depth` is set the stage performs the 4x4 relayout AFTER
    augmenting (the finish and the host both skip packing under
    augmentation — see the module docstring's ordering contract)."""
    if aug_cfg is None or not aug_cfg.enabled:
        return None
    mean = jnp.asarray(mean_rgb, jnp.float32)
    inv_std = jnp.float32(1.0) / jnp.asarray(stddev_rgb, jnp.float32)
    hflip = bool(aug_cfg.hflip)
    jitter = int(aug_cfg.crop_jitter)
    mixup_alpha = float(aug_cfg.mixup_alpha)
    cutmix_alpha = float(aug_cfg.cutmix_alpha)
    rand_ops = int(aug_cfg.rand_ops)
    magnitude = float(aug_cfg.rand_magnitude)
    pack = bool(space_to_depth)

    def augment(rng: jax.Array, images: jnp.ndarray, labels: jnp.ndarray):
        if images.ndim != 4 or images.shape[-1] != 3:
            raise ValueError(
                f"device augmentation expects the unpacked (B, S, S, 3) "
                f"post-finish batch, got {images.shape} — when "
                f"data.augment.enabled the host must not pack "
                f"(DataConfig.host_space_to_depth) and the finish defers "
                f"space-to-depth to this stage")
        if images.dtype == jnp.uint8:
            raise TypeError(
                "device augmentation runs AFTER the device finish — a raw "
                "uint8 batch here means the finish was not installed")
        in_dtype = images.dtype
        x = images.astype(jnp.float32)
        k_flip, k_jit, k_rand, k_mix = jax.random.split(rng, 4)
        if hflip:
            x = _hflip(k_flip, x)
        if jitter > 0:
            x = _crop_jitter(k_jit, x, jitter)
        if rand_ops > 0:
            x = _rand_ops(k_rand, x, mean, inv_std, rand_ops, magnitude)
        mix_labels = mix_lam = None
        if mixup_alpha > 0 or cutmix_alpha > 0:
            x, mix_labels, mix_lam = _mix(k_mix, x, labels,
                                          mixup_alpha, cutmix_alpha)
        x = x.astype(in_dtype)
        if pack and x.shape[1] % 4 == 0 and x.shape[2] % 4 == 0:
            x = space_to_depth_batch(x)
        return x, mix_labels, mix_lam

    return augment
