"""ctypes bindings for the native libjpeg training loader
(native/jpeg_loader.cc — DCT-scaled partial decode + inception crop + resize
+ normalize in C++ worker threads).

This is the framework's own native decode path for the raw-JPEG directory
layout (SURVEY.md §2.2 native layer; README measures the tf.data host path as
the end-to-end bottleneck). Built on demand with g++ -ljpeg; all callers must
tolerate `load_native_jpeg() is None` and fall back to the tf.data pipeline —
the native loader is a throughput optimization, not a correctness dependency.

Determinism contract: the batch stream is a pure function of (seed, batch
index) — same seed, same stream, regardless of thread count — and
`restore_state(step)` is an O(1) exact seek (no snapshot files), satisfying
the trainer's deterministic-resume protocol (SURVEY.md §5).
"""

from __future__ import annotations

import ctypes
import logging
import os
import threading
from typing import Optional, Sequence

import numpy as np

from distributed_vgg_f_tpu.data.native_build import build_native_lib

log = logging.getLogger(__name__)

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def load_native_jpeg() -> Optional[ctypes.CDLL]:
    global _lib, _build_failed
    with _lock:
        if _lib is not None or _build_failed:
            return _lib
        so_path = build_native_lib("jpeg_loader.cc", "libdvgg_jpeg.so",
                                   extra_link_args=("-ljpeg",))
        if so_path is None:
            _build_failed = True
            return None
        try:
            lib = ctypes.CDLL(so_path)
        except OSError as e:
            log.warning("native jpeg loader load failed: %s", e)
            _build_failed = True
            return None
        lib.dvgg_jpeg_loader_create.restype = ctypes.c_void_p
        lib.dvgg_jpeg_loader_create.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int64, ctypes.c_int,
            ctypes.c_int, ctypes.c_uint64, ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_float), ctypes.c_int, ctypes.c_int,
            ctypes.c_double, ctypes.c_double]
        lib.dvgg_jpeg_loader_next.restype = ctypes.c_int
        lib.dvgg_jpeg_loader_next.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32)]
        lib.dvgg_jpeg_loader_seek.restype = None
        lib.dvgg_jpeg_loader_seek.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.dvgg_jpeg_loader_decode_errors.restype = ctypes.c_int64
        lib.dvgg_jpeg_loader_decode_errors.argtypes = [ctypes.c_void_p]
        lib.dvgg_jpeg_loader_destroy.restype = None
        lib.dvgg_jpeg_loader_destroy.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


class NativeJpegTrainIterator:
    """Infinite deterministic train iterator over (jpeg_path, label) pairs.

    Yields {'image': (B, S, S, 3) float32|bfloat16, 'label': (B,) int32}.
    `restore_state(step)` seeks to "next batch = step" in O(1).
    """

    supports_state = True

    def __init__(self, files: Sequence[str], labels: Sequence[int],
                 batch: int, image_size: int, *, seed: int,
                 mean: np.ndarray, std: np.ndarray,
                 image_dtype: str = "float32",
                 num_threads: int | None = None,
                 area_range=(0.08, 1.0)):
        lib = load_native_jpeg()
        if lib is None:
            raise RuntimeError("native jpeg loader unavailable")
        if not len(files):
            raise ValueError("empty file list")
        self._lib = lib
        self.batch = int(batch)
        self.image_size = int(image_size)
        self._bf16 = image_dtype == "bfloat16"
        blob = b"".join(p.encode() for p in files)
        offsets = np.zeros(len(files) + 1, np.int64)
        np.cumsum([len(p.encode()) for p in files], out=offsets[1:])
        labels_arr = np.ascontiguousarray(labels, np.int32)
        mean = np.ascontiguousarray(mean, np.float32)
        std = np.ascontiguousarray(std, np.float32)
        if num_threads is None:
            num_threads = max(1, min(8, (os.cpu_count() or 1)))
        self._handle = lib.dvgg_jpeg_loader_create(
            blob, offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            labels_arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            len(files), self.batch, self.image_size, seed,
            mean.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            std.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            num_threads, int(self._bf16),
            float(area_range[0]), float(area_range[1]))
        if not self._handle:
            raise RuntimeError("dvgg_jpeg_loader_create failed")
        if self._bf16:
            import ml_dtypes
            self._np_dtype = np.dtype(ml_dtypes.bfloat16)
            self._raw_dtype = np.uint16
        else:
            self._np_dtype = np.dtype(np.float32)
            self._raw_dtype = np.float32
        self._started = False

    def restore_state(self, step: int) -> bool:
        if self._started:
            return False  # seek is only exact before the first draw
        self._lib.dvgg_jpeg_loader_seek(self._handle, int(step))
        return True

    def __iter__(self):
        return self

    def __next__(self):
        self._started = True
        s = self.image_size
        raw = np.empty((self.batch, s, s, 3), self._raw_dtype)
        labels = np.empty((self.batch,), np.int32)
        rc = self._lib.dvgg_jpeg_loader_next(
            self._handle, raw.ctypes.data_as(ctypes.c_void_p),
            labels.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
        if rc != 0:
            raise RuntimeError(f"dvgg_jpeg_loader_next rc={rc}")
        return {"image": raw.view(self._np_dtype) if self._bf16 else raw,
                "label": labels}

    def decode_errors(self) -> int:
        return int(self._lib.dvgg_jpeg_loader_decode_errors(self._handle))

    def close(self) -> None:
        if getattr(self, "_handle", None):
            self._lib.dvgg_jpeg_loader_destroy(self._handle)
            self._handle = None

    def __del__(self):  # pragma: no cover — best-effort cleanup
        try:
            self.close()
        except Exception:
            pass
