"""ctypes bindings for the native libjpeg loader (native/jpeg_loader.cc —
DCT-scaled partial decode + crop + resize + normalize in C++ worker threads).

This is the framework's own native decode path (SURVEY.md §2.2 native layer;
README measures the tf.data host path as the end-to-end bottleneck). Items are
byte ranges, so the same decoder serves both ImageNet layouts: whole .JPEG
files (raw directory-per-class) and JPEG values inside TFRecord shards
(ranges emitted by data/native_tfrecord.py). Built on demand with g++ -ljpeg;
all callers must tolerate `load_native_jpeg() is None` and fall back to the
tf.data pipeline — the native loader is a throughput optimization, not a
correctness dependency.

The resample half of the decode runs through runtime-dispatched SIMD kernels
(AVX2+FMA with a byte-identical scalar fallback — jpeg_loader.cc "resample
kernels"): `simd_kind()` reports the active path, `set_simd()` forces it
(parity tests, before/after benches), `decode_profile()` exposes the
libjpeg-vs-resample phase split, and DVGGF_DECODE_SIMD=0 is the env
kill-switch.

The libjpeg half (r7) dispatches the same way: `scaled_kind()` /
`set_scaled()` control the DCT-scaled + partial decode strategy
(DVGGF_DECODE_SCALED=0 is its env kill-switch, -DDVGGF_NO_SCALED the
compile-out), `partial_supported()` reports whether the running
libjpeg-turbo resolves the crop/skip-scanline partial-decode API (dlsym
probe — plain libjpeg gets the full-decode fallback), `choose_scale()`
exposes the native scale chooser (`expected_scale_denom` is its pure-Python
mirror, pinned equal by the tests), and `decode_stats()` returns the decode
receipts: chosen-scale histogram, scanlines skipped/truncated around the
crop window, and the per-thread decode-buffer-pool hit rate.

The wire half (r8): `image_dtype='uint8'` selects the uint8 wire — raw
resampled HWC pixels through fixed-point integer kernels (normalize, dtype
cast and space-to-depth move to the device-finish prologue,
data/device_ingest.py), shrinking the output ring 4x vs f32.
`wire_u8_supported()` / `wire_u8_enabled()` / `set_wire_u8()` mirror the
PR 2/3 dispatch surface; DVGGF_WIRE_U8=0 is the env kill-switch and
-DDVGGF_NO_WIRE_U8 the compile-out — with the wire refused, loader creation
with the u8 kind FAILS and data/imagenet.py falls back to the
host-normalize wire (byte-identical to the r7 behavior).

The entropy half (r9): `restart_kind()` / `set_restart()` control the
restart-marker excerpt decode — when a stream carries usable RSTn structure
(DRI interval dividing or divisible by the MCU row), the decoder
entropy-parses ONLY the segments covering the crop band instead of every
row above it, byte-identically to the sequential path
(DVGGF_DECODE_RESTART=0 is the env kill-switch, -DDVGGF_NO_RESTART the
compile-out). `restart_fanout()` / `set_restart_fanout()` split one image's
band across the native chunk pool (latency lever; default 1),
`restart_stats()` returns the engagement receipts, and
`reencode_restart()` losslessly injects markers into plain JPEGs (the
offline dataset tool's engine, benchmarks/reencode_restart.py).

The pool half (r11): `set_num_threads()` / `num_threads()` grow or shrink a
LIVE loader's decode worker pool (ABI v8) — the ingest autotuner's
decode-worker knob (data/autotune.py). `thread_resize_supported()` /
`thread_resize_enabled()` / `set_thread_resize()` mirror the dispatch
surface; DVGGF_THREAD_RESIZE=0 is the env kill-switch and
-DDVGGF_NO_RESIZE the compile-out (resize then refuses; the stream itself
is identical at any width, so the switch guards who may actuate, not what
is decoded).

The flip half (r13, ABI v9): per-loader flip ownership — construct the
train iterator with `hflip=False` when the fused on-device augmentation
stage (data/augment.py, `data.augment.hflip`) owns the horizontal flip, and
the host decode never flips (exactly one side holds the flag, so
double-flip is structurally impossible). `decode_single_image` takes the
same `hflip` switch for the snapshot cache's repair path. The per-item flip
bit is drawn from the RNG either way, so crop geometry — and every later
item in the stream — is bit-identical at both settings.

Determinism contract (train): the batch stream is a pure function of (seed,
batch index) — same seed, same stream, regardless of thread count — and
`restore_state(step)` is an O(1) exact seek (no snapshot files), satisfying
the trainer's deterministic-resume protocol (SURVEY.md §5).

Eval (`NativeJpegEvalIterator`): deterministic center crop (the original-
coordinate preimage of resize-short-side-256 → center-crop), one in-order
finite pass; the final partial batch arrives zero-padded with a `valid` count
for the exact-eval pad-and-mask protocol (data/eval_pad.py).
"""

from __future__ import annotations

import ctypes
import logging
import os
import threading
from typing import Optional, Sequence

import numpy as np


log = logging.getLogger(__name__)

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False

_I64P = ctypes.POINTER(ctypes.c_int64)
_I32P = ctypes.POINTER(ctypes.c_int32)
_F32P = ctypes.POINTER(ctypes.c_float)

#: Must match dvgg_jpeg_loader_abi_version() in native/jpeg_loader.cc —
#: single source for the load gate and the build smoke test.
JPEG_ABI_VERSION = 9

#: out_kind values of the v6 ABI (the loaders' former bf16_out int; 0/1
#: keep their meaning). 2 = the uint8 wire: raw resampled HWC pixels —
#: normalize/cast/space-to-depth move to the device-finish prologue
#: (data/device_ingest.py).
_OUT_KINDS = {"float32": 0, "bfloat16": 1, "uint8": 2}


def load_native_jpeg() -> Optional[ctypes.CDLL]:
    global _lib, _build_failed
    with _lock:
        if _lib is not None or _build_failed:
            return _lib
        from distributed_vgg_f_tpu.data.native_build import load_abi_checked
        lib = load_abi_checked("jpeg_loader.cc", "libdvgg_jpeg.so",
                               "dvgg_jpeg_loader_abi_version",
                               JPEG_ABI_VERSION,
                               extra_link_args=("-ljpeg", "-ldl"))
        if lib is None:
            _build_failed = True
            return None
        lib.dvgg_jpeg_loader_create.restype = ctypes.c_void_p
        lib.dvgg_jpeg_loader_create.argtypes = [
            ctypes.c_char_p, _I64P, _I32P, ctypes.c_int64, ctypes.c_int,
            ctypes.c_int, ctypes.c_uint64, _F32P, _F32P, ctypes.c_int,
            ctypes.c_int, ctypes.c_double, ctypes.c_double]
        lib.dvgg_jpeg_loader_create_ranged.restype = ctypes.c_void_p
        lib.dvgg_jpeg_loader_create_ranged.argtypes = [
            ctypes.c_char_p, _I64P, ctypes.c_int64, _I32P, _I64P, _I64P,
            _I32P, ctypes.c_int64, ctypes.c_int, ctypes.c_int,
            ctypes.c_uint64, _F32P, _F32P, ctypes.c_int, ctypes.c_int,
            ctypes.c_double, ctypes.c_double, ctypes.c_int, ctypes.c_int,
            ctypes.c_int]
        lib.dvgg_jpeg_loader_next.restype = ctypes.c_int
        lib.dvgg_jpeg_loader_next.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, _I32P]
        lib.dvgg_jpeg_loader_next_valid.restype = ctypes.c_int
        lib.dvgg_jpeg_loader_next_valid.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, _I32P, _I32P]
        lib.dvgg_jpeg_loader_seek.restype = None
        lib.dvgg_jpeg_loader_seek.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.dvgg_jpeg_loader_decode_errors.restype = ctypes.c_int64
        lib.dvgg_jpeg_loader_decode_errors.argtypes = [ctypes.c_void_p]
        lib.dvgg_jpeg_loader_destroy.restype = None
        lib.dvgg_jpeg_loader_destroy.argtypes = [ctypes.c_void_p]
        lib.dvgg_jpeg_decode_single.restype = ctypes.c_int
        lib.dvgg_jpeg_decode_single.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_int, _F32P, _F32P,
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_double, ctypes.c_double, ctypes.c_uint64,
            ctypes.c_void_p]
        lib.dvgg_jpeg_simd_supported.restype = ctypes.c_int
        lib.dvgg_jpeg_simd_supported.argtypes = []
        lib.dvgg_jpeg_simd_kind.restype = ctypes.c_int
        lib.dvgg_jpeg_simd_kind.argtypes = []
        lib.dvgg_jpeg_set_simd.restype = ctypes.c_int
        lib.dvgg_jpeg_set_simd.argtypes = [ctypes.c_int]
        lib.dvgg_jpeg_profile_ns.restype = None
        lib.dvgg_jpeg_profile_ns.argtypes = [_I64P]
        lib.dvgg_jpeg_profile_reset.restype = None
        lib.dvgg_jpeg_profile_reset.argtypes = []
        lib.dvgg_jpeg_scaled_supported.restype = ctypes.c_int
        lib.dvgg_jpeg_scaled_supported.argtypes = []
        lib.dvgg_jpeg_scaled_kind.restype = ctypes.c_int
        lib.dvgg_jpeg_scaled_kind.argtypes = []
        lib.dvgg_jpeg_set_scaled.restype = ctypes.c_int
        lib.dvgg_jpeg_set_scaled.argtypes = [ctypes.c_int]
        lib.dvgg_jpeg_partial_supported.restype = ctypes.c_int
        lib.dvgg_jpeg_partial_supported.argtypes = []
        lib.dvgg_jpeg_choose_scale.restype = ctypes.c_int
        lib.dvgg_jpeg_choose_scale.argtypes = [ctypes.c_int, ctypes.c_int,
                                               ctypes.c_int]
        lib.dvgg_jpeg_decode_stats.restype = None
        lib.dvgg_jpeg_decode_stats.argtypes = [_I64P]
        lib.dvgg_jpeg_decode_stats_reset.restype = None
        lib.dvgg_jpeg_decode_stats_reset.argtypes = []
        lib.dvgg_jpeg_wire_u8_supported.restype = ctypes.c_int
        lib.dvgg_jpeg_wire_u8_supported.argtypes = []
        lib.dvgg_jpeg_wire_u8_kind.restype = ctypes.c_int
        lib.dvgg_jpeg_wire_u8_kind.argtypes = []
        lib.dvgg_jpeg_set_wire_u8.restype = ctypes.c_int
        lib.dvgg_jpeg_set_wire_u8.argtypes = [ctypes.c_int]
        lib.dvgg_jpeg_restart_supported.restype = ctypes.c_int
        lib.dvgg_jpeg_restart_supported.argtypes = []
        lib.dvgg_jpeg_restart_kind.restype = ctypes.c_int
        lib.dvgg_jpeg_restart_kind.argtypes = []
        lib.dvgg_jpeg_set_restart.restype = ctypes.c_int
        lib.dvgg_jpeg_set_restart.argtypes = [ctypes.c_int]
        lib.dvgg_jpeg_restart_fanout.restype = ctypes.c_int
        lib.dvgg_jpeg_restart_fanout.argtypes = []
        lib.dvgg_jpeg_set_restart_fanout.restype = ctypes.c_int
        lib.dvgg_jpeg_set_restart_fanout.argtypes = [ctypes.c_int]
        lib.dvgg_jpeg_restart_stats.restype = None
        lib.dvgg_jpeg_restart_stats.argtypes = [_I64P]
        lib.dvgg_jpeg_restart_stats_reset.restype = None
        lib.dvgg_jpeg_restart_stats_reset.argtypes = []
        lib.dvgg_jpeg_reencode_restart.restype = ctypes.c_int64
        lib.dvgg_jpeg_reencode_restart.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_int, ctypes.c_void_p,
            ctypes.c_int64]
        lib.dvgg_jpeg_resize_supported.restype = ctypes.c_int
        lib.dvgg_jpeg_resize_supported.argtypes = []
        lib.dvgg_jpeg_resize_kind.restype = ctypes.c_int
        lib.dvgg_jpeg_resize_kind.argtypes = []
        lib.dvgg_jpeg_set_resize.restype = ctypes.c_int
        lib.dvgg_jpeg_set_resize.argtypes = [ctypes.c_int]
        lib.dvgg_jpeg_loader_set_threads.restype = ctypes.c_int
        lib.dvgg_jpeg_loader_set_threads.argtypes = [ctypes.c_void_p,
                                                     ctypes.c_int]
        lib.dvgg_jpeg_loader_num_threads.restype = ctypes.c_int
        lib.dvgg_jpeg_loader_num_threads.argtypes = [ctypes.c_void_p]
        lib.dvgg_jpeg_loader_set_hflip.restype = ctypes.c_int
        lib.dvgg_jpeg_loader_set_hflip.argtypes = [ctypes.c_void_p,
                                                   ctypes.c_int]
        lib.dvgg_jpeg_loader_hflip.restype = ctypes.c_int
        lib.dvgg_jpeg_loader_hflip.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


_SIMD_KINDS = {0: "scalar", 1: "avx2"}


def simd_kind() -> Optional[str]:
    """Resample path the native decoder is currently dispatching to
    ('scalar' | 'avx2'), or None when the library is unavailable. The
    initial value honors cpuid and the DVGGF_DECODE_SIMD=0 kill-switch."""
    lib = load_native_jpeg()
    if lib is None:
        return None
    return _SIMD_KINDS.get(int(lib.dvgg_jpeg_simd_kind()), "unknown")


def set_simd(enabled: bool) -> Optional[str]:
    """Force the resample path at runtime (False → scalar; True → SIMD when
    the CPU supports it). Returns the now-active kind — how the parity tests
    and the decode bench run both paths in one process."""
    lib = load_native_jpeg()
    if lib is None:
        return None
    return _SIMD_KINDS.get(int(lib.dvgg_jpeg_set_simd(int(enabled))),
                           "unknown")


_SCALED_KINDS = {0: "full", 1: "scaled"}

#: The power-of-two scale_num candidates the native chooser draws from.
#: libjpeg-turbo carries SIMD IDCT kernels ONLY for these output sizes
#: (8x8 / 4x4 / 2x2; 1x1 is DC-only) — a 5/8..7/8 decode runs a slower
#: plain-C IDCT and measured net-SLOWER than full 8/8 on the same crop.
SCALE_CANDIDATES = (1, 2, 4, 8)


def expected_scale_denom(crop_w: int, crop_h: int, out_size: int) -> int:
    """Pure-Python mirror of the native scale chooser (jpeg_loader.cc
    choose_scale_m, exported as dvgg_jpeg_choose_scale): the smallest M in
    SCALE_CANDIDATES whose M/8-scaled crop still covers `out_size` in both
    dims (floor semantics), else 8 — so the resample NEVER upscales pixels
    that a smaller DCT scale would have thrown away. The tests pin this
    mirror equal to the native ABI's reported choice across source sizes
    and crop modes; drift between the two is a chooser bug."""
    for m in SCALE_CANDIDATES:
        if (crop_w * m) // 8 >= out_size and (crop_h * m) // 8 >= out_size:
            return m
    return 8


def scaled_supported() -> Optional[bool]:
    """Whether the DCT-scaled + partial decode machinery was compiled in
    (False on a -DDVGGF_NO_SCALED build), or None when the library is
    unavailable."""
    lib = load_native_jpeg()
    if lib is None:
        return None
    return bool(lib.dvgg_jpeg_scaled_supported())


def scaled_kind() -> Optional[str]:
    """Decode strategy the native decoder is currently dispatching to
    ('full' | 'scaled'), or None when the library is unavailable. The
    initial value honors the DVGGF_DECODE_SCALED=0 kill-switch."""
    lib = load_native_jpeg()
    if lib is None:
        return None
    return _SCALED_KINDS.get(int(lib.dvgg_jpeg_scaled_kind()), "unknown")


def set_scaled(enabled: bool) -> Optional[str]:
    """Force the decode strategy at runtime (False → full-resolution
    decode; True → DCT-scaled + partial when compiled in). Returns the
    now-active kind — how the tolerance-parity suite and the decode bench
    run both strategies in one process."""
    lib = load_native_jpeg()
    if lib is None:
        return None
    return _SCALED_KINDS.get(int(lib.dvgg_jpeg_set_scaled(int(enabled))),
                             "unknown")


def partial_supported() -> Optional[bool]:
    """Whether the running libjpeg resolves the turbo-only partial-decode
    API (jpeg_crop_scanline + jpeg_skip_scanlines, dlsym-probed). False
    means the scaled path decodes full-width rows and discards — same
    pixels, more IDCT. None when the library is unavailable."""
    lib = load_native_jpeg()
    if lib is None:
        return None
    return bool(lib.dvgg_jpeg_partial_supported())


def wire_u8_supported() -> Optional[bool]:
    """Whether the uint8 wire mode was compiled in (False on a
    -DDVGGF_NO_WIRE_U8 build), or None when the library is unavailable."""
    lib = load_native_jpeg()
    if lib is None:
        return None
    return bool(lib.dvgg_jpeg_wire_u8_supported())


def wire_u8_enabled() -> bool:
    """True iff a uint8-wire loader can be created RIGHT NOW: library
    loaded, wire compiled in, and neither the DVGGF_WIRE_U8=0 env
    kill-switch nor set_wire_u8(False) has refused it. The ingest layer
    (data/imagenet.py) checks this BEFORE requesting image_dtype='uint8' —
    when False it falls back to the host-normalize wire, byte-identical to
    the pre-u8 (r7) behavior."""
    lib = load_native_jpeg()
    if lib is None:
        return False
    return bool(lib.dvgg_jpeg_wire_u8_kind())


def set_wire_u8(enabled: bool) -> Optional[bool]:
    """Force the u8-wire availability at runtime (False → loader creation
    with the u8 kind refuses; True → available when compiled in). Returns
    the now-active availability — how the fallback tests exercise both
    wires in one process. Only affects loaders created after the call."""
    lib = load_native_jpeg()
    if lib is None:
        return None
    return bool(lib.dvgg_jpeg_set_wire_u8(int(enabled)))


_RESTART_KINDS = {0: "sequential", 1: "restart"}


def restart_supported() -> Optional[bool]:
    """Whether the restart-marker excerpt decode (r9) was compiled in
    (False on a -DDVGGF_NO_RESTART build), or None when the library is
    unavailable."""
    lib = load_native_jpeg()
    if lib is None:
        return None
    return bool(lib.dvgg_jpeg_restart_supported())


def restart_kind() -> Optional[str]:
    """Entropy-decode strategy the native decoder is currently dispatching
    to ('sequential' | 'restart'), or None when the library is unavailable.
    The initial value honors the DVGGF_DECODE_RESTART=0 kill-switch.
    'restart' engages per image, only when the stream carries usable RSTn
    structure — sources without markers ride the sequential path either way
    (receipted in restart_stats()['marker_absent'])."""
    lib = load_native_jpeg()
    if lib is None:
        return None
    return _RESTART_KINDS.get(int(lib.dvgg_jpeg_restart_kind()), "unknown")


def set_restart(enabled: bool) -> Optional[str]:
    """Force the entropy strategy at runtime (False → sequential; True →
    restart excerpts when compiled in). Returns the now-active kind — how
    the parity suite decodes the same marker-bearing bytes through both
    entropy paths in one process. Byte-identical either way, by contract."""
    lib = load_native_jpeg()
    if lib is None:
        return None
    return _RESTART_KINDS.get(int(lib.dvgg_jpeg_set_restart(int(enabled))),
                              "unknown")


def restart_fanout() -> Optional[int]:
    """Active intra-image fan-out width (1 = no fan-out). The initial value
    honors the DVGGF_RESTART_FANOUT env default."""
    lib = load_native_jpeg()
    if lib is None:
        return None
    return int(lib.dvgg_jpeg_restart_fanout())


def set_restart_fanout(width: int) -> Optional[int]:
    """Set how many entropy chunks one image's crop band may be split into
    and decoded concurrently (clamped to [1, 64]). Returns the now-active
    width. Fan-out trades cores for LATENCY (decode_single, predict
    ingest); per-core throughput — the provisioning metric — is served by
    width 1, the default."""
    lib = load_native_jpeg()
    if lib is None:
        return None
    return int(lib.dvgg_jpeg_set_restart_fanout(int(width)))


#: Field order of dvgg_jpeg_restart_stats (single source for the wrapper
#: and its tests).
_RESTART_STAT_FIELDS = (
    "images", "marker_absent", "unsupported", "misaligned", "scan_failures",
    "excerpt_fallbacks", "segments_used", "segments_skipped",
    "fanout_images", "fanout_width_max", "chunk_jobs_pooled", "no_gain")


def restart_stats(reset: bool = False) -> Optional[dict]:
    """Cumulative restart-path receipts since load (or the last reset),
    process-wide: images decoded via excerpts, the fallback causes split
    by reason (marker_absent / unsupported / misaligned / scan_failures /
    excerpt_fallbacks), entropy segments decoded vs never parsed (the
    skipped Huffman work — the whole point), fan-out accounting, and
    no_gain (the band needed every segment, so sequential was used). A
    dataset that never engages the path is diagnosable from this receipt
    alone."""
    lib = load_native_jpeg()
    if lib is None:
        return None
    buf = (ctypes.c_int64 * 16)()
    lib.dvgg_jpeg_restart_stats(buf)
    if reset:
        lib.dvgg_jpeg_restart_stats_reset()
    return {k: int(buf[i]) for i, k in enumerate(_RESTART_STAT_FIELDS)}


def thread_resize_supported() -> Optional[bool]:
    """Whether runtime thread-pool grow/shrink (r11, ABI v8) was compiled
    in (False on a -DDVGGF_NO_RESIZE build), or None when the library is
    unavailable."""
    lib = load_native_jpeg()
    if lib is None:
        return None
    return bool(lib.dvgg_jpeg_resize_supported())


def thread_resize_enabled() -> bool:
    """True iff a live loader's worker pool can be resized RIGHT NOW:
    library loaded, resize compiled in, and neither the
    DVGGF_THREAD_RESIZE=0 env kill-switch nor set_thread_resize(False) has
    refused it. The ingest autotuner (data/autotune.py) checks this before
    binding its decode-worker knob — a refused resize means the knob is
    simply absent, never a silent no-op."""
    lib = load_native_jpeg()
    if lib is None:
        return False
    return bool(lib.dvgg_jpeg_resize_kind())


def set_thread_resize(enabled: bool) -> Optional[bool]:
    """Force the resize availability at runtime (False → set_num_threads
    refuses; True → allowed when compiled in). Returns the now-active
    availability — how the kill-switch tests exercise both behaviors in
    one process."""
    lib = load_native_jpeg()
    if lib is None:
        return None
    return bool(lib.dvgg_jpeg_set_resize(int(enabled)))


def reencode_restart(data: bytes, interval_mcus: int = 0) -> Optional[bytes]:
    """Losslessly transcode one JPEG so its entropy stream carries restart
    markers every `interval_mcus` MCUs (0 = one marker per MCU row — the
    row-trimmable layout the excerpt decoder engages on). Coefficient-
    domain copy (jpeg_read/write_coefficients, the jpegtran move): decoded
    pixels are bit-identical to the source's; progressive sources
    additionally normalize to baseline sequential. Returns the transcoded
    bytes, or None when the source doesn't decode (corrupt/unsupported).
    Raises when the native library itself is unavailable. This is the
    engine of the offline dataset tool (benchmarks/reencode_restart.py)."""
    lib = load_native_jpeg()
    if lib is None:
        raise RuntimeError("native jpeg loader unavailable")
    data = bytes(data)
    cap = len(data) + len(data) // 2 + 65536
    for _ in range(2):
        buf = ctypes.create_string_buffer(cap)
        rc = int(lib.dvgg_jpeg_reencode_restart(data, len(data),
                                                int(interval_mcus), buf, cap))
        if rc > 0:
            return buf.raw[:rc]
        if rc == -1:
            return None
        if rc == -2:
            raise ValueError("bad reencode_restart arguments")
        cap = -rc  # buffer too small: the return names the needed size
    raise RuntimeError("reencode_restart did not converge on a buffer size")


def choose_scale(crop_w: int, crop_h: int, out_size: int) -> Optional[int]:
    """The native ABI's scale chooser (scale_num over a fixed denom of 8)
    for a (crop_w, crop_h) source region resized to out_size — the value
    `expected_scale_denom` mirrors. None when the library is unavailable."""
    lib = load_native_jpeg()
    if lib is None:
        return None
    return int(lib.dvgg_jpeg_choose_scale(int(crop_w), int(crop_h),
                                          int(out_size)))


def decode_stats(reset: bool = False) -> Optional[dict]:
    """Cumulative decode receipts since load (or the last reset),
    process-wide across all worker threads: images decoded, the
    chosen-scale histogram {scale_num: count}, scanlines skipped above /
    truncated below the crop window, decode-buffer-pool hits/misses (and
    the derived hit rate), images decoded through the partial crop+skip
    path, and full-decode fallbacks (scaled wanted, turbo API absent).
    The decode bench embeds this as the 'what did the decoder actually
    do' receipt next to the phase profile."""
    lib = load_native_jpeg()
    if lib is None:
        return None
    buf = (ctypes.c_int64 * 16)()
    lib.dvgg_jpeg_decode_stats(buf)
    if reset:
        lib.dvgg_jpeg_decode_stats_reset()
    hits, misses = int(buf[11]), int(buf[12])
    return {
        "images": int(buf[0]),
        "scale_histogram": {m: int(buf[m]) for m in range(1, 9)
                            if int(buf[m])},
        "rows_skipped": int(buf[9]),
        "rows_truncated": int(buf[10]),
        "pool_hits": hits,
        "pool_misses": misses,
        "pool_hit_rate": (hits / (hits + misses)
                          if hits + misses else None),
        "partial_images": int(buf[13]),
        "full_fallbacks": int(buf[14]),
    }


def decode_profile(reset: bool = False) -> Optional[dict]:
    """Cumulative successful-decode phase split since load (or the last
    reset): {'jpeg_s', 'resample_s', 'images'} — libjpeg entropy+IDCT time
    vs the resample kernels, process-wide across all worker threads. The
    committed-profile source for 'where does the remaining decode time go'
    (benchmarks/host_pipeline_bench.py --decode-bench)."""
    lib = load_native_jpeg()
    if lib is None:
        return None
    buf = (ctypes.c_int64 * 3)()
    lib.dvgg_jpeg_profile_ns(buf)
    if reset:
        lib.dvgg_jpeg_profile_reset()
    return {"jpeg_s": buf[0] / 1e9, "resample_s": buf[1] / 1e9,
            "images": int(buf[2])}


def register_decode_poller() -> None:
    """Fold the native decoder's process-wide receipts into the telemetry
    registry under the `decode/` namespace (cumulative, so per-window
    deltas work): images, scale histogram, skipped/truncated scanlines,
    pool hits/misses, partial/fallback counts, and the libjpeg-vs-resample
    phase seconds. Called by the iterator constructors AFTER the library is
    known to be loaded — the telemetry package itself never imports this
    module, so `import distributed_vgg_f_tpu.telemetry` can never trigger a
    native build (the import-isolation contract). Idempotence is keyed on
    the REGISTRY's state (has_poller), not a module flag: telemetry.reset()
    drops pollers, and a module flag would sever decode counters for every
    iterator constructed after a reset (code-review r8)."""
    from distributed_vgg_f_tpu import telemetry
    if telemetry.get_registry().has_poller("decode"):
        return

    def _poll():
        st = decode_stats()
        if st is None:
            return None
        out = {k: st[k] for k in
               ("images", "rows_skipped", "rows_truncated", "pool_hits",
                "pool_misses", "partial_images", "full_fallbacks")}
        out["scale_histogram"] = st["scale_histogram"]
        prof = decode_profile()
        if prof is not None:
            out["jpeg_s"] = prof["jpeg_s"]
            out["resample_s"] = prof["resample_s"]
        rst = restart_stats()
        if rst is not None:  # r9: the entropy-path receipts ride along
            for k, v in rst.items():
                out[f"restart_{k}"] = v
        return out

    telemetry.register_poller("decode", _poll, cumulative=True)


def decode_single_image(data: bytes, out_size: int, mean, std, *,
                        image_dtype: str = "float32", pack4: bool = False,
                        eval_mode: bool = False, area_range=(0.08, 1.0),
                        rng_seed: int = 0, hflip: bool = True, out=None):
    """Stateless one-image decode through the SAME native crop/resize/
    normalize math as the batch loader (native/jpeg_loader.cc
    dvgg_jpeg_decode_single). Returns the decoded array, or None on decode
    failure (corrupt/unsupported JPEG — callers zero-fill). Raises when the
    native library itself is unavailable. The parity suite drives both
    resample paths through this.

    `hflip=False` (ABI v9) reproduces the crop from a flips-disabled
    stream — the fused on-device augmentation stage (data/augment.py) owns
    the flip then, and the snapshot cache's repair path must match the
    unflipped capture. The flip bit is drawn either way, so the crop
    geometry is identical at both settings.

    `out` (r16): decode straight into a caller-owned C-contiguous array of
    the right shape/dtype — the disaggregated-ingest worker assembles
    batches item-by-item, and a per-item temp + copy is ~10%% of its
    produce budget at batch 64. Returns `out` on success."""
    lib = load_native_jpeg()
    if lib is None:
        raise RuntimeError("native jpeg loader unavailable")
    if pack4 and out_size % 4 != 0:
        raise ValueError("pack4 needs out_size % 4 == 0")
    if image_dtype not in _OUT_KINDS:
        raise ValueError(
            f"image_dtype {image_dtype!r} not one of {sorted(_OUT_KINDS)}")
    if image_dtype == "uint8" and pack4:
        raise ValueError("the uint8 wire never packs on the host — "
                         "space-to-depth belongs to the device-finish "
                         "prologue (data/device_ingest.py)")
    bf16 = image_dtype == "bfloat16"
    if bf16:
        import ml_dtypes
        raw_dtype, np_dtype = np.uint16, np.dtype(ml_dtypes.bfloat16)
    elif image_dtype == "uint8":
        raw_dtype, np_dtype = np.uint8, np.dtype(np.uint8)
    else:
        raw_dtype, np_dtype = np.float32, np.dtype(np.float32)
    if pack4:
        shape = (out_size // 4, out_size // 4, 48)
    else:
        shape = (out_size, out_size, 3)
    mean = np.ascontiguousarray(mean, np.float32)
    std = np.ascontiguousarray(std, np.float32)
    if out is None:
        out = np.empty(shape, raw_dtype)
    else:
        if tuple(out.shape) != shape:
            raise ValueError(f"out shape {out.shape} != {shape}")
        if bf16:
            # only a 2-byte-element buffer may alias the bf16 output: a
            # wider dtype would pass .view() after a reshape and end up
            # silently half-filled with bf16 bit patterns
            if out.dtype.itemsize != 2:
                raise ValueError(
                    f"out dtype {out.dtype} is not 2-byte (bfloat16/"
                    f"uint16) for the bfloat16 wire")
            out = out.view(np.uint16)
        elif out.dtype != raw_dtype:
            raise ValueError(f"out dtype {out.dtype} != {raw_dtype}")
        if not out.flags.c_contiguous:
            raise ValueError("out must be C-contiguous")
    rc = lib.dvgg_jpeg_decode_single(
        bytes(data), len(data), int(out_size),
        mean.ctypes.data_as(_F32P), std.ctypes.data_as(_F32P),
        _OUT_KINDS[image_dtype], int(pack4), int(eval_mode), int(hflip),
        float(area_range[0]), float(area_range[1]), int(rng_seed),
        out.ctypes.data_as(ctypes.c_void_p))
    if rc == 1:
        return None
    if rc != 0:
        if image_dtype == "uint8" and not wire_u8_enabled():
            raise RuntimeError(
                "uint8 wire refused by the native library (compiled out or "
                "kill-switched) — use the host-normalize wire")
        raise RuntimeError(f"dvgg_jpeg_decode_single rc={rc}")
    return out.view(np_dtype) if bf16 else out


def _paths_blob(files: Sequence[str]):
    blob = b"".join(p.encode() for p in files)
    offsets = np.zeros(len(files) + 1, np.int64)
    np.cumsum([len(p.encode()) for p in files], out=offsets[1:])
    return blob, offsets


def _whole_file_ranges(n: int):
    """(path_idx, offsets, lengths) for n whole-file items — one path per
    item, offset<0 meaning "the entire file" (the raw-JPEG layout)."""
    return (np.arange(n, dtype=np.int32), np.full(n, -1, np.int64),
            np.zeros(n, np.int64))


class _NativeJpegBase:
    """Shared handle/buffer plumbing for the train and eval iterators.

    Handles are EXPLICIT: `_create_ranged` returns one and tracks it in
    `_live`; `_next_raw`/`_destroy` take it as an argument. The eval iterator
    gives each pass (each `iter()`) its own handle, so interleaved or
    abandoned generators can never consume or destroy each other's stream.

    Buffer ownership: by default every batch is a FRESH numpy array the
    caller owns outright — safe for any consumer, including device_put
    paths that may alias host memory. `enable_output_buffer_reuse(depth)`
    switches to a ring of `depth` preallocated output arrays (a large-batch
    array is multi-MB; allocating + page-faulting one per batch costs real
    per-image time): a yielded batch is then only valid until `depth` more
    `next()` calls, which is why `maybe_prefetch` REFUSES such an iterator
    (data/prefetch.py — the device-prefetch thread hands batches to an
    async device_put whose lifetime the ring cannot see). Bench-only.
    """

    def __init__(self, lib, batch: int, image_size: int, image_dtype: str):
        self._lib = lib
        self.batch = int(batch)
        self.image_size = int(image_size)
        if image_dtype not in _OUT_KINDS:
            raise ValueError(
                f"image_dtype {image_dtype!r} not one of {sorted(_OUT_KINDS)}")
        self._out_kind = _OUT_KINDS[image_dtype]
        self._bf16 = image_dtype == "bfloat16"
        if self._bf16:
            import ml_dtypes
            self._np_dtype = np.dtype(ml_dtypes.bfloat16)
            self._raw_dtype = np.uint16
        elif image_dtype == "uint8":
            # the u8 wire: raw resampled pixels — consumers MUST run the
            # device-finish prologue (data/device_ingest.py) exactly once
            self._np_dtype = np.dtype(np.uint8)
            self._raw_dtype = np.uint8
        else:
            self._np_dtype = np.dtype(np.float32)
            self._raw_dtype = np.float32
        #: public receipt of the dtype this iterator actually ships — the
        #: bench reads it to refuse printing a u8-labeled row for a loader
        #: that silently fell back to a host-normalize kind
        self.image_dtype = image_dtype
        self._live: list = []            # open native handles
        self._decode_errors_closed = 0   # latched counts of destroyed handles
        # per-item output shape; the packed train iterator overrides this
        self._out_shape = (self.image_size, self.image_size, 3)
        self._buf_ring: list = []        # output-array ring (opt-in)
        self._buf_i = 0

    @property
    def reuses_output_buffers(self) -> bool:
        """True once `enable_output_buffer_reuse` armed the ring — consumers
        that keep batch references alive (device prefetch) must check this
        and refuse."""
        return bool(self._buf_ring)

    def enable_output_buffer_reuse(self, depth: int = 3) -> None:
        """Arm a ring of `depth` preallocated (batch, ...) output arrays —
        each `next()` then recycles the oldest instead of allocating. The
        returned batch is only valid until `depth` further `next()` calls:
        strictly for benchmarking loops that consume batches synchronously
        (benchmarks/host_pipeline_bench.py --decode-bench)."""
        if depth < 2:
            raise ValueError(f"ring depth must be >= 2, got {depth}")
        self._buf_ring = [
            (np.empty((self.batch,) + self._out_shape, self._raw_dtype),
             np.empty((self.batch,), np.int32))
            for _ in range(depth)]
        self._buf_i = 0

    def _create_ranged(self, files, path_idx, offsets, lengths, labels, *,
                       seed, mean, std, num_threads, area_range, eval_mode,
                       finite, pack4=False):
        lib = self._lib
        blob, path_offsets = _paths_blob(files)
        path_idx = np.ascontiguousarray(path_idx, np.int32)
        offsets = np.ascontiguousarray(offsets, np.int64)
        lengths = np.ascontiguousarray(lengths, np.int64)
        labels = np.ascontiguousarray(labels, np.int32)
        mean = np.ascontiguousarray(mean, np.float32)
        std = np.ascontiguousarray(std, np.float32)
        if num_threads is None:
            num_threads = max(1, min(8, (os.cpu_count() or 1)))
        handle = lib.dvgg_jpeg_loader_create_ranged(
            blob, path_offsets.ctypes.data_as(_I64P), len(files),
            path_idx.ctypes.data_as(_I32P), offsets.ctypes.data_as(_I64P),
            lengths.ctypes.data_as(_I64P), labels.ctypes.data_as(_I32P),
            len(labels), self.batch, self.image_size, seed,
            mean.ctypes.data_as(_F32P), std.ctypes.data_as(_F32P),
            num_threads, self._out_kind,
            float(area_range[0]), float(area_range[1]),
            int(eval_mode), int(finite), int(pack4))
        if not handle:
            if self._out_kind == _OUT_KINDS["uint8"] and not wire_u8_enabled():
                raise RuntimeError(
                    "uint8 wire refused by the native library (compiled out "
                    "with -DDVGGF_NO_WIRE_U8, or killed via DVGGF_WIRE_U8=0 "
                    "/ set_wire_u8(False)) — use the host-normalize wire")
            raise RuntimeError("dvgg_jpeg_loader_create_ranged failed")
        self._live.append(handle)
        return handle

    def _next_raw(self, handle):
        """(images, labels, valid) for the next batch; None at end-of-stream."""
        if self._buf_ring:
            raw, labels = self._buf_ring[self._buf_i % len(self._buf_ring)]
            self._buf_i += 1
        else:
            raw = np.empty((self.batch,) + self._out_shape, self._raw_dtype)
            labels = np.empty((self.batch,), np.int32)
        valid = ctypes.c_int32(self.batch)
        rc = self._lib.dvgg_jpeg_loader_next_valid(
            handle, raw.ctypes.data_as(ctypes.c_void_p),
            labels.ctypes.data_as(_I32P), ctypes.byref(valid))
        if rc == 1:
            return None
        if rc != 0:
            raise RuntimeError(f"dvgg_jpeg_loader_next rc={rc}")
        images = raw.view(self._np_dtype) if self._bf16 else raw
        return images, labels, int(valid.value)

    def _destroy(self, handle) -> None:
        if handle in self._live:
            self._decode_errors_closed += int(
                self._lib.dvgg_jpeg_loader_decode_errors(handle))
            self._lib.dvgg_jpeg_loader_destroy(handle)
            self._live.remove(handle)

    def decode_errors(self) -> int:
        """Cumulative corrupt-image count across this iterator's lifetime
        (live handles + already-closed passes)."""
        live = sum(int(self._lib.dvgg_jpeg_loader_decode_errors(h))
                   for h in self._live)
        return self._decode_errors_closed + live

    def set_num_threads(self, n: int) -> Optional[int]:
        """Runtime-resize the native decode worker pool (r11, ABI v8) —
        the ingest autotuner's decode-worker knob. Grow spawns workers into
        the live item-claim loop; shrink retires idle workers before their
        next item claim. The batch stream is BYTE-IDENTICAL at any width
        (pure function of (seed, batch index)), so this is an operational
        knob, not a format one. Returns the now-active target, or None when
        refused (no live handle, -DDVGGF_NO_RESIZE build, or the
        DVGGF_THREAD_RESIZE=0 / set_thread_resize(False) kill-switch) —
        callers must treat None as 'knob unavailable'."""
        if not self._live:
            return None
        rc = -1
        for handle in self._live:
            rc = int(self._lib.dvgg_jpeg_loader_set_threads(handle, int(n)))
        return None if rc < 0 else rc

    def num_threads(self) -> Optional[int]:
        """Current worker-count target (creation value until the first
        resize), or None with no live handle."""
        if not self._live:
            return None
        rc = int(self._lib.dvgg_jpeg_loader_num_threads(self._live[-1]))
        return None if rc < 0 else rc

    def close(self) -> None:
        for handle in list(getattr(self, "_live", [])):
            self._destroy(handle)

    def __del__(self):  # pragma: no cover — best-effort cleanup
        try:
            self.close()
        except Exception:
            pass


class NativeJpegTrainIterator(_NativeJpegBase):
    """Infinite deterministic train iterator over JPEG items.

    Items are either whole files (`files` + `labels`) or byte ranges into
    container files (`ranges=(path_idx, offsets, lengths)` — the TFRecord
    layout via data/native_tfrecord.py). Yields {'image': (B, S, S, 3)
    float32|bfloat16, 'label': (B,) int32}. `restore_state(step)` seeks to
    "next batch = step" in O(1).
    """

    supports_state = True

    def __init__(self, files: Sequence[str], labels: Sequence[int],
                 batch: int, image_size: int, *, seed: int,
                 mean: np.ndarray, std: np.ndarray,
                 image_dtype: str = "float32",
                 num_threads: int | None = None,
                 area_range=(0.08, 1.0),
                 ranges=None,
                 space_to_depth: bool = False,
                 hflip: bool = True):
        lib = load_native_jpeg()
        if lib is None:
            raise RuntimeError("native jpeg loader unavailable")
        if not len(files):
            raise ValueError("empty file list")
        if space_to_depth and image_size % 4 != 0:
            raise ValueError("space_to_depth needs image_size % 4 == 0")
        if space_to_depth and image_dtype == "uint8":
            raise ValueError(
                "the uint8 wire never packs on the host: space-to-depth "
                "rides the device-finish prologue (data/device_ingest.py) "
                "— construct with space_to_depth=False")
        super().__init__(lib, batch, image_size, image_dtype)
        self._pack4 = bool(space_to_depth)
        if self._pack4:
            self._out_shape = (image_size // 4, image_size // 4, 48)
        if ranges is None:
            n = len(files)
            if len(labels) != n:
                raise ValueError("labels must match files")
            path_idx, offsets, lengths = _whole_file_ranges(n)
        else:
            path_idx, offsets, lengths = ranges
            if not (len(path_idx) == len(offsets) == len(lengths)
                    == len(labels)):
                raise ValueError("ranges/labels length mismatch")
        self._handle = self._create_ranged(
            files, path_idx, offsets, lengths, labels, seed=seed, mean=mean,
            std=std, num_threads=num_threads, area_range=area_range,
            eval_mode=0, finite=0, pack4=self._pack4)
        #: Flip ownership (ABI v9): False = the fused on-device augmentation
        #: stage owns the horizontal flip and this loader must never flip
        #: (double-flip is structurally impossible because exactly one side
        #: holds the flag). Set immediately after create — the native
        #: workers start lazily on the first next(), so this is race-free,
        #: same contract as restore_state's seek.
        self.hflip = bool(hflip)
        if not self.hflip:
            rc = int(lib.dvgg_jpeg_loader_set_hflip(self._handle, 0))
            if rc != 0:
                raise RuntimeError(
                    f"dvgg_jpeg_loader_set_hflip refused (rc={rc}) — the "
                    "loader already started decoding")
        self._started = False
        register_decode_poller()

    def restore_state(self, step: int) -> bool:
        if self._started:
            return False  # seek is only exact before the first draw
        self._lib.dvgg_jpeg_loader_seek(self._handle, int(step))
        return True

    def __iter__(self):
        return self

    def __next__(self):
        self._started = True
        images, labels, _ = self._next_raw(self._handle)
        return {"image": images, "label": labels}


class NativeJpegEvalIterator(_NativeJpegBase):
    """One finite in-order eval pass: deterministic center crop, no flip.

    Yields {'image', 'label', 'valid'} with `valid` a (B,) bool mask — the
    final partial batch is zero-padded and masked, matching the exact-eval
    protocol (data/eval_pad.py: is_finite + padding_batch, so Trainer.evaluate
    drives it exactly like the tf.data FiniteEvalIterable). Re-iterable: each
    `iter()` restarts the pass with a fresh native handle.
    """

    is_finite = True

    def __init__(self, files: Sequence[str], labels: Sequence[int],
                 batch: int, image_size: int, *,
                 mean: np.ndarray, std: np.ndarray,
                 image_dtype: str = "float32",
                 num_threads: int | None = None,
                 ranges=None):
        lib = load_native_jpeg()
        if lib is None:
            raise RuntimeError("native jpeg loader unavailable")
        if not len(files):
            raise ValueError("empty file list")
        super().__init__(lib, batch, image_size, image_dtype)
        self._files = list(files)
        self._labels = list(labels)
        self._mean = np.ascontiguousarray(mean, np.float32)
        self._std = np.ascontiguousarray(std, np.float32)
        self._num_threads = num_threads
        self._ranges = ranges
        self.num_examples = len(labels)
        self.local_batch = self.batch
        register_decode_poller()

    def __iter__(self):
        # Each pass owns a PRIVATE handle: interleaved iterators read their
        # own streams, and an abandoned generator's cleanup (the finally also
        # runs on GeneratorExit) frees its own C++ workers/buffers without
        # touching any newer pass.
        if self._ranges is None:
            path_idx, offsets, lengths = _whole_file_ranges(len(self._files))
        else:
            path_idx, offsets, lengths = self._ranges
        handle = self._create_ranged(
            self._files, path_idx, offsets, lengths, self._labels, seed=0,
            mean=self._mean, std=self._std, num_threads=self._num_threads,
            area_range=(1.0, 1.0), eval_mode=1, finite=1)
        try:
            while True:
                out = self._next_raw(handle)
                if out is None:
                    break
                images, labels, valid = out
                mask = np.zeros((self.batch,), bool)
                mask[:valid] = True
                yield {"image": images, "label": labels, "valid": mask}
        finally:
            self._destroy(handle)

    def padding_batch(self):
        """All-invalid batch for the uneven-host-shard lockstep protocol
        (data/eval_pad.py)."""
        s = self.image_size
        return {
            "image": np.zeros((self.batch, s, s, 3), self._np_dtype),
            "label": np.zeros((self.batch,), np.int32),
            "valid": np.zeros((self.batch,), np.bool_),
        }
