"""Teacher-task dataset: offline generalization evidence (VERDICT r2 #3).

Every other offline dataset in this repo is class-separable by construction
and saturates at ~1.0 top-1 — proving the fit/eval loop runs, not that
optimization GENERALIZES. This dataset manufactures a real train/val gap
with zero external data, deterministically:

- **Images**: per-index procedural textures — low-resolution uniform noise
  upsampled to the target size plus high-frequency noise. The low-res
  component is the learnable signal; the high-frequency part is nuisance.
- **Labels**: argmax of a FIXED random nonlinear teacher (mean-pool 4×4 →
  tanh hidden layer → logits) applied to the CLEAN image. The teacher's
  class biases are calibrated once, deterministically, so no class dominates
  and chance is ≈ 1/num_classes.
- **Train split** (index range [0, num_train)): inputs are AUGMENTED
  (pad-reflect random crop, horizontal flip, additive noise) and 10 % of
  labels are resampled uniformly (seeded per index) — so train top-1 has a
  ceiling below 1.0 and memorization is penalized on val.
- **Val split** (disjoint index range): clean images, clean labels, exact
  finite eval via the pad-and-mask protocol (data/eval_pad.py).

A model that only memorizes scores ≈ chance on val; a model that learns the
teacher's low-frequency decision rule generalizes — val top-1 well above
chance, below train top-1. tests/test_teacher_generalization.py pins the
band; benchmarks/teacher_generalization.py commits the full curve.

Everything is a pure function of (seed, index): multi-host sharding and
resume replay reproduce streams exactly like the other numpy pipelines.
"""

from __future__ import annotations

from typing import Iterator, Mapping

import numpy as np

from distributed_vgg_f_tpu.config import DataConfig


class Teacher:
    """The fixed random labeler: mean-pool 8×8 → tanh(W1·) → W2· + b.

    Kept deliberately coarse (a 4×4 spatial grid at image_size 32, 32 hidden
    units): a sharper teacher produces near-boundary labels everywhere and
    the task degenerates into unlearnable noise; this one is learnable from
    a few thousand examples while still non-separable (10 % label noise plus
    nuisance high-frequency image noise keep train top-1 off 1.0)."""

    HIDDEN = 32
    POOL = 8

    def __init__(self, image_size: int, num_classes: int, *, seed: int = 7,
                 channels: int = 3):
        rng = np.random.default_rng(seed)
        side = image_size // self.POOL
        feat = side * side * channels
        self.image_size = image_size
        self.channels = channels
        self.w1 = rng.standard_normal((feat, self.HIDDEN)).astype(np.float32) \
            / np.sqrt(feat)
        self.b1 = 0.1 * rng.standard_normal(self.HIDDEN).astype(np.float32)
        self.w2 = rng.standard_normal(
            (self.HIDDEN, num_classes)).astype(np.float32) \
            / np.sqrt(self.HIDDEN)
        # calibrate per-class biases on a deterministic sample so argmax
        # labels come out roughly balanced (keeps chance at ~1/num_classes)
        self.b2 = np.zeros(num_classes, np.float32)
        sample = _raw_images(rng.integers(0, 2**31, size=2048), image_size,
                             base_seed=seed + 1)
        logits = self._logits(sample)
        self.b2 = (-logits.mean(axis=0)).astype(np.float32)

    def _features(self, images: np.ndarray) -> np.ndarray:
        n, s, _, c = images.shape
        p = self.POOL
        x = images.reshape(n, s // p, p, s // p, p, c).mean(axis=(2, 4))
        return x.reshape(n, -1) / 255.0 - 0.5

    def _logits(self, images: np.ndarray) -> np.ndarray:
        h = np.tanh(self._features(images) @ self.w1 + self.b1)
        return h @ self.w2 + self.b2

    def logits(self, images: np.ndarray) -> np.ndarray:
        """Teacher logits on uint8-ranged pixels — the distillation target
        (train/distill.py KL head) uses the FULL distribution, not just
        the argmax `label()` trains against."""
        return self._logits(np.asarray(images, np.float32))

    def label(self, images: np.ndarray) -> np.ndarray:
        return np.argmax(self._logits(images), axis=1).astype(np.int32)


def _raw_images(indices: np.ndarray, image_size: int, *,
                base_seed: int) -> np.ndarray:
    """Per-index procedural texture: 8×8 low-res signal upsampled + 30 %
    high-frequency nuisance noise, uint8-ranged float32."""
    out = np.empty((len(indices), image_size, image_size, 3), np.float32)
    rep = image_size // 8
    for i, idx in enumerate(np.asarray(indices, np.int64)):
        rng = np.random.default_rng((base_seed << 32) ^ int(idx))
        low = rng.uniform(0.0, 255.0, size=(8, 8, 3)).astype(np.float32)
        img = np.repeat(np.repeat(low, rep, axis=0), rep, axis=1)
        img += rng.normal(0.0, 12.0, size=img.shape).astype(np.float32)
        out[i] = np.clip(img, 0.0, 255.0)
    return out


class TeacherTaskDataset:
    """Train iterator of {'image', 'label'} batches over the teacher task."""

    LABEL_NOISE = 0.10

    def __init__(self, batch_size: int, image_size: int, num_classes: int,
                 *, seed: int, num_examples: int, start_index: int = 0,
                 shard_index: int = 0, num_shards: int = 1,
                 image_dtype: str = "float32",
                 mean: np.ndarray | None = None,
                 std: np.ndarray | None = None):
        from distributed_vgg_f_tpu.data.dtypes import resolve_image_dtype
        self.batch_size = batch_size
        self.image_size = image_size
        self.num_examples = num_examples
        self.start_index = start_index
        self.seed = seed
        self.teacher = Teacher(image_size, num_classes, seed=7)
        self.mean = (np.asarray(mean, np.float32) if mean is not None
                     else np.float32(127.5))
        self.std = (np.asarray(std, np.float32) if std is not None
                    else np.float32(64.0))
        self.dtype = resolve_image_dtype(image_dtype)
        self.num_classes = num_classes
        # per-host shard of the example index space (SURVEY.md §1 data layer)
        self._indices = np.arange(start_index,
                                  start_index + num_examples)[
                                      shard_index::num_shards]
        self._rng = np.random.default_rng(seed + 1000 * shard_index)
        self._order = self._indices.copy()
        self._pos = len(self._order)  # shuffle on first draw

    def _clean_labels(self, images: np.ndarray) -> np.ndarray:
        return self.teacher.label(images)

    def _noisy_labels(self, labels: np.ndarray,
                      indices: np.ndarray) -> np.ndarray:
        out = labels.copy()
        for i, idx in enumerate(np.asarray(indices, np.int64)):
            r = np.random.default_rng((77 << 32) ^ int(idx))
            if r.random() < self.LABEL_NOISE:
                out[i] = r.integers(0, self.num_classes)
        return out

    def _augment(self, images: np.ndarray) -> np.ndarray:
        n, s = images.shape[0], self.image_size
        # sub-cell shifts only: the teacher pools 8×8 blocks, so a crop shift
        # ≥ half a block would relabel the image under the teacher's own rule
        # and turn augmentation into label corruption
        pad = 2
        padded = np.pad(images, ((0, 0), (pad, pad), (pad, pad), (0, 0)),
                        mode="reflect")
        out = np.empty_like(images)
        ys = self._rng.integers(0, 2 * pad + 1, size=n)
        xs = self._rng.integers(0, 2 * pad + 1, size=n)
        for i in range(n):
            out[i] = padded[i, ys[i]:ys[i] + s, xs[i]:xs[i] + s]
        # NO horizontal flip: the teacher is not flip-invariant (measured:
        # 88 % of flipped images change teacher label), so flipping would
        # corrupt ~44 % of train labels — far beyond the designed 10 % noise
        out += self._rng.normal(0.0, 4.0, size=out.shape).astype(np.float32)
        return out

    def __iter__(self):
        return self

    def __next__(self) -> Mapping[str, np.ndarray]:
        if self._pos + self.batch_size > len(self._order):
            self._rng.shuffle(self._order)
            self._pos = 0
        idx = self._order[self._pos:self._pos + self.batch_size]
        self._pos += self.batch_size
        clean = _raw_images(idx, self.image_size, base_seed=11)
        labels = self._noisy_labels(self._clean_labels(clean), idx)
        images = (self._augment(clean) - self.mean) / self.std
        return {"image": images.astype(self.dtype), "label": labels}


def build_teacher(cfg: DataConfig, split: str, local_batch: int, *,
                  seed: int = 0, num_shards: int = 1,
                  shard_index: int = 0) -> Iterator:
    """Factory (data/__init__.py `build_dataset`, data.name == "teacher").

    `train`: indices [0, num_train_examples), augmented + label noise.
    `eval`: DISJOINT indices starting at num_train_examples, clean, exact
    finite eval.
    `train_clean`: the TRAIN index range under the eval protocol (clean
    images, clean teacher labels) — the memorization-side number the
    generalization gap is measured against.
    """
    num_classes = 10
    if split == "train":
        return TeacherTaskDataset(
            local_batch, cfg.image_size, num_classes, seed=seed,
            num_examples=cfg.num_train_examples,
            shard_index=shard_index, num_shards=num_shards,
            image_dtype=cfg.image_dtype)

    from distributed_vgg_f_tpu.data.dtypes import resolve_image_dtype
    from distributed_vgg_f_tpu.data.eval_pad import FiniteEvalIterable
    dtype = resolve_image_dtype(cfg.image_dtype)
    teacher = Teacher(cfg.image_size, num_classes, seed=7)
    if split == "train_clean":
        indices = np.arange(0, cfg.num_train_examples)[
            shard_index::num_shards]
    else:
        # base 0 = legacy (val starts right after the train range). A fixed
        # far-offset base decouples the held-out SET from the train-set
        # size so train-size sweeps score every arm on identical examples
        # (config.py eval_index_base rationale).
        base = cfg.eval_index_base or cfg.num_train_examples
        if base < cfg.num_train_examples:
            raise ValueError(
                f"data.eval_index_base={base} overlaps the train range "
                f"[0, {cfg.num_train_examples}) — the val split must stay "
                f"disjoint")
        indices = np.arange(base, base + cfg.num_eval_examples)[
            shard_index::num_shards]
    mean, std = np.float32(127.5), np.float32(64.0)

    def epoch():
        for i in range(0, len(indices), local_batch):
            idx = indices[i:i + local_batch]
            clean = _raw_images(idx, cfg.image_size, base_seed=11)
            yield {"image": ((clean - mean) / std).astype(dtype),
                   "label": teacher.label(clean)}

    return FiniteEvalIterable(epoch, local_batch,
                              (cfg.image_size, cfg.image_size, 3), dtype)
