"""Disaggregated-ingest client — the training-host half of the multi-host
data service (r16; the thin fetch-and-device_put side of the tf.data-service
split, arXiv 2101.12127; worker plane in data/ingest_service.py).

`ServiceIngestClient` is a drop-in host-batch iterator: it yields the SAME
{'image', 'label'} numpy batches the local pipeline would produce, in the
same cursor order, so it slots under the existing
HostPrefetchIterator/DevicePrefetchIterator chain (and the data watchdog,
fault injectors, and stall attributor) with zero trainer changes beyond
`build_dataset` routing. Position-exactness is free: the stream is keyed by
batch cursor, so `restore_state(step)` is a variable assignment.

Routing and pipelining: cursor b belongs to `shard_owner(b, ...)` — the
epoch-keyed SplitMix64 split both sides compute independently. The client
keeps up to `fetch_ahead` cursors in flight across the worker fleet (one
request outstanding per worker socket, more workers = more parallel decode
— the aggregation that makes N workers ≈ N× one host) and delivers strictly
in cursor order.

Failure contract (the resilience story, mirrors the r4 watchdog taxonomy):

- a worker that dies mid-epoch (socket error, truncated frame, checksum
  mismatch, timeout) is marked dead with a logged warning and its cursors
  are REASSIGNED to the surviving workers (`ingest_service/failovers`);
  because every worker serves any cursor statelessly, the stream stays
  byte-identical through the failover;
- when EVERY worker is dead, the client falls back to LOCAL ingest
  (`local_factory`, the ordinary build_dataset pipeline) with a logged
  warning and `ingest_service/local_fallbacks` — the run degrades to r15
  behavior instead of dying;
- with no local fallback configured, the client notes a `data_stall` crash
  class in the flight recorder and raises DataStallError — the SAME typed
  stall the prefetch watchdog raises, so the trainer's existing handling
  (and the chaos suite's classification assertions) apply unchanged.

Elastic seam (r19, parallel/elastic.py): stateless cursor-keyed serving is
exactly why a TRAINER-side mesh resize needs no service-plane change — the
surviving trainer rebuilds a fresh client at the cursor blob's position
(data/iterator_state.py restore_from_blob) and ownership of the dead
shards' cursors moves by routing alone, the same mechanism as the
worker-death failover above but driven from the consumer side.
"""

from __future__ import annotations

import logging
import socket
import threading
import time
import uuid
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from distributed_vgg_f_tpu import telemetry
from distributed_vgg_f_tpu.data.ingest_service import (
    ServiceProtocolError, ingest_label, recv_message, send_message,
    shard_owner)
from distributed_vgg_f_tpu.resilience.errors import DataStallError

log = logging.getLogger(__name__)

#: hello fields that identify THE STREAM; a mismatch between what the
#: trainer expects and what a worker serves would silently train on wrong
#: data, so the handshake fails loudly instead.
_IDENTITY_FIELDS = ("batch", "image_size", "seed", "shard_index",
                    "num_shards")


class _WorkerLink:
    """One worker endpoint: a small pool of persistent sockets (default 2)
    so one request's payload TRANSFER overlaps the worker's decode of the
    next cursor — without the second connection, the worker sits idle for
    the full transfer time of every batch (measured ~35% of the service
    budget at batch 64 on loopback). Plus liveness + receipt state."""

    def __init__(self, endpoint: str, index: int, *,
                 connect_timeout_s: float, request_timeout_s: float,
                 max_conns: int = 2):
        host, sep, port = endpoint.rpartition(":")
        if not sep or not port.isdigit():
            raise ValueError(
                f"data.service.workers entry {endpoint!r} is not host:port")
        self.endpoint = endpoint
        self.index = int(index)
        self._addr = (host, int(port))
        self._connect_timeout = float(connect_timeout_s)
        self._request_timeout = float(request_timeout_s)
        self._cv = threading.Condition()
        self._free: list = []
        self._created = 0
        self._max_conns = max(1, int(max_conns))
        self.alive = True
        self.hello: Dict = {}
        self.batches = 0
        self.decode_errors = 0

    def _connect(self) -> socket.socket:
        sock = socket.create_connection(self._addr,
                                        timeout=self._connect_timeout)
        sock.settimeout(self._request_timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return sock

    def _acquire(self) -> socket.socket:
        with self._cv:
            while True:
                if not self.alive:
                    raise OSError(f"worker {self.endpoint} is dead")
                if self._free:
                    return self._free.pop()
                if self._created < self._max_conns:
                    self._created += 1
                    break
                self._cv.wait(0.1)
        try:
            return self._connect()
        except OSError:
            with self._cv:
                self._created -= 1
                self._cv.notify()
            self.mark_dead()
            raise

    def _release(self, sock: socket.socket, broken: bool) -> None:
        with self._cv:
            if broken or not self.alive:
                self._created -= 1
                try:
                    sock.close()
                except OSError:
                    pass
            else:
                self._free.append(sock)
            self._cv.notify()

    def request(self, header: Dict):
        """(header, arrays) for one request/response pair; any transport
        or protocol error marks the link dead and re-raises. The request
        timeout is a WHOLE-message deadline (recv_message re-arms the
        remaining budget before every recv), so a trickling worker is
        treated as dead, not kept alive one byte per timeout window."""
        sock = self._acquire()
        deadline = time.monotonic() + self._request_timeout
        try:
            send_message(sock, header)
            resp, arrays = recv_message(sock, deadline)
        except (OSError, ServiceProtocolError):
            self._release(sock, broken=True)
            self.mark_dead()
            raise
        self._release(sock, broken=False)
        if not resp.get("ok", False):
            raise ServiceProtocolError(
                f"worker {self.endpoint} refused {header.get('op')!r}: "
                f"{resp.get('error')}")
        return resp, arrays

    def mark_dead(self) -> None:
        with self._cv:
            self.alive = False
            free, self._free = list(self._free), []
            self._cv.notify_all()
        for sock in free:
            try:
                sock.close()
            except OSError:
                pass

    def close(self) -> None:
        self.mark_dead()


class ServiceIngestClient:
    """Iterator of process-local host batches fetched from the decode-
    worker fleet. See the module docstring for the routing/failover
    contract; construction performs the hello handshake against every
    reachable worker and validates stream identity (`expect`)."""

    supports_state = True

    def __init__(self, endpoints: Sequence[str], *, seed: int,
                 batches_per_epoch: int, fetch_ahead: int = 0,
                 local_factory: Optional[Callable[[], object]] = None,
                 connect_timeout_s: float = 5.0,
                 request_timeout_s: float = 60.0,
                 expect: Optional[Dict] = None):
        if not endpoints:
            raise ValueError(
                "data.service.enabled=true needs at least one worker "
                "endpoint in data.service.workers (host:port,host:port,...)")
        self._seed = int(seed)
        self._batches_per_epoch = max(1, int(batches_per_epoch))
        self._links = [
            _WorkerLink(e, i, connect_timeout_s=connect_timeout_s,
                        request_timeout_s=request_timeout_s)
            for i, e in enumerate(endpoints)]
        # auto depth = 3 per worker: 2 keep the worker's decode + transfer
        # overlapped (the link's connection pool), the 3rd absorbs
        # delivery-order head-of-line jitter — measured the knee of the
        # N=4 scaling curve on the r15 receipt box
        self._fetch_ahead = int(fetch_ahead) if fetch_ahead \
            else max(2, 3 * len(self._links))
        self._local_factory = local_factory
        self._local_it = None
        self._local_pos = 0
        self._local_buffer: Dict[int, Dict] = {}
        self._local_lock = threading.Lock()
        self._state_lock = threading.Lock()
        self._pending: Dict[int, object] = {}
        self._next_deliver = 0
        self._started = False
        self._closed = False
        import concurrent.futures
        # 2 fetchers per worker: one can be mid-transfer while the other's
        # request keeps the worker's decode pool busy (the link's
        # connection pool is sized to match)
        self._executor = concurrent.futures.ThreadPoolExecutor(
            max_workers=max(2, 2 * len(self._links)),
            thread_name_prefix="svc-fetch")
        reg = telemetry.get_registry()
        for name in ("ingest_service/client_batches",
                     "ingest_service/client_bytes",
                     "ingest_service/client_wait_ns",
                     "ingest_service/failovers",
                     "ingest_service/local_fallbacks"):
            reg.counter(name)
        reg.set_gauge("ingest_service/workers", len(self._links))
        reg.set_gauge("ingest_service/workers_live", len(self._links))
        # Bind the method objects ONCE (before the handshake, whose failure
        # path runs close()) — `self.describe` creates a fresh object per
        # access, so identity-based deregistration would never match
        # otherwise.
        self._describe_ref = self.describe
        self._chaos_kill_ref = self.kill_one_worker_for_chaos
        self._handshake(expect or {})
        # live observability: /ingestz serves this client's state; chaos:
        # the worker@N fault injector kills a live worker through us
        from distributed_vgg_f_tpu.telemetry import exporter as _exporter
        _exporter.set_ingest_source(self._describe_ref)
        from distributed_vgg_f_tpu.resilience import faults as _faults
        _faults.set_worker_kill_hook(self._chaos_kill_ref)

    # ----------------------------------------------------------- handshake
    def _handshake(self, expect: Dict) -> None:
        errors = []
        for link in self._links:
            try:
                resp, _ = link.request({"op": "hello"})
            except (OSError, ServiceProtocolError) as e:
                log.warning("ingest service: worker %s unreachable at "
                            "startup (%s) — will serve from survivors",
                            link.endpoint, e)
                continue
            link.hello = resp
            for field in _IDENTITY_FIELDS:
                if field in expect and field in resp \
                        and resp[field] != expect[field]:
                    errors.append(
                        f"{link.endpoint}: {field}={resp[field]!r} but the "
                        f"trainer expects {expect[field]!r}")
        if errors:
            self.close()
            raise ValueError(
                "ingest service stream-identity mismatch — the worker "
                "fleet is serving a different stream than this trainer "
                "was configured for: " + "; ".join(errors))
        live = [l for l in self._links if l.alive]
        telemetry.set_gauge("ingest_service/workers_live", len(live))
        if not live and self._local_factory is None:
            self.close()
            raise ConnectionError(
                "no ingest-service worker reachable and no local fallback "
                "configured (data.service.fallback_local=false)")

    # ------------------------------------------------------------- routing
    def _live_links(self) -> List[_WorkerLink]:
        return [l for l in self._links if l.alive]

    def _candidates(self, cursor: int) -> List[_WorkerLink]:
        """Owner first, then the surviving workers in deterministic
        rotation — every client replica reassigns a dead worker's cursors
        the same way."""
        owner = shard_owner(cursor, len(self._links), self._seed,
                            self._batches_per_epoch)
        ordered = [self._links[(owner + k) % len(self._links)]
                   for k in range(len(self._links))]
        return [l for l in ordered if l.alive]

    def _fetch(self, cursor: int) -> Dict[str, np.ndarray]:
        first = True
        while True:
            with self._state_lock:
                if self._closed:
                    # a straggler future running past close() must not
                    # rebuild pipelines (observed: a post-close fetch
                    # re-initializing the local fallback from scratch)
                    raise RuntimeError("ingest service client closed")
            candidates = self._candidates(cursor)
            if not candidates:
                return self._local_batch(cursor)
            link = candidates[0]
            # client-generated correlation id: rides the existing JSON
            # header (wire-tolerant — pre-r22 workers ignore it) and tags
            # the client-side span so telemetry/stitch.py can draw the
            # flow arrow from THIS fetch to the owning worker's decode
            trace_id = f"get-{uuid.uuid4().hex[:12]}"
            t0_ns = time.monotonic_ns()
            try:
                resp, arrays = link.request({"op": "get", "cursor": cursor,
                                             "trace_id": trace_id})
            except (OSError, ServiceProtocolError) as e:
                with self._state_lock:
                    if self._closed:
                        # shutdown race, not a worker death: close() pulled
                        # the sockets out from under an in-flight request
                        raise RuntimeError(
                            "ingest service client closed") from None
                # a REFUSED request (ok:false — the worker is up but its
                # produce() is failing) must also kill the link: retrying
                # the owner forever would spin instead of reaching the
                # survivors / local fallback ("never hang" contract)
                link.mark_dead()
                telemetry.inc("ingest_service/failovers")
                telemetry.set_gauge("ingest_service/workers_live",
                                    len(self._live_links()))
                log.warning(
                    "ingest service: worker %s failed serving cursor %d "
                    "(%s) — reassigning its shard to the %d surviving "
                    "worker(s)", link.endpoint, cursor, e,
                    len(self._live_links()))
                first = False
                continue
            if "image" not in arrays or "label" not in arrays:
                # an ok:true reply without the batch blobs is a worker bug
                # — same treatment (and same receipts) as a transport
                # failure: dead link, logged, failover to the survivors
                link.mark_dead()
                telemetry.inc("ingest_service/failovers")
                telemetry.set_gauge("ingest_service/workers_live",
                                    len(self._live_links()))
                log.warning(
                    "ingest service: worker %s replied without batch "
                    "arrays for cursor %d — reassigning its shard to the "
                    "%d surviving worker(s)", link.endpoint, cursor,
                    len(self._live_links()))
                first = False
                continue
            telemetry.record(
                "service_get", "infeed_source", t0_ns,
                time.monotonic_ns() - t0_ns,
                {"trace_id": trace_id, "flow": "out", "cursor": cursor})
            link.batches += 1
            link.decode_errors = int(resp.get("decode_errors", 0))
            nbytes = sum(int(a.nbytes) for a in arrays.values())
            reg = telemetry.get_registry()
            reg.inc("ingest_service/client_batches")
            reg.inc("ingest_service/client_bytes", nbytes)
            if not first:
                reg.inc("ingest_service/reassigned_batches")
            return arrays

    # ------------------------------------------------------ local fallback
    def _local_batch(self, cursor: int) -> Dict[str, np.ndarray]:
        """Every worker is gone. Degrade to the ordinary local pipeline at
        the exact stream position (or raise the typed stall when the run
        has no fallback) — never hang, never skip a batch."""
        if self._local_factory is None:
            from distributed_vgg_f_tpu.telemetry import flight
            telemetry.inc("resilience/data_stall_errors")
            flight.note_crash(
                "data_stall",
                f"ingest service: all {len(self._links)} decode workers "
                f"dead at cursor {cursor}, no local fallback")
            raise DataStallError(
                f"ingest service: all {len(self._links)} decode workers "
                f"are dead (cursor {cursor}) and "
                f"data.service.fallback_local is off — restart the worker "
                f"fleet or re-run with local ingest")
        with self._local_lock:
            if self._local_it is None:
                telemetry.inc("ingest_service/local_fallbacks")
                with self._state_lock:
                    start = self._next_deliver
                log.warning(
                    "ingest service: all %d decode workers dead — falling "
                    "back to LOCAL ingest from cursor %d (the r15 "
                    "single-host path; throughput drops to one host's "
                    "decode rate)", len(self._links), start)
                it = iter(self._local_factory())
                pos = 0
                if start and getattr(it, "supports_state", False) \
                        and it.restore_state(start):
                    pos = start
                while pos < start:  # replay fallback (synthetic et al.)
                    next(it)
                    pos += 1
                self._local_it, self._local_pos = it, pos
            if cursor in self._local_buffer:
                return self._local_buffer.pop(cursor)
            while self._local_pos <= cursor:
                batch = {k: np.array(v, copy=True)
                         for k, v in next(self._local_it).items()}
                self._local_buffer[self._local_pos] = batch
                self._local_pos += 1
            return self._local_buffer.pop(cursor)

    # ------------------------------------------------------------ iterator
    def __iter__(self) -> "ServiceIngestClient":
        return self

    def _schedule_through(self, last: int) -> None:
        with self._state_lock:
            if self._closed:
                return
            for c in range(self._next_deliver, last + 1):
                if c not in self._pending:
                    self._pending[c] = self._executor.submit(self._fetch, c)

    def __next__(self) -> Dict[str, np.ndarray]:
        with self._state_lock:
            if self._closed:
                raise StopIteration
            cursor = self._next_deliver
        self._started = True
        self._schedule_through(cursor + self._fetch_ahead - 1)
        with self._state_lock:
            fut = self._pending.pop(cursor)
        t0 = time.monotonic_ns()
        try:
            batch = fut.result()
        finally:
            telemetry.inc("ingest_service/client_wait_ns",
                          time.monotonic_ns() - t0)
        with self._state_lock:
            self._next_deliver = cursor + 1
        if self._local_it is not None:
            # prune a fallback-buffered copy of a cursor that was ALSO
            # served by a worker (the future raced the fleet's death) —
            # without this, up to fetch_ahead ~10 MB batches stay
            # referenced until close()
            with self._local_lock:
                self._local_buffer.pop(cursor, None)
        return batch

    # ----------------------------------------------------------- contracts
    def restore_state(self, step: int) -> bool:
        """O(1) position-exact seek — the stream is keyed by cursor, so
        resuming IS setting the cursor (only before the first draw, the
        same contract as the native iterator). Cursor semantics are the
        shared next-item-to-emit contract (data/iterator_state.epoch_of):
        `step` is the batch the trainer will consume NEXT, so the epoch
        the routing split re-draws at is `epoch_of(step, N)` — pinned to
        agree with the blob restore in tests/test_iterator_state.py."""
        if self._started:
            return False
        with self._state_lock:
            self._next_deliver = int(step)
        return True

    def restore_state_blob(self, blob) -> bool:
        """`restore_state(step)` generalized to the r18 checkpoint blob
        (data/iterator_state.py capture_state shape): ONE validation
        implementation — delegates to `restore_from_blob` (schema +
        version gate + stream identity against what this client
        handshook with the worker fleet), then seeks the cursor. False
        on any mismatch — the caller falls back to replay, never a
        wrong-position seek."""
        from distributed_vgg_f_tpu.data.iterator_state import (
            restore_from_blob)
        if not isinstance(blob, dict) \
                or not isinstance(blob.get("cursor"), int):
            return False
        return restore_from_blob(
            self, blob, step=blob["cursor"],
            expect={"seed": self._seed,
                    "batches_per_epoch": self._batches_per_epoch}) \
            is not None

    def decode_errors(self) -> int:
        total = sum(l.decode_errors for l in self._links)
        it = self._local_it
        fn = getattr(it, "decode_errors", None)
        return total + (int(fn()) if callable(fn) else 0)

    def kill_one_worker_for_chaos(self) -> Optional[str]:
        """The `worker@N` fault injector's hook (resilience/faults.py):
        ask one worker to shut down through the production op — a real
        mid-epoch worker death, not a simulation. The link is deliberately
        NOT pre-marked dead: the client must DISCOVER the death on its
        next request and fail over through the production path, which is
        what the chaos suite is testing. Returns the killed endpoint (or
        None when no worker is alive to kill)."""
        for link in self._live_links():
            try:
                link.request({"op": "shutdown"})
            except (OSError, ServiceProtocolError):
                continue  # already dead (request() marked it); next one
            return link.endpoint
        return None

    def describe(self) -> Dict:
        """The /ingestz payload (telemetry/exporter.py) and the bench
        receipt: fleet topology, liveness, per-worker serve counts."""
        with self._state_lock:
            next_deliver = self._next_deliver
            in_flight = len(self._pending)
        return {
            "enabled": True,
            "label": ingest_label(len(self._links)),
            "workers": [{
                "endpoint": l.endpoint, "index": l.index, "alive": l.alive,
                "batches": l.batches, "decode_errors": l.decode_errors,
                "hello": {k: v for k, v in l.hello.items()
                          if k != "arrays" and k != "ok"},
            } for l in self._links],
            "workers_live": len(self._live_links()),
            "next_cursor": next_deliver,
            "in_flight": in_flight,
            "fetch_ahead": self._fetch_ahead,
            "batches_per_epoch": self._batches_per_epoch,
            "local_fallback_active": self._local_it is not None,
        }

    def close(self) -> None:
        with self._state_lock:
            if self._closed:
                return
            self._closed = True
            pending, self._pending = dict(self._pending), {}
        for fut in pending.values():
            fut.cancel()
        self._executor.shutdown(wait=False)
        for link in self._links:
            link.close()
        it, self._local_it = self._local_it, None
        close = getattr(it, "close", None)
        if callable(close):
            close()
        from distributed_vgg_f_tpu.resilience import faults as _faults
        _faults.clear_worker_kill_hook(self._chaos_kill_ref)
        from distributed_vgg_f_tpu.telemetry import exporter as _exporter
        _exporter.clear_ingest_source(self._describe_ref)

    def __del__(self):  # pragma: no cover — best-effort cleanup
        try:
            self.close()
        except Exception:
            pass


def build_service_client(data_cfg, local_batch: int, *, seed: int = 0,
                         num_shards: int = 1, shard_index: int = 0,
                         num_classes: Optional[int] = None,
                         state_dir: str = "",
                         snapshot_every: int = 0) -> ServiceIngestClient:
    """`build_dataset`'s service branch: the client for this host's worker
    fleet, with the ordinary local pipeline as the all-workers-dead
    fallback (service disabled in the fallback config so the factory can
    never recurse into another client)."""
    import dataclasses
    svc = data_cfg.service
    local_factory = None
    if svc.fallback_local:
        off = dataclasses.replace(
            data_cfg, service=dataclasses.replace(svc, enabled=False))
        from distributed_vgg_f_tpu.data import build_dataset

        def local_factory():
            return build_dataset(off, "train", seed=seed,
                                 num_shards=num_shards,
                                 shard_index=shard_index,
                                 state_dir=state_dir,
                                 snapshot_every=snapshot_every,
                                 num_classes=num_classes)
    steps_per_epoch = max(
        1, data_cfg.num_train_examples // data_cfg.global_batch_size)
    return ServiceIngestClient(
        tuple(svc.workers), seed=seed, batches_per_epoch=steps_per_epoch,
        fetch_ahead=svc.fetch_ahead, local_factory=local_factory,
        connect_timeout_s=svc.connect_timeout_s,
        request_timeout_s=svc.request_timeout_s,
        expect={"batch": local_batch, "image_size": data_cfg.image_size,
                "seed": int(seed), "shard_index": int(shard_index),
                "num_shards": int(num_shards)})
