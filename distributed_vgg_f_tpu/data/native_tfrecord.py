"""ctypes bindings for the native TFRecord indexer (native/tfrecord_index.cc).

The standard ImageNet distribution is TFRecord shards of tf.train.Example
protos. The indexer walks each shard ONCE (framing + minimal protobuf wire
parse, fseek-skipping the JPEG payload bytes — ~tens of bytes of IO per
record) and emits the absolute byte range of every encoded JPEG plus its
integer label. Those ranges feed jpeg_loader.cc's ranged decoder, so TFRecord
training runs with no TensorFlow, no proto library, and no per-step parsing.

Index results are cached as an .npz keyed by (path, size, mtime) — re-runs
and restarts skip the scan entirely. The cache lives in `cache_dir` (not next
to the data, which is commonly read-only).
"""

from __future__ import annotations

import ctypes
import hashlib
import logging
import os
import threading
from typing import Optional, Sequence

import numpy as np


log = logging.getLogger(__name__)

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False

_I64P = ctypes.POINTER(ctypes.c_int64)

#: Must match dvgg_tfrecord_index_abi_version() in native/tfrecord_index.cc
#: — single source for the load gate and the ABI contract checker
#: (tools/abi_check.py).
TFRECORD_ABI_VERSION = 1


def load_native_tfrecord() -> Optional[ctypes.CDLL]:
    global _lib, _build_failed
    with _lock:
        if _lib is not None or _build_failed:
            return _lib
        from distributed_vgg_f_tpu.data.native_build import load_abi_checked
        lib = load_abi_checked("tfrecord_index.cc", "libdvgg_tfrecord.so",
                               "dvgg_tfrecord_index_abi_version",
                               TFRECORD_ABI_VERSION)
        if lib is None:
            _build_failed = True
            return None
        lib.dvgg_tfrecord_index_create.restype = ctypes.c_void_p
        lib.dvgg_tfrecord_index_create.argtypes = [ctypes.c_char_p,
                                                   ctypes.c_int]
        lib.dvgg_tfrecord_index_size.restype = ctypes.c_int64
        lib.dvgg_tfrecord_index_size.argtypes = [ctypes.c_void_p]
        lib.dvgg_tfrecord_index_error.restype = ctypes.c_char_p
        lib.dvgg_tfrecord_index_error.argtypes = [ctypes.c_void_p]
        lib.dvgg_tfrecord_index_skipped.restype = ctypes.c_int64
        lib.dvgg_tfrecord_index_skipped.argtypes = [ctypes.c_void_p]
        lib.dvgg_tfrecord_index_fill.restype = None
        lib.dvgg_tfrecord_index_fill.argtypes = [ctypes.c_void_p, _I64P,
                                                 _I64P, _I64P]
        lib.dvgg_tfrecord_index_destroy.restype = None
        lib.dvgg_tfrecord_index_destroy.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def index_tfrecord(path: str, *, verify_payload_crc: bool = False):
    """(offsets, lengths, labels) int64 arrays for one TFRecord shard.
    Raises ValueError on malformed/corrupt framing (the 12-byte length CRC is
    always verified; payload CRC only when asked — it forfeits the seek-skip).
    """
    lib = load_native_tfrecord()
    if lib is None:
        raise RuntimeError("native tfrecord indexer unavailable")
    handle = lib.dvgg_tfrecord_index_create(
        path.encode(), int(verify_payload_crc))
    try:
        n = lib.dvgg_tfrecord_index_size(handle)
        if n < 0:
            err = lib.dvgg_tfrecord_index_error(handle).decode()
            raise ValueError(f"indexing {path!r} failed: {err}")
        skipped = lib.dvgg_tfrecord_index_skipped(handle)
        if skipped:
            log.warning("%s: %d records without an image/encoded value "
                        "skipped", path, skipped)
        offsets = np.empty(n, np.int64)
        lengths = np.empty(n, np.int64)
        labels = np.empty(n, np.int64)
        if n:
            lib.dvgg_tfrecord_index_fill(
                handle, offsets.ctypes.data_as(_I64P),
                lengths.ctypes.data_as(_I64P),
                labels.ctypes.data_as(_I64P))
        return offsets, lengths, labels
    finally:
        lib.dvgg_tfrecord_index_destroy(handle)


def _cache_path(cache_dir: str, files: Sequence[str],
                verify_payload_crc: bool) -> str:
    h = hashlib.sha256()
    # the verification level is part of the key: a cached non-verified index
    # must not satisfy a verify_payload_crc=True request
    h.update(f"crc={int(verify_payload_crc)}|".encode())
    for f in files:
        st = os.stat(f)
        h.update(f.encode())
        h.update(f"|{st.st_size}|{int(st.st_mtime)}|".encode())
    return os.path.join(cache_dir, f"tfrecord_index_{h.hexdigest()[:16]}.npz")


def index_tfrecords(files: Sequence[str], *, cache_dir: str = "",
                    verify_payload_crc: bool = False):
    """Concatenated (path_idx, offsets, lengths, labels) over `files`.

    `path_idx[i]` indexes into `files`; together with offsets/lengths these
    are exactly the ranged items NativeJpegTrainIterator/EvalIterator take.
    With `cache_dir`, the result is cached keyed on every file's
    (path, size, mtime) — any change re-indexes.
    """
    files = list(files)
    if not files:
        return (np.zeros(0, np.int32), np.zeros(0, np.int64),
                np.zeros(0, np.int64), np.zeros(0, np.int64))
    cache = _cache_path(cache_dir, files, verify_payload_crc) \
        if cache_dir else None
    if cache and os.path.exists(cache):
        try:
            z = np.load(cache)
            return (z["path_idx"], z["offsets"], z["lengths"], z["labels"])
        except Exception:
            pass  # unreadable cache — rebuild
    parts = [index_tfrecord(f, verify_payload_crc=verify_payload_crc)
             for f in files]
    path_idx = np.concatenate([
        np.full(len(off), i, np.int32) for i, (off, _, _) in enumerate(parts)])
    offsets = np.concatenate([p[0] for p in parts])
    lengths = np.concatenate([p[1] for p in parts])
    labels = np.concatenate([p[2] for p in parts])
    if cache:
        os.makedirs(cache_dir, exist_ok=True)
        # np.savez appends ".npz" unless the name already ends with it
        tmp = f"{cache}.{os.getpid()}.tmp.npz"
        try:
            np.savez(tmp, path_idx=path_idx, offsets=offsets,
                     lengths=lengths, labels=labels)
            os.replace(tmp, cache)
            _prune_cache(cache_dir)
        except OSError:
            pass
    return path_idx, offsets, lengths, labels


def _prune_cache(cache_dir: str, keep: int = 16) -> None:
    """Drop all but the newest `keep` index files — superseded entries (moved
    or re-sharded datasets, test runs) must not accumulate forever. The exact
    final-name pattern only: another process's in-flight
    `<cache>.<pid>.tmp.npz` must never be pruned out from under its
    os.replace."""
    import re
    pat = re.compile(r"^tfrecord_index_[0-9a-f]{16}\.npz$")
    try:
        entries = [os.path.join(cache_dir, f) for f in os.listdir(cache_dir)
                   if pat.match(f)]
        entries.sort(key=os.path.getmtime, reverse=True)
        for path in entries[keep:]:
            os.remove(path)
    except OSError:
        pass
