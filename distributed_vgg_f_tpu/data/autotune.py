"""Closed-loop ingest autotuner — verdict-driven online tuning of the live
host-pipeline knobs (ROADMAP item 2: tf.data's AUTOTUNE, arXiv 2101.12127,
but with a receipt trail).

The hand-derived provisioning constants (`HOST_DECODE_RATE_R*`) pin how many
host cores ONE measured box needs; they go stale the moment the box, dataset,
or host class changes, and a heterogeneous fleet (different host classes
feeding one mesh — the TF-system deployment shape, arXiv 1605.08695) can't
inherit one box's bench pins at all. The PR 4 stall attributor already names
every log window (`infeed_bound` / `compute_bound` / ...) and the PR 7
observability plane serves those verdicts live; this module CLOSES the loop:
a per-process feedback controller consumes the per-window verdicts and
actuates the knobs the pipeline actually exposes —

- **native decode workers** (`data.native_threads`): runtime pool
  grow/shrink on the live loader (native ABI v8,
  `NativeJpegTrainIterator.set_num_threads`);
- **host prefetch depth** (`data.prefetch`): the resizable read-ahead stage
  (`data/prefetch.py HostPrefetchIterator.set_depth`);
- **device ring depth** (`train.prefetch_to_device`):
  `DevicePrefetchIterator.set_buffer_size`;
- **restart fan-out** (`native_jpeg.set_restart_fanout`) when the entropy
  path is engaged and config rails allow it;
- **wire downgrade/upgrade** (`data.wire` host↔u8) where the parity
  contract allows: the u8 wire is pixel-parity with the host wires for
  TRAIN streams (the r8 gates), and switching requires rebuilding the
  loader at an exact stream position. The bench harness always supplied
  that hook; since r18 the TRAINER does too — `data/iterator_state.py
  ResumableIngest.rebuild_live` reconstructs the live source at the
  captured cursor (read-ahead batches keep their old wire; the device
  finish dispatches per batch on dtype), so the trainer binds the knob
  whenever a position-exact rebuild is available (native imagenet, local
  ingest) and a live run escalates host_f32→u8 mid-epoch. The r11
  "trainer deliberately leaves it unbound" receipt is retired.

Control discipline — every actuation passes hysteresis before it happens
and leaves three receipts after:

- **hysteresis**: K consecutive same-direction verdicts (`k_windows`)
  before any move; an actuation resets the streak.
- **cooldown**: `cooldown_windows` quiet windows after a move, so the
  verdict stream re-equilibrates before the next one.
- **bounded steps + hard rails**: one knob, one bounded step per window
  (geometric for the thread pool, +1 for depths), clamped to config
  min/max; at the rails the controller reports `blocked: rail` instead of
  pushing.
- **oscillation guard**: a knob whose actuation direction flips
  `freeze_after_flips` times is frozen for the run (receipted); alternating
  verdicts therefore converge to no-op — the hysteresis streak additionally
  never reaches K under alternation.

Receipt trail (the difference from tf.data's silent AUTOTUNE): every
decision lands in (1) `autotune/*` registry counters + per-knob gauges,
(2) the trainer's per-window JSONL `autotune` block (schema-validated,
telemetry/schema.py), and (3) the live `/autotunez` exporter endpoint —
and the flight recorder retains the last N actuations so a post-crash
triage can see whether the controller moved before the abort.

Verdict→action matrix (README "Ingest autotuning"):

    infeed_bound      → step the first un-railed knob UP (escalation order
                        = the knob list order: threads, host prefetch,
                        device ring, fan-out, wire)
    compute_bound     → no actuation (the GOOD verdict); with
                        `relax_after_windows` > 0, knobs the controller
                        itself raised step back DOWN after a sustained
                        compute-bound streak (off by default)
    checkpoint_bound  → no actuation (not the ingest's problem)
    guard_stalled     → no actuation (a run skipping updates needs a human)

Kill-switch discipline (same as r6–r10): `data.autotune.enabled` is off by
default (the flagship preset turns it on), and `DVGGF_AUTOTUNE=0` kills the
controller regardless of config — behavior is then byte-identical to
controller-absent (no wrapper stages, no observe calls, no counters).

Stdlib-only at import (telemetry aside): the native decoder is only touched
through hooks the caller binds, so importing this module never triggers a
g++ build.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence

from distributed_vgg_f_tpu import telemetry

#: Environment kill-switch (checked at controller-creation sites, the same
#: discipline as DVGGF_DECODE_SIMD / DVGGF_WIRE_U8 / DVGGF_DECODE_RESTART):
#: "0" disables autotuning regardless of config, byte-identical to
#: controller-absent.
ENV_KILL = "DVGGF_AUTOTUNE"

#: Verdicts that drive an UP escalation vs the one that may relax.
_UP_VERDICT = "infeed_bound"
_RELAX_VERDICT = "compute_bound"


def autotune_killed() -> bool:
    return os.environ.get(ENV_KILL, "").strip() == "0"


def autotune_active(cfg) -> bool:
    """The single activation predicate: config-enabled AND not env-killed.
    Call sites (trainer, bench) must gate EVERYTHING — wrapper stages
    included — on this, so the kill-switch path is byte-identical to
    controller-absent."""
    return bool(getattr(cfg, "enabled", False)) and not autotune_killed()


@dataclass
class Knob:
    """One actuatable pipeline parameter. `apply(target)` returns the
    now-active value (possibly clamped by the subsystem) or None when the
    subsystem refuses — the controller then marks the knob unavailable
    instead of believing an actuation that never happened."""
    name: str
    get: Callable[[], Optional[int]]
    apply: Callable[[int], Optional[int]]
    min_value: int
    max_value: int
    step: int = 1
    geometric: bool = False       # double/halve instead of +/- step
    # -- controller-owned state --------------------------------------------
    value: Optional[int] = None
    baseline: Optional[int] = None
    available: bool = True
    frozen: bool = False
    last_direction: int = 0
    flips: int = 0
    unavailable_reason: str = ""

    def target(self, direction: int) -> int:
        v = int(self.value)
        if self.geometric:
            t = v * 2 if direction > 0 else v // 2
        else:
            t = v + direction * self.step
        if direction < 0 and self.baseline is not None:
            # relax steps back down TOWARD the baseline, never past it — a
            # geometric halving from a railed value would otherwise
            # overshoot below the user-configured starting point
            t = max(t, self.baseline)
        return max(self.min_value, min(self.max_value, t))


def thread_knob(loader, *, min_value: int = 1,
                max_value: int = 8) -> Optional[Knob]:
    """Decode-worker knob over a live native loader (or the snapshot-cache
    wrapper forwarding to one). None when the loader exposes no resize
    surface or the native resize dispatch refuses
    (-DDVGGF_NO_RESIZE / DVGGF_THREAD_RESIZE=0)."""
    get = getattr(loader, "num_threads", None)
    setter = getattr(loader, "set_num_threads", None)
    if not (callable(get) and callable(setter)):
        return None
    if get() is None:
        return None
    # probe: a set to the current value must round-trip, else the native
    # dispatch is refusing (kill-switch/compile-out) and the knob is absent
    if setter(get()) is None:
        return None
    return Knob("native_threads", get, setter, min_value, max_value,
                geometric=True)


def host_prefetch_knob(hp, *, min_value: int = 1,
                       max_value: int = 8) -> Optional[Knob]:
    if not hasattr(hp, "set_depth"):
        return None
    return Knob("host_prefetch", lambda: hp.depth, hp.set_depth,
                min_value, max_value)


def device_ring_knob(dp, *, min_value: int = 1,
                     max_value: int = 4) -> Optional[Knob]:
    if not hasattr(dp, "set_buffer_size"):
        return None
    return Knob("prefetch_to_device", lambda: dp.buffer_size,
                dp.set_buffer_size, min_value, max_value)


def fanout_knob(*, max_value: int = 1) -> Optional[Knob]:
    """Restart fan-out knob — only bound when config rails allow fan-out
    (max > 1: it trades cores for latency, so the throughput-provisioned
    default keeps it off) AND the restart entropy path is actually
    dispatching (a fan-out move on a sequential path actuates nothing)."""
    if max_value <= 1:
        return None
    from distributed_vgg_f_tpu.data import native_jpeg
    if native_jpeg.restart_kind() != "restart":
        return None
    return Knob("restart_fanout", native_jpeg.restart_fanout,
                native_jpeg.set_restart_fanout, 1, max_value)


def wire_knob(get: Callable[[], Optional[int]],
              apply: Callable[[int], Optional[int]]) -> Knob:
    """Wire downgrade/upgrade knob (0 = host wire, 1 = u8). The caller
    owns the rebuild hook and with it the parity/position contract: the
    bench rebuilds per window, and the trainer (r18) binds it through
    `data/iterator_state.ResumableIngest.wire_knob()` — a position-exact
    live rebuild at the captured cursor."""
    return Knob("wire_u8", get, apply, 0, 1)


class IngestAutotuner:
    """The per-process feedback controller. `observe(stall_record)` once
    per log window; everything else is receipts."""

    def __init__(self, cfg, knobs: Sequence[Optional[Knob]], *,
                 registry=None, flight=None,
                 clock: Callable[[], float] = time.time):
        self.cfg = cfg
        self._reg = registry if registry is not None \
            else telemetry.get_registry()
        if flight is None:
            from distributed_vgg_f_tpu.telemetry.flight import get_flight
            flight = get_flight()
        self._flight = flight
        self._clock = clock
        self._lock = threading.Lock()
        self._windows = 0
        self._streak_verdict: Optional[str] = None
        self._streak = 0
        self._last_actuation_window: Optional[int] = None
        self._actuations_total = 0
        self._history: deque = deque(maxlen=int(cfg.history))
        self.knobs: List[Knob] = [k for k in knobs if k is not None]
        for k in self.knobs:
            v = k.get()
            if v is None:
                k.available = False
                k.unavailable_reason = "get() returned None at bind"
            else:
                k.value = int(v)
                k.baseline = int(v)
        # Pre-created counters/gauges with LITERAL names: the README
        # counter-namespace drift guard (tests/test_telemetry.py) scans
        # registration-site literals, and a zero that is visible reads as
        # "instrumented, nothing happened".
        reg = self._reg
        reg.counter("autotune/windows")
        reg.counter("autotune/actuations")
        reg.counter("autotune/blocked_hysteresis")
        reg.counter("autotune/blocked_cooldown")
        reg.counter("autotune/blocked_rail")
        reg.counter("autotune/oscillation_freezes")
        # -1 = knob not bound in this process (vs a real value once bound)
        reg.set_gauge("autotune/native_threads", -1)
        reg.set_gauge("autotune/host_prefetch", -1)
        reg.set_gauge("autotune/prefetch_to_device", -1)
        reg.set_gauge("autotune/restart_fanout", -1)
        reg.set_gauge("autotune/wire_u8", -1)
        reg.set_gauge("autotune/settled", 0)
        for k in self.knobs:
            if k.available:
                reg.set_gauge(f"autotune/{k.name}", k.value)

    # ------------------------------------------------------------ properties
    @property
    def settled(self) -> bool:
        with self._lock:
            return self._settled_locked()

    def _settled_locked(self) -> bool:
        since = self._windows - (self._last_actuation_window or 0)
        return since >= int(self.cfg.settled_after_windows)

    @property
    def actuations_total(self) -> int:
        with self._lock:
            return self._actuations_total

    def history(self) -> List[dict]:
        with self._lock:
            return [dict(a) for a in self._history]

    # -------------------------------------------------------------- control
    def observe(self, stall: Optional[Dict] = None) -> Dict[str, object]:
        """One log window: fold the stall verdict into the hysteresis
        state, maybe actuate ONE bounded step, and return the window's
        `autotune` record (the trainer attaches it to the JSONL train
        entry). Thread-safe against concurrent `describe()` probes."""
        with self._lock:
            self._windows += 1
            self._reg.inc("autotune/windows")
            verdict = (stall or {}).get("verdict")
            if verdict == self._streak_verdict:
                self._streak += 1
            else:
                self._streak_verdict, self._streak = verdict, 1
            direction, needed = 0, 0
            if verdict == _UP_VERDICT:
                direction, needed = 1, int(self.cfg.k_windows)
            elif verdict == _RELAX_VERDICT \
                    and int(self.cfg.relax_after_windows) > 0 \
                    and any(k.available and not k.frozen
                            and k.value > k.baseline for k in self.knobs):
                direction, needed = -1, int(self.cfg.relax_after_windows)
            blocked = None
            actuations: List[dict] = []
            if direction != 0:
                if self._streak < needed:
                    blocked = "hysteresis"
                    self._reg.inc("autotune/blocked_hysteresis")
                elif self._in_cooldown():
                    blocked = "cooldown"
                    self._reg.inc("autotune/blocked_cooldown")
                else:
                    act = self._actuate(direction, verdict)
                    if act is not None:
                        actuations.append(act)
                    else:
                        blocked = "rail"
                        self._reg.inc("autotune/blocked_rail")
            settled = self._settled_locked()
            self._reg.set_gauge("autotune/settled", int(settled))
            record: Dict[str, object] = {
                "window": self._windows,
                "verdict": verdict,
                "settled": settled,
                "knobs": {k.name: k.value for k in self.knobs
                          if k.available},
            }
            if actuations:
                record["actuations"] = actuations
            if blocked is not None:
                record["blocked"] = blocked
            return record

    def _in_cooldown(self) -> bool:
        if self._last_actuation_window is None:
            return False
        return (self._windows - self._last_actuation_window) \
            <= int(self.cfg.cooldown_windows)

    def _actuate(self, direction: int, verdict: str) -> Optional[dict]:
        """Step the first eligible knob in escalation order (reversed for
        relax: undo the most-escalated lever first). Returns the actuation
        record, or None when every knob is railed/frozen/unavailable."""
        order = self.knobs if direction > 0 else list(reversed(self.knobs))
        for k in order:
            if not k.available or k.frozen or k.value is None:
                continue
            if direction > 0 and k.value >= k.max_value:
                continue
            if direction < 0 and k.value <= max(k.min_value, k.baseline):
                continue
            target = k.target(direction)
            if target == k.value:
                continue
            applied = k.apply(target)
            if applied is None:
                # the subsystem refused (kill-switch flipped mid-run, warm
                # snapshot closed the decode pool, ...) — the knob is gone,
                # not actuated
                k.available = False
                k.unavailable_reason = "apply() refused at runtime"
                continue
            applied = int(applied)
            if applied == k.value:
                # clamped back by the subsystem: treat as railed here on
                continue
            if k.last_direction and direction != k.last_direction:
                k.flips += 1
                if k.flips >= int(self.cfg.freeze_after_flips):
                    k.frozen = True
                    self._reg.inc("autotune/oscillation_freezes")
            old, k.value = k.value, applied
            k.last_direction = direction
            self._last_actuation_window = self._windows
            self._streak = 0  # fresh evidence required before the next move
            self._actuations_total += 1
            self._reg.inc("autotune/actuations")
            self._reg.set_gauge(f"autotune/{k.name}", applied)
            act = {"window": self._windows, "knob": k.name,
                   "from": old, "to": applied,
                   "direction": "up" if direction > 0 else "down",
                   "verdict": verdict,
                   "ts_unix": round(float(self._clock()), 3)}
            if k.frozen:
                act["frozen"] = True
            self._history.append(act)
            try:
                self._flight.record_actuation(act)
            except Exception:  # noqa: BLE001 — receipts never kill the run
                pass
            return act
        return None

    # -------------------------------------------------------------- receipts
    def describe(self) -> dict:
        """Full controller state — the /autotunez payload and the bench
        artifact's `autotune` receipt."""
        with self._lock:
            cfg = self.cfg
            return {
                "enabled": True,
                "live": True,
                "windows": self._windows,
                "settled": self._settled_locked(),
                "actuations_total": self._actuations_total,
                "streak": {"verdict": self._streak_verdict,
                           "count": self._streak},
                "config": {
                    "k_windows": int(cfg.k_windows),
                    "cooldown_windows": int(cfg.cooldown_windows),
                    "settled_after_windows":
                        int(cfg.settled_after_windows),
                    "relax_after_windows": int(cfg.relax_after_windows),
                    "freeze_after_flips": int(cfg.freeze_after_flips),
                },
                "knobs": [{
                    "name": k.name, "value": k.value,
                    "baseline": k.baseline,
                    "min": k.min_value, "max": k.max_value,
                    "available": k.available, "frozen": k.frozen,
                    **({"unavailable_reason": k.unavailable_reason}
                       if k.unavailable_reason else {}),
                } for k in self.knobs],
                "history": [dict(a) for a in self._history],
            }
