"""ctypes bindings for the native (C++) batch assembler in native/dataloader.cc.

The library is built on demand with g++ (no pybind11 in this image — C ABI via
ctypes per the environment constraints) and cached next to the source. All
callers must tolerate `load_native() is None` and fall back to the numpy path:
the native loader is a throughput optimization, not a correctness dependency.
"""

from __future__ import annotations

import ctypes
import logging
import os
import threading
import time
from typing import Iterator, Mapping, Optional

import numpy as np

from distributed_vgg_f_tpu import telemetry

log = logging.getLogger(__name__)

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False

_I32P = ctypes.POINTER(ctypes.c_int32)
_F32P = ctypes.POINTER(ctypes.c_float)

#: Must match dvgg_abi_version() in native/dataloader.cc — single source
#: for the load gate and the ABI contract checker (tools/abi_check.py).
DATA_ABI_VERSION = 1


def load_native() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library; None if unavailable.
    Build/cache mechanics are shared with the jpeg loader — see
    data/native_build.py (pid-temp compile + atomic rename + mtime check)."""
    global _lib, _build_failed
    with _lock:
        if _lib is not None:
            return _lib
        if _build_failed:
            return None
        from distributed_vgg_f_tpu.data.native_build import build_native_lib
        so_path = build_native_lib("dataloader.cc", "libdvgg_data.so")
        if so_path is None:
            _build_failed = True
            return None
        try:
            lib = ctypes.CDLL(so_path)
            # Exhaustive argtypes/restype on EVERY export (r15): ctypes'
            # silent defaults (int restype, unchecked arity) are the exact
            # corruption vector the ABI checker exists to close — it
            # cross-checks these against the C signatures.
            lib.dvgg_loader_create.restype = ctypes.c_void_p
            lib.dvgg_loader_create.argtypes = [
                ctypes.c_void_p, _I32P, ctypes.c_int64,
                ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
                ctypes.c_int, ctypes.c_int, ctypes.c_uint64,
                _F32P, _F32P, ctypes.c_int,
            ]
            lib.dvgg_loader_next.restype = None
            lib.dvgg_loader_next.argtypes = [ctypes.c_void_p, _F32P, _I32P]
            lib.dvgg_loader_destroy.restype = None
            lib.dvgg_loader_destroy.argtypes = [ctypes.c_void_p]
            lib.dvgg_abi_version.restype = ctypes.c_int
            lib.dvgg_abi_version.argtypes = []
            if lib.dvgg_abi_version() != DATA_ABI_VERSION:
                raise OSError("ABI version mismatch")
        except (OSError, AttributeError) as e:
            log.warning("native dataloader load failed: %s", e)
            _build_failed = True
            return None
        _lib = lib
        return _lib


class NativeBatchIterator:
    """Iterator over augmented, normalized float32 batches produced by the
    native double-buffered assembler. Holds references to the source arrays
    (the C++ side does not copy them)."""

    def __init__(self, images: np.ndarray, labels: np.ndarray, batch_size: int,
                 *, train: bool, seed: int, mean, std, pad: int = 4,
                 num_threads: Optional[int] = None):
        lib = load_native()
        if lib is None:
            raise RuntimeError("native dataloader unavailable")
        assert images.dtype == np.uint8 and images.ndim == 4
        self._lib = lib
        # keep alive: the native loader reads these buffers directly
        self._images = np.ascontiguousarray(images)
        self._labels = np.ascontiguousarray(labels.astype(np.int32))
        n, h, w, c = self._images.shape
        self.batch_size = batch_size
        self._shape = (batch_size, h, w, c)
        mean3 = (ctypes.c_float * 3)(*[float(m) for m in mean][:3])
        std3 = (ctypes.c_float * 3)(*[float(s) for s in std][:3])
        if num_threads is None:
            num_threads = min(4, os.cpu_count() or 1)
        self._handle = lib.dvgg_loader_create(
            self._images.ctypes.data_as(ctypes.c_void_p),
            self._labels.ctypes.data_as(_I32P),
            n, h, w, c, batch_size, pad if train else 0, int(train),
            seed, mean3, std3, num_threads)
        if not self._handle:
            raise RuntimeError("dvgg_loader_create failed")
        self._buf_ring: list = []
        self._buf_i = 0

    @property
    def reuses_output_buffers(self) -> bool:
        """Same ownership contract as the jpeg loader (data/native_jpeg.py):
        True once the output-array ring is armed — device prefetch refuses
        such iterators (data/prefetch.py)."""
        return bool(self._buf_ring)

    def enable_output_buffer_reuse(self, depth: int = 3) -> None:
        """Recycle `depth` preallocated output arrays instead of allocating
        a multi-MB batch array per `next()` — batches are then only valid
        until `depth` further calls. Bench-only (synchronous consumers)."""
        if depth < 2:
            raise ValueError(f"ring depth must be >= 2, got {depth}")
        self._buf_ring = [(np.empty(self._shape, np.float32),
                           np.empty((self.batch_size,), np.int32))
                          for _ in range(depth)]
        self._buf_i = 0

    def __iter__(self) -> Iterator[Mapping[str, np.ndarray]]:
        return self

    def __next__(self) -> Mapping[str, np.ndarray]:
        if not self._handle:
            raise RuntimeError("NativeBatchIterator used after close()")
        if self._buf_ring:
            images, labels = self._buf_ring[self._buf_i % len(self._buf_ring)]
            self._buf_i += 1
        else:
            # fresh arrays per call: the C side memcpys out of its staging
            # buffer, so these are immediately safe to hand to the caller —
            # one copy total
            images = np.empty(self._shape, np.float32)
            labels = np.empty((self.batch_size,), np.int32)
        t0 = time.monotonic_ns()
        self._lib.dvgg_loader_next(
            self._handle,
            images.ctypes.data_as(_F32P),
            labels.ctypes.data_as(_I32P))
        # per-BATCH, not per-image: the time blocked on the native
        # double-buffer is the loader's contribution to an infeed stall
        telemetry.record("native_loader_next", "infeed_source", t0,
                         time.monotonic_ns() - t0)
        telemetry.inc("native_loader/batches")
        return {"image": images, "label": labels}

    def close(self) -> None:
        handle, self._handle = self._handle, None
        if handle:
            self._lib.dvgg_loader_destroy(handle)

    def __del__(self):  # pragma: no cover - GC timing
        try:
            self.close()
        except Exception:
            pass
