"""Structured metrics logging (SURVEY.md §5 observability): human-readable stdout
line + machine-readable JSONL file per step-log event, plus optional TensorBoard
scalar summaries. Replaces the reference's console prints + TF summaries.

The JSONL stream is the telemetry spine's output surface: the trainer routes
stall-attribution verdicts and registry counter deltas through `log` as nested
mappings, which serialize into the record but stay off the compact stdout
mirror. Records are guaranteed spec-legal JSON: non-finite floats (a NaN loss
is exactly what the resilience layer logs) serialize as ``null`` plus a
``<key>_nonfinite`` string — ``json.dumps`` would otherwise emit bare ``NaN``
tokens that break every strict downstream parser
(telemetry/schema.py validates this contract).
"""

from __future__ import annotations

import json
import logging
import math
import os
import sys
from typing import IO, Mapping

from distributed_vgg_f_tpu.telemetry.schema import SCHEMA_VERSION

log = logging.getLogger("dvggf")


def _fmt(v) -> str:
    if isinstance(v, float):
        return f"{v:.6g}"
    return str(v)


def _nonfinite_name(v: float) -> str:
    if math.isnan(v):
        return "nan"
    return "inf" if v > 0 else "-inf"


def _sanitize(value):
    """JSON-legal deep copy: non-finite floats become None, with dict
    entries gaining a sibling `<key>_nonfinite` string naming what the
    value WAS — the information (that a loss was NaN, not merely missing)
    is the whole point of logging the event."""
    if isinstance(value, float) and not math.isfinite(value):
        return None
    if isinstance(value, Mapping):
        out = {}
        for k, v in value.items():
            k = str(k)
            if isinstance(v, float) and not math.isfinite(v):
                out[k] = None
                out[f"{k}_nonfinite"] = _nonfinite_name(v)
            else:
                out[k] = _sanitize(v)
        return out
    if isinstance(value, (list, tuple)):
        return [_sanitize(v) for v in value]
    return value


class MetricLogger:
    """Writes one JSONL record per event; mirrors a compact line to stdout.
    Only process 0 should construct one in multi-host runs.

    Usable as a context manager: ``with MetricLogger(...) as logger`` closes
    (flushing the JSONL file and the TensorBoard writer exactly once) on the
    way out of a crashing run, so the record stream on disk is complete up
    to the failure."""

    def __init__(self, jsonl_path: str | None = None, stream: IO = sys.stdout,
                 tensorboard_dir: str | None = None):
        self._stream = stream
        self._file: IO | None = None
        self._tb = None
        if jsonl_path:
            os.makedirs(os.path.dirname(jsonl_path) or ".", exist_ok=True)
            self._file = open(jsonl_path, "a", buffering=1)
        if tensorboard_dir:
            # Lazy TF import: only paid when TensorBoard output is requested.
            import tensorflow as tf
            self._tb = tf.summary.create_file_writer(tensorboard_dir)

    def log(self, event: str, metrics: Mapping[str, object]) -> None:
        # schema_version rides EVERY record (telemetry/schema.py): a reader
        # written against an old major must be able to refuse a new one
        # per-record, not per-file — archives concatenate across versions.
        record = {"event": event, "schema_version": SCHEMA_VERSION,
                  **{k: _to_py(v) for k, v in metrics.items()}}
        if self._file is not None:
            # allow_nan=False is the backstop: if sanitization ever misses a
            # non-finite value, fail HERE (named, at the write) rather than
            # emit a record that poisons the archive for every later reader
            self._file.write(json.dumps(_sanitize(record), allow_nan=False)
                             + "\n")
        if self._tb is not None:
            self._write_tb(event, record)
        pairs = " ".join(f"{k}={_fmt(v)}" for k, v in record.items()
                         if k not in ("event", "schema_version")
                         and not isinstance(v, Mapping))
        print(f"[{event}] {pairs}", file=self._stream, flush=True)

    def _write_tb(self, event: str, record: Mapping[str, object]) -> None:
        step = record.get("step")
        if not isinstance(step, int):
            return
        import tensorflow as tf
        with self._tb.as_default():
            for k, v in record.items():
                if k in ("event", "step") or not isinstance(v, (int, float)) \
                        or isinstance(v, bool):
                    continue
                if isinstance(v, float) and not math.isfinite(v):
                    continue  # TB scalars reject non-finite values
                tf.summary.scalar(f"{event}/{k}", float(v), step=step)
        self._tb.flush()

    def close(self) -> None:
        """Flush and close both sinks exactly once; safe to call again (the
        trainer's finally path and a caller's context-manager exit may both
        reach here). NEVER raises: cli.py runs the whole training under
        ``with MetricLogger(...)``, so an exception out of here (a broken
        TB writer, a full disk at flush) would mask the real run error in
        ``__exit__``. Failures are logged and swallowed; each sink's close
        is attempted even when its flush fails."""
        file, self._file = self._file, None
        tb, self._tb = self._tb, None
        for sink in (file, tb):
            if sink is None:
                continue
            try:
                sink.flush()
            except Exception as e:
                log.warning("MetricLogger flush failed: %r", e)
            try:
                sink.close()
            except Exception as e:
                log.warning("MetricLogger close failed: %r", e)

    def __enter__(self) -> "MetricLogger":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False


def _to_py(v):
    if hasattr(v, "item"):
        try:
            return v.item()
        except Exception:
            return str(v)
    if isinstance(v, float):
        return v
    return v
