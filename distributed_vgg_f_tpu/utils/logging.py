"""Structured metrics logging (SURVEY.md §5 observability): human-readable stdout
line + machine-readable JSONL file per step-log event, plus optional TensorBoard
scalar summaries. Replaces the reference's console prints + TF summaries."""

from __future__ import annotations

import json
import logging
import os
import sys
from typing import IO, Mapping

log = logging.getLogger("dvggf")


def _fmt(v) -> str:
    if isinstance(v, float):
        return f"{v:.6g}"
    return str(v)


class MetricLogger:
    """Writes one JSONL record per event; mirrors a compact line to stdout.
    Only process 0 should construct one in multi-host runs."""

    def __init__(self, jsonl_path: str | None = None, stream: IO = sys.stdout,
                 tensorboard_dir: str | None = None):
        self._stream = stream
        self._file: IO | None = None
        self._tb = None
        if jsonl_path:
            os.makedirs(os.path.dirname(jsonl_path) or ".", exist_ok=True)
            self._file = open(jsonl_path, "a", buffering=1)
        if tensorboard_dir:
            # Lazy TF import: only paid when TensorBoard output is requested.
            import tensorflow as tf
            self._tb = tf.summary.create_file_writer(tensorboard_dir)

    def log(self, event: str, metrics: Mapping[str, object]) -> None:
        record = {"event": event, **{k: _to_py(v) for k, v in metrics.items()}}
        if self._file is not None:
            self._file.write(json.dumps(record) + "\n")
        if self._tb is not None:
            self._write_tb(event, record)
        pairs = " ".join(f"{k}={_fmt(v)}" for k, v in record.items() if k != "event")
        print(f"[{event}] {pairs}", file=self._stream, flush=True)

    def _write_tb(self, event: str, record: Mapping[str, object]) -> None:
        step = record.get("step")
        if not isinstance(step, int):
            return
        import tensorflow as tf
        with self._tb.as_default():
            for k, v in record.items():
                if k in ("event", "step") or not isinstance(v, (int, float)):
                    continue
                tf.summary.scalar(f"{event}/{k}", float(v), step=step)
        self._tb.flush()

    def close(self) -> None:
        if self._file is not None:
            self._file.close()
            self._file = None
        if self._tb is not None:
            self._tb.close()
            self._tb = None


def _to_py(v):
    if hasattr(v, "item"):
        try:
            return v.item()
        except Exception:
            return str(v)
    if isinstance(v, float):
        return v
    return v
