"""Analytic multi-chip scaling model — the ≥90 % v4-8 → v4-128 north star
(BASELINE.json `north_star`; VERDICT r3 What's-missing #3).

Real multi-chip hardware is not reachable from this machine (SURVEY.md §0:
one tunneled v5e chip), so the scaling-efficiency target cannot be *measured*
here. What CAN be committed is the physics: synchronous data-parallel SGD has
exactly one cross-replica dependency per step — the gradient all-reduce
(train/step.py [SYNC]) — so predicted efficiency is a function of

  - the measured single-chip step time (benchmarks/runs/tpu_r*/),
  - the per-step collective bytes (param bytes and layout — replicated
    all-reduce vs ZeRO-1 reduce-scatter + all-gather),
  - the chip's ICI injection bandwidth and the slice's hop latency,
  - how much of the collective XLA hides under backward compute, and
  - the host input pipeline, which binds before ICI does for the fast
    models (SURVEY.md §7 names the host path as where the target is won
    or lost).

Every input is an explicit field with its provenance in `ASSUMPTIONS`;
`predict()` is pure arithmetic (unit-tested in tests/test_scaling_model.py),
and `benchmarks/scaling_model.py` renders the committed table.

Collective cost model (bandwidth-optimal ring all-reduce; the scaling-book
recipe): a gradient of G bytes costs 2·G·(N−1)/N wire bytes per chip.
ZeRO-1 moves the SAME wire bytes (reduce-scatter G·(N−1)/N + all-gather
G·(N−1)/N) — its win is opt-state memory and update FLOPs, not bandwidth.
On a v4 3-D torus the reduction runs per-dimension, so the latency term uses
torus hops (3·(∛N−1) per traversal direction), not a flat ring's N−1; with
µs-class hops it is negligible at these message sizes either way.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Mapping, Sequence

# ---------------------------------------------------------------------------
# Inputs, each with provenance. Values are overridable per-call; these are the
# committed defaults the README table is generated from.
# ---------------------------------------------------------------------------

#: The r5-measured native-loader decode rate (img/s/core): the LOWER of the
#: two committed quiet-host best-of-3 contract lines after the r5 bilinear
#: hoists in native/jpeg_loader.cc (734.31 spread 0.014 / 728.05 spread
#: 0.039 — benchmarks/runs/host_r5/host_pipeline_run{1,2}.json). Historical
#: since r6 (kept as a sensitivity row; float32 unpacked output, 1-vCPU
#: host). The frozen r4 baseline 556.34 lives in benchmarks/baseline.json
#: so vs_baseline keeps recording the win.
HOST_DECODE_RATE_R5 = 728.05

#: The r6-measured native-loader decode rate (img/s/core) after the SIMD
#: resample path (native/jpeg_loader.cc "resample kernels": runtime-
#: dispatched AVX2+FMA vertical/horizontal lerp + normalize, bf16 rounded
#: in-lane, memcpy space-to-depth repack). Measured in the FLAGSHIP INGEST
#: configuration — bfloat16 output + space-to-depth, the exact layout the
#: judged 22,028 img/s/chip device rate consumes (bench.py) — because the
#: provisioning quotient divides that device rate; r5's constant was the
#: float32-unpacked rate, a different (then-faster, now-slower) basis.
#: Quiet-host min-of-6 windows, two committed runs, LOWER contract value
#: kept (1064.76 spread 0.049 / 1031.36 spread 0.109); same-box same-config
#: scalar before-rate 862.17/854.68 → the kernels are a 1.21–1.24×
#: end-to-end win with the resample phase cut ~410→~160 µs/img and the
#: residual 80 % of the budget pinned as libjpeg entropy+IDCT (the
#: committed profile split in each artifact). Host: 2-vCPU AVX2/AVX512 box,
#: benchmarks/runs/host_r6/decode_{scalar,simd}_bf16s2d_run{1,2}.json; the
#: r5 1-vCPU box is gone, so cross-round ratios must go through the
#: same-box scalar column, not HOST_DECODE_RATE_R5. Historical since r7
#: (kept as a sensitivity row).
HOST_DECODE_RATE_R6 = 1031.36

#: The r7-measured native-loader decode rate (img/s/core) after the DCT-
#: scaled + partial decode rework in native/jpeg_loader.cc (ABI v5:
#: power-of-two scale chooser over libjpeg-turbo's SIMD IDCT sizes,
#: dlsym-probed jpeg_crop_scanline/jpeg_skip_scanlines partial decode with
#: a fancy-upsampling context margin, per-thread reused decode context +
#: grow-only buffer pool). Same flagship ingest basis as r6 (bfloat16 +
#: space-to-depth, tfrecord, 320x256 noise sources — the continuity
#: protocol): LOWER of the final alternating drift-controlled pair
#: (1027.79 / 991.15, runs 3/4 of benchmarks/runs/host_r7/
#: decode_r7_bf16s2d_320noise_run{1..4}.json). The movement from
#: HOST_DECODE_RATE_R6=1031.36 is BOX DRIFT, not a decode regression:
#: same-session worktree runs of the r6 code on the same sources measure
#: 989.3–1047.1 (decode_r6code_* columns) — this virtualized box now sits
#: ~3-4 % below its r6-era windows, and r7 ≡ r6 code within noise on this
#: config. The r7 wins live elsewhere, receipted in host_r7/README.md:
#: +12.1 % same-box on the f32-unpacked contract config (buffer pool +
#: output ring; 907.3 → 1017.0), +17-26 % over full decode at ≥448px
#: sources (scaled+partial machinery, now kill-switchable and exact), and
#: the committed entropy-floor analysis showing why no decode-side change
#: moves the ≥448px rate past ~1150 img/s/core on this host class. The
#: SINGLE source for the provisioning default below, the predict()
#: host-ceiling default, the sensitivity rows in benchmarks/
#: scaling_model.py, and the tests — an r8 re-measure is a one-line
#: change here.
HOST_DECODE_RATE_R7 = 991.15

#: The r8-measured native-loader decode rate (img/s/core) on the uint8
#: ingest wire (native/jpeg_loader.cc ABI v6: fixed-point integer resample
#: kernels emitting raw uint8 HWC — normalize/cast/space-to-depth move to
#: the device-finish prologue, data/device_ingest.py). The provisioning
#: basis FOLLOWS the production ingest contract: the flagship now ships
#: data.wire='u8' (1 B/px through device_put, 0.5x the bf16 wire, with
#: the finishing math fused into the jitted step), so the constant is the
#: LOWER of the committed u8 flagship-replacement pair (1114.19 / 1200.29
#: — benchmarks/runs/host_r9/decode_r8_u8_s2d_320noise_run{1,2}.json;
#: s2d requested, deferred to device — host work identical to the plain
#: u8 rows, which measured 1180.9-1226.4 in the same session). Same-
#: session controls (host_r9/README.md): r7-code worktree f32 columns sat
#: at 1069.9-1089.9 (this box currently runs ~5-8 % ABOVE its r7-era
#: windows — cross-round ratios must go through the same-session columns,
#: not HOST_DECODE_RATE_R7), r8 host wires are parity-within-noise with
#: r7 code, and the u8 win is +10.4 % lower-vs-lower / +12.5 % best-vs-
#: best over the same-session f32 control, with the resample phase cut
#: ~130-140 → ~81-89 µs/img. Kill-switches: DVGGF_WIRE_U8=0 env /
#: dvgg_jpeg_set_wire_u8 runtime / -DDVGGF_NO_WIRE_U8 compile-out, all
#: falling back to the byte-identical r7 host path. The SINGLE source for
#: the provisioning default below, the predict() host-ceiling default,
#: and the tests — an r9 re-measure is a one-line change here.
HOST_DECODE_RATE_R8 = 1114.19

#: The r9-measured native-loader decode rate (img/s/core) with the
#: restart-marker excerpt entropy decode engaged (native/jpeg_loader.cc
#: ABI v7: the decoder scans RSTn segment boundaries with a pure memchr
#: byte walk, splices a synthetic JPEG from only the segments covering
#: the sampled crop band, and entropy-parses nothing outside it — the
#: sequential path must Huffman-parse every row above the crop; parity
#: suite pins the excerpt byte-identical). Same continuity basis as r8
#: (u8 wire + deferred s2d, tfrecord, 320x256 noise sources, min-of-6
#: alternating windows) with one NEW dataset assumption the constant
#: inherits from the production ingest contract: the dataset carries
#: interval-1 restart markers, injected ONCE offline by the lossless
#: coefficient-domain transcode (benchmarks/reencode_restart.py, ~1-3 %
#: size cost — pixels identical). LOWER of the committed restart-on trio
#: (1228.96 / 1336.17 / 1268.34 — benchmarks/runs/host_r10/
#: decode_r10_on_320noise_rst1_run{1..3}.json). Same-session controls
#: (host_r10/README.md): the restart-OFF columns on the same marker
#: sources measured 1032.0-1050.7 — this box has drifted ~6 % BELOW its
#: r9-session windows, so the committed-vs-committed +10.3 % over
#: HOST_DECODE_RATE_R8 UNDERSTATES the feature; drift-controlled the
#: excerpt decode is +19.1 % lower-vs-lower on this basis, +10.1 % at
#: 448 px textured and +35.9 % at 768 px (the win rises with resolution
#: because the Huffman share does). A marker-absent dataset decodes
#: sequentially (receipted in restart_stats) and reads as the off
#: column, i.e. the r8 rate modulo drift. Kill-switches:
#: DVGGF_DECODE_RESTART=0 env / dvgg_jpeg_set_restart runtime /
#: -DDVGGF_NO_RESTART compile-out, all byte-identical fallbacks. The
#: SINGLE source for the provisioning default below, the predict()
#: host-ceiling default, and the tests — an r10 re-measure is a one-line
#: change here. (The r9 snapshot cache — warm epochs 2.69x cold,
#: host_r10 — is opt-in and deliberately NOT a provisioning basis: warm
#: epochs re-serve epoch-1 crop geometry, a training-distribution trade
#: the spec must not silently assume.)
HOST_DECODE_RATE_R9 = 1228.96

#: r13 (bench round r13, feature round r10) — the fused-on-device-
#: augmentation + one-ingest-contract round's pins. All four are
#: measured on the SAME protocol as HOST_DECODE_RATE_R9 (u8 wire,
#: tfrecord, 320x256 noise, interval-1 restart markers, min-of-6
#: alternating windows, LOWER of the committed run pair —
#: benchmarks/runs/host_r13/) and gate their OWN (model, augment) basis
#: in the regression sentinel, independent of the VGG-F flips-on-host
#: line. Absolute levels sit ~9-15 % below HOST_DECODE_RATE_R9 because
#: this box drifted between sessions (window spreads 4-16 % in the
#: committed artifacts; host_r13/README.md carries the same-session
#: evidence) — the within-session claims are what these rows pin:
#:
#: AUG (vggf, augment-on): host flips DELETED (ABI v9 per-loader
#: switch; the fused stage in data/augment.py owns them on device).
#: The same-session alternating receipt (decode_r13_augment_on_run1
#: `augment_overhead`) measured augment-ON 1209.06 vs OFF 1181.18
#: img/s/core (-2.36 % "overhead" = noise-floor; ON does strictly less
#: host work) at IDENTICAL wire bytes/image (150528) — augmentation
#: diversity at zero host cost, the r13 acceptance claim. The fused
#: stage's STEP cost is the separate augment_step_overhead.json receipt
#: (+0.27 % min-of-6, <2 % budget).
HOST_DECODE_RATE_R10_AUG = 1057.42
#: Zoo rows (vgg16 / resnet50 / vit_s16 ingest descriptors: u8 wire,
#: NO space-to-depth — models/ingest.py): host decode work is identical
#: to the flagship's on the u8 wire by construction (packing was already
#: deferred to the device), so these pin the SAME pipeline under each
#: model's label; their value is that a zoo preset's ingest regression
#: now fails its own gate instead of hiding behind the VGG-F line.
HOST_ZOO_RATE_R10_VGG16 = 1055.52
HOST_ZOO_RATE_R10_RESNET50 = 1076.98
HOST_ZOO_RATE_R10_VIT_S16 = 1041.85

#: r14 (feature round r17) — the serving chain's first pin, its OWN metric
#: (`serving_admitted_rps`, telemetry/regress.SERVING_PINS): peak admitted
#: requests/sec of the dynamic-batching predict server among open-loop
#: RPS-ramp stages whose admitted p99 stayed within the SLO budget —
#: benchmarks/serving_bench.py on CPU (vggf head, 128 px u8 payloads,
#: bucket ladder 1..8, LOWER of the committed run pair,
#: benchmarks/runs/host_r16/serving_openloop_run{1,2}.json). A CPU number
#: on a shared box: it pins the admission machinery's throughput floor
#: (batching + HTTP + shed path), not device inference — the device
#: serving row is queued in benchmarks/tpu_session_r14.sh.
SERVING_RPS_R14 = 278.05

#: r18 (feature round r23) — the latency-TIER ladder's pins, one per
#: (vggf, tier) basis: same open-loop protocol as SERVING_RPS_R14
#: (Poisson ramp, admitted-RPS-within-SLO contract, LOWER of the
#: committed run pair, benchmarks/runs/host_r23/serving_r18_tier_*) but
#: on TRAINED weights at the teacher task's native 32 px geometry —
#: where CNN-F's FC heads dominate the forward (fc6_in=256), the compute
#: profile the tier designs target. NOT comparable to the 128 px
#: fresh-init R14 line (different basis, drift-noted in SERVING_PINS).
#: The frontier claim the receipts gate: int8 (calibrated sub-LSB
#: channel elision over per-out-channel-quantized heads) and student
#: (half-width distilled vggf_student) admit STRICTLY more RPS than
#: fp32 within the same SLO, at top-1 deltas within the configured
#: bounds (row `accuracy` blocks); bf16 is emulated on XLA:CPU and pins
#: its CPU baseline only — its latency claim is the queued MXU device
#: row (benchmarks/tpu_session_r18.sh).
SERVING_RPS_R18_FP32 = 165.97
SERVING_RPS_R18_BF16 = 172.85
SERVING_RPS_R18_INT8 = 210.09
SERVING_RPS_R18_STUDENT = 300.94

ASSUMPTIONS: Mapping[str, str] = {
    "v4_peak_bf16_flops": "275e12 — TPU v4 public spec (ISCA'23 paper class)",
    "v5e_peak_bf16_flops": "197e12 — TPU v5e public spec",
    "ici_links_v4": "6 links/chip (3-D torus), ~45 GB/s usable per link per "
                    "direction — 50 GB/s-class links derated ~10 % for "
                    "protocol overhead",
    "ici_collective_utilization": "0.8 — fraction of aggregate injection "
                                  "bandwidth a multi-ring torus all-reduce "
                                  "sustains (XLA uses all torus dimensions)",
    "hop_latency_s": "1e-6 — per-ICI-hop latency, µs class",
    "overlap_fraction": "0.75 — fraction of backward compute XLA's latency-"
                        "hiding scheduler can run under the all-reduce "
                        "(layerwise grads are ready before backward ends); "
                        "0.0 row = no-overlap worst case",
    "backward_fraction_of_step": "2/3 — fwd:bwd FLOP ratio 1:2 for these "
                                 "nets; the optimizer tail is ~free",
    "v4_step_time_scaling": "t_v4 = t_v5e × 197/275 — assumes the measured "
                            "v5e MFU carries to v4 (both MXU-bound on the "
                            "same fusions); HBM ratio (1228/819 GB/s) is "
                            "MORE favorable, so this is the conservative "
                            "axis",
    "grad_dtype_bytes": "4 — grads/params are fp32 in train/step.py "
                        "(compute is bf16; the reduction is full precision)",
    "v4_chips_per_host": "4 — one v4 host serves a 2×2×1 tray",
    "v4_host_cores": "240 — v4 VM host vCPUs (n2d class)",
    "host_decode_rate_per_core": f"{HOST_DECODE_RATE_R9} img/s/core "
                                 "(HOST_DECODE_RATE_R9) — measured r9 "
                                 "with the restart-marker excerpt "
                                 "entropy decode (native/jpeg_loader.cc "
                                 "ABI v7) on the u8 ingest wire: LOWER "
                                 "of the committed restart-on continuity "
                                 "trio (1228.96/1336.17/1268.34 — "
                                 "benchmarks/runs/host_r10/decode_r10_"
                                 "on_320noise_rst1_run{1..3}.json), "
                                 "+19.1 % lower-vs-lower over the same-"
                                 "session restart-off columns (1032.0-"
                                 "1050.7; the box drifted ~6 % BELOW its "
                                 "r9-session windows, so the +10.3 % "
                                 "over the committed r8 value "
                                 "understates). ASSUMES the dataset "
                                 "carries interval-1 restart markers "
                                 "(one-time lossless transcode, "
                                 "benchmarks/reencode_restart.py); a "
                                 "marker-absent dataset reads as the r8 "
                                 "rate 1114.19 modulo drift. The r8 rate "
                                 "(u8 wire, marker-free), r7 991.15, r6 "
                                 "1031.36, r5 728.05 and the frozen r4 "
                                 "baseline 556.34 stay as sensitivity "
                                 "rows / vs_baseline anchor",
    "step_times": "measured v5e device benches, benchmarks/runs/tpu_r3/ "
                  "(vggf 22,028 img/s/chip @2048; vgg16 1,372.8 @128; "
                  "resnet50 2,543.4 @256; vit_s16 1,910.1 @256)",
}


@dataclasses.dataclass(frozen=True)
class ChipSpec:
    name: str
    peak_bf16_flops: float
    ici_links: int
    ici_link_bytes_per_s: float      # usable, per direction
    chips_per_host: int
    host_cores: int

    @property
    def injection_bytes_per_s(self) -> float:
        return self.ici_links * self.ici_link_bytes_per_s


V4 = ChipSpec("TPU v4", 275e12, 6, 45e9, 4, 240)
V5E = ChipSpec("TPU v5e", 197e12, 4, 45e9, 8, 224)


@dataclasses.dataclass(frozen=True)
class ModelPoint:
    """A measured single-chip operating point (v5e, device-only bench)."""
    name: str
    param_count: int                 # exact, jax.eval_shape over model.init
    per_chip_batch: int
    v5e_images_per_sec_per_chip: float

    @property
    def v5e_step_time_s(self) -> float:
        return self.per_chip_batch / self.v5e_images_per_sec_per_chip

    def step_time_on(self, chip: ChipSpec) -> float:
        """Compute-bound rescale by peak-FLOPs ratio (ASSUMPTIONS)."""
        return self.v5e_step_time_s * (V5E.peak_bf16_flops
                                       / chip.peak_bf16_flops)


# Exact param counts: jax.eval_shape over model.init (models/*.py), 2026-07.
MEASURED: Sequence[ModelPoint] = (
    ModelPoint("vggf", 60_834_536, 2048, 22_028.4),
    ModelPoint("vgg16", 138_357_544, 128, 1_372.79),
    ModelPoint("resnet50", 25_557_032, 256, 2_543.39),
    ModelPoint("vit_s16", 22_050_664, 256, 1_910.06),
)


# ---------------------------------------------------------------------------
# The model
# ---------------------------------------------------------------------------

def allreduce_bytes_per_chip(grad_bytes: float, n_chips: int,
                             *, zero1: bool = False,
                             param_bytes: float | None = None) -> float:
    """Wire bytes each chip moves for one gradient sync.

    Replicated DP: ring all-reduce = reduce-scatter + all-gather fused,
    2·G·(N−1)/N — BOTH internal phases move the gradient's wire dtype.
    ZeRO-1 (train/step.py zero1=True): explicit psum_scatter of gradients
    (G·(N−1)/N) then all-gather of updated PARAMS (P·(N−1)/N) — the gather
    leg moves parameters, which stay fp32 regardless of mesh.reduce_dtype
    (replicas must re-sync exactly; config.py). With fp32 grads the two
    layouts move identical bytes; with a narrower gradient wire dtype
    ZeRO-1 saves only the scatter leg (code-review r4). `param_bytes`
    defaults to `grad_bytes` (the fp32 case)."""
    if n_chips <= 1:
        return 0.0
    frac = (n_chips - 1) / n_chips
    if zero1:
        return (grad_bytes + (param_bytes if param_bytes is not None
                              else grad_bytes)) * frac
    return 2.0 * grad_bytes * frac


def exchange_bytes_per_chip(grad_bytes: float, n_chips: int, *,
                            sharding: str = "dp",
                            param_bytes: float | None = None) -> float:
    """Wire bytes per chip per step for one gradient exchange, by sharding
    basis (r14/r21 — the (dp | zero1 | zero2 | zero3) key of train/step.py
    comm_meta). ZeRO-2 moves EXACTLY ZeRO-1's bytes: the reduce-scatter
    leg and the param all-gather leg are unchanged — its win is
    gradient-state MEMORY (`gradient_state_bytes_per_chip`), not
    bandwidth. ZeRO-3 (r21, mesh.shard_params) also moves the same bytes
    at the fp32 wire: the trailing param re-sync all-gather simply becomes
    the just-in-time pre-forward gather (same P·(N−1)/N) — but its gather
    leg follows `mesh.reduce_dtype` where ZeRO-1/2's stays fp32 by the
    replica-sync contract, so a narrowed wire is expressed by passing the
    narrowed `param_bytes` under zero3 only. Bucketing changes the message
    SCHEDULE (`bucketed_exposed_comm_s`), not the byte total (each element
    still crosses the wire once per leg)."""
    if sharding not in ("dp", "zero1", "zero2", "zero3"):
        raise ValueError(f"sharding {sharding!r} not one of "
                         "('dp', 'zero1', 'zero2', 'zero3')")
    return allreduce_bytes_per_chip(grad_bytes, n_chips,
                                    zero1=sharding != "dp",
                                    param_bytes=param_bytes)


def param_bytes_per_chip(param_count: int, n_chips: int, *,
                         sharding: str = "dp",
                         ema: bool = False) -> float:
    """Per-chip bytes of PERSISTENT parameter state, by sharding basis —
    the ZeRO-3 memory claim (arXiv 2004.13336 §parameter sharding;
    train/state.py): dp/zero1/zero2 replicate the full fp32 tree on every
    chip (O(params)); zero3 (r21, mesh.shard_params) persists only the 1/N
    padded flat shard (O(params/N) — the padding is < N elements per
    bucket, noise at these sizes). The just-in-time gathered full tree is
    TRANSIENT (alive only inside the step, like the AD activations), so it
    does not count as persistent state. `ema=True` doubles the figure (the
    EMA trace rides the same layout as the params in every basis)."""
    if sharding not in ("dp", "zero1", "zero2", "zero3"):
        raise ValueError(f"sharding {sharding!r} not one of "
                         "('dp', 'zero1', 'zero2', 'zero3')")
    b = 4.0 * param_count
    per_chip = b / max(1, n_chips) if sharding == "zero3" else b
    return per_chip * (2.0 if ema else 1.0)


def gradient_state_bytes_per_chip(param_count: int, n_chips: int, *,
                                  sharding: str = "dp",
                                  grad_accum_steps: int = 1,
                                  bucket_bytes: int = 0,
                                  momentum: bool = True) -> Mapping[str, float]:
    """Per-chip bytes of persistent GRADIENT-adjacent state, by sharding
    basis — the ZeRO-2 memory claim, O(params/N) where DP/ZeRO-1 hold
    O(params) (arXiv 2004.13336 §gradient sharding; train/step.py):

      - `opt_state`: the momentum trace — sharded 1/N under ZeRO-1 and
        ZeRO-2, replicated under DP (the PR-10 ZeRO-1 win, unchanged).
      - `grad_accumulator`: the scan carry at grad_accum_steps > 1 —
        O(params) for DP and plain ZeRO-1, O(params/N) under ZeRO-2
        (`shard_gradients` shards the carry; `grad_accum_shard` was the
        ZeRO-1 opt-in for the same shape). 0 at grad_accum_steps == 1 (no
        carry exists).
      - `exchange_buffer`: the largest flat send buffer the exchange
        materializes beyond the AD-transient per-leaf gradients —
        O(params) for the monolithic ZeRO flat scatter, O(bucket) when
        bucketed (each bucket's concat send — DP included — exists only
        until its collective issues), 0 for monolithic DP (the per-leaf
        pmean consumes leaves in place).

    Gradients are fp32 on the wire frame (4 B/elem; mesh.reduce_dtype
    narrows the WIRE, not the state). ZeRO-3 (r21) keeps ZeRO-2's gradient
    state exactly — its additional win is PARAM state, reported by
    `param_bytes_per_chip`, not here."""
    if sharding not in ("dp", "zero1", "zero2", "zero3"):
        raise ValueError(f"sharding {sharding!r} not one of "
                         "('dp', 'zero1', 'zero2', 'zero3')")
    b = 4.0 * param_count
    shard = b / max(1, n_chips)
    opt = 0.0 if not momentum else (b if sharding == "dp" else shard)
    if grad_accum_steps > 1:
        accum = shard if sharding in ("zero2", "zero3") else b
    else:
        accum = 0.0
    if bucket_bytes > 0:
        # per-bucket concat send buffer — DP's bucketed pmean builds one
        # too (GradBucketLayout._bucket_vector), not just the ZeRO scatter
        exchange = float(min(b, bucket_bytes))
    elif sharding == "dp":
        exchange = 0.0
    else:
        exchange = b
    return {"opt_state_bytes": opt, "grad_accumulator_bytes": accum,
            "exchange_buffer_bytes": exchange,
            "total_bytes": opt + accum + exchange}


def bucketed_exposed_comm_s(t_comm_s: float, num_buckets: int, *,
                            overlappable_s: float,
                            hop_latency_s: float = 1e-6,
                            n_chips: int = 2) -> float:
    """Exposed (un-hidden) exchange time under the bucketed schedule.

    The monolithic exchange exposes max(0, t_comm − overlappable): one
    collective that can only start once EVERY gradient exists, so overlap
    is whatever backward happens to remain (for the flat ZeRO scatter:
    nothing — the committed HLO reports show it depends on the whole
    backward). Bucketing bounds the serial tail by the LAST bucket
    instead: buckets 0..B−2 issue while backward still runs, so the
    exposed time is at least t_comm/B (the final bucket's wire time — its
    gradients finish WITH the backward) and at most the monolithic
    exposure; each extra collective pays one more latency term (the
    many-small-buckets ViT caveat — B λ·hops grows linearly in B)."""
    if num_buckets < 1:
        raise ValueError(f"num_buckets {num_buckets} < 1")
    mono = max(0.0, t_comm_s - overlappable_s)
    exposed = max(t_comm_s / num_buckets, mono)
    return exposed + num_buckets * 2 * torus_hops(n_chips) * hop_latency_s


def approx_num_buckets(param_count: int, bucket_mb: float,
                       num_leaves: int | None = None) -> int:
    """Bucket-count estimate for the analytic tables: ceil(grad bytes /
    target), capped by the leaf count when known (parallel/buckets.py
    keeps leaves atomic, so a tree can never split into more buckets than
    it has leaves — VGG's FC-dominated trees land far below the naive
    byte quotient)."""
    if bucket_mb <= 0:
        return 1
    n = max(1, math.ceil(4.0 * param_count / (bucket_mb * 1024 * 1024)))
    if num_leaves is not None:
        n = min(n, max(1, num_leaves))
    return n


def torus_hops(n_chips: int, dims: int = 3) -> int:
    """Per-direction hop count for a dimension-wise reduction on a `dims`-D
    torus of N chips (≈ dims·(N^(1/dims) − 1)); ring fallback for dims=1."""
    side = n_chips ** (1.0 / dims)
    return max(1, round(dims * (side - 1)))


@dataclasses.dataclass(frozen=True)
class Prediction:
    model: str
    layout: str
    n_chips: int
    step_time_s: float
    comm_time_s: float          # full wire time, before overlap
    exposed_comm_s: float       # what the step actually waits on
    latency_s: float
    efficiency: float           # vs the same chip running alone
    images_per_sec_per_chip: float
    host_bound_images_per_sec_per_chip: float
    binding_constraint: str     # "ici" | "host" | "compute"


def predict(point: ModelPoint, n_chips: int, *, chip: ChipSpec = V4,
            zero1: bool = False, overlap_fraction: float = 0.75,
            collective_utilization: float = 0.8,
            hop_latency_s: float = 1e-6,
            backward_fraction: float = 2.0 / 3.0,
            host_decode_per_core: float = HOST_DECODE_RATE_R9,
            grad_bytes_per_param: int = 4) -> Prediction:
    """Predicted throughput/efficiency for `point` data-parallel over
    `n_chips` of `chip`. Pure arithmetic — see module docstring.

    `grad_bytes_per_param=2` models `mesh.reduce_dtype='bfloat16'`
    (parallel/collectives.py): the GRADIENT wire moves bf16 — the lever for
    the fp32 no-overlap worst case (VGG-16). Under ZeRO-1 only the
    reduce-scatter leg narrows; the param all-gather stays fp32 by design,
    so bf16+ZeRO-1 saves 25 %, not 50 % (matches train/step.py)."""
    t_step = point.step_time_on(chip)
    wire = allreduce_bytes_per_chip(
        point.param_count * grad_bytes_per_param, n_chips, zero1=zero1,
        param_bytes=point.param_count * 4)
    bw = chip.injection_bytes_per_s * collective_utilization
    t_comm = wire / bw
    # 2 traversals (reduce + broadcast phase) of the torus' hop count
    t_lat = 2 * torus_hops(n_chips) * hop_latency_s if n_chips > 1 else 0.0
    overlappable = overlap_fraction * backward_fraction * t_step
    exposed = max(0.0, t_comm - overlappable)
    t_total = t_step + exposed + t_lat
    eff = t_step / t_total
    device_rate = point.per_chip_batch / t_total
    host_rate = (chip.host_cores * host_decode_per_core) / chip.chips_per_host
    if host_rate < device_rate:
        binding = "host"
    elif exposed + t_lat > 0.005 * t_step:
        binding = "ici"
    else:
        binding = "compute"
    return Prediction(point.name, "zero1" if zero1 else "replicated",
                      n_chips, t_step, t_comm, exposed, t_lat, eff,
                      device_rate, host_rate, binding)


def predict_table(n_chips_list: Sequence[int] = (8, 32, 128),
                  points: Sequence[ModelPoint] = MEASURED,
                  **kw) -> list[Prediction]:
    out = []
    for p in points:
        for zero1 in (False, True):
            for n in n_chips_list:
                out.append(predict(p, n, zero1=zero1, **kw))
    return out


@dataclasses.dataclass(frozen=True)
class HostProvisioning:
    model: str
    chip: str
    device_rate_img_s_chip: float   # compute-rescaled single-chip rate
    decode_per_core: float          # measured host decode rate basis
    cores_per_chip_required: float  # bare: device_rate / decode rate
    cores_per_chip_with_margin: float  # x headroom
    stock_cores_per_chip: float     # what the chip's standard host ships
    stock_sufficient: bool          # margin requirement <= stock
    stock_utilization: float        # bare requirement / stock


def host_provisioning_requirement(
        point: ModelPoint, *, chip: ChipSpec = V4,
        decode_per_core: float = HOST_DECODE_RATE_R9,
        headroom: float = 1.2) -> HostProvisioning:
    """The deployable host spec (VERDICT r4 #8): how many host cores per
    chip the input pipeline needs to sustain this model's device rate.

    cores/chip = device_rate × headroom / decode_per_core, against the
    chip's stock host (chip.host_cores / chip.chips_per_host).
    `decode_per_core` defaults to the r8-measured native-loader rate
    (HOST_DECODE_RATE_R8 — the LOWER of the committed u8-wire flagship-
    replacement pair on the quiet-host min-of-6 continuity protocol,
    benchmarks/runs/host_r9/decode_r8_u8_s2d_320noise_run{1,2}.json;
    the r7 rate 991.15, the r6 rate 1031.36, the r5 rate 728.05 and the
    FROZEN r4 baseline 556.34 appear as sensitivity rows so the spec's
    history stays visible). At the r8 rate the v5e margin WIDENS — a
    stock v5e host (28 cores/chip) covers the flagship's 22k img/s/chip
    at 23.7 cores needed incl. 1.2× headroom, a 4.3-core cushion vs the
    1.3-core one at r7 (26.7). `headroom` covers decode-rate variance — the
    measured medians moved ~±5 % between windows across r4-r7, so 1.2
    is two of those swings."""
    if headroom < 1.0:
        raise ValueError(f"headroom {headroom} < 1 would spec a host that "
                         f"stalls at the MEASURED rate")
    device_rate = point.per_chip_batch / point.step_time_on(chip)
    bare = device_rate / decode_per_core
    stock = chip.host_cores / chip.chips_per_host
    return HostProvisioning(
        point.name, chip.name, device_rate, decode_per_core, bare,
        bare * headroom, stock, bare * headroom <= stock, bare / stock)


def host_provisioning_table(points: Sequence[ModelPoint] = MEASURED,
                            **kw) -> list[HostProvisioning]:
    return [host_provisioning_requirement(p, **kw) for p in points]


@dataclasses.dataclass(frozen=True)
class RingAttentionPrediction:
    n_chips: int
    t_local: int
    hop_bytes: float            # K/V block a chip sends per hop
    hop_comm_s: float           # one ppermute hop, neighbor link only
    hop_compute_s: float        # one block's QK^T + PV GEMM work
    compute_to_comm: float      # >1 → the ring hides its own hops
    min_t_local_to_hide: int    # smallest T_local where ratio reaches 1
    ring_time_s: float          # double-buffered: own block, then N−1
    #                             arrivals each costing max(compute, comm)
    comm_exposed_fraction: float  # 1 − N·hop_compute / ring_time


def ring_attention_comm_model(
        t_local: int, n_chips: int, *, head_dim: int = 64, heads: int = 8,
        batch: int = 1, bytes_per_elem: int = 2, chip: ChipSpec = V4,
        mxu_efficiency: float = 0.5, links_used: int = 1,
        collective_utilization: float = 0.8) -> RingAttentionPrediction:
    """Analytic compute/comm balance for ring attention
    (parallel/ring_attention.py, ring_flash.py) — the long-context half of
    the scaling story. Each of the N−1 hops moves this chip's K/V block
    (2·B·T_local·H·D·bytes) to ONE neighbor (`lax.ppermute` rides a single
    ICI link, not the injection aggregate) while the MXU computes the
    current block: the FORWARD hop is two einsums (QKᵀ and P·V) of
    B·H·T_local²·D MACs each → 4·B·H·T_local²·D FLOPs (the backward ring
    does strictly more compute per hop for the same bytes, so forward is
    the conservative leg). The ratio grows LINEARLY in T_local — the
    defining property of ring attention at long context. `ring_time_s`
    models the double-buffered pipeline over `n_chips`: compute the
    resident block, then N−1 arrivals each costing
    max(hop_compute, hop_comm); `comm_exposed_fraction` is the slice of
    that wall time not covered by attention FLOPs (0 above break-even)."""
    d = head_dim
    hop_bytes = 2.0 * batch * t_local * heads * d * bytes_per_elem
    link_bw = chip.ici_link_bytes_per_s * links_used * collective_utilization
    hop_comm = hop_bytes / link_bw
    flops = 4.0 * batch * heads * (t_local ** 2) * d
    hop_compute = flops / (chip.peak_bf16_flops * mxu_efficiency)
    ratio = hop_compute / hop_comm
    # ratio(T) is linear in T — solve ratio == 1 for break-even length
    min_t = math.ceil(t_local / ratio) if ratio > 0 else 0
    ring_time = hop_compute + (n_chips - 1) * max(hop_compute, hop_comm)
    exposed = max(0.0, 1.0 - n_chips * hop_compute / ring_time)
    return RingAttentionPrediction(n_chips, t_local, hop_bytes, hop_comm,
                                   hop_compute, ratio, min_t, ring_time,
                                   exposed)


@dataclasses.dataclass(frozen=True)
class UlyssesCommPrediction:
    n_chips: int
    t_local: int
    a2a_bytes: float            # bytes one chip injects per all_to_all
    wire_bytes_total: float     # 4 all_to_alls (q, k, v, o)
    ring_wire_bytes: float      # the ppermute ring's per-chip total
    bytes_ratio_vs_ring: float  # ring / ulysses injected bytes = n/2
    comm_time_s: float          # hop-distance-serialized, all 4 a2a's
    ring_comm_time_s: float     # the ring's n−1 neighbor hops
    time_ratio_vs_ring: float   # ring / ulysses wire TIME on torus ICI
    compute_s: float            # local attention on (T, H/n) — equals the
    #                             ring's total per-chip attention FLOPs
    #                             times padding_overhead
    comm_exposed_fraction: float  # conservative: a2a's at layer edges,
    #                               nothing overlaps them
    heads_effective: int = 0    # ceil(H/n)·n — zero-padded head count
    padding_overhead: float = 1.0  # heads_effective / heads: the honest
    #                                compute-and-wire multiplier when H
    #                                doesn't divide n (parallel/ulysses.py
    #                                head padding, VERDICT r4 weak #5)


def ulysses_comm_model(
        t_local: int, n_chips: int, *, head_dim: int = 64, heads: int = 8,
        batch: int = 1, bytes_per_elem: int = 2, chip: ChipSpec = V4,
        mxu_efficiency: float = 0.5, links_used: int = 1,
        collective_utilization: float = 0.8,
        mean_hop_distance: float | None = None) -> UlyssesCommPrediction:
    """Analytic comparison of the two SP layouts (parallel/ulysses.py vs
    ring_attention.py) — same conventions as `ring_attention_comm_model`.

    Injected bytes per chip: each of the four all_to_alls (q, k, v in;
    o out) moves (n−1)/n of the local shard s = B·T_local·H·D·bytes →
    4·s·(n−1)/n total, vs the ring's 2·s·(n−1): an n/2× byte advantage.
    On torus ICI that advantage does NOT carry to wire time — all_to_all
    traffic crosses `mean_hop_distance` links (n/4 on a bidirectional
    1-D ring; the default), serializing on shared links, so the time
    advantage collapses to ≈2× — while the ring's neighbor ppermute always
    crosses exactly one link AND overlaps each hop with that block's
    matmuls. The model therefore charges ulysses its full wire time as
    exposed (`comm_exposed_fraction`), the conservative reading: its
    all_to_alls sit at layer boundaries where only cross-layer scheduling
    could hide them. Local attention FLOPs are identical in both layouts
    (H/n heads × (n·T_local)² positions = H × n × T_local² — the ring does
    the same total across its n hops) up to the head-padding overhead, so
    the layouts differ in comm and padding: prefer ulysses while its
    padding-adjusted wire time beats the ring's exposure — for divisible H
    that means T_local below ≈ HALF the
    ring's break-even (there its wire time — (n−1)·hop_comm/2 under the
    default hop-distance model — undercuts the ring's exposed
    (n−1)·(hop_comm − hop_compute); the inequality flips exactly at
    compute_to_comm = 1/2). From half-break-even up the ring is strictly
    better: its exposure shrinks to zero at break-even and stays zero,
    while the ulysses all-to-alls remain fully exposed at any length.

    Head counts that don't divide `n_chips` are zero-padded per shard
    (parallel/ulysses.py): every padded head crosses the wire and burns
    MXU cycles like a real one, so BOTH the a2a bytes and the local
    compute here use heads_effective = ceil(H/n)·n — e.g. ViT-S/16's H=6
    on n=4 is charged 8/6 = 1.33×. The ring comparison keeps the TRUE
    head count (it never pads)."""
    d = head_dim
    h_eff = -(-heads // n_chips) * n_chips
    s = float(batch * t_local * h_eff * d * bytes_per_elem)
    s_ring = float(batch * t_local * heads * d * bytes_per_elem)
    frac = (n_chips - 1) / n_chips
    a2a_bytes = s * frac
    wire_total = 4.0 * a2a_bytes
    if mean_hop_distance is None:
        mean_hop_distance = max(1.0, n_chips / 4.0)
    link_bw = chip.ici_link_bytes_per_s * links_used * collective_utilization
    a2a_time = a2a_bytes * mean_hop_distance / link_bw
    comm_time = 4.0 * a2a_time
    ring_wire = 2.0 * s_ring * (n_chips - 1)
    ring_comm = ring_wire / link_bw
    flops = 4.0 * batch * h_eff * n_chips * (t_local ** 2) * d
    compute = flops / (chip.peak_bf16_flops * mxu_efficiency)
    return UlyssesCommPrediction(
        n_chips, t_local, a2a_bytes, wire_total, ring_wire,
        ring_wire / wire_total, comm_time, ring_comm,
        ring_comm / comm_time, compute,
        comm_time / (comm_time + compute),
        h_eff, h_eff / heads)


def north_star_summary(**kw) -> dict:
    """The single judged claim: predicted v4-8 → v4-128 scaling efficiency
    for the flagship, defined the way the target reads — images/sec/chip at
    128 chips over images/sec/chip at 8 chips (device-limited; the host
    ceiling is reported separately because it binds per-HOST, identically at
    any slice size)."""
    flagship = MEASURED[0]
    at8 = predict(flagship, 8, **kw)
    at128 = predict(flagship, 128, **kw)
    return {
        "model": flagship.name,
        "efficiency_8_to_128": (at128.images_per_sec_per_chip
                                / at8.images_per_sec_per_chip),
        "predicted_at_8": at8,
        "predicted_at_128": at128,
        "host_bound_ceiling_img_s_chip": at128.host_bound_images_per_sec_per_chip,
        "note": "device-rate ratio; the host ceiling (per-host-constant, so "
                "it never bends the 8→128 ratio) cleared the flagship's "
                "device rate with ~2x margin once the r6 SIMD decode rate "
                "landed — host provisioning was the watch item through r5 "
                "and is now covered by stock hosts on both chips",
    }
