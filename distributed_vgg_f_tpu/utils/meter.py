"""Throughput measurement — images/sec and images/sec/chip are THE judged metrics
(BASELINE.json `metric`), so the meter itself is unit-testable with an injectable
clock (SURVEY.md §4).

Alongside the cumulative rate the meter keeps a ROLLING-window rate over the
last `window` updates (`window_images_per_sec`): a cumulative average hides
exactly the transient stalls the stall-attribution layer (telemetry/stall.py)
exists to classify — a 10-second infeed stall 500 steps into a window barely
moves the cumulative rate but craters the rolling one.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Callable, Optional


class ThroughputMeter:
    def __init__(self, num_chips: int, clock: Callable[[], float] = time.monotonic,
                 window: int = 20):
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        self.num_chips = max(1, num_chips)
        self.window = int(window)
        self._clock = clock
        self.reset()

    def reset(self) -> None:
        self._start = self._clock()
        self._examples = 0
        self._steps = 0
        # (time, cumulative examples) AFTER each update, seeded with the
        # window start: `window` updates back needs window+1 anchor points
        self._history: deque = deque(maxlen=self.window + 1)
        self._history.append((self._start, 0))

    def update(self, num_examples: int) -> None:
        self._examples += num_examples
        self._steps += 1
        self._history.append((self._clock(), self._examples))

    @property
    def elapsed(self) -> float:
        return max(self._clock() - self._start, 1e-9)

    @property
    def images_per_sec(self) -> float:
        return self._examples / self.elapsed

    @property
    def images_per_sec_per_chip(self) -> float:
        return self.images_per_sec / self.num_chips

    @property
    def steps_per_sec(self) -> float:
        return self._steps / self.elapsed

    @property
    def window_images_per_sec(self) -> Optional[float]:
        """Rate over (at most) the last `window` updates; None before the
        first update."""
        if len(self._history) < 2:
            return None
        t0, n0 = self._history[0]
        t1, n1 = self._history[-1]
        return (n1 - n0) / max(t1 - t0, 1e-9)

    def snapshot(self) -> dict:
        out = {
            "images_per_sec": self.images_per_sec,
            "images_per_sec_per_chip": self.images_per_sec_per_chip,
            "steps_per_sec": self.steps_per_sec,
        }
        window_rate = self.window_images_per_sec
        if window_rate is not None:
            out["window_images_per_sec"] = window_rate
        return out
