"""Throughput measurement — images/sec and images/sec/chip are THE judged metrics
(BASELINE.json `metric`), so the meter itself is unit-testable with an injectable
clock (SURVEY.md §4)."""

from __future__ import annotations

import time
from typing import Callable


class ThroughputMeter:
    def __init__(self, num_chips: int, clock: Callable[[], float] = time.monotonic):
        self.num_chips = max(1, num_chips)
        self._clock = clock
        self.reset()

    def reset(self) -> None:
        self._start = self._clock()
        self._examples = 0
        self._steps = 0

    def update(self, num_examples: int) -> None:
        self._examples += num_examples
        self._steps += 1

    @property
    def elapsed(self) -> float:
        return max(self._clock() - self._start, 1e-9)

    @property
    def images_per_sec(self) -> float:
        return self._examples / self.elapsed

    @property
    def images_per_sec_per_chip(self) -> float:
        return self.images_per_sec / self.num_chips

    @property
    def steps_per_sec(self) -> float:
        return self._steps / self.elapsed

    def snapshot(self) -> dict:
        return {
            "images_per_sec": self.images_per_sec,
            "images_per_sec_per_chip": self.images_per_sec_per_chip,
            "steps_per_sec": self.steps_per_sec,
        }
