"""Profiling / tracing subsystem (SURVEY.md §5 tracing).

Reference: at most TF-timeline prints. Here: `jax.profiler` traces — the
TPU-native tool — captured for a small window of steps mid-run (after compile
and warmup) so the trace shows steady-state device time, ICI collectives, and
host-infeed gaps. View with TensorBoard's profile plugin or Perfetto.
"""

from __future__ import annotations

import jax


class StepProfiler:
    """Captures a `jax.profiler` trace over steps [start, start+num_steps).

    Driven by the trainer loop: call `step(i)` once per step with the global
    step index; the trace starts/stops at the window edges. `stop()` is
    idempotent and must run on interrupted loops (the trainer calls it in a
    finally block) — an unterminated trace corrupts the output directory.
    """

    def __init__(self, logdir: str, *, start_step: int, num_steps: int = 5):
        self.logdir = logdir
        self.start_step = start_step
        self.end_step = start_step + num_steps
        self._active = False
        self.captured = False

    def step(self, global_step: int, sync=None) -> None:
        """`sync`: zero-arg callable that drains the device queue (e.g.
        `lambda: jax.device_get(state.step)`). JAX dispatch is async, so
        without it the trace window brackets host *dispatch* of the windowed
        steps while the device is still executing earlier ones. (On this
        machine's tunneled backend only a value fetch syncs —
        `block_until_ready` does not — so the caller supplies the fetch.)"""
        if not self.captured and not self._active \
                and global_step >= self.start_step:
            if sync is not None:
                sync()
            jax.profiler.start_trace(self.logdir)
            self._active = True
        elif self._active and global_step >= self.end_step:
            if sync is not None:
                sync()
            self.stop()

    def stop(self) -> None:
        if self._active:
            jax.profiler.stop_trace()
            self._active = False
            self.captured = True


def annotate(name: str):
    """Named host-side region, visible on the trace timeline
    (`jax.profiler.TraceAnnotation`). Use around host work (input feed,
    checkpoint save) to attribute host-device gaps."""
    return jax.profiler.TraceAnnotation(name)
