"""Analytic FLOP counting from the jaxpr (VERDICT r2 #8: validate `mfu_est`).

`bench.py` derives its MFU estimate from XLA's compiled-program
`cost_analysis()`, which reflects what the compiler SCHEDULED — fusions can
double-count (a recomputed value costs twice) and backend-specific rewrites
shift totals, so it is not a stable "useful work" denominator. This module
counts matmul/conv FLOPs by walking the traced jaxpr instead: shape-exact,
backend-independent, no compilation, and counted BEFORE optimization — the
standard definition MFU wants (useful FLOPs / peak).

Counted primitives: `conv_general_dilated` and `dot_general` (where ~all
model FLOPs live — MXU work). Elementwise/reduction ops are ignored; on a
CNN/ViT they are <2 % of FLOPs and are exactly the ops XLA fuses to free.
Sub-jaxprs (pjit, shard_map, custom-vjp calls, scan/cond) are walked
recursively; scan multiplies by trip count, cond takes the widest branch,
and shard_map multiplies by the mesh size it maps over — so the returned
total is whole-program, matching cost_analysis semantics (divide by chip
count for per-chip).
"""

from __future__ import annotations

import math

import jax
from jax.extend import core as jex_core


def _conv_flops(eqn) -> float:
    """2 × output_elements × kernel_elements_per_output. The kernel's input-
    channel dim is already per-group (grouped/depthwise convs included)."""
    out = eqn.outvars[0].aval
    rhs = eqn.invars[1].aval
    dnums = eqn.params["dimension_numbers"]
    spatial = [rhs.shape[d] for d in dnums.rhs_spec[2:]]
    cin_per_group = rhs.shape[dnums.rhs_spec[1]]
    return 2.0 * math.prod(out.shape) * math.prod(spatial) * cin_per_group


def _dot_flops(eqn) -> float:
    """2 × batch × M × N × K."""
    lhs, rhs = eqn.invars[0].aval, eqn.invars[1].aval
    (lhs_c, rhs_c), (lhs_b, rhs_b) = eqn.params["dimension_numbers"]
    k = math.prod(lhs.shape[d] for d in lhs_c)
    b = math.prod(lhs.shape[d] for d in lhs_b)
    m = math.prod(s for d, s in enumerate(lhs.shape)
                  if d not in set(lhs_c) | set(lhs_b))
    n = math.prod(s for d, s in enumerate(rhs.shape)
                  if d not in set(rhs_c) | set(rhs_b))
    return 2.0 * b * m * n * k


def _sub_jaxprs(params: dict) -> list:
    subs = []
    for v in params.values():
        vals = v if isinstance(v, (list, tuple)) else [v]
        for item in vals:
            if isinstance(item, jex_core.ClosedJaxpr):
                subs.append(("plain", item.jaxpr))
            elif isinstance(item, jex_core.Jaxpr):
                subs.append(("plain", item))
    return subs


def walk_matmul_eqns(jaxpr, visit, mult: float = 1.0) -> None:
    """THE traversal: calls `visit(eqn, mult)` for every conv/dot equation,
    with `mult` carrying the structural multipliers — scan × trip count,
    cond → widest branch (by FLOPs), shard_map × mesh size (per-shard
    shapes scaled back to the whole program, matching cost_analysis).
    Single copy shared by the FLOP counter here and the roofline
    extractor (utils/mxu_model.views_from_jaxpr) so the two can never
    diverge on walk rules (code-review r5)."""
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name in ("conv_general_dilated", "dot_general"):
            visit(eqn, mult)
        elif name == "scan":
            length = float(eqn.params.get("length", 1))
            for _, sub in _sub_jaxprs(eqn.params):
                walk_matmul_eqns(sub, visit, mult * length)
        elif name == "cond":
            branches = eqn.params.get("branches", [])
            if branches:
                widest = max(branches, key=lambda b: _walk(b.jaxpr, 1.0))
                walk_matmul_eqns(widest.jaxpr, visit, mult)
        elif name == "shard_map":
            mesh = eqn.params.get("mesh")
            size = float(getattr(mesh, "size", 1) or 1)
            for _, sub in _sub_jaxprs(eqn.params):
                walk_matmul_eqns(sub, visit, mult * size)
        else:
            for _, sub in _sub_jaxprs(eqn.params):
                walk_matmul_eqns(sub, visit, mult)


def _walk(jaxpr, mult: float) -> float:
    total = 0.0

    def visit(eqn, m):
        nonlocal total
        total += m * (_conv_flops(eqn)
                      if eqn.primitive.name == "conv_general_dilated"
                      else _dot_flops(eqn))

    walk_matmul_eqns(jaxpr, visit, mult)
    return total


def jaxpr_flops(fn, *args, **kwargs) -> float:
    """Whole-program matmul/conv FLOPs of `fn(*args)` by tracing (no
    compile). For a jitted train step this includes forward AND backward
    (grad is already part of the traced program)."""
    closed = jax.make_jaxpr(fn, **kwargs)(*args)
    return _walk(closed.jaxpr, 1.0)


def conv_fc_reference_flops(layers, batch: int) -> float:
    """Hand formula for a plain conv/fc stack — the oracle the jaxpr counter
    is tested against. `layers`: sequence of
    ("conv", H_out, W_out, K_h, K_w, C_in, C_out) |
    ("fc", in_dim, out_dim). Forward only."""
    total = 0.0
    for layer in layers:
        if layer[0] == "conv":
            _, ho, wo, kh, kw, cin, cout = layer
            total += 2.0 * batch * ho * wo * kh * kw * cin * cout
        else:
            _, din, dout = layer
            total += 2.0 * batch * din * dout
    return total
