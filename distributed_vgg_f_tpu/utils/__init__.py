from distributed_vgg_f_tpu.utils.meter import ThroughputMeter  # noqa: F401
from distributed_vgg_f_tpu.utils.logging import MetricLogger  # noqa: F401
