"""Per-op achievable-MFU arithmetic — the MXU-fill bound (VERDICT r4 #3).

Rounds 3 and 4 *defended* the ResNet-50 ≈0.36 and ViT-S/16 ≈0.27 MFU
ceilings with traces (80.2 % of the ResNet step inside XLA conv fusions,
batch sweep monotone down past 256, s2d stem neutral) but never *derived*
them. This module is the derivation: the same treatment
`utils/scaling_model.py` gives communication, applied to compute.

The model. Every matmul/conv is a GEMM view (M, K, N) — M output rows,
K contraction depth, N output columns. The v5e MXU is a 128×128 systolic
array fed 8 sublanes at a time: a GEMM executes as ⌈K/128⌉ × ⌈N/128⌉ tile
passes over ⌈M/8⌉ row groups, so the fraction of MXU slots doing useful
work is

    fill(M, K, N) = (K / (⌈K/128⌉·128)) · (N / (⌈N/128⌉·128))
                    · (M / (⌈M/8⌉·8))

— e.g. a ResNet stage-1 1×1 conv (K=64, N=64) can never exceed 0.25 MFU
on this hardware *no matter how XLA schedules it*: three quarters of every
systolic pass multiplies zeros. A whole model's bound is the
FLOP-weighted harmonic mean over its GEMM views (time adds, not rates):

    achievable_mfu = Σ flops_i / Σ (flops_i / fill_i)

Tile fill alone is NOT the ResNet ceiling — computing it shows that
immediately (train-view fill bound 0.82 vs 0.36 measured). The binding
term is the memory roofline: a stage-1 1×1 conv moves ~2 bytes per
32 MACs (arithmetic intensity K·N/(K+N) ≈ 32 FLOPs/elem ≈ 16 FLOPs/byte
in bf16) against a v5e ridge of peak/bw ≈ 240 FLOPs/byte — those convs
run at ≤ ~7 % of peak no matter what, and they top the r4 trace's time
sinks exactly as this predicts. So each view is charged BOTH walls:

    time_i = max(flops_i / (peak · fill_i), bytes_i / hbm_bw)
    achievable_mfu = Σ flops_i / (peak · Σ time_i)

with `bytes_i` the real tensor traffic (conv views use B·H·W·C activation
shapes, not the never-materialized im2col operand). `max` assumes the two
pipes overlap perfectly; `serial_mfu` adds them (no overlap). The true
per-op ceiling lies between, so the committed claim is a BRACKET, scaled
by the measured non-matmul fraction of the step (`ceiling_bracket`).

The result (v5e, r4 measurements): ResNet-50 b256 bracket
[0.320, 0.468] — measured 0.364 INSIDE it; ViT-S/16 b256 bracket
[0.240, 0.399] — measured 0.267 inside it. The ~0.36/~0.27 ceilings are
thereby DERIVED from shapes: HBM-walled stage-1/2 convs (op-level
roofline ≤ 0.10 at K=N=64) and the ViT attention einsums' 64-wide head
dimension, not scheduling waste. Remaining headroom per the arithmetic:
even perfect overlap with zero non-matmul time caps ResNet-50 at 0.58 —
the levers the table exposes are fusion width (raising arithmetic
intensity across the HBM-walled 1×1 convs) and the non-matmul step
fraction, not conv scheduling.

Backward views follow the standard GEMM calculus: forward C[M,N] =
A[M,K]·B[K,N] differentiates to dA = dC·Bᵀ (view (M, N, K)) and
dB = Aᵀ·dC (view (K, M, N)); a conv's dgrad/wgrad are exactly these with
the im2col dimensions (dgrad contracts Cout·kh·kw, wgrad contracts
B·Ho·Wo). Inventories below list every conv/matmul in the shipped models
(models/resnet.py v1.5 incl. downsample projections and the FC head;
models/vit.py DeiT-S dims incl. the attention einsums whose K=64 / N=64
head dimension is the ViT ceiling's main term); their forward-FLOP totals
are pinned against the jaxpr counter (utils/flops.py) in
tests/test_mxu_model.py, so the arithmetic cannot silently drift from the
real models. Rendered into the committed artifact by
benchmarks/mxu_bounds.py.
"""

from __future__ import annotations

import dataclasses
import math

#: MXU contraction/lane tile and sublane granularity (v4/v5e/v5p alike).
MXU = 128
SUBLANES = 8

#: HBM bandwidth (bytes/s), public specs. v5e: ~819 GB/s (peak_bf16
#: 197e12 / 819e9 ≈ 240 FLOPs/byte ridge); v6e (Trillium): ~1640 GB/s.
HBM_BYTES_PER_S = {"TPU v5e": 819e9, "TPU v4": 1228e9, "TPU v5p": 2765e9,
                   "TPU v6e": 1640e9}

#: jax device_kind strings → the chip names this module's tables use.
DEVICE_KIND_TO_CHIP = {
    "TPU v4": "TPU v4",
    "TPU v5 lite": "TPU v5e", "TPU v5e": "TPU v5e",
    "TPU v5": "TPU v5p", "TPU v5p": "TPU v5p",
    "TPU v6 lite": "TPU v6e", "TPU v6e": "TPU v6e",
}

BF16 = 2  # bytes


@dataclasses.dataclass(frozen=True)
class GemmView:
    """One GEMM's (M, K, N) with a multiplicity (layer repeats × batched
    gemm count, e.g. B·H independent attention score matmuls). `bytes_`
    is the op's real HBM traffic PER count — defaults to the dense GEMM
    operands (A + B + C in bf16); conv views override it with the actual
    activation/weight tensor sizes (the im2col operand never exists in
    memory)."""
    name: str
    m: int
    k: int
    n: int
    count: int = 1
    bytes_: float | None = None

    @property
    def flops(self) -> float:
        return 2.0 * self.m * self.k * self.n * self.count

    @property
    def fill(self) -> float:
        return mxu_fill(self.m, self.k, self.n)

    @property
    def hbm_bytes(self) -> float:
        if self.bytes_ is not None:
            return self.bytes_ * self.count
        return BF16 * (self.m * self.k + self.k * self.n
                       + self.m * self.n) * self.count


def _pad_frac(x: int, tile: int) -> float:
    return x / (math.ceil(x / tile) * tile)


def mxu_fill(m: int, k: int, n: int) -> float:
    """Fraction of MXU multiply slots doing useful work for an (M, K, N)
    GEMM under 128×128 tiling with 8-row sublane groups."""
    return (_pad_frac(k, MXU) * _pad_frac(n, MXU) * _pad_frac(m, SUBLANES))


def bwd_views(v: GemmView) -> list[GemmView]:
    """The two backward GEMMs of a forward view (dA and dB). Byte traffic
    per backward GEMM mirrors the forward's tensor set (reads two of
    {activation, cotangent, weights}, writes the third), so each inherits
    the forward's byte count."""
    return [GemmView(v.name + ":dgrad", v.m, v.n, v.k, v.count, v.bytes_),
            GemmView(v.name + ":wgrad", v.k, v.m, v.n, v.count, v.bytes_)]


def train_views(fwd: list[GemmView]) -> list[GemmView]:
    """Forward + both backward views — the train-step GEMM population."""
    out = list(fwd)
    for v in fwd:
        out.extend(bwd_views(v))
    return out


def view_time_s(v: GemmView, *, peak_flops: float,
                hbm_bw: float) -> float:
    """Roofline time for one view: the slower of the MXU pipe (at its
    tile fill) and the HBM pipe."""
    return max(v.flops / (peak_flops * v.fill), v.hbm_bytes / hbm_bw)


def achievable_mfu(views: list[GemmView], *, chip: str = "TPU v5e") -> float:
    """Per-op roofline bound on model FLOPs utilization: every view charged
    max(MXU-fill time, HBM time); totals are time-additive. This is the
    PERFECT-OVERLAP reading — the true ceiling's upper edge."""
    peak = _peak(chip)
    bw = HBM_BYTES_PER_S[chip]
    total = sum(v.flops for v in views)
    t = sum(view_time_s(v, peak_flops=peak, hbm_bw=bw) for v in views)
    return total / (peak * t)


def serial_mfu(views: list[GemmView], *, chip: str = "TPU v5e") -> float:
    """The NO-OVERLAP reading (MXU time + HBM time add per op) — the true
    ceiling's lower edge. A real chip pipelines the two partially, so the
    achievable step MFU lies in [serial_mfu, achievable_mfu] — and the r4
    measurements land inside exactly that bracket for both sub-0.4
    configs (see benchmarks/mxu_bounds.py)."""
    peak = _peak(chip)
    bw = HBM_BYTES_PER_S[chip]
    total = sum(v.flops for v in views)
    t = sum(v.flops / (peak * v.fill) + v.hbm_bytes / bw for v in views)
    return total / (peak * t)


def ceiling_bracket(views: list[GemmView], matmul_fraction: float, *,
                    chip: str = "TPU v5e") -> tuple[float, float]:
    """[lower, upper] expected step-MFU ceiling: the overlap bracket scaled
    by the measured matmul fraction of the step."""
    if not 0.0 < matmul_fraction <= 1.0:
        raise ValueError(f"matmul_fraction {matmul_fraction} outside (0, 1]")
    return (serial_mfu(views, chip=chip) * matmul_fraction,
            achievable_mfu(views, chip=chip) * matmul_fraction)


def mxu_fill_bound(views: list[GemmView]) -> float:
    """The fill-only bound (no HBM term) — kept separate so the artifact
    can show WHICH wall binds: for ResNet-50 the fill bound is ~0.82 while
    the roofline bound drops to the measured regime, identifying HBM as
    the ceiling's mechanism."""
    total = sum(v.flops for v in views)
    return total / sum(v.flops / v.fill for v in views)


def _peak(chip: str) -> float:
    peaks = {"TPU v5e": 197e12, "TPU v4": 275e12, "TPU v5p": 459e12,
             "TPU v6e": 918e12}
    return peaks[chip]


def headroom_table(views: list[GemmView], *,
                   chip: str = "TPU v5e") -> list[dict]:
    """Per-view share of total roofline *time*, its fill, and which wall
    binds — the table that shows WHERE the ceiling comes from and which op
    would repay a layout change (a large `time_share` with wall='hbm' is
    a fusion/layout target; wall='mxu' with low fill is a tiling target)."""
    peak = _peak(chip)
    bw = HBM_BYTES_PER_S[chip]
    timed = [(v, view_time_s(v, peak_flops=peak, hbm_bw=bw)) for v in views]
    total = sum(t for _, t in timed)
    rows = [{"name": v.name, "m": v.m, "k": v.k, "n": v.n,
             "count": v.count, "fill": round(v.fill, 4),
             "wall": ("hbm" if v.hbm_bytes / bw
                      > v.flops / (peak * v.fill) else "mxu"),
             "op_mfu_bound": round(v.flops / (peak * t), 4),
             "time_share": round(t / total, 4),
             "flops": v.flops}
            for v, t in timed]
    rows.sort(key=lambda r: -r["time_share"])
    return rows


# ---------------------------------------------------------------------------
# Conv → GEMM views
# ---------------------------------------------------------------------------


def conv_view(name: str, batch: int, out_hw: int, cin: int, cout: int,
              kh: int = 1, kw: int | None = None, in_hw: int | None = None,
              count: int = 1) -> GemmView:
    """Forward im2col view of a conv: M = B·Ho·Wo, K = Cin·kh·kw, N = Cout.
    HBM bytes are the REAL tensors — input (B·Hi·Wi·Cin), weights, output
    (B·Ho·Wo·Cout) in bf16 — not the im2col operand, which never exists;
    `in_hw` defaults to `out_hw` (stride 1)."""
    kw = kh if kw is None else kw
    in_hw = out_hw if in_hw is None else in_hw
    bytes_ = BF16 * (batch * in_hw * in_hw * cin
                     + kh * kw * cin * cout
                     + batch * out_hw * out_hw * cout)
    return GemmView(name, batch * out_hw * out_hw, cin * kh * kw, cout,
                    count, bytes_)


# ---------------------------------------------------------------------------
# Model inventories (shapes from the shipped Flax modules)
# ---------------------------------------------------------------------------


def resnet50_fwd_views(batch: int, image: int = 224,
                       num_classes: int = 1000) -> list[GemmView]:
    """Every conv/matmul in models/resnet.py (v1.5: stride-2 on the 3×3;
    downsample projection on each stage's first block) at `image`=224:
    stem 7×7/2 → 112², maxpool/2 → 56²; stages at 56/28/14/7."""
    views = [conv_view("stem7x7", batch, 112, 3, 64, 7, in_hw=image)]
    stage_defs = [  # (width, blocks, out_hw)
        (64, 3, 56), (128, 4, 28), (256, 6, 14), (512, 3, 7)]
    in_c = 64
    for s, (w, blocks, hw) in enumerate(stage_defs):
        for b in range(blocks):
            first = b == 0
            cin = in_c if first else 4 * w
            # v1.5: conv1 1×1 at the INPUT spatial size; the 3×3 strides
            in_hw = hw * 2 if (first and s > 0) else hw
            views.append(conv_view(f"s{s + 1}b{b + 1}_c1", batch, in_hw,
                                   cin, w))
            views.append(conv_view(f"s{s + 1}b{b + 1}_c2", batch, hw, w, w,
                                   3, in_hw=in_hw))
            views.append(conv_view(f"s{s + 1}b{b + 1}_c3", batch, hw, w,
                                   4 * w))
            if first:
                views.append(conv_view(f"s{s + 1}b{b + 1}_proj", batch, hw,
                                       cin, 4 * w, in_hw=in_hw))
        in_c = 4 * w
    views.append(GemmView("fc", batch, 2048, num_classes))
    return views


def vit_s16_fwd_views(batch: int, image: int = 224, hidden: int = 384,
                      depth: int = 12, heads: int = 6, mlp: int = 1536,
                      num_classes: int = 1000) -> list[GemmView]:
    """Every matmul in models/vit.py (DeiT-S): patch-embed conv (a 768-deep
    GEMM), then per block QKV / scores / A·V / out-proj / MLP, then the
    head. T = (image/16)² + 1 = 197 — the odd token count whose 8-sublane
    padding is visible but small; the dominant fill losses are the
    attention einsums' K=64 and N=64 head dimension (fill 0.5) and T=197
    on a lane dimension (197/256 = 0.77)."""
    t = (image // 16) ** 2 + 1
    head_dim = hidden // heads
    views = [
        GemmView("patch_embed", batch * (image // 16) ** 2, 16 * 16 * 3,
                 hidden),
        GemmView("qkv", batch * t, hidden, 3 * hidden, depth),
        # per-(batch, head) score/value einsums — count = B·H·depth
        GemmView("scores_qk", t, head_dim, t, batch * heads * depth),
        GemmView("attn_av", t, t, head_dim, batch * heads * depth),
        GemmView("out_proj", batch * t, hidden, hidden, depth),
        GemmView("mlp_in", batch * t, hidden, mlp, depth),
        GemmView("mlp_out", batch * t, mlp, hidden, depth),
        GemmView("head", batch, hidden, num_classes),
    ]
    return views


def vggf_fwd_views(batch: int, num_classes: int = 1000) -> list[GemmView]:
    """models/vggf.py as it actually traces: the stem is the
    space-to-depth packed conv (11×11/4 zero-padded to 12×12 and
    rearranged to a 3×3×48 stride-1 GEMM — K = 432, what the MXU really
    contracts), and the two LRNs are the banded-matmul implementation
    (ops/lrn.py): (B·HW, C)·(C, C) band GEMMs whose C = 64 case is a
    0.25-fill op. Then the three 3×3 convs and the FC stack whose
    4096-wide GEMMs fill perfectly."""
    return [
        conv_view("conv1_s2d", batch, 54, 48, 64, 3, in_hw=56),
        GemmView("lrn1_band", batch * 54 * 54, 64, 64),
        conv_view("conv2", batch, 27, 64, 256, 5),
        GemmView("lrn2_band", batch * 27 * 27, 256, 256),
        conv_view("conv3", batch, 13, 256, 256, 3),
        conv_view("conv4", batch, 13, 256, 256, 3),
        conv_view("conv5", batch, 13, 256, 256, 3),
        GemmView("fc6", batch, 6 * 6 * 256, 4096),
        GemmView("fc7", batch, 4096, 4096),
        GemmView("fc8", batch, 4096, num_classes),
    ]


def vgg16_fwd_views(batch: int, num_classes: int = 1000) -> list[GemmView]:
    """models/vgg16.py: thirteen 3×3 convs (channel widths 64→512, all
    K ≥ 576 → fill ≥ 0.9) + the FC stack — the zoo's best measured MFU
    (0.656) and the model this arithmetic predicts the highest bound for."""
    cfg = [(64, 224, 3), (64, 224, 64),
           (128, 112, 64), (128, 112, 128),
           (256, 56, 128), (256, 56, 256), (256, 56, 256),
           (512, 28, 256), (512, 28, 512), (512, 28, 512),
           (512, 14, 512), (512, 14, 512), (512, 14, 512)]
    views = [conv_view(f"conv{i + 1}", batch, hw, cin, cout, 3)
             for i, (cout, hw, cin) in enumerate(cfg)]
    views += [GemmView("fc6", batch, 7 * 7 * 512, 4096),
              GemmView("fc7", batch, 4096, 4096),
              GemmView("fc8", batch, 4096, num_classes)]
    return views


#: Model name → forward-view builder, for the artifact generator.
INVENTORIES = {
    "resnet50": resnet50_fwd_views,
    "vit_s16": vit_s16_fwd_views,
    "vggf": vggf_fwd_views,
    "vgg16": vgg16_fwd_views,
}


# ---------------------------------------------------------------------------
# Automatic GEMM-view extraction from a traced program (any model)
# ---------------------------------------------------------------------------


def views_from_jaxpr(fn, *args) -> list[GemmView]:
    """GEMM views for EVERY conv/matmul in `fn(*args)`, by tracing — the
    roofline bound for arbitrary user models, not just the four hand
    inventories above (which remain the validated oracle:
    tests/test_mxu_model.py pins this extractor's totals against them).

    Traversal (scan × trip count, cond → widest branch, shard_map × mesh
    size) is utils/flops.walk_matmul_eqns — the same single copy the FLOP
    counter uses, so the two can never diverge on walk rules. Per view:
    (M, K, N) from the contraction structure, batch dims → `count`, and
    bytes from the REAL operand/output avals (for a conv that is input +
    kernel + output — the im2col operand never exists; for a dot the
    actual A/B/C). Grouped/depthwise convs become `groups` independent
    GEMMs of N = cout/groups each (count × groups) — modeling them as one
    wide GEMM would overstate fill by the group count. Tracing a full
    train step yields forward AND backward views directly — XLA's own
    transposed-conv backward shapes, not the synthetic bwd_views
    calculus."""
    import jax

    from distributed_vgg_f_tpu.utils.flops import walk_matmul_eqns

    views: list[GemmView] = []

    def aval_bytes(aval) -> float:
        return float(aval.size) * aval.dtype.itemsize

    def add_conv(eqn, mult):
        out = eqn.outvars[0].aval
        lhs, rhs = eqn.invars[0].aval, eqn.invars[1].aval
        dn = eqn.params["dimension_numbers"]
        groups = int(eqn.params.get("feature_group_count", 1) or 1)
        spatial = [rhs.shape[d] for d in dn.rhs_spec[2:]]
        cin_per_group = rhs.shape[dn.rhs_spec[1]]
        cout = out.shape[dn.out_spec[1]]
        batch = out.shape[dn.out_spec[0]]
        out_spatial = [out.shape[d] for d in dn.out_spec[2:]]
        m = batch * math.prod(out_spatial)
        k = cin_per_group * math.prod(spatial)
        per = ((aval_bytes(lhs) + aval_bytes(rhs) + aval_bytes(out))
               / groups)
        views.append(GemmView(
            "conv", m, k, cout // groups,
            count=max(1, round(mult * groups)), bytes_=per))

    def add_dot(eqn, mult):
        lhs, rhs = eqn.invars[0].aval, eqn.invars[1].aval
        out = eqn.outvars[0].aval
        (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
        k = math.prod(lhs.shape[d] for d in lc)
        b = math.prod(lhs.shape[d] for d in lb)
        m = math.prod(s for d, s in enumerate(lhs.shape)
                      if d not in set(lc) | set(lb))
        n = math.prod(s for d, s in enumerate(rhs.shape)
                      if d not in set(rc) | set(rb))
        if m == 0 or n == 0 or k == 0:
            return
        # batched GEMMs: per-element operand/output bytes, batch → count
        per = ((aval_bytes(lhs) + aval_bytes(rhs) + aval_bytes(out))
               / max(1, b))
        views.append(GemmView("dot", m, k, n,
                              count=max(1, round(b * mult)), bytes_=per))

    def visit(eqn, mult):
        if eqn.primitive.name == "conv_general_dilated":
            add_conv(eqn, mult)
        else:
            add_dot(eqn, mult)

    closed = jax.make_jaxpr(fn)(*args)
    walk_matmul_eqns(closed.jaxpr, visit, 1.0)
    return views


def roofline_report(fn, *args, chip: str = "TPU v5e") -> dict:
    """One-call roofline bounds for an arbitrary traced computation — the
    user-facing surface of this module: pass any model's apply (or a whole
    train step) and get the achievable-MFU bracket plus the op table that
    names which wall binds. `views_from_jaxpr` supplies the views; tracing
    a full train step includes backward automatically."""
    views = views_from_jaxpr(fn, *args)
    return {
        "chip": chip,
        "gemm_views": len(views),
        "total_gflops": round(sum(v.flops for v in views) / 1e9, 3),
        "mxu_fill_bound": round(mxu_fill_bound(views), 4),
        "roofline_overlap_bound": round(achievable_mfu(views, chip=chip), 4),
        "roofline_serial_bound": round(serial_mfu(views, chip=chip), 4),
        "top_ops": headroom_table(views, chip=chip)[:10],
    }
