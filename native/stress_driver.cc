// Concurrency stress harness for the native jpeg loader — built with the
// sanitizer in the MAIN executable (native/Makefile stress_driver.{asan,tsan})
// so TSan observes every pthread from birth; preloading the runtime into an
// uninstrumented interpreter only instruments the .so's own threads after
// the fact and misses lock orders established during startup.
//
// Drives the exact surfaces the tier-1 suite can only exercise politely:
//   A  runtime pool resize hammered WHILE a consumer drains batches and a
//      third thread polls num_threads/decode_errors/stats (ABI v8 grow/
//      shrink races against the claim loop and the retire path)
//   B  ChunkPool fan-out: restart-marker excerpt decode of one image split
//      across pool threads, called concurrently from several client threads
//   C  producer-consumer: two independent loaders draining on their own
//      threads while the main thread reads + resets the process-wide stats
//      (the cumulative atomics are shared across all loaders)
//   D  create/seek/next/destroy churn across threads (handle lifecycle vs
//      the lazily-started worker pool)
//
// Exit 0 = every phase completed and every decode returned the expected rc.
// Any sanitizer report fails the run via halt_on_error=1 (set by the pytest
// wrapper, tests/test_sanitizers.py). The driver is deliberately a single
// translation unit including jpeg_loader.cc: the sanitizer instruments the
// whole library with no separate-TU blind spots.

#include "jpeg_loader.cc"

#include <sys/stat.h>
#include <unistd.h>

#include <cassert>
#include <cstdio>
#include <random>
#include <thread>

namespace {

// Synthesize a baseline JPEG in memory with libjpeg itself — the driver has
// no file-format dependencies beyond the library it stresses.
std::vector<uint8_t> synth_jpeg(int w, int h, unsigned seed, int quality) {
  std::vector<uint8_t> rgb((size_t)w * h * 3);
  std::mt19937 rng(seed);
  // Textured, not noise: smooth gradients + per-pixel jitter keeps the
  // entropy stream realistic (pure noise defeats the DCT and bloats files).
  for (int y = 0; y < h; ++y)
    for (int x = 0; x < w; ++x) {
      size_t i = ((size_t)y * w + x) * 3;
      rgb[i + 0] = (uint8_t)((x * 255) / w + (int)(rng() % 32));
      rgb[i + 1] = (uint8_t)((y * 255) / h + (int)(rng() % 32));
      rgb[i + 2] = (uint8_t)(((x + y) * 255) / (w + h) + (int)(rng() % 32));
    }
  jpeg_compress_struct cinfo;
  JerrMgr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = jerr_exit;
  jpeg_create_compress(&cinfo);
  unsigned char* buf = nullptr;
  unsigned long size = 0;
  if (setjmp(jerr.jb)) {
    jpeg_destroy_compress(&cinfo);
    if (buf) free(buf);
    return {};
  }
  jpeg_mem_dest(&cinfo, &buf, &size);
  cinfo.image_width = (JDIMENSION)w;
  cinfo.image_height = (JDIMENSION)h;
  cinfo.input_components = 3;
  cinfo.in_color_space = JCS_RGB;
  jpeg_set_defaults(&cinfo);
  jpeg_set_quality(&cinfo, quality, TRUE);
  jpeg_start_compress(&cinfo, TRUE);
  while (cinfo.next_scanline < cinfo.image_height) {
    JSAMPROW row = &rgb[(size_t)cinfo.next_scanline * w * 3];
    jpeg_write_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_compress(&cinfo);
  jpeg_destroy_compress(&cinfo);
  std::vector<uint8_t> out(buf, buf + size);
  free(buf);
  return out;
}

struct Corpus {
  std::vector<std::string> paths;
  std::string blob;
  std::vector<int64_t> path_offsets;
  std::vector<int32_t> item_path;
  std::vector<int64_t> item_offset, item_length;
  std::vector<int32_t> labels;
};

Corpus write_corpus(const std::string& dir, int n, int w, int h) {
  Corpus c;
  c.path_offsets.push_back(0);
  for (int i = 0; i < n; ++i) {
    auto bytes = synth_jpeg(w, h, (unsigned)(1234 + i), 88);
    assert(!bytes.empty());
    std::string p = dir + "/stress_" + std::to_string(i) + ".jpg";
    FILE* f = fopen(p.c_str(), "wb");
    assert(f);
    fwrite(bytes.data(), 1, bytes.size(), f);
    fclose(f);
    c.paths.push_back(p);
    c.blob += p;
    c.path_offsets.push_back((int64_t)c.blob.size());
    c.item_path.push_back(i);
    c.item_offset.push_back(-1);  // whole file
    c.item_length.push_back(0);
    c.labels.push_back(i % 7);
  }
  return c;
}

const float kMean[3] = {0.f, 0.f, 0.f};
const float kStd[3] = {1.f, 1.f, 1.f};

void* make_loader(const Corpus& c, int batch, int out_size, uint64_t seed,
                  int threads) {
  return dvgg_jpeg_loader_create_ranged(
      c.blob.c_str(), c.path_offsets.data(), (int64_t)c.paths.size(),
      c.item_path.data(), c.item_offset.data(), c.item_length.data(),
      c.labels.data(), (int64_t)c.labels.size(), batch, out_size, seed,
      kMean, kStd, threads, /*out_kind=*/0, 0.3, 1.0, /*eval_mode=*/0,
      /*finite=*/0, /*pack4=*/0);
}

// --- Phase A: live pool resize under load ---------------------------------
int phase_resize_under_load(const Corpus& c) {
  void* h = make_loader(c, 8, 64, 42, 2);
  assert(h);
  std::atomic<bool> stop{false};
  std::atomic<int> bad{0};
  std::thread consumer([&] {
    std::vector<uint8_t> img((size_t)8 * 64 * 64 * 3 * 4);
    std::vector<int32_t> lab(8);
    for (int i = 0; i < 48; ++i)
      if (dvgg_jpeg_loader_next(h, img.data(), lab.data()) != 0) bad++;
    stop = true;
  });
  std::thread poller([&] {
    int64_t stats[16];
    while (!stop.load()) {
      (void)dvgg_jpeg_loader_num_threads(h);
      (void)dvgg_jpeg_loader_decode_errors(h);
      dvgg_jpeg_decode_stats(stats);
      dvgg_jpeg_profile_ns(stats);
      std::this_thread::sleep_for(std::chrono::milliseconds(1));
    }
  });
  // hammer grow/shrink against the live claim loop
  for (int i = 0; !stop.load() && i < 1000; ++i) {
    int target = 1 + (i % 8);
    int got = dvgg_jpeg_loader_set_threads(h, target);
    if (got >= 0 && got != target) bad++;
    std::this_thread::sleep_for(std::chrono::milliseconds(2));
  }
  consumer.join();
  poller.join();
  dvgg_jpeg_loader_destroy(h);
  return bad.load();
}

// --- Phase B: restart-marker ChunkPool fan-out ----------------------------
int phase_fanout(const std::string& dir) {
  auto plain = synth_jpeg(512, 512, 777, 90);
  assert(!plain.empty());
  std::vector<uint8_t> marked(plain.size() * 2 + 65536);
  int64_t n = dvgg_jpeg_reencode_restart(plain.data(), (int64_t)plain.size(),
                                         /*interval_mcus=*/0, marked.data(),
                                         (int64_t)marked.size());
  if (n <= 0) return 1;
  marked.resize((size_t)n);
  dvgg_jpeg_set_restart(1);
  dvgg_jpeg_set_restart_fanout(8);
  std::atomic<int> bad{0};
  std::vector<std::thread> clients;
  for (int t = 0; t < 4; ++t)
    clients.emplace_back([&, t] {
      std::vector<uint8_t> out((size_t)96 * 96 * 3 * 4);
      for (int i = 0; i < 8; ++i) {
        int rc = dvgg_jpeg_decode_single(
            marked.data(), (int64_t)marked.size(), 96, kMean, kStd,
            /*out_kind=*/0, /*pack4=*/0, /*eval_mode=*/0, /*hflip=*/1,
            0.3, 1.0, (uint64_t)(t * 100 + i), out.data());
        if (rc != 0) bad++;
      }
    });
  for (auto& t : clients) t.join();
  dvgg_jpeg_set_restart_fanout(1);
  (void)dir;
  return bad.load();
}

// --- Phase C: independent producers + stats reader ------------------------
int phase_producer_consumer(const Corpus& c) {
  std::atomic<int> bad{0};
  std::atomic<bool> stop{false};
  auto produce = [&](uint64_t seed) {
    void* h = make_loader(c, 4, 48, seed, 3);
    if (!h) { bad++; return; }
    std::vector<uint8_t> img((size_t)4 * 48 * 48 * 3 * 4);
    std::vector<int32_t> lab(4);
    for (int i = 0; i < 32; ++i)
      if (dvgg_jpeg_loader_next(h, img.data(), lab.data()) != 0) bad++;
    dvgg_jpeg_loader_destroy(h);
  };
  std::thread p1(produce, 1), p2(produce, 2);
  std::thread reader([&] {
    int64_t buf[16];
    while (!stop.load()) {
      dvgg_jpeg_restart_stats(buf);
      dvgg_jpeg_decode_stats(buf);
      dvgg_jpeg_decode_stats_reset();
      std::this_thread::sleep_for(std::chrono::milliseconds(1));
    }
  });
  p1.join();
  p2.join();
  stop = true;
  reader.join();
  return bad.load();
}

// --- Phase D: handle lifecycle churn --------------------------------------
int phase_churn(const Corpus& c) {
  std::atomic<int> bad{0};
  std::vector<std::thread> threads;
  for (int t = 0; t < 4; ++t)
    threads.emplace_back([&, t] {
      for (int i = 0; i < 6; ++i) {
        void* h = make_loader(c, 2, 32, (uint64_t)(t * 10 + i), 2);
        if (!h) { bad++; continue; }
        dvgg_jpeg_loader_seek(h, i);  // pre-start seek, per the contract
        std::vector<uint8_t> img((size_t)2 * 32 * 32 * 3 * 4);
        std::vector<int32_t> lab(2);
        if (dvgg_jpeg_loader_next(h, img.data(), lab.data()) != 0) bad++;
        dvgg_jpeg_loader_destroy(h);
      }
    });
  for (auto& t : threads) t.join();
  return bad.load();
}

}  // namespace

int main(int argc, char** argv) {
  std::string dir = argc > 1 ? argv[1] : "/tmp";
  struct stat st;
  if (stat(dir.c_str(), &st) != 0 || !S_ISDIR(st.st_mode)) {
    std::fprintf(stderr, "scratch dir %s missing\n", dir.c_str());
    return 2;
  }
  Corpus c = write_corpus(dir, 12, 160, 160);
  int bad = 0;
  bad += phase_resize_under_load(c);
  std::fprintf(stderr, "[stress] resize_under_load done (bad=%d)\n", bad);
  bad += phase_fanout(dir);
  std::fprintf(stderr, "[stress] fanout done (bad=%d)\n", bad);
  bad += phase_producer_consumer(c);
  std::fprintf(stderr, "[stress] producer_consumer done (bad=%d)\n", bad);
  bad += phase_churn(c);
  std::fprintf(stderr, "[stress] churn done (bad=%d)\n", bad);
  for (const auto& p : c.paths) unlink(p.c_str());
  if (bad) {
    std::fprintf(stderr, "[stress] FAILED: %d bad results\n", bad);
    return 1;
  }
  std::fprintf(stderr, "[stress] OK\n");
  return 0;
}
