// Native TFRecord → JPEG-range indexer for distributed_vgg_f_tpu.
//
// Role (SURVEY.md §2.2 native layer): the standard ImageNet distribution is
// TFRecord shards of tf.train.Example protos ({"image/encoded": bytes,
// "image/class/label": int64} — data/imagenet.py IMAGE_FEATURES). This
// library indexes those shards ONCE, emitting per record the absolute byte
// range of the encoded JPEG inside the shard file plus the integer label —
// exactly the (path, offset, length) items jpeg_loader.cc's ranged loader
// decodes. After indexing, training reads JPEG bytes straight out of the
// TFRecord files with no TensorFlow, no proto library, and no per-step
// parsing: the whole tf.data TFRecordDataset → parse_single_example →
// decode path collapses into pread + libjpeg partial decode.
//
// TFRecord framing (each record):
//   uint64 length (LE) | uint32 masked-crc32c(length) | payload | u32 crc
// The length CRC (12 bytes) is ALWAYS verified — it is what detects
// truncation/corruption of the framing walk. The payload CRC is optional
// (verify_payload_crc): checking it requires reading every payload byte,
// whereas the indexer otherwise SKIPS the JPEG values via fseek and reads
// only ~tens of bytes of proto around them.
//
// Proto wire parse (no protoc): Example{1: Features{1: map entry{1: key,
// 2: Feature{1: BytesList{1: bytes} | 3: Int64List{1: varint|packed}}}}}.
// Unknown fields/keys are skipped by length; field order is not assumed.
//
// C ABI (ctypes):
//   dvgg_tfrecord_index_create(path, verify_payload_crc) -> handle (never 0)
//   dvgg_tfrecord_index_size(h)   -> #records with a JPEG, or -1 on error
//   dvgg_tfrecord_index_error(h)  -> error message ("" if ok)
//   dvgg_tfrecord_index_fill(h, offsets, lengths, labels)  (size() entries;
//       label is int64; records missing a label get -1)
//   dvgg_tfrecord_index_destroy(h)

#include <algorithm>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

namespace {

// ---------------------------------------------------------------- crc32c
struct Crc32cTable {
  uint32_t t[256];
  Crc32cTable() {
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = i;
      for (int k = 0; k < 8; ++k)
        c = (c & 1) ? (0x82f63b78u ^ (c >> 1)) : (c >> 1);
      t[i] = c;
    }
  }
};

uint32_t crc32c(const uint8_t* data, size_t n) {
  static const Crc32cTable tbl;
  uint32_t c = 0xffffffffu;
  for (size_t i = 0; i < n; ++i) c = tbl.t[(c ^ data[i]) & 0xff] ^ (c >> 8);
  return c ^ 0xffffffffu;
}

// TFRecord's masked CRC (the TensorFlow masking constant).
uint32_t masked_crc32c(const uint8_t* data, size_t n) {
  uint32_t c = crc32c(data, n);
  return ((c >> 15) | (c << 17)) + 0xa282ead8u;
}

// ---------------------------------------------------------------- reader
// Small-buffer reader with absolute positions: proto walking reads a few
// dozen bytes per record while fseek skips the JPEG values, so the index
// pass costs ~buffer-size bytes of IO per record, not the dataset size.
class Reader {
 public:
  explicit Reader(const char* path) : f_(std::fopen(path, "rb")) {
    if (f_) {
      std::fseek(f_, 0, SEEK_END);
      file_size_ = std::ftell(f_);
      std::fseek(f_, 0, SEEK_SET);
    }
  }
  ~Reader() {
    if (f_) std::fclose(f_);
  }
  bool ok() const { return f_ != nullptr; }
  int64_t file_size() const { return file_size_; }

  // Copy [pos, pos+n) into out. False past EOF / on IO error.
  bool read_at(int64_t pos, uint8_t* out, size_t n) {
    if (pos < 0 || pos + (int64_t)n > file_size_) return false;
    size_t done = 0;
    while (done < n) {
      if (pos + (int64_t)done >= buf_pos_ &&
          pos + (int64_t)done < buf_pos_ + (int64_t)buf_len_) {
        size_t o = (size_t)(pos + done - buf_pos_);
        size_t take = std::min(n - done, buf_len_ - o);
        std::memcpy(out + done, buf_ + o, take);
        done += take;
      } else if (!fill(pos + (int64_t)done)) {
        return false;
      }
    }
    return true;
  }

 private:
  bool fill(int64_t pos) {
    if (std::fseek(f_, (long)pos, SEEK_SET) != 0) return false;
    size_t n = std::fread(buf_, 1, sizeof(buf_), f_);
    if (n == 0) return false;
    buf_pos_ = pos;
    buf_len_ = n;
    return true;
  }

  FILE* f_;
  int64_t file_size_ = 0;
  uint8_t buf_[4096];
  int64_t buf_pos_ = -1;
  size_t buf_len_ = 0;
};

// ---------------------------------------------------------------- varint
// Parse a varint at *pos (< end); advances *pos. False on malformed/overrun.
bool read_varint(Reader& r, int64_t* pos, int64_t end, uint64_t* out) {
  uint64_t v = 0;
  for (int shift = 0; shift < 64; shift += 7) {
    if (*pos >= end) return false;
    uint8_t b;
    if (!r.read_at((*pos)++, &b, 1)) return false;
    v |= (uint64_t)(b & 0x7f) << shift;
    if (!(b & 0x80)) {
      *out = v;
      return true;
    }
  }
  return false;
}

// Skip a field of wire type `wt` whose tag was already consumed.
bool skip_field(Reader& r, int64_t* pos, int64_t end, uint32_t wt) {
  uint64_t tmp;
  switch (wt) {
    case 0:
      return read_varint(r, pos, end, &tmp);
    case 1:
      *pos += 8;
      return *pos <= end;
    case 2:
      if (!read_varint(r, pos, end, &tmp)) return false;
      *pos += (int64_t)tmp;
      return *pos <= end;
    case 5:
      *pos += 4;
      return *pos <= end;
    default:
      return false;  // groups (3/4) don't appear in Example
  }
}

struct RecordInfo {
  int64_t jpeg_off = -1;
  int64_t jpeg_len = -1;
  int64_t label = -1;
};

// Feature{1: BytesList{1: repeated bytes} | 2: FloatList | 3: Int64List}.
// `want_bytes`: capture the first bytes value's absolute range; else parse
// the first int64 (unpacked varint or packed list).
bool parse_feature(Reader& r, int64_t pos, int64_t end, bool want_bytes,
                   RecordInfo* out) {
  while (pos < end) {
    uint64_t tag;
    if (!read_varint(r, &pos, end, &tag)) return false;
    uint32_t field = (uint32_t)(tag >> 3), wt = (uint32_t)(tag & 7);
    if (want_bytes && field == 1 && wt == 2) {  // BytesList
      uint64_t list_len;
      if (!read_varint(r, &pos, end, &list_len)) return false;
      int64_t list_end = pos + (int64_t)list_len;
      if (list_end > end) return false;
      while (pos < list_end) {
        uint64_t vtag;
        if (!read_varint(r, &pos, list_end, &vtag)) return false;
        if ((vtag >> 3) == 1 && (vtag & 7) == 2) {
          uint64_t blen;
          if (!read_varint(r, &pos, list_end, &blen)) return false;
          out->jpeg_off = pos;
          out->jpeg_len = (int64_t)blen;
          return true;  // first value wins
        }
        if (!skip_field(r, &pos, list_end, (uint32_t)(vtag & 7))) return false;
      }
      pos = list_end;
    } else if (!want_bytes && field == 3 && wt == 2) {  // Int64List
      uint64_t list_len;
      if (!read_varint(r, &pos, end, &list_len)) return false;
      int64_t list_end = pos + (int64_t)list_len;
      if (list_end > end) return false;
      while (pos < list_end) {
        uint64_t vtag;
        if (!read_varint(r, &pos, list_end, &vtag)) return false;
        uint32_t vf = (uint32_t)(vtag >> 3), vwt = (uint32_t)(vtag & 7);
        if (vf == 1 && vwt == 0) {  // unpacked varint
          uint64_t v;
          if (!read_varint(r, &pos, list_end, &v)) return false;
          out->label = (int64_t)v;
          return true;
        }
        if (vf == 1 && vwt == 2) {  // packed
          uint64_t plen;
          if (!read_varint(r, &pos, list_end, &plen)) return false;
          int64_t pend = pos + (int64_t)plen;
          uint64_t v;
          if (pend > list_end || !read_varint(r, &pos, pend, &v)) return false;
          out->label = (int64_t)v;
          return true;
        }
        if (!skip_field(r, &pos, list_end, vwt)) return false;
      }
      pos = list_end;
    } else if (!skip_field(r, &pos, end, wt)) {
      return false;
    }
  }
  return true;  // reached end cleanly; value simply absent (fields stay -1)
}

// One features-map entry: {1: key string, 2: Feature}. Field order is not
// assumed: ranges are captured first, then the value is parsed per the key.
bool parse_map_entry(Reader& r, int64_t pos, int64_t end, RecordInfo* out) {
  std::string key;
  int64_t val_pos = -1, val_end = -1;
  while (pos < end) {
    uint64_t tag;
    if (!read_varint(r, &pos, end, &tag)) return false;
    uint32_t field = (uint32_t)(tag >> 3), wt = (uint32_t)(tag & 7);
    if (field == 1 && wt == 2) {
      uint64_t klen;
      if (!read_varint(r, &pos, end, &klen)) return false;
      if (pos + (int64_t)klen > end || klen > 256) return false;
      key.resize((size_t)klen);
      if (klen && !r.read_at(pos, (uint8_t*)&key[0], (size_t)klen))
        return false;
      pos += (int64_t)klen;
    } else if (field == 2 && wt == 2) {
      uint64_t vlen;
      if (!read_varint(r, &pos, end, &vlen)) return false;
      val_pos = pos;
      val_end = pos + (int64_t)vlen;
      if (val_end > end) return false;
      pos = val_end;
    } else if (!skip_field(r, &pos, end, wt)) {
      return false;
    }
  }
  if (val_pos < 0) return true;  // entry without a value — ignore
  if (key == "image/encoded")
    return parse_feature(r, val_pos, val_end, /*want_bytes=*/true, out);
  if (key == "image/class/label")
    return parse_feature(r, val_pos, val_end, /*want_bytes=*/false, out);
  return true;  // unknown key — ignore
}

// Example payload: {1: Features{1: repeated map entry}}.
bool parse_example(Reader& r, int64_t pos, int64_t end, RecordInfo* out) {
  while (pos < end) {
    uint64_t tag;
    if (!read_varint(r, &pos, end, &tag)) return false;
    uint32_t field = (uint32_t)(tag >> 3), wt = (uint32_t)(tag & 7);
    if (field == 1 && wt == 2) {  // Features
      uint64_t flen;
      if (!read_varint(r, &pos, end, &flen)) return false;
      int64_t fend = pos + (int64_t)flen;
      if (fend > end) return false;
      while (pos < fend) {
        uint64_t etag;
        if (!read_varint(r, &pos, fend, &etag)) return false;
        if ((etag >> 3) == 1 && (etag & 7) == 2) {  // map entry
          uint64_t elen;
          if (!read_varint(r, &pos, fend, &elen)) return false;
          int64_t eend = pos + (int64_t)elen;
          if (eend > fend) return false;
          if (!parse_map_entry(r, pos, eend, out)) return false;
          pos = eend;
        } else if (!skip_field(r, &pos, fend, (uint32_t)(etag & 7))) {
          return false;
        }
      }
      pos = fend;
    } else if (!skip_field(r, &pos, end, wt)) {
      return false;
    }
  }
  return true;
}

// ---------------------------------------------------------------- index
struct TfrecordIndex {
  std::vector<int64_t> offsets;  // absolute JPEG byte offset in the file
  std::vector<int64_t> lengths;
  std::vector<int64_t> labels;   // -1 when the record has no label feature
  std::string error;             // non-empty => index unusable
  int64_t skipped = 0;           // records without an image/encoded value
};

TfrecordIndex* build_index(const char* path, int verify_payload_crc) {
  auto* idx = new TfrecordIndex();
  Reader r(path);
  if (!r.ok()) {
    idx->error = "cannot open file";
    return idx;
  }
  int64_t pos = 0;
  const int64_t fsize = r.file_size();
  std::vector<uint8_t> payload;  // only allocated when verifying payload crc
  while (pos < fsize) {
    uint8_t hdr[12];
    if (!r.read_at(pos, hdr, 12)) {
      idx->error = "truncated record header at offset " + std::to_string(pos);
      break;
    }
    uint64_t len;
    uint32_t len_crc;
    std::memcpy(&len, hdr, 8);        // little-endian host assumed (x86/arm)
    std::memcpy(&len_crc, hdr + 8, 4);
    if (masked_crc32c(hdr, 8) != len_crc) {
      idx->error = "bad length crc at offset " + std::to_string(pos);
      break;
    }
    int64_t payload_off = pos + 12;
    if (payload_off + (int64_t)len + 4 > fsize) {
      idx->error = "truncated record payload at offset " + std::to_string(pos);
      break;
    }
    if (verify_payload_crc) {
      payload.resize((size_t)len + 4);
      if (!r.read_at(payload_off, payload.data(), (size_t)len + 4)) {
        idx->error = "payload read failed at offset " + std::to_string(pos);
        break;
      }
      uint32_t data_crc;
      std::memcpy(&data_crc, payload.data() + len, 4);
      if (masked_crc32c(payload.data(), (size_t)len) != data_crc) {
        idx->error = "bad payload crc at offset " + std::to_string(pos);
        break;
      }
    }
    RecordInfo info;
    if (!parse_example(r, payload_off, payload_off + (int64_t)len, &info)) {
      idx->error = "malformed Example proto at offset " + std::to_string(pos);
      break;
    }
    if (info.jpeg_off >= 0 && info.jpeg_len > 0) {
      idx->offsets.push_back(info.jpeg_off);
      idx->lengths.push_back(info.jpeg_len);
      idx->labels.push_back(info.label);
    } else {
      ++idx->skipped;
    }
    pos = payload_off + (int64_t)len + 4;
  }
  return idx;
}

}  // namespace

extern "C" {

// See jpeg_loader.cc: bumped on every C-ABI change, checked by the binding.
int64_t dvgg_tfrecord_index_abi_version() { return 1; }

void* dvgg_tfrecord_index_create(const char* path, int verify_payload_crc) {
  try {
    return build_index(path, verify_payload_crc);
  } catch (...) {
    auto* idx = new TfrecordIndex();
    idx->error = "exception while indexing";
    return idx;
  }
}

int64_t dvgg_tfrecord_index_size(void* handle) {
  auto* idx = static_cast<TfrecordIndex*>(handle);
  if (!idx || !idx->error.empty()) return -1;
  return (int64_t)idx->offsets.size();
}

const char* dvgg_tfrecord_index_error(void* handle) {
  auto* idx = static_cast<TfrecordIndex*>(handle);
  return idx ? idx->error.c_str() : "null handle";
}

int64_t dvgg_tfrecord_index_skipped(void* handle) {
  auto* idx = static_cast<TfrecordIndex*>(handle);
  return idx ? idx->skipped : -1;
}

void dvgg_tfrecord_index_fill(void* handle, int64_t* offsets,
                              int64_t* lengths, int64_t* labels) {
  auto* idx = static_cast<TfrecordIndex*>(handle);
  if (!idx) return;
  std::memcpy(offsets, idx->offsets.data(),
              idx->offsets.size() * sizeof(int64_t));
  std::memcpy(lengths, idx->lengths.data(),
              idx->lengths.size() * sizeof(int64_t));
  std::memcpy(labels, idx->labels.data(),
              idx->labels.size() * sizeof(int64_t));
}

void dvgg_tfrecord_index_destroy(void* handle) {
  delete static_cast<TfrecordIndex*>(handle);
}

}  // extern "C"
