// Native ImageNet JPEG training loader for distributed_vgg_f_tpu.
//
// Role (SURVEY.md §2.2 native layer, §7 input-pipeline hard part): the host
// JPEG decode path is the measured end-to-end bottleneck (README: one vCPU
// decodes ~370 img/s through tf.data vs ~20k img/s/chip device demand). This
// library is the framework's own native decode path for the raw-JPEG
// directory layout:
//
//   sample random-resized crop in ORIGINAL coords (area 8-100%, aspect 3/4-4/3,
//   10 attempts — the standard Inception crop the tf.data path also uses)
//   → libjpeg-turbo DCT-SCALED decode (scale M/8 chosen so the scaled crop
//     still covers the output size — decoding 1/4-1/2 of the pixels costs a
//     fraction of a full-res decode; tf.image.decode_and_crop_jpeg always
//     decodes the crop window at FULL resolution)
//   → jpeg_crop_scanline + jpeg_skip_scanlines (decode only the crop rows/MCU
//     columns) → bilinear resize to out_size → optional h-flip → mean/std
//     normalize → float32 or bfloat16 batch buffer.
//
// Threading: N workers each own an output slot ring entry and produce WHOLE
// batches (batch index b → ring slot b % depth), so batch composition and
// order are deterministic for a given seed regardless of thread count.
// Determinism: per-item RNG is derived from (seed, global item index) with
// splitmix64 — the stream is a pure function of (seed, position), which makes
// `seek(batch)` an O(1) exact resume (no iterator snapshot files needed).
//
// C ABI (ctypes, no pybind11 in this image):
//   dvgg_jpeg_loader_create(...)            -> handle (0 on error)
//   dvgg_jpeg_loader_next(handle, imgs, labels) -> 0 ok
//   dvgg_jpeg_loader_seek(handle, batch_index)  (call before first next)
//   dvgg_jpeg_loader_decode_errors(handle)  -> count of corrupt-image fallbacks
//   dvgg_jpeg_loader_destroy(handle)

#include <cstdio>  // jpeglib.h needs FILE declared first

#include <jpeglib.h>

#include <algorithm>
#include <atomic>
#include <cmath>
#include <condition_variable>
#include <csetjmp>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

struct SplitMix64 {
  uint64_t s;
  explicit SplitMix64(uint64_t seed) : s(seed) {}
  uint64_t next() {
    uint64_t z = (s += 0x9e3779b97f4a7c15ULL);
    z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ULL;
    z = (z ^ (z >> 27)) * 0x94d049bb133111ebULL;
    return z ^ (z >> 31);
  }
  double uniform() { return (next() >> 11) * (1.0 / 9007199254740992.0); }
};

inline uint64_t mix(uint64_t a, uint64_t b) {
  SplitMix64 r(a * 0x9e3779b97f4a7c15ULL + b);
  r.next();
  return r.next();
}

void shuffle_indices(std::vector<int64_t>& idx, uint64_t seed, uint64_t epoch) {
  SplitMix64 r(mix(seed, 0x5eedULL + epoch));
  for (int64_t i = (int64_t)idx.size() - 1; i > 0; --i) {
    int64_t j = (int64_t)(r.next() % (uint64_t)(i + 1));
    std::swap(idx[i], idx[j]);
  }
}

inline uint16_t f32_to_bf16(float v) {
  uint32_t bits;
  std::memcpy(&bits, &v, 4);
  // round-to-nearest-even
  uint32_t lsb = (bits >> 16) & 1;
  return (uint16_t)((bits + 0x7fffu + lsb) >> 16);
}

// ---------------------------------------------------------------- jpeg error
struct JerrMgr {
  jpeg_error_mgr pub;
  std::jmp_buf jb;
};

void jerr_exit(j_common_ptr cinfo) {
  JerrMgr* j = reinterpret_cast<JerrMgr*>(cinfo->err);
  std::longjmp(j->jb, 1);
}

// ---------------------------------------------------------------- config
struct Config {
  std::vector<std::string> paths;
  std::vector<int32_t> labels;
  int batch;
  int out_size;
  uint64_t seed;
  float mean[3];
  float std_[3];
  int num_threads;
  int bf16_out;
  double area_min, area_max;
};

// Decode `file_bytes`, random-resized-crop per `rng`, write normalized pixels
// for one item into `dst` (float32 or bf16 at item stride). Returns false on
// decode failure (caller zero-fills).
bool decode_one(const Config& cfg, const std::vector<uint8_t>& bytes,
                SplitMix64& rng, uint8_t* dst_base) {
  jpeg_decompress_struct cinfo;
  JerrMgr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = jerr_exit;
  std::vector<uint8_t> scaled;   // decoded crop region (rows x stride)
  if (setjmp(jerr.jb)) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, bytes.data(), bytes.size());
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  const int W = (int)cinfo.image_width, H = (int)cinfo.image_height;
  if (W < 1 || H < 1) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }

  // Inception-style crop sampled in original coordinates.
  int cx = 0, cy = 0, cw = W, ch = H;
  for (int attempt = 0; attempt < 10; ++attempt) {
    double area = (double)W * H *
                  (cfg.area_min + rng.uniform() * (cfg.area_max - cfg.area_min));
    double log_lo = std::log(3.0 / 4.0), log_hi = std::log(4.0 / 3.0);
    double aspect = std::exp(log_lo + rng.uniform() * (log_hi - log_lo));
    int w = (int)std::lround(std::sqrt(area * aspect));
    int h = (int)std::lround(std::sqrt(area / aspect));
    if (w > 0 && h > 0 && w <= W && h <= H) {
      cx = (int)(rng.next() % (uint64_t)(W - w + 1));
      cy = (int)(rng.next() % (uint64_t)(H - h + 1));
      cw = w;
      ch = h;
      break;
    }
  }
  const bool flip = (rng.next() & 1) != 0;

  // DCT-scaled decode: smallest M/8 (M in 1..8) whose scaled crop still
  // covers out_size in both dims — never decode more pixels than needed.
  int m = 8;
  for (int cand = 1; cand <= 8; ++cand) {
    if ((int64_t)cw * cand / 8 >= cfg.out_size &&
        (int64_t)ch * cand / 8 >= cfg.out_size) {
      m = cand;
      break;
    }
  }
  cinfo.scale_num = (unsigned)m;
  cinfo.scale_denom = 8;
  cinfo.out_color_space = JCS_RGB;
  jpeg_start_decompress(&cinfo);
  const int SW = (int)cinfo.output_width, SH = (int)cinfo.output_height;
  // crop coords in scaled space
  int sx = std::min((int)((int64_t)cx * SW / W), SW - 1);
  int sy = std::min((int)((int64_t)cy * SH / H), SH - 1);
  int sw = std::max(1, std::min((int)((int64_t)cw * SW / W), SW - sx));
  int sh = std::max(1, std::min((int)((int64_t)ch * SH / H), SH - sy));

  // horizontal MCU-aligned crop; libjpeg widens [sx, sw] to alignment
  JDIMENSION jx = (JDIMENSION)sx, jw = (JDIMENSION)sw;
  jpeg_crop_scanline(&cinfo, &jx, &jw);
  const int row_stride = (int)jw * 3;
  const int x_off = sx - (int)jx;  // offset of the true crop inside the band
  if (sy > 0) jpeg_skip_scanlines(&cinfo, (JDIMENSION)sy);
  scaled.resize((size_t)sh * row_stride);
  for (int r = 0; r < sh;) {
    JSAMPROW row = scaled.data() + (size_t)r * row_stride;
    r += (int)jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_abort_decompress(&cinfo);  // skip remaining rows without error
  jpeg_destroy_decompress(&cinfo);

  // bilinear resize (half-pixel centers) from the (sh, sw) region to out_size
  const int out = cfg.out_size;
  const float sxf = (float)sw / out, syf = (float)sh / out;
  float* f32 = nullptr;
  uint16_t* b16 = nullptr;
  if (cfg.bf16_out)
    b16 = reinterpret_cast<uint16_t*>(dst_base);
  else
    f32 = reinterpret_cast<float*>(dst_base);
  for (int oy = 0; oy < out; ++oy) {
    float fy = ((float)oy + 0.5f) * syf - 0.5f;
    int y0 = (int)std::floor(fy);
    float wy = fy - y0;
    int y1 = std::min(std::max(y0 + 1, 0), sh - 1);
    y0 = std::min(std::max(y0, 0), sh - 1);
    const uint8_t* r0 = scaled.data() + (size_t)y0 * row_stride;
    const uint8_t* r1 = scaled.data() + (size_t)y1 * row_stride;
    for (int ox = 0; ox < out; ++ox) {
      int ox_src = flip ? (out - 1 - ox) : ox;
      float fx = ((float)ox_src + 0.5f) * sxf - 0.5f;
      int x0 = (int)std::floor(fx);
      float wx = fx - x0;
      int x1 = std::min(std::max(x0 + 1, 0), sw - 1);
      x0 = std::min(std::max(x0, 0), sw - 1);
      const int p00 = (x_off + x0) * 3, p01 = (x_off + x1) * 3;
      size_t o = ((size_t)oy * out + ox) * 3;
      for (int c = 0; c < 3; ++c) {
        float top = r0[p00 + c] + wx * (r0[p01 + c] - r0[p00 + c]);
        float bot = r1[p00 + c] + wx * (r1[p01 + c] - r1[p00 + c]);
        float v = (top + wy * (bot - top) - cfg.mean[c]) / cfg.std_[c];
        if (b16)
          b16[o + c] = f32_to_bf16(v);
        else
          f32[o + c] = v;
      }
    }
  }
  return true;
}

// ---------------------------------------------------------------- loader
class JpegLoader {
 public:
  explicit JpegLoader(Config cfg)
      : cfg_(std::move(cfg)),
        item_bytes_((size_t)cfg_.out_size * cfg_.out_size * 3 *
                    (cfg_.bf16_out ? 2 : 4)),
        depth_(std::max(2, cfg_.num_threads + 1)),
        slots_(depth_) {
    for (auto& s : slots_) {
      s.images.resize(item_bytes_ * cfg_.batch);
      s.labels.resize(cfg_.batch);
      s.batch_index = -1;
    }
    next_to_produce_.store(0);
    // workers start lazily on the first next(): seek() must be able to set
    // the stream position before any batch is produced (otherwise a worker
    // already decoding batch 0 could race a post-seek worker for a slot).
  }

  ~JpegLoader() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      stop_ = true;
    }
    cv_prod_.notify_all();
    cv_cons_.notify_all();
    for (auto& t : workers_) t.join();
  }

  void seek(int64_t batch_index) {
    // only valid before the first next() (workers have not started yet); the
    // stream is a pure function of (seed, batch_index), so this IS exact
    // deterministic resume.
    std::lock_guard<std::mutex> lk(mu_);
    if (!workers_.empty()) return;  // too late — position already consumed
    consume_index_ = batch_index;
    next_to_produce_.store(batch_index);
  }

  int next(uint8_t* out_images, int32_t* out_labels) {
    std::unique_lock<std::mutex> lk(mu_);
    if (workers_.empty() && !stop_)
      for (int t = 0; t < std::max(1, cfg_.num_threads); ++t)
        workers_.emplace_back([this] { worker(); });
    Slot& s = slots_[(size_t)(consume_index_ % depth_)];
    cv_cons_.wait(lk, [&] { return stop_ || s.batch_index == consume_index_; });
    if (stop_) return 1;
    // The slot is exclusively ours while batch_index == consume_index_ (no
    // producer targets it until consume_index_ advances), so the big copy
    // runs with the lock RELEASED — holding mu_ across a multi-hundred-MB
    // memcpy would stall every decode worker each batch.
    lk.unlock();
    std::memcpy(out_images, s.images.data(), s.images.size());
    std::memcpy(out_labels, s.labels.data(),
                s.labels.size() * sizeof(int32_t));
    lk.lock();
    s.batch_index = -1;  // slot free
    ++consume_index_;
    cv_prod_.notify_all();
    return 0;
  }

  int64_t decode_errors() const { return decode_errors_.load(); }

 private:
  struct Slot {
    std::vector<uint8_t> images;
    std::vector<int32_t> labels;
    int64_t batch_index;  // -1 = free
  };

  void worker() {
    std::vector<uint8_t> bytes;
    while (true) {
      int64_t b;
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_prod_.wait(lk, [&] {
          if (stop_) return true;
          int64_t cand = next_to_produce_.load();
          return cand - consume_index_ < depth_;
        });
        if (stop_) return;
        b = next_to_produce_.fetch_add(1);
        if (b - consume_index_ >= depth_) {
          // raced past the window; undo and retry
          next_to_produce_.fetch_sub(1);
          continue;
        }
      }
      produce(b, bytes);
      {
        std::lock_guard<std::mutex> lk(mu_);
        slots_[(size_t)(b % depth_)].batch_index = b;
      }
      cv_cons_.notify_all();
    }
  }

  // index of the j-th example of batch b in the epoch-shuffled order
  int64_t item_index(int64_t global_item, std::vector<int64_t>& order,
                     int64_t& cached_epoch) {
    const int64_t n = (int64_t)cfg_.paths.size();
    int64_t epoch = global_item / n, pos = global_item % n;
    if (epoch != cached_epoch) {
      if ((int64_t)order.size() != n) {
        order.resize(n);
      }
      for (int64_t i = 0; i < n; ++i) order[i] = i;
      shuffle_indices(order, cfg_.seed, (uint64_t)epoch);
      cached_epoch = epoch;
    }
    return order[pos];
  }

  void produce(int64_t b, std::vector<uint8_t>& bytes) {
    thread_local std::vector<int64_t> order;
    thread_local int64_t cached_epoch = -1;
    Slot& s = slots_[(size_t)(b % depth_)];
    for (int j = 0; j < cfg_.batch; ++j) {
      int64_t gi = b * cfg_.batch + j;
      int64_t idx = item_index(gi, order, cached_epoch);
      s.labels[(size_t)j] = cfg_.labels[(size_t)idx];
      SplitMix64 rng(mix(cfg_.seed, 0xA0A0ULL + (uint64_t)gi));
      uint8_t* dst = s.images.data() + (size_t)j * item_bytes_;
      bool ok = false;
      FILE* f = std::fopen(cfg_.paths[(size_t)idx].c_str(), "rb");
      if (f) {
        std::fseek(f, 0, SEEK_END);
        long sz = std::ftell(f);
        std::fseek(f, 0, SEEK_SET);
        if (sz > 0) {
          bytes.resize((size_t)sz);
          if (std::fread(bytes.data(), 1, (size_t)sz, f) == (size_t)sz)
            ok = decode_one(cfg_, bytes, rng, dst);
        }
        std::fclose(f);
      }
      if (!ok) {
        std::memset(dst, 0, item_bytes_);
        decode_errors_.fetch_add(1);
      }
    }
  }

  Config cfg_;
  size_t item_bytes_;
  int depth_;
  std::vector<Slot> slots_;
  std::vector<std::thread> workers_;
  std::mutex mu_;
  std::condition_variable cv_prod_, cv_cons_;
  std::atomic<int64_t> next_to_produce_{0};
  int64_t consume_index_ = 0;
  bool stop_ = false;
  std::atomic<int64_t> decode_errors_{0};
};

}  // namespace

extern "C" {

void* dvgg_jpeg_loader_create(const char* paths_blob,
                              const int64_t* path_offsets,  // n+1 offsets
                              const int32_t* labels, int64_t n, int batch,
                              int out_size, uint64_t seed, const float* mean,
                              const float* stddev, int num_threads,
                              int bf16_out, double area_min, double area_max) {
  if (n <= 0 || batch <= 0 || out_size <= 0) return nullptr;
  Config cfg;
  cfg.paths.reserve((size_t)n);
  for (int64_t i = 0; i < n; ++i)
    cfg.paths.emplace_back(paths_blob + path_offsets[i],
                           (size_t)(path_offsets[i + 1] - path_offsets[i]));
  cfg.labels.assign(labels, labels + n);
  cfg.batch = batch;
  cfg.out_size = out_size;
  cfg.seed = seed;
  for (int c = 0; c < 3; ++c) {
    cfg.mean[c] = mean[c];
    cfg.std_[c] = stddev[c];
  }
  cfg.num_threads = std::max(1, num_threads);
  cfg.bf16_out = bf16_out;
  cfg.area_min = area_min;
  cfg.area_max = area_max;
  try {
    return new JpegLoader(std::move(cfg));
  } catch (...) {
    return nullptr;
  }
}

int dvgg_jpeg_loader_next(void* handle, void* out_images,
                          int32_t* out_labels) {
  if (!handle) return 2;
  return static_cast<JpegLoader*>(handle)->next(
      reinterpret_cast<uint8_t*>(out_images), out_labels);
}

void dvgg_jpeg_loader_seek(void* handle, int64_t batch_index) {
  if (handle) static_cast<JpegLoader*>(handle)->seek(batch_index);
}

int64_t dvgg_jpeg_loader_decode_errors(void* handle) {
  return handle ? static_cast<JpegLoader*>(handle)->decode_errors() : -1;
}

void dvgg_jpeg_loader_destroy(void* handle) {
  delete static_cast<JpegLoader*>(handle);
}

}  // extern "C"
