// Native ImageNet JPEG loader for distributed_vgg_f_tpu.
//
// Role (SURVEY.md §2.2 native layer, §7 input-pipeline hard part): the host
// JPEG decode path is the measured end-to-end bottleneck (README: one vCPU
// decodes ~370 img/s through tf.data vs ~20k img/s/chip device demand). This
// library is the framework's own native decode path. Items are byte ranges
// `(path, offset, length)` — a standalone .JPEG file (offset<0) or an
// encoded-JPEG value inside a container such as a TFRecord file (see
// tfrecord_index.cc, which emits exactly these ranges) — so BOTH ImageNet
// layouts ride the same decoder:
//
//   TRAIN: sample random-resized crop in ORIGINAL coords (area 8-100%, aspect
//   3/4-4/3, 10 attempts — the standard Inception crop the tf.data path also
//   uses) → libjpeg-turbo DCT-SCALED decode (scale M/8 chosen so the scaled
//   crop still covers the output size — decoding 1/4-1/2 of the pixels costs
//   a fraction of a full-res decode; tf.image.decode_and_crop_jpeg always
//   decodes the crop window at FULL resolution)
//   → jpeg_crop_scanline + jpeg_skip_scanlines (decode only the crop rows/MCU
//   columns) → bilinear resize to out_size → optional h-flip → mean/std
//   normalize → float32 or bfloat16 batch buffer. The resize+normalize+pack
//   half runs through runtime-dispatched SIMD kernels (AVX2+FMA with a
//   bit-identical scalar fallback — see "resample kernels" below).
//
//   EVAL (eval_mode=1): deterministic center crop — the centered region that
//   "resize short side to 256 → center-crop 224" maps back to in original
//   coordinates (side = min(W,H) * out/256), DCT-scale-decoded and bilinearly
//   resized to out_size in ONE resampling step. No RNG, no flip; a finite
//   in-order pass whose final partial batch reports a valid count (the
//   exact-eval pad-and-mask protocol, data/eval_pad.py).
//
// Threading: N workers share a fixed ring of 3 batch slots at ITEM
// granularity — each worker claims the next global item index under the lock
// and decodes it directly into its slot position, so first-batch latency and
// intra-batch work are spread across all threads and host RAM is 3 batch
// buffers regardless of thread count. Determinism: per-item RNG is derived
// from (seed, global item index) with splitmix64 and the epoch shuffle from
// (seed, epoch) — the stream is a pure function of (seed, position)
// regardless of thread count, which makes `seek(batch)` an O(1) exact resume
// (no iterator snapshot files needed).
//
// C ABI (ctypes, no pybind11 in this image):
//   dvgg_jpeg_loader_create(...)                 -> handle (0 on error)
//   dvgg_jpeg_loader_create_ranged(...)          -> handle; items are byte
//       ranges into a path table, plus eval_mode/finite flags
//   dvgg_jpeg_loader_next(handle, imgs, labels)  -> 0 ok, 1 end-of-stream
//   dvgg_jpeg_loader_next_valid(handle, imgs, labels, &valid) -> 0 ok;
//       valid < batch on the final partial batch of a finite pass
//   dvgg_jpeg_loader_seek(handle, batch_index)   (call before first next)
//   dvgg_jpeg_loader_set_hflip(handle, enable) / dvgg_jpeg_loader_hflip
//       (v9) -> flip ownership per loader (0 = device-side augmentation
//       owns the horizontal flip; call before first next, like seek);
//       crops are bit-identical either way — only the flip is gated
//   dvgg_jpeg_loader_decode_errors(handle)       -> corrupt-image fallbacks
//   dvgg_jpeg_loader_destroy(handle)
//   dvgg_jpeg_simd_supported()                   -> 1 if AVX2+FMA compiled
//       in AND the running CPU has them
//   dvgg_jpeg_simd_kind() / dvgg_jpeg_set_simd(enable) -> active resample
//       path (0 scalar, 1 avx2); initial value honors DVGGF_DECODE_SIMD=0
//   dvgg_jpeg_scaled_supported()                 -> 1 unless -DDVGGF_NO_SCALED
//   dvgg_jpeg_scaled_kind() / dvgg_jpeg_set_scaled(enable) -> active decode
//       strategy (0 full-resolution, 1 DCT-scaled + partial); initial value
//       honors DVGGF_DECODE_SCALED=0
//   dvgg_jpeg_partial_supported()                -> 1 iff the running libjpeg
//       resolves jpeg_crop_scanline + jpeg_skip_scanlines (dlsym probe — the
//       turbo-only partial-decode entry points; plain libjpeg gets the
//       full-decode fallback)
//   dvgg_jpeg_wire_u8_supported()                -> 1 unless -DDVGGF_NO_WIRE_U8
//   dvgg_jpeg_wire_u8_kind() / dvgg_jpeg_set_wire_u8(enable) -> u8-wire
//       availability (0 refused, 1 available); initial value honors
//       DVGGF_WIRE_U8=0. The loaders' out_kind int selects the wire per
//       instance: 0 f32 / 1 bf16 (host-normalized), 2 = raw uint8 HWC pixels
//       through the fixed-point resample kernels — normalize, dtype cast and
//       space-to-depth then happen on DEVICE (data/device_ingest.py), and
//       the output ring shrinks 4x vs f32
//   dvgg_jpeg_restart_supported()                -> 1 unless -DDVGGF_NO_RESTART
//   dvgg_jpeg_restart_kind() / dvgg_jpeg_set_restart(enable) -> active
//       entropy strategy (0 sequential, 1 restart-marker excerpt decode when
//       the stream carries usable RSTn structure); initial value honors
//       DVGGF_DECODE_RESTART=0. Fallback is always byte-identical.
//   dvgg_jpeg_restart_fanout() / dvgg_jpeg_set_restart_fanout(n) -> intra-
//       image fan-out width across the chunk pool (default 1; env default
//       DVGGF_RESTART_FANOUT) — latency lever, not a per-core-throughput one
//   dvgg_jpeg_restart_stats(out[16])             -> cumulative restart
//       receipts (images via excerpts, fallback causes, segments used/
//       skipped, fan-out width); dvgg_jpeg_restart_stats_reset()
//   dvgg_jpeg_reencode_restart(in, n, interval, out, cap) -> lossless
//       coefficient-domain transcode injecting restart markers every
//       `interval` MCUs (0 = one MCU row) — the offline re-encode tool
//   dvgg_jpeg_choose_scale(cw, ch, out)          -> the scale_num the scaled
//       path would pick for a (cw, ch) crop resized to out (scale_denom is
//       always 8) — exported so the Python mirror test can pin the chooser
//   dvgg_jpeg_profile_ns(out[3])                 -> cumulative {libjpeg ns,
//       resample ns, images} phase split; dvgg_jpeg_profile_reset()
//   dvgg_jpeg_decode_stats(out[16])              -> cumulative decode receipts
//       {images, scale histogram m=1..8, rows skipped/truncated, buffer-pool
//       hits/misses, partial-path images, full fallbacks};
//       dvgg_jpeg_decode_stats_reset()
//
// r7 decode strategy (the "attack the 81-83% libjpeg phase" round): the
// scale chooser picks the smallest M/8 from {1, 2, 4, 8} — NOT the smallest
// of 1..8 — because libjpeg-turbo only carries SIMD IDCT kernels for the
// power-of-two output sizes (8x8, 4x4, 2x2; 1x1 is DC-only). Measured on the
// r7 box: a 5/8..7/8 scaled decode is SLOWER than the full 8/8 SIMD decode
// of the same crop (e.g. 448px source, 70% crop: m=7 1165 us vs m=8
// 1011 us; m=4 819 us), so rounding the minimal covering scale UP to the
// next power of two is both the never-upscale-safe and the fast choice.
// Each worker thread owns a reusable DecodeCtx: the jpeg_decompress_struct
// is created once per thread (jpeg_abort between images keeps it reusable —
// create/destroy per image is allocator churn), and the decode plane + tap
// tables are grow-only pooled vectors, so the hot loop stops paying a
// ~130-600 KB allocate+fault+zero cycle per image.

#include <cstdio>  // jpeglib.h needs FILE declared first

#include <jpeglib.h>

#if !defined(DVGGF_NO_SCALED)
#include <dlfcn.h>  // runtime probe for the libjpeg-turbo partial-decode API
#endif

#include <algorithm>
#include <atomic>
#include <cmath>
#include <condition_variable>
#include <csetjmp>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <ctime>
#include <deque>
#include <functional>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

// AVX2+FMA kernels are compiled via per-function target attributes (the
// translation unit's baseline stays whatever the Makefile says), selected at
// runtime by cpuid. -DDVGGF_NO_SIMD compiles them out entirely — the build
// the parity/smoke tests use to prove the scalar fallback stands alone.
#if (defined(__x86_64__) || defined(__i386__)) && !defined(DVGGF_NO_SIMD)
#define DVGG_SIMD_X86 1
#include <immintrin.h>
#else
#define DVGG_SIMD_X86 0
#endif

// DCT-scaled + partial decode is compiled out with -DDVGGF_NO_SCALED — the
// build the smoke tests use to prove the full-resolution fallback stands
// alone (mirrors the -DDVGGF_NO_SIMD pattern).
#if !defined(DVGGF_NO_SCALED)
#define DVGG_SCALED 1
#else
#define DVGG_SCALED 0
#endif

// The uint8 wire mode (r8) is compiled out with -DDVGGF_NO_WIRE_U8 — the
// build the smoke tests use to prove the host-normalize (f32/bf16) paths
// stand alone. When compiled out (or killed via DVGGF_WIRE_U8=0 /
// dvgg_jpeg_set_wire_u8(0)), loader creation with the u8 output kind FAILS
// and the Python ingest layer falls back to the host-normalize wire — the
// fallback is a FORMAT decision, so it must happen above the ABI, not
// silently inside it.
#if !defined(DVGGF_NO_WIRE_U8)
#define DVGG_WIRE_U8 1
#else
#define DVGG_WIRE_U8 0
#endif

// Restart-marker-parallel entropy decode (r9) is compiled out with
// -DDVGGF_NO_RESTART — the build the smoke tests use to prove the
// sequential Huffman path stands alone. The machinery attacks the one cost
// the r7 profile pinned as unskippable: libjpeg's Huffman entropy decode is
// strictly sequential WITHIN a scan, but RSTn markers reset the DC
// predictors every `restart_interval` MCUs, so a marker-bearing stream can
// be cut at segment boundaries, re-assembled into a synthetic JPEG covering
// only the MCU band the crop needs (headers copied, SOF dims patched, RST
// sequence renumbered), and entropy-decoded (a) WITHOUT parsing the
// segments outside the band — the throughput lever: today rows above the
// crop are entropy-parsed even when their IDCT is skipped — and (b) fanned
// out across threads chunk-by-chunk when idle cores exist. Sources without
// markers (or with misaligned/corrupt marker structure) fall through to the
// sequential path, receipted in dvgg_jpeg_restart_stats.
#if !defined(DVGGF_NO_RESTART)
#define DVGG_RESTART 1
#else
#define DVGG_RESTART 0
#endif

// Runtime thread-pool grow/shrink (r11 — the closed-loop ingest autotuner's
// decode-worker knob) is compiled out with -DDVGGF_NO_RESIZE: loaders then
// keep their creation-time worker count for life and
// dvgg_jpeg_loader_set_threads returns -1 (refused), which the Python
// controller reads as "knob unavailable" — an actuation that silently does
// nothing would let the controller believe it fixed an infeed stall it
// didn't touch.
#if !defined(DVGGF_NO_RESIZE)
#define DVGG_RESIZE 1
#else
#define DVGG_RESIZE 0
#endif

namespace {

struct SplitMix64 {
  uint64_t s;
  explicit SplitMix64(uint64_t seed) : s(seed) {}
  uint64_t next() {
    uint64_t z = (s += 0x9e3779b97f4a7c15ULL);
    z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ULL;
    z = (z ^ (z >> 27)) * 0x94d049bb133111ebULL;
    return z ^ (z >> 31);
  }
  double uniform() { return (next() >> 11) * (1.0 / 9007199254740992.0); }
};

inline uint64_t mix(uint64_t a, uint64_t b) {
  SplitMix64 r(a * 0x9e3779b97f4a7c15ULL + b);
  r.next();
  return r.next();
}

void shuffle_indices(std::vector<int64_t>& idx, uint64_t seed, uint64_t epoch) {
  SplitMix64 r(mix(seed, 0x5eedULL + epoch));
  for (int64_t i = (int64_t)idx.size() - 1; i > 0; --i) {
    int64_t j = (int64_t)(r.next() % (uint64_t)(i + 1));
    std::swap(idx[i], idx[j]);
  }
}

inline uint16_t f32_to_bf16(float v) {
  uint32_t bits;
  std::memcpy(&bits, &v, 4);
  // round-to-nearest-even
  uint32_t lsb = (bits >> 16) & 1;
  return (uint16_t)((bits + 0x7fffu + lsb) >> 16);
}

// ------------------------------------------------------- resample kernels
//
// The bilinear resize + normalize + pack half of decode_one, restructured
// from the r5 per-pixel loop into two data-parallel passes per output row
// (the SIMD lever VERDICT r5 #6 named):
//
//   vertical:    vtmp[i] = r0[i] + wy*(r1[i] - r0[i]) over the full decoded
//                row — contiguous u8→f32 convert + one fused lerp;
//   horizontal:  per output pixel, lerp the two 3-float taps at the
//                precomputed per-pixel x positions (flip folded in), then
//                (v - mean) * (1/std), bf16 rounded directly in the lanes.
//
// The AVX2 horizontal kernel is deliberately GATHER-FREE: an output pixel's
// two taps are CONTIGUOUS rgb triples in vtmp, so two pixels pack into one
// YMM as 4-float quads loaded with plain vmovups (lane 3 is a dead lane) —
// vpgatherdps would express this more directly but is microcode-slow
// exactly where this runs (post-GDS-mitigation Intel hosts; AMD EPYC
// TPU-VM hosts), measured SLOWER than scalar on this box. Quad stores
// overlap one float into the next pixel, which the next (always later)
// pixel's store overwrites; the last pixel of every row is written scalar
// so nothing strays past the row.
//
// Every kernel exists twice: an AVX2+FMA version (runtime-dispatched) and a
// scalar version written with std::fmaf so each lane-level operation —
// convert, subtract, fused lerp, normalize, bf16 round — is the SAME
// single-rounded IEEE op in both. That makes the two paths byte-identical
// (f32 AND bf16), which tests/test_native_jpeg_parity.py pins; scalar-vs-
// vector is a dispatch decision, never a numerics decision.

typedef void (*VLerpFn)(const uint8_t*, const uint8_t*, float, float*, int);
// u8 wire kernels (r8): FIXED-POINT bilinear, 8-bit fractional weights.
// Vertical emits u16 lanes (r0*(256-wy8) + r1*wy8 — max 255*256 fits u16);
// horizontal combines two u16 taps in u32 lanes and rounds back to u8 with
// (a*(256-wx8) + b*wx8 + 32768) >> 16. All-integer, so the AVX2 and scalar
// versions are byte-identical by construction, and the result is within
// one intensity level (1/255 of full scale per channel) of the float
// bilinear the host-normalize paths compute — the quantization bound the
// parity suite pins. Normalize / dtype cast / space-to-depth deliberately
// do NOT happen here: they move to the device-finish prologue
// (data/device_ingest.py), which is the whole point of the u8 wire.
typedef void (*VLerpU8Fn)(const uint8_t*, const uint8_t*, uint32_t,
                          uint16_t*, int);
typedef void (*HLerpU8Fn)(const int32_t*, const int32_t*, const uint32_t*,
                          const uint16_t*, uint8_t*, int);
// (p0, p1, w4, mean, inv, vtmp, dst, out): p0/p1 are per-PIXEL float
// indices of the two taps' first channel; w4 is the per-pixel x weight
// replicated 4x (one 256-bit load covers a pixel pair); mean/inv are the
// 3-channel normalize constants.
typedef void (*HLerpF32Fn)(const int32_t*, const int32_t*, const float*,
                           const float*, const float*, const float*,
                           float*, int);
typedef void (*HLerpBf16Fn)(const int32_t*, const int32_t*, const float*,
                            const float*, const float*, const float*,
                            uint16_t*, int);

void vlerp_scalar(const uint8_t* r0, const uint8_t* r1, float wy,
                  float* vtmp, int n) {
  for (int i = 0; i < n; ++i)
    vtmp[i] = std::fmaf(wy, (float)r1[i] - (float)r0[i], (float)r0[i]);
}

void hlerp_f32_scalar(const int32_t* p0, const int32_t* p1, const float* w4,
                      const float* mean, const float* inv, const float* vtmp,
                      float* dst, int out) {
  for (int ox = 0; ox < out; ++ox) {
    const float w = w4[4 * ox];
    const float* a = vtmp + p0[ox];
    const float* b = vtmp + p1[ox];
    for (int c = 0; c < 3; ++c)
      dst[3 * ox + c] =
          (std::fmaf(w, b[c] - a[c], a[c]) - mean[c]) * inv[c];
  }
}

void hlerp_bf16_scalar(const int32_t* p0, const int32_t* p1, const float* w4,
                       const float* mean, const float* inv, const float* vtmp,
                       uint16_t* dst, int out) {
  for (int ox = 0; ox < out; ++ox) {
    const float w = w4[4 * ox];
    const float* a = vtmp + p0[ox];
    const float* b = vtmp + p1[ox];
    for (int c = 0; c < 3; ++c)
      dst[3 * ox + c] =
          f32_to_bf16((std::fmaf(w, b[c] - a[c], a[c]) - mean[c]) * inv[c]);
  }
}

void vlerp_u8_scalar(const uint8_t* r0, const uint8_t* r1, uint32_t wy8,
                     uint16_t* vtmp, int n) {
  const uint32_t inv = 256u - wy8;
  for (int i = 0; i < n; ++i)
    vtmp[i] = (uint16_t)((uint32_t)r0[i] * inv + (uint32_t)r1[i] * wy8);
}

void hlerp_u8_scalar(const int32_t* p0, const int32_t* p1,
                     const uint32_t* w4, const uint16_t* vtmp,
                     uint8_t* dst, int out) {
  for (int ox = 0; ox < out; ++ox) {
    const uint32_t w = w4[4 * ox], winv = 256u - w;
    const uint16_t* a = vtmp + p0[ox];
    const uint16_t* b = vtmp + p1[ox];
    for (int c = 0; c < 3; ++c)
      dst[3 * ox + c] = (uint8_t)(((uint32_t)a[c] * winv
                                   + (uint32_t)b[c] * w + 32768u) >> 16);
  }
}

#if DVGG_SIMD_X86

__attribute__((target("avx2,fma")))
void vlerp_avx2(const uint8_t* r0, const uint8_t* r1, float wy,
                float* vtmp, int n) {
  const __m256 wv = _mm256_set1_ps(wy);
  int i = 0;
  for (; i + 8 <= n; i += 8) {
    __m256 a = _mm256_cvtepi32_ps(_mm256_cvtepu8_epi32(
        _mm_loadl_epi64(reinterpret_cast<const __m128i*>(r0 + i))));
    __m256 b = _mm256_cvtepi32_ps(_mm256_cvtepu8_epi32(
        _mm_loadl_epi64(reinterpret_cast<const __m128i*>(r1 + i))));
    _mm256_storeu_ps(vtmp + i, _mm256_fmadd_ps(wv, _mm256_sub_ps(b, a), a));
  }
  for (; i < n; ++i)  // tail: same single-rounded ops, lane-for-lane
    vtmp[i] = std::fmaf(wy, (float)r1[i] - (float)r0[i], (float)r0[i]);
}

// One lerped+normalized pixel PAIR: lanes [r g b x | r g b x], dead x
// lanes forced to 0 by the zeroed lane-3 of mean8/inv8. The 4-float tap
// loads read one float past each rgb triple — vtmp carries a 4-float
// zeroed pad for the row-end taps.
__attribute__((target("avx2,fma")))
static inline __m256 hpair(const int32_t* p0, const int32_t* p1,
                           const float* w4, __m256 mean8, __m256 inv8,
                           const float* vtmp, int ox) {
  __m256 a = _mm256_insertf128_ps(
      _mm256_castps128_ps256(_mm_loadu_ps(vtmp + p0[ox])),
      _mm_loadu_ps(vtmp + p0[ox + 1]), 1);
  __m256 b = _mm256_insertf128_ps(
      _mm256_castps128_ps256(_mm_loadu_ps(vtmp + p1[ox])),
      _mm_loadu_ps(vtmp + p1[ox + 1]), 1);
  __m256 h = _mm256_fmadd_ps(_mm256_loadu_ps(w4 + 4 * ox),
                             _mm256_sub_ps(b, a), a);
  return _mm256_mul_ps(_mm256_sub_ps(h, mean8), inv8);
}

__attribute__((target("avx2,fma")))
void hlerp_f32_avx2(const int32_t* p0, const int32_t* p1, const float* w4,
                    const float* mean, const float* inv, const float* vtmp,
                    float* dst, int out) {
  const __m256 mean8 = _mm256_setr_ps(mean[0], mean[1], mean[2], 0.0f,
                                      mean[0], mean[1], mean[2], 0.0f);
  const __m256 inv8 = _mm256_setr_ps(inv[0], inv[1], inv[2], 0.0f,
                                     inv[0], inv[1], inv[2], 0.0f);
  int ox = 0;
  // pairs stop before the LAST pixel: each quad store strays one float
  // into the next pixel, legal only while a later store overwrites it
  for (; ox + 3 <= out; ox += 2) {
    __m256 r = hpair(p0, p1, w4, mean8, inv8, vtmp, ox);
    _mm_storeu_ps(dst + 3 * ox, _mm256_castps256_ps128(r));
    _mm_storeu_ps(dst + 3 * (ox + 1), _mm256_extractf128_ps(r, 1));
  }
  for (; ox < out; ++ox) {
    const float w = w4[4 * ox];
    const float* a = vtmp + p0[ox];
    const float* b = vtmp + p1[ox];
    for (int c = 0; c < 3; ++c)
      dst[3 * ox + c] =
          (std::fmaf(w, b[c] - a[c], a[c]) - mean[c]) * inv[c];
  }
}

// 8 f32 lanes -> 8 bf16 lanes: the f32_to_bf16 round-to-nearest-even
// formula in integer lanes (values after >>16 fit u16, so packus is exact).
__attribute__((target("avx2,fma")))
static inline __m128i bf16_8(__m256 r) {
  __m256i bits = _mm256_castps_si256(r);
  __m256i lsb = _mm256_and_si256(_mm256_srli_epi32(bits, 16),
                                 _mm256_set1_epi32(1));
  bits = _mm256_srli_epi32(
      _mm256_add_epi32(bits, _mm256_add_epi32(lsb, _mm256_set1_epi32(0x7fff))),
      16);
  __m256i packed = _mm256_packus_epi32(bits, bits);
  return _mm_unpacklo_epi64(_mm256_castsi256_si128(packed),
                            _mm256_extracti128_si256(packed, 1));
}

__attribute__((target("avx2,fma")))
void hlerp_bf16_avx2(const int32_t* p0, const int32_t* p1, const float* w4,
                     const float* mean, const float* inv, const float* vtmp,
                     uint16_t* dst, int out) {
  const __m256 mean8 = _mm256_setr_ps(mean[0], mean[1], mean[2], 0.0f,
                                      mean[0], mean[1], mean[2], 0.0f);
  const __m256 inv8 = _mm256_setr_ps(inv[0], inv[1], inv[2], 0.0f,
                                     inv[0], inv[1], inv[2], 0.0f);
  int ox = 0;
  for (; ox + 3 <= out; ox += 2) {
    __m128i q = bf16_8(hpair(p0, p1, w4, mean8, inv8, vtmp, ox));
    _mm_storel_epi64(reinterpret_cast<__m128i*>(dst + 3 * ox), q);
    _mm_storel_epi64(reinterpret_cast<__m128i*>(dst + 3 * (ox + 1)),
                     _mm_unpackhi_epi64(q, q));
  }
  for (; ox < out; ++ox) {
    const float w = w4[4 * ox];
    const float* a = vtmp + p0[ox];
    const float* b = vtmp + p1[ox];
    for (int c = 0; c < 3; ++c)
      dst[3 * ox + c] =
          f32_to_bf16((std::fmaf(w, b[c] - a[c], a[c]) - mean[c]) * inv[c]);
  }
}

__attribute__((target("avx2")))
void vlerp_u8_avx2(const uint8_t* r0, const uint8_t* r1, uint32_t wy8,
                   uint16_t* vtmp, int n) {
  // u16 lanes: a*(256-wy8) + b*wy8 <= 255*256, exact in 16 bits because
  // the two weights sum to 256 — mullo_epi16 never wraps.
  const __m256i wv = _mm256_set1_epi16((short)wy8);
  const __m256i iv = _mm256_set1_epi16((short)(256u - wy8));
  int i = 0;
  for (; i + 16 <= n; i += 16) {
    __m256i a = _mm256_cvtepu8_epi16(
        _mm_loadu_si128(reinterpret_cast<const __m128i*>(r0 + i)));
    __m256i b = _mm256_cvtepu8_epi16(
        _mm_loadu_si128(reinterpret_cast<const __m128i*>(r1 + i)));
    _mm256_storeu_si256(reinterpret_cast<__m256i*>(vtmp + i),
                        _mm256_add_epi16(_mm256_mullo_epi16(a, iv),
                                         _mm256_mullo_epi16(b, wv)));
  }
  const uint32_t inv = 256u - wy8;  // tail: identical integer ops
  for (; i < n; ++i)
    vtmp[i] = (uint16_t)((uint32_t)r0[i] * inv + (uint32_t)r1[i] * wy8);
}

// One pixel PAIR per iteration, same gather-free tap discipline as the
// float kernels: a pixel's two taps are contiguous rgb u16 triples in
// vtmp, loaded as 4-lane quads (dead 4th lane), widened to u32 for the
// weighted sum, rounded, and packed back to u8. Each 4-byte store strays
// one byte into the next pixel — legal for the same reason as the float
// quad stores (a later store or the scalar-written last pixel overwrites
// it). The 4-u16 tap loads read one u16 past the last rgb triple, so
// vtmp carries the same +4-element zeroed pad as the float path.
__attribute__((target("avx2")))
void hlerp_u8_avx2(const int32_t* p0, const int32_t* p1, const uint32_t* w4,
                   const uint16_t* vtmp, uint8_t* dst, int out) {
  const __m256i c256 = _mm256_set1_epi32(256);
  const __m256i half = _mm256_set1_epi32(32768);
  int ox = 0;
  for (; ox + 3 <= out; ox += 2) {
    __m128i a16 = _mm_unpacklo_epi64(
        _mm_loadl_epi64(reinterpret_cast<const __m128i*>(vtmp + p0[ox])),
        _mm_loadl_epi64(reinterpret_cast<const __m128i*>(vtmp + p0[ox + 1])));
    __m128i b16 = _mm_unpacklo_epi64(
        _mm_loadl_epi64(reinterpret_cast<const __m128i*>(vtmp + p1[ox])),
        _mm_loadl_epi64(reinterpret_cast<const __m128i*>(vtmp + p1[ox + 1])));
    __m256i a = _mm256_cvtepu16_epi32(a16);
    __m256i b = _mm256_cvtepu16_epi32(b16);
    __m256i w = _mm256_loadu_si256(
        reinterpret_cast<const __m256i*>(w4 + 4 * ox));
    __m256i h = _mm256_add_epi32(
        _mm256_add_epi32(_mm256_mullo_epi32(a, _mm256_sub_epi32(c256, w)),
                         _mm256_mullo_epi32(b, w)),
        half);
    h = _mm256_srli_epi32(h, 16);
    // within-lane packs: 128-bit lane 0 holds pixel ox, lane 1 pixel ox+1
    __m256i p8 = _mm256_packus_epi16(_mm256_packus_epi32(h, h),
                                     _mm256_packus_epi32(h, h));
    uint32_t q0 = (uint32_t)_mm_cvtsi128_si32(_mm256_castsi256_si128(p8));
    uint32_t q1 = (uint32_t)_mm_cvtsi128_si32(_mm256_extracti128_si256(p8, 1));
    std::memcpy(dst + 3 * ox, &q0, 4);
    std::memcpy(dst + 3 * (ox + 1), &q1, 4);
  }
  for (; ox < out; ++ox) {
    const uint32_t w = w4[4 * ox], winv = 256u - w;
    const uint16_t* a = vtmp + p0[ox];
    const uint16_t* b = vtmp + p1[ox];
    for (int c = 0; c < 3; ++c)
      dst[3 * ox + c] = (uint8_t)(((uint32_t)a[c] * winv
                                   + (uint32_t)b[c] * w + 32768u) >> 16);
  }
}

#endif  // DVGG_SIMD_X86

struct ResampleKernels {
  VLerpFn vlerp;
  HLerpF32Fn h_f32;
  HLerpBf16Fn h_bf16;
  VLerpU8Fn v_u8;
  HLerpU8Fn h_u8;
};

const ResampleKernels kScalarKernels = {vlerp_scalar, hlerp_f32_scalar,
                                        hlerp_bf16_scalar, vlerp_u8_scalar,
                                        hlerp_u8_scalar};
#if DVGG_SIMD_X86
const ResampleKernels kAvx2Kernels = {vlerp_avx2, hlerp_f32_avx2,
                                      hlerp_bf16_avx2, vlerp_u8_avx2,
                                      hlerp_u8_avx2};
#endif

int simd_supported() {
#if DVGG_SIMD_X86
  return (__builtin_cpu_supports("avx2") && __builtin_cpu_supports("fma"))
             ? 1 : 0;
#else
  return 0;
#endif
}

// Active path: -1 = uninitialized; 0 scalar, 1 avx2. First read resolves
// from cpuid + the DVGGF_DECODE_SIMD env kill-switch; dvgg_jpeg_set_simd
// flips it at runtime (how the parity tests decode the same bytes through
// BOTH paths in one process).
std::atomic<int> g_simd_kind{-1};

int active_simd_kind() {
  int k = g_simd_kind.load(std::memory_order_relaxed);
  if (k < 0) {
    const char* env = std::getenv("DVGGF_DECODE_SIMD");
    k = (env && env[0] == '0') ? 0 : simd_supported();
    g_simd_kind.store(k, std::memory_order_relaxed);
  }
  return k;
}

const ResampleKernels& active_kernels() {
#if DVGG_SIMD_X86
  if (active_simd_kind() == 1) return kAvx2Kernels;
#endif
  active_simd_kind();  // resolve the sticky kind even on the scalar path
  return kScalarKernels;
}

// ------------------------------------------------- scaled/partial dispatch
//
// Same sticky-atomic pattern as the SIMD kind above: -1 = uninitialized;
// 0 = full-resolution decode; 1 = DCT-scaled + partial decode. First read
// resolves the DVGGF_DECODE_SCALED env kill-switch; dvgg_jpeg_set_scaled
// flips it at runtime (how the tolerance-parity suite decodes the same
// bytes through both strategies in one process).
std::atomic<int> g_scaled_kind{-1};

int scaled_supported() { return DVGG_SCALED; }

int active_scaled_kind() {
  int k = g_scaled_kind.load(std::memory_order_relaxed);
  if (k < 0) {
    const char* env = std::getenv("DVGGF_DECODE_SCALED");
    k = (env && env[0] == '0') ? 0 : scaled_supported();
    g_scaled_kind.store(k, std::memory_order_relaxed);
  }
  return k;
}

// The partial-decode entry points are libjpeg-turbo EXTENSIONS (absent from
// IJG libjpeg), so they are resolved by dlsym at first use instead of being
// link-time references: the .so loads against any libjpeg, and hosts without
// the API take the graceful full-decode fallback (decode full-width rows,
// discard the rows above the crop) — receipted in the decode stats.
typedef void (*JpegCropScanlineFn)(j_decompress_ptr, JDIMENSION*,
                                   JDIMENSION*);
typedef JDIMENSION (*JpegSkipScanlinesFn)(j_decompress_ptr, JDIMENSION);

struct PartialApi {
  JpegCropScanlineFn crop = nullptr;
  JpegSkipScanlinesFn skip = nullptr;
};

const PartialApi& partial_api() {
  static const PartialApi api = [] {
    PartialApi a;
#if DVGG_SCALED
    void* crop = dlsym(RTLD_DEFAULT, "jpeg_crop_scanline");
    void* skip = dlsym(RTLD_DEFAULT, "jpeg_skip_scanlines");
    if (crop && skip) {  // both or neither: the path needs the pair
      a.crop = reinterpret_cast<JpegCropScanlineFn>(crop);
      a.skip = reinterpret_cast<JpegSkipScanlinesFn>(skip);
    }
#endif
    return a;
  }();
  return api;
}

int partial_supported() { return partial_api().crop ? 1 : 0; }

// ---------------------------------------------------------- u8 wire dispatch
//
// Same sticky-atomic pattern as the SIMD / scaled kinds: -1 = uninitialized;
// 0 = u8 wire refused (host-normalize output kinds only); 1 = u8 wire
// available. First read resolves the DVGGF_WIRE_U8 env kill-switch;
// dvgg_jpeg_set_wire_u8 flips it at runtime. NOTE the fallback shape
// differs from the other two switches: killing the u8 wire changes the
// OUTPUT FORMAT, which the native layer cannot absorb transparently —
// loader creation with the u8 kind fails instead, and the Python ingest
// layer (data/imagenet.py) selects the host-normalize wire, byte-identical
// to the pre-u8 (r7) behavior.
std::atomic<int> g_wire_u8{-1};

int wire_u8_supported() { return DVGG_WIRE_U8; }

int active_wire_u8() {
  int k = g_wire_u8.load(std::memory_order_relaxed);
  if (k < 0) {
    const char* env = std::getenv("DVGGF_WIRE_U8");
    k = (env && env[0] == '0') ? 0 : wire_u8_supported();
    g_wire_u8.store(k, std::memory_order_relaxed);
  }
  return k;
}

// ------------------------------------------------ restart-marker dispatch
//
// Same sticky-atomic pattern as the SIMD / scaled / u8 kinds: -1 =
// uninitialized; 0 = sequential entropy decode only; 1 = restart-marker
// excerpt decode when the stream carries usable RSTn structure. First read
// resolves the DVGGF_DECODE_RESTART env kill-switch; dvgg_jpeg_set_restart
// flips it at runtime (how the parity suite decodes the same marker-bearing
// bytes through both entropy paths in one process). Falling back is always
// byte-identical: the excerpt path reproduces the sequential band decode
// pixel-for-pixel or is not taken.
std::atomic<int> g_restart_kind{-1};

int restart_supported() { return DVGG_RESTART; }

int active_restart_kind() {
  int k = g_restart_kind.load(std::memory_order_relaxed);
  if (k < 0) {
    const char* env = std::getenv("DVGGF_DECODE_RESTART");
    k = (env && env[0] == '0') ? 0 : restart_supported();
    g_restart_kind.store(k, std::memory_order_relaxed);
  }
  return k;
}

// Intra-image fan-out width: how many entropy chunks one image's band may
// be split into and decoded concurrently (the existing per-thread DecodeCtx
// pool picks them up). 1 = no fan-out (the default: per-CORE throughput is
// the provisioning metric, and fan-out trades cores for latency); the env
// default DVGGF_RESTART_FANOUT and dvgg_jpeg_set_restart_fanout raise it
// for latency-bound consumers (decode_single / predict, bench columns).
std::atomic<int> g_restart_fanout{-1};

int clamp_fanout(int n) { return n < 1 ? 1 : (n > 64 ? 64 : n); }

int active_restart_fanout() {
  int k = g_restart_fanout.load(std::memory_order_relaxed);
  if (k < 0) {
    const char* env = std::getenv("DVGGF_RESTART_FANOUT");
    k = clamp_fanout(env ? std::atoi(env) : 1);
    g_restart_fanout.store(k, std::memory_order_relaxed);
  }
  return k;
}

// ------------------------------------------------ thread-resize dispatch
//
// Same sticky-atomic pattern as the SIMD / scaled / u8 / restart kinds:
// -1 = uninitialized; 0 = resize refused (set_threads is a no-op returning
// -1); 1 = live pool grow/shrink allowed. First read resolves the
// DVGGF_THREAD_RESIZE env kill-switch; dvgg_jpeg_set_resize flips it at
// runtime. Resizing never changes pixels: the batch stream is a pure
// function of (seed, batch index) at ANY worker count (items are claimed
// under the lock in global order), so this kill-switch guards operational
// behavior only — unlike the decode-strategy switches there is no parity
// question, just "may an external controller move my thread count".
std::atomic<int> g_resize_kind{-1};

int resize_supported() { return DVGG_RESIZE; }

int active_resize_kind() {
  int k = g_resize_kind.load(std::memory_order_relaxed);
  if (k < 0) {
    const char* env = std::getenv("DVGGF_THREAD_RESIZE");
    k = (env && env[0] == '0') ? 0 : resize_supported();
    g_resize_kind.store(k, std::memory_order_relaxed);
  }
  return k;
}

// Worker-count rail shared by creation and resize (resize clamps into it;
// creation already floors at 1). 64 matches the ChunkPool's cap.
int clamp_threads(int n) { return n < 1 ? 1 : (n > 64 ? 64 : n); }

// Restart-path receipts (process-wide, all threads; exported via
// dvgg_jpeg_restart_stats): how often the excerpt path engaged, why it
// didn't, how many entropy segments it decoded vs skipped, and the fan-out
// it actually used. `marker_absent` vs `unsupported` vs `misaligned` vs
// `scan_failures` split the fallbacks by cause so a dataset that never
// engages the path is diagnosable from the bench artifact alone.
struct RestartStats {
  std::atomic<int64_t> images{0};            // decoded via excerpts
  std::atomic<int64_t> marker_absent{0};     // no DRI / zero interval
  std::atomic<int64_t> unsupported{0};       // progressive / arithmetic /
                                             // multi-scan / non-interleaved
  std::atomic<int64_t> misaligned{0};        // interval neither divides nor
                                             // is divisible by the MCU row
  std::atomic<int64_t> scan_failures{0};     // bogus RSTn order, segment
                                             // count mismatch, truncation
  std::atomic<int64_t> excerpt_fallbacks{0}; // excerpt decode failed →
                                             // sequential retry
  std::atomic<int64_t> segments_used{0};     // band segments entropy-decoded
  std::atomic<int64_t> segments_skipped{0};  // segments never parsed
  std::atomic<int64_t> fanout_images{0};     // images split across threads
  std::atomic<int64_t> fanout_width_max{0};
  std::atomic<int64_t> chunk_jobs_pooled{0}; // chunks run by pool threads
  std::atomic<int64_t> no_gain{0};           // plan covered every segment
};

RestartStats g_rstats;

#if DVGG_RESTART

// ----------------------------------------------------- restart-marker plan
//
// Geometry + segment index of one JPEG's entropy stream, produced by a pure
// byte scan (never touches a jpeg struct — a failed scan leaves the caller's
// decode state exactly as it was). Eligibility is deliberately narrow:
// baseline/extended-sequential Huffman, single interleaved scan, a DRI
// interval that either divides an MCU row (column-trimmable segments) or is
// a whole number of MCU rows (row-trimmable) — everything else falls back
// to the sequential path with a cause-specific receipt. The scan walks the
// header segments by length, then memchr-hops the entropy bytes recording
// every RSTn boundary (stuffed 0xFF00 and fill bytes skipped), verifying
// the RST sequence numbers cycle 0..7 in order and the segment count
// matches ceil(total_mcus / interval) — a stream that lies about its own
// structure is not one to cut apart.
struct RestartPlan {
  int interval = 0;           // DRI restart interval, in MCUs
  int ncomp = 0;
  int hmax = 1, vmax = 1;     // max sampling factors (MCU = 8h x 8v px)
  int width = 0, height = 0;
  int mcu_w = 0;              // MCUs per row
  int mcu_rows = 0;           // MCU rows in the image
  int rows_per_seg = 0;       // >0: interval is this many whole MCU rows
  int segs_per_row = 0;       // >0 (>=2): this many segments per MCU row
  size_t sof_dims_off = 0;    // byte offset of the SOF height field (H,W
                              // big-endian u16 pairs — patched per excerpt)
  size_t entropy_start = 0;   // first entropy byte after the SOS header
  std::vector<size_t> seg_start, seg_end;  // entropy bytes of each segment
};

enum RestartScanResult {
  kRestartOk = 0,
  kRestartAbsent,       // no DRI marker / zero interval
  kRestartUnsupported,  // progressive/arithmetic/multi-scan/non-interleaved
  kRestartMisaligned,   // interval neither divides nor is divisible by a row
  kRestartScanFailure,  // bogus RSTn order, count mismatch, truncation
};

inline int be16(const uint8_t* p) { return (p[0] << 8) | p[1]; }

RestartScanResult scan_restart_plan(const uint8_t* d, size_t n,
                                    RestartPlan& p) {
  if (n < 4 || d[0] != 0xFF || d[1] != 0xD8) return kRestartScanFailure;
  size_t i = 2;
  bool have_sof = false;
  while (true) {
    size_t j = i;
    while (j < n && d[j] == 0xFF) ++j;  // marker prefix + optional fill
    if (j >= n || j == i) return kRestartScanFailure;
    const uint8_t mk = d[j];
    i = j + 1;
    if (mk == 0xD8 || mk == 0x01) continue;  // SOI / TEM: no payload
    if (mk == 0xD9) return kRestartScanFailure;  // EOI before any scan
    if (i + 2 > n) return kRestartScanFailure;
    const size_t len = (size_t)be16(d + i);
    if (len < 2 || i + len > n) return kRestartScanFailure;
    const uint8_t* seg = d + i + 2;
    const size_t seg_len = len - 2;
    if (mk == 0xC0 || mk == 0xC1) {  // baseline / extended sequential DCT
      if (have_sof || seg_len < 6) return kRestartUnsupported;
      have_sof = true;
      p.sof_dims_off = (size_t)(seg + 1 - d);  // after the precision byte
      p.height = be16(seg + 1);
      p.width = be16(seg + 3);
      p.ncomp = seg[5];
      if (p.height < 1 || p.width < 1 || p.ncomp < 1 ||
          seg_len < 6 + (size_t)p.ncomp * 3)
        return kRestartUnsupported;
      for (int c = 0; c < p.ncomp; ++c) {
        const int hv = seg[6 + 3 * c + 1];
        p.hmax = std::max(p.hmax, hv >> 4);
        p.vmax = std::max(p.vmax, hv & 15);
      }
      if (p.hmax < 1 || p.vmax < 1 || p.hmax > 4 || p.vmax > 4)
        return kRestartUnsupported;
      // single-component scans are non-interleaved: MCU = one 8x8 block
      if (p.ncomp == 1 && (p.hmax != 1 || p.vmax != 1))
        return kRestartUnsupported;
      if (p.ncomp != 1 && p.ncomp != 3) return kRestartUnsupported;
    } else if (mk >= 0xC2 && mk <= 0xCF && mk != 0xC4 && mk != 0xC8 &&
               mk != 0xCC) {
      return kRestartUnsupported;  // progressive/arithmetic/hierarchical SOF
    } else if (mk == 0xDD) {  // DRI
      if (seg_len < 2) return kRestartScanFailure;
      p.interval = be16(seg);
    } else if (mk == 0xDA) {  // SOS
      if (!have_sof) return kRestartUnsupported;
      if (seg_len < 1 || (int)seg[0] != p.ncomp)
        return kRestartUnsupported;  // non-interleaved (multi-scan) file
      p.entropy_start = i + len;
      break;
    }
    i += len;  // DQT/DHT/APPn/COM/...: skip by length
  }
  if (p.interval <= 0) return kRestartAbsent;
  p.mcu_w = (p.width + 8 * p.hmax - 1) / (8 * p.hmax);
  p.mcu_rows = (p.height + 8 * p.vmax - 1) / (8 * p.vmax);
  if (p.interval % p.mcu_w == 0)
    p.rows_per_seg = p.interval / p.mcu_w;
  else if (p.mcu_w % p.interval == 0)
    p.segs_per_row = p.mcu_w / p.interval;
  else
    return kRestartMisaligned;
  const int64_t total = (int64_t)p.mcu_w * p.mcu_rows;
  const size_t expect = (size_t)((total + p.interval - 1) / p.interval);
  p.seg_start.reserve(expect);
  p.seg_end.reserve(expect);
  size_t pos = p.entropy_start;
  if (pos >= n) return kRestartScanFailure;
  p.seg_start.push_back(pos);
  bool closed = false;
  while (pos + 1 < n) {
    const uint8_t* ff = static_cast<const uint8_t*>(
        std::memchr(d + pos, 0xFF, n - pos));
    if (!ff) break;
    pos = (size_t)(ff - d);
    if (pos + 1 >= n) break;
    const uint8_t b = d[pos + 1];
    if (b == 0x00) { pos += 2; continue; }  // stuffed data byte
    if (b == 0xFF) { pos += 1; continue; }  // fill byte
    if (b >= 0xD0 && b <= 0xD7) {
      if ((int)(b - 0xD0) != (int)(p.seg_end.size() & 7))
        return kRestartScanFailure;  // RSTn out of sequence
      p.seg_end.push_back(pos);
      p.seg_start.push_back(pos + 2);
      pos += 2;
      continue;
    }
    if (b == 0xD9) {  // EOI
      p.seg_end.push_back(pos);
      closed = true;
      break;
    }
    return kRestartUnsupported;  // DNL / a second SOS / stray marker
  }
  if (!closed) return kRestartScanFailure;  // truncated entropy stream
  if (p.seg_end.size() != expect) return kRestartScanFailure;
  return kRestartOk;
}

// ------------------------------------------------- intra-image fan-out pool
//
// Persistent worker pool for fan-out widths > 1 (DVGGF_RESTART_FANOUT /
// dvgg_jpeg_set_restart_fanout): chunk jobs are ~100 us-class entropy
// decodes, so per-image std::thread spawns would eat the win. Threads are
// spawned lazily up to the requested width (capped), each keeps its own
// thread_local DecodeCtx alive across images, and batches from concurrent
// loader workers interleave through one job queue. The CALLER always
// participates (claims jobs too), so a pool with zero threads degrades to
// sequential chunk execution instead of deadlocking. Leaked singleton:
// joining decode threads from static destructors deadlocks under dlclose.
class ChunkPool {
 public:
  static ChunkPool& instance() {
    static ChunkPool* p = new ChunkPool();
    return *p;
  }

  // Runs every job (each returns success); returns the AND of the results.
  // `pooled` reports how many jobs ran on pool threads (receipt only).
  bool run(std::vector<std::function<bool()>>& jobs, int64_t* pooled) {
    auto b = std::make_shared<Batch>();
    b->jobs = &jobs;
    b->n = jobs.size();
    {
      std::lock_guard<std::mutex> lk(mu_);
      ensure_threads(std::min(jobs.size() - 1, (size_t)kMaxThreads));
      queue_.push_back(b);
    }
    cv_.notify_all();
    drain(*b, /*from_pool=*/false);
    std::unique_lock<std::mutex> lk(mu_);
    cv_done_.wait(lk, [&] { return b->done.load() == jobs.size(); });
    for (auto it = queue_.begin(); it != queue_.end(); ++it)
      if (it->get() == b.get()) { queue_.erase(it); break; }
    if (pooled) *pooled = b->pooled.load();
    return b->ok.load();
    // jobs (caller-owned) are only dereferenced for claimed i < n, which
    // implies done < n and therefore a still-waiting submitter; the Batch
    // itself is shared_ptr-kept for late over-claiming workers.
  }

 private:
  static constexpr size_t kMaxThreads = 15;

  struct Batch {
    std::vector<std::function<bool()>>* jobs = nullptr;
    // Job count snapshotted at submit: `jobs` is caller-owned and dies when
    // the submitter returns, so a late worker that copied the shared_ptr may
    // only read Batch fields until it CLAIMS an i < n (a live claim pins the
    // submitter in cv_done_.wait, keeping *jobs alive).
    size_t n = 0;
    std::atomic<size_t> next{0};
    std::atomic<size_t> done{0};
    std::atomic<int64_t> pooled{0};
    std::atomic<bool> ok{true};
  };

  void ensure_threads(size_t want) {  // caller holds mu_
    const unsigned hw = std::max(2u, std::thread::hardware_concurrency());
    want = std::min(want, (size_t)(hw - 1));
    while (threads_.size() < want)
      threads_.emplace_back([this] { worker(); });
  }

  // Claim-and-run loop shared by pool workers and the submitting caller.
  void drain(Batch& b, bool from_pool) {
    const size_t n = b.n;
    while (true) {
      const size_t i = b.next.fetch_add(1, std::memory_order_relaxed);
      if (i >= n) return;
      if (!(*b.jobs)[i]()) b.ok.store(false, std::memory_order_relaxed);
      if (from_pool) b.pooled.fetch_add(1, std::memory_order_relaxed);
      if (b.done.fetch_add(1, std::memory_order_acq_rel) + 1 == n) {
        std::lock_guard<std::mutex> lk(mu_);
        cv_done_.notify_all();
      }
    }
  }

  void worker() {
    while (true) {
      std::shared_ptr<Batch> b;
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_.wait(lk, [&] { return !queue_.empty(); });
        b = queue_.front();
        if (b->next.load(std::memory_order_relaxed) >= b->n) {
          // exhausted: retire it from the queue (submitter erases too —
          // both are erase-if-present under mu_) and look again
          queue_.pop_front();
          continue;
        }
      }
      drain(*b, /*from_pool=*/true);
    }
  }

  std::mutex mu_;
  std::condition_variable cv_, cv_done_;
  std::deque<std::shared_ptr<Batch>> queue_;
  std::vector<std::thread> threads_;
};

#endif  // DVGG_RESTART

// Smallest scale_num M (scale_denom 8) from {1, 2, 4, 8} whose scaled crop
// still covers `out` in both dims (floor semantics — conservative against
// libjpeg's ceil-rounded output size), else 8. Power-of-two only: those are
// libjpeg-turbo's SIMD IDCT sizes — 3/8..7/8 decode fewer pixels through a
// SLOWER (plain-C) IDCT and measured net-slower than 8/8 (header comment).
// 8 is also the never-upscale anchor: a crop smaller than out decodes at
// full resolution and the resample upscales from true source pixels.
int choose_scale_m(int cw, int ch, int out) {
  static const int kCandidates[4] = {1, 2, 4, 8};
  for (int m : kCandidates)
    if ((int64_t)cw * m / 8 >= out && (int64_t)ch * m / 8 >= out) return m;
  return 8;
}

// ------------------------------------------------------- decode receipts
//
// Cumulative, process-wide (all threads), exported via
// dvgg_jpeg_decode_stats: the bench's "what did the decoder actually do"
// receipt — chosen-scale histogram, scanlines skipped above / truncated
// below the crop window, decode-buffer pool hit rate, and how many images
// rode the partial path vs the full-decode fallback.
struct DecodeStats {
  std::atomic<int64_t> images{0};
  std::atomic<int64_t> scale_count[8];  // index m-1 for m in 1..8
  std::atomic<int64_t> rows_skipped{0};    // above the crop: entropy-parsed,
                                           // IDCT skipped (turbo API)
  std::atomic<int64_t> rows_truncated{0};  // below the crop: never decoded
  std::atomic<int64_t> pool_hits{0};    // buffer reuse with capacity held
  std::atomic<int64_t> pool_misses{0};  // buffer had to grow (cold/bigger)
  std::atomic<int64_t> partial_images{0};  // decoded via crop+skip
  std::atomic<int64_t> full_fallbacks{0};  // scaled path wanted partial but
                                           // the API is absent
};

DecodeStats g_stats;

// Cumulative per-phase wall time (libjpeg entropy-decode+IDCT vs the
// resample kernels), ~50 ns of clock_gettime per image against a ~ms-class
// decode — cheap enough to stay always-on. This is the committed-profile
// instrument the provisioning model's "where does the remaining time go"
// question reads from (benchmarks/host_pipeline_bench.py --decode-bench).
std::atomic<int64_t> g_ns_jpeg{0}, g_ns_resample{0}, g_profiled_images{0};

inline int64_t now_ns() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return (int64_t)ts.tv_sec * 1000000000LL + ts.tv_nsec;
}

// ---------------------------------------------------------------- jpeg error
struct JerrMgr {
  jpeg_error_mgr pub;
  std::jmp_buf jb;
};

void jerr_exit(j_common_ptr cinfo) {
  JerrMgr* j = reinterpret_cast<JerrMgr*>(cinfo->err);
  std::longjmp(j->jb, 1);
}

// ---------------------------------------------------------------- config
struct Item {
  int32_t path;    // index into Config::paths
  int64_t offset;  // byte offset of the JPEG within the file; <0 = whole file
  int64_t length;  // byte length of the JPEG (ignored when offset < 0)
};

struct Config {
  std::vector<std::string> paths;
  std::vector<Item> items;
  std::vector<int32_t> labels;  // one per item
  int batch;
  int out_size;
  uint64_t seed;
  float mean[3];
  float std_[3];
  int num_threads;
  int out_kind;   // 0 = float32, 1 = bfloat16 (both host-normalized),
                  // 2 = uint8 wire (raw resampled pixels; normalize/cast/
                  // space-to-depth move to the device-finish prologue).
                  // ABI v6: this slot was `bf16_out` through v5 — 0/1 keep
                  // their meaning, 2 is new.
  double area_min, area_max;
  int eval_mode;  // 1: deterministic center crop, no flip, identity order
  int finite;     // 1: one pass over items, then end-of-stream
  int pack4;      // 1: emit 4x4 space-to-depth layout (out/4, out/4, 48) —
                  // same bytes, packed destination indexing (the host side of
                  // the VGG-F stem contract; requires out_size % 4 == 0;
                  // host-normalize kinds only — the u8 wire packs on device)
  int hflip = 1;  // ABI v9: 0 = the host never flips (the fused on-device
                  // augmentation stage, data/augment.py, owns the flip —
                  // applying it here too would double-flip). The per-item
                  // flip bit is still DRAWN from the RNG either way, so the
                  // crop stream is bit-identical at both settings.
};

constexpr int kOutF32 = 0, kOutBf16 = 1, kOutU8 = 2;

inline size_t out_kind_bytes(int kind) {
  return kind == kOutF32 ? 4 : kind == kOutBf16 ? 2 : 1;
}

// Per-thread reusable decode context: one jpeg_decompress_struct created
// lazily and kept alive across images (jpeg_abort_decompress between them;
// jpeg_create/destroy per image is pure allocator churn — libjpeg rebuilds
// its memory pools every time), plus grow-only buffers for the decode plane
// and the resample tap tables, so steady-state decodes touch the allocator
// zero times. Buffer reuse is receipted via the pool hit/miss counters.
// After a libjpeg longjmp the struct's state is unknown, so the error path
// destroys it and the next decode recreates (live==false).
struct DecodeCtx {
  jpeg_decompress_struct cinfo;
  JerrMgr jerr;
  bool live = false;
  std::vector<uint8_t> plane;    // decoded crop band (rows x stride)
  std::vector<uint8_t> discard;  // fallback-path scratch row (rows above
                                 // the crop when jpeg_skip_scanlines is
                                 // unavailable)
  std::vector<float> vtmp;       // vertical-lerp row (+4 pad floats)
  std::vector<uint16_t> vtmp16;  // u8-wire vertical-lerp row (+4 pad u16)
  std::vector<int32_t> p0, p1;   // per-output-pixel horizontal taps
  std::vector<float> w4;         // per-pixel x weight, replicated 4x
  std::vector<uint32_t> w4i;     // u8 wire: 8-bit-fraction weight, repl. 4x
  std::vector<float> row_f32;    // pack4 staging rows
  std::vector<uint16_t> row_b16;
  std::vector<uint8_t> excerpt;  // restart-path synthetic JPEG (grow-only)
  std::vector<uint8_t> exrow;    // restart-path decoded-row staging

  ~DecodeCtx() {
    if (live) jpeg_destroy_decompress(&cinfo);
  }
};

// Grow-only ensure with pool accounting: a hit means capacity was already
// there (steady state — no allocator call); a miss means cold start or a
// bigger source than any seen by this thread. vector::resize value-fills
// only the newly grown tail, so hits skip the memset too.
template <typename T>
T* pool_ensure(std::vector<T>& v, size_t n) {
  if (v.capacity() >= n)
    g_stats.pool_hits.fetch_add(1, std::memory_order_relaxed);
  else
    g_stats.pool_misses.fetch_add(1, std::memory_order_relaxed);
  if (v.size() < n) v.resize(n);
  return v.data();
}

#if DVGG_RESTART

// Decode the absolute scaled rows [ay0, ay1) of a crop band through a
// restart-segment excerpt: pick the MCU-row (and, when the interval divides
// an MCU row, MCU-column) range covering those rows plus the upsampling
// context margin, splice header + the covering segments + renumbered RSTn
// markers + EOI into a synthetic JPEG whose SOF dims are patched to the
// excerpt rectangle, decode it with the SAME scale/fancy/partial settings
// as the sequential path, and memcpy ONLY the true crop columns of the
// owned rows into `plane` (tight sw*3 stride, row 0 = absolute row sy).
//
// Byte-identity argument (pinned by tests/test_native_jpeg_parity.py):
// RSTn resets the DC predictors, so every segment entropy-decodes
// identically wherever the scan starts; IDCT and color conversion are
// block/pixel-local; the only cross-block coupling is chroma upsampling,
// whose reach is <= 2 output pixels (h2v2 fancy) — and the excerpt keeps
// every owned row >= kMargin pixels away from a synthetic edge (or on the
// true image edge, where the sequential path replicates identically).
//
// Runs on its OWN thread_local DecodeCtx so the caller's jpeg state is
// never disturbed: a failed chunk (truncated segment, corrupt bytes —
// libjpeg longjmps land here) just returns false and the caller's
// sequential fallback proceeds from its still-armed context.
// Excerpt selection geometry, shared between decode_one's gain test and
// decode_restart_chunk's splice plan — ONE copy on purpose: if the two
// ever disagreed, the gain test would either engage an excerpt that covers
// every segment (no win, pure overhead) or skip one that would win.
// `count` is the number of segments an excerpt over absolute scaled rows
// [ay0, ay1) of a (sx, sw) crop band splices; since contiguous [ay0, ay1)
// sub-bands select contiguous row ranges, the union over a fan-out's
// chunks of their selections equals the whole band's selection — so the
// whole-band `count` is also the UNIQUE segments-parsed receipt under
// fan-out (per-chunk counts double-count the overlapping context).
constexpr int kExcerptMargin = 2;  // the r7 fancy-upsampling contract

struct ExcerptSel {
  int rr0, rr1;                // MCU-row range (segment-aligned, rows mode)
  int c0, c1;                  // MCU-col range (interval-aligned, col mode)
  size_t first_seg, last_seg;  // rows mode: spliced segment range
  size_t cs0, cs1;             // col mode: per-row segment slots
  size_t count;                // segments the excerpt splices (unique)
};

ExcerptSel select_excerpt(const RestartPlan& p, int m, int sx, int sw,
                          int ay0, int ay1) {
  const int smcu_h = p.vmax * m;  // scaled px per MCU row/col — exact for
  const int smcu_w = p.hmax * m;  // m in {1,2,4,8} (8*v * m/8 = v*m)
  const size_t nseg = p.seg_end.size();
  ExcerptSel s;
  s.rr0 = std::max(0, ay0 - kExcerptMargin) / smcu_h;
  s.rr1 = std::min(p.mcu_rows,
                   (ay1 + kExcerptMargin + smcu_h - 1) / smcu_h);
  s.c0 = 0;
  s.c1 = p.mcu_w;
  s.first_seg = 0;
  s.last_seg = nseg;
  s.cs0 = 0;
  s.cs1 = 1;
  if (p.rows_per_seg > 0) {
    s.first_seg = (size_t)(s.rr0 / p.rows_per_seg);
    s.last_seg = std::min(nseg,
        (size_t)((s.rr1 + p.rows_per_seg - 1) / p.rows_per_seg));
    s.rr0 = (int)s.first_seg * p.rows_per_seg;  // segment-aligned
    s.rr1 = std::min(p.mcu_rows, (int)s.last_seg * p.rows_per_seg);
    s.count = s.last_seg - s.first_seg;
  } else {
    s.c0 = std::max(0, sx - kExcerptMargin) / smcu_w;
    s.c1 = std::min(p.mcu_w,
                    (sx + sw + kExcerptMargin + smcu_w - 1) / smcu_w);
    s.c0 = (s.c0 / p.interval) * p.interval;  // segment-aligned columns
    s.c1 = std::min(p.mcu_w,
                    ((s.c1 + p.interval - 1) / p.interval) * p.interval);
    s.cs0 = (size_t)(s.c0 / p.interval);
    s.cs1 = (size_t)((s.c1 + p.interval - 1) / p.interval);
    s.count = (size_t)(s.rr1 - s.rr0) * (s.cs1 - s.cs0);
  }
  return s;
}

bool decode_restart_chunk(const uint8_t* d, const RestartPlan& p, int m,
                          int sx, int sy, int sw, int sh, int ay0, int ay1,
                          uint8_t* plane) {
  static thread_local DecodeCtx tl_ctx;
  DecodeCtx& ctx = tl_ctx;
  constexpr int kMargin = kExcerptMargin;
  const int smcu_h = p.vmax * m;
  const int smcu_w = p.hmax * m;
  const int read0 = std::max(0, ay0 - kMargin);  // first row to READ
  const ExcerptSel es = select_excerpt(p, m, sx, sw, ay0, ay1);
  const int rr0 = es.rr0, rr1 = es.rr1;
  const int c0 = es.c0, c1 = es.c1;
  const size_t first_seg = es.first_seg, last_seg = es.last_seg;
  const size_t cs0 = es.cs0, cs1 = es.cs1;
  const int px0 = c0 * 8 * p.hmax;
  const int px1 = std::min(p.width, c1 * 8 * p.hmax);
  const int py0 = rr0 * 8 * p.vmax;
  const int py1 = std::min(p.height, rr1 * 8 * p.vmax);
  const int new_w = px1 - px0, new_h = py1 - py0;
  if (new_w < 1 || new_h < 1) return false;
  // --- splice the excerpt (grow-only buffer; clear() keeps capacity)
  std::vector<uint8_t>& ex = ctx.excerpt;
  ex.clear();
  size_t need = p.entropy_start + 2;
  if (p.rows_per_seg > 0) {
    for (size_t s = first_seg; s < last_seg; ++s)
      need += p.seg_end[s] - p.seg_start[s] + 2;
  } else {
    for (int r = rr0; r < rr1; ++r)
      for (size_t s = (size_t)r * p.segs_per_row + cs0;
           s < (size_t)r * p.segs_per_row + cs1; ++s)
        need += p.seg_end[s] - p.seg_start[s] + 2;
  }
  ex.reserve(need);
  ex.insert(ex.end(), d, d + p.entropy_start);
  ex[p.sof_dims_off] = (uint8_t)(new_h >> 8);
  ex[p.sof_dims_off + 1] = (uint8_t)(new_h & 0xFF);
  ex[p.sof_dims_off + 2] = (uint8_t)(new_w >> 8);
  ex[p.sof_dims_off + 3] = (uint8_t)(new_w & 0xFF);
  size_t copied = 0;
  auto append_seg = [&](size_t s) {
    if (copied) {  // renumbered restart marker BETWEEN copied segments
      ex.push_back(0xFF);
      ex.push_back((uint8_t)(0xD0 + ((copied - 1) & 7)));
    }
    ex.insert(ex.end(), d + p.seg_start[s], d + p.seg_end[s]);
    ++copied;
  };
  if (p.rows_per_seg > 0) {
    for (size_t s = first_seg; s < last_seg; ++s) append_seg(s);
  } else {
    for (int r = rr0; r < rr1; ++r)
      for (size_t s = (size_t)r * p.segs_per_row + cs0;
           s < (size_t)r * p.segs_per_row + cs1; ++s)
        append_seg(s);
  }
  ex.push_back(0xFF);
  ex.push_back(0xD9);
  // --- decode the excerpt exactly like the sequential band decode
  jpeg_decompress_struct& ci = ctx.cinfo;
  if (!ctx.live) {
    ci.err = jpeg_std_error(&ctx.jerr.pub);
    ctx.jerr.pub.error_exit = jerr_exit;
    jpeg_create_decompress(&ci);
    ctx.live = true;
  }
  if (setjmp(ctx.jerr.jb)) {
    jpeg_destroy_decompress(&ci);
    ctx.live = false;
    return false;
  }
  jpeg_mem_src(&ci, ex.data(), ex.size());
  if (jpeg_read_header(&ci, TRUE) != JPEG_HEADER_OK) {
    jpeg_abort_decompress(&ci);
    return false;
  }
  ci.scale_num = (unsigned)m;
  ci.scale_denom = 8;
  ci.out_color_space = JCS_RGB;
  ci.do_fancy_upsampling = (m < 8) ? FALSE : TRUE;
  jpeg_start_decompress(&ci);
  const int SWx = (int)ci.output_width, SHx = (int)ci.output_height;
  const int sx_ex = sx - c0 * smcu_w;      // crop coords, excerpt-local
  const int local0 = read0 - rr0 * smcu_h;  // first row to READ
  const int owned0 = ay0 - rr0 * smcu_h;    // first row to KEEP
  const int local_end = ay1 - rr0 * smcu_h;
  if (SWx != (new_w * m + 7) / 8 || SHx != (new_h * m + 7) / 8 ||
      local_end > SHx || sx_ex < 0 || sx_ex + sw > SWx) {
    jpeg_abort_decompress(&ci);  // geometry drifted from the plan: bail
    return false;
  }
  const PartialApi& papi = partial_api();
  int stride, xloc;
  if (papi.crop) {
    const int px = std::max(0, sx_ex - kMargin);
    JDIMENSION jx = (JDIMENSION)px;
    JDIMENSION jw = (JDIMENSION)std::min(SWx - px, (sx_ex - px) + sw
                                         + kMargin);
    papi.crop(&ci, &jx, &jw);  // widens to iMCU alignment
    stride = (int)jw * 3;
    xloc = sx_ex - (int)jx;
    if (local0 > 0) papi.skip(&ci, (JDIMENSION)local0);
  } else {
    stride = SWx * 3;
    xloc = sx_ex;
    uint8_t* scratch0 = pool_ensure(ctx.discard, (size_t)stride);
    for (int r = 0; r < local0;) {
      JSAMPROW row = scratch0;
      r += (int)jpeg_read_scanlines(&ci, &row, 1);
    }
  }
  uint8_t* rowbuf = pool_ensure(ctx.exrow, (size_t)stride);
  for (int r = local0; r < local_end;) {
    JSAMPROW row = rowbuf;
    const int got = (int)jpeg_read_scanlines(&ci, &row, 1);
    if (got < 1) {
      jpeg_abort_decompress(&ci);
      return false;
    }
    if (r >= owned0)  // context rows above the owned range are discarded
      std::memcpy(plane + (size_t)(rr0 * smcu_h + r - sy) * (size_t)sw * 3,
                  rowbuf + (size_t)xloc * 3, (size_t)sw * 3);
    r += got;
  }
  jpeg_abort_decompress(&ci);  // rows below never parsed; struct reusable
  return true;
}

#endif  // DVGG_RESTART

// Decode `bytes`, crop per mode, write normalized pixels for one item into
// `dst_base` (float32 or bf16). Train mode samples the Inception crop + flip
// from `rng`; eval mode (cfg.eval_mode) uses the deterministic center crop.
// Returns false on decode failure (caller zero-fills). `ctx` is the calling
// thread's reusable decode context.
bool decode_one(const Config& cfg, const uint8_t* data, size_t size,
                SplitMix64& rng, uint8_t* dst_base, DecodeCtx& ctx) {
  const int64_t t_start = now_ns();
  jpeg_decompress_struct& cinfo = ctx.cinfo;
  if (!ctx.live) {
    cinfo.err = jpeg_std_error(&ctx.jerr.pub);
    ctx.jerr.pub.error_exit = jerr_exit;
    jpeg_create_decompress(&cinfo);
    ctx.live = true;
  }
  if (setjmp(ctx.jerr.jb)) {
    jpeg_destroy_decompress(&cinfo);
    ctx.live = false;
    return false;
  }
  jpeg_mem_src(&cinfo, data, size);
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_abort_decompress(&cinfo);  // soft failure: struct stays reusable
    return false;
  }
  const int W = (int)cinfo.image_width, H = (int)cinfo.image_height;
  if (W < 1 || H < 1) {
    jpeg_abort_decompress(&cinfo);
    return false;
  }

  int cx = 0, cy = 0, cw = W, ch = H;
  bool flip = false;
  if (cfg.eval_mode) {
    // Center crop: the original-coordinate preimage of "resize short side to
    // 256 → center-crop out_size": a centered square of side
    // min(W,H)*out/256, then one bilinear resample to out_size.
    int side = std::max(1, (int)std::lround(
        (double)std::min(W, H) * cfg.out_size / 256.0));
    side = std::min(side, std::min(W, H));
    cw = ch = side;
    cx = (W - side) / 2;
    cy = (H - side) / 2;
  } else {
    // Inception-style crop sampled in original coordinates.
    for (int attempt = 0; attempt < 10; ++attempt) {
      double area = (double)W * H *
          (cfg.area_min + rng.uniform() * (cfg.area_max - cfg.area_min));
      double log_lo = std::log(3.0 / 4.0), log_hi = std::log(4.0 / 3.0);
      double aspect = std::exp(log_lo + rng.uniform() * (log_hi - log_lo));
      int w = (int)std::lround(std::sqrt(area * aspect));
      int h = (int)std::lround(std::sqrt(area / aspect));
      if (w > 0 && h > 0 && w <= W && h <= H) {
        cx = (int)(rng.next() % (uint64_t)(W - w + 1));
        cy = (int)(rng.next() % (uint64_t)(H - h + 1));
        cw = w;
        ch = h;
        break;
      }
    }
    // The flip bit is ALWAYS drawn (even when host flips are disabled) so
    // the RNG stream — and therefore every later crop in the stream — is
    // identical whether flips live here or on the device (ABI v9 flip
    // ownership: data.augment.hflip moves the flip into the jitted step).
    flip = (rng.next() & 1) != 0;
    if (!cfg.hflip) flip = false;
  }

  // DCT-scaled decode: smallest power-of-two M/8 whose scaled crop still
  // covers out_size in both dims (choose_scale_m — {1,2,4,8} are turbo's
  // SIMD IDCT sizes; odd scales are net-slower). The DVGGF_DECODE_SCALED
  // kill-switch / -DDVGGF_NO_SCALED pin m=8 full-resolution decode.
  const bool use_scaled = active_scaled_kind() == 1;
  const int m = use_scaled ? choose_scale_m(cw, ch, cfg.out_size) : 8;
  // jpeg_calc_output_dimensions mirror (out = ceil(dim * m / 8)) — needed
  // BEFORE any start_decompress so the restart-excerpt geometry can be
  // planned; identical to what libjpeg reports after start_decompress.
  const int SW = (int)(((int64_t)W * m + 7) / 8);
  const int SH = (int)(((int64_t)H * m + 7) / 8);
  // crop coords in scaled space
  int sx = std::min((int)((int64_t)cx * SW / W), SW - 1);
  int sy = std::min((int)((int64_t)cy * SH / H), SH - 1);
  int sw = std::max(1, std::min((int)((int64_t)cw * SW / W), SW - sx));
  int sh = std::max(1, std::min((int)((int64_t)ch * SH / H), SH - sy));

  int row_stride = 0, x_off = 0, y_off = 0;
  int plane_rows = 0;
  uint8_t* plane = nullptr;
  bool band_ready = false;
#if DVGG_RESTART
  // Restart-marker excerpt decode (r9): when the stream carries usable
  // RSTn structure, entropy-decode ONLY the segments covering the crop
  // band (the sequential path entropy-parses every row above the crop even
  // when their IDCT is skipped), optionally fanned out across the chunk
  // pool. Any failure — scan mismatch, truncated segment, geometry drift —
  // falls through to the sequential path below, whose caller-side jpeg
  // state the attempt never touches (chunks run on their own thread_local
  // contexts; the plan scan is a pure byte walk).
  if (active_restart_kind() == 1) {
    RestartPlan plan;
    const RestartScanResult why = scan_restart_plan(data, size, plan);
    if (why != kRestartOk) {
      auto& c = why == kRestartAbsent ? g_rstats.marker_absent
                : why == kRestartUnsupported ? g_rstats.unsupported
                : why == kRestartMisaligned ? g_rstats.misaligned
                                            : g_rstats.scan_failures;
      c.fetch_add(1, std::memory_order_relaxed);
    } else {
      const size_t nseg = plan.seg_end.size();
      // whole-band selection (gain test + the unique segments-used receipt)
      // — the SAME geometry the chunks splice, via the shared helper
      const ExcerptSel band = select_excerpt(plan, m, sx, sw, sy, sy + sh);
      const size_t sel = band.count;
      const int chunks = std::min(active_restart_fanout(),
                                  std::max(1, band.rr1 - band.rr0));
      if (sel >= nseg && chunks <= 1) {
        // the band needs every segment anyway: excerpting would re-decode
        // the whole stream plus a memcpy — sequential is strictly better
        g_rstats.no_gain.fetch_add(1, std::memory_order_relaxed);
      } else {
        plane = pool_ensure(ctx.plane, (size_t)sh * sw * 3);
        int64_t pooled = 0;
        bool ok;
        if (chunks <= 1) {
          ok = decode_restart_chunk(data, plan, m, sx, sy, sw, sh,
                                    sy, sy + sh, plane);
        } else {
          std::vector<std::function<bool()>> jobs;
          jobs.reserve((size_t)chunks);
          for (int c = 0; c < chunks; ++c) {
            const int a0 = sy + (int)((int64_t)sh * c / chunks);
            const int a1 = sy + (int)((int64_t)sh * (c + 1) / chunks);
            jobs.emplace_back([&, a0, a1] {
              return decode_restart_chunk(data, plan, m, sx, sy, sw, sh,
                                          a0, a1, plane);
            });
          }
          ok = ChunkPool::instance().run(jobs, &pooled);
        }
        if (ok) {
          band_ready = true;
          row_stride = sw * 3;
          x_off = 0;
          y_off = 0;
          plane_rows = sh;
          jpeg_abort_decompress(&cinfo);  // caller struct back to start
          g_rstats.images.fetch_add(1, std::memory_order_relaxed);
          // band.count, not a per-chunk sum: overlapping chunk context
          // under fan-out would count shared segments once per chunk
          g_rstats.segments_used.fetch_add((int64_t)sel,
                                           std::memory_order_relaxed);
          if (nseg > sel)
            g_rstats.segments_skipped.fetch_add((int64_t)(nseg - sel),
                                                std::memory_order_relaxed);
          if (chunks > 1) {
            g_rstats.fanout_images.fetch_add(1, std::memory_order_relaxed);
            g_rstats.chunk_jobs_pooled.fetch_add(
                pooled, std::memory_order_relaxed);
            int64_t cur =
                g_rstats.fanout_width_max.load(std::memory_order_relaxed);
            while (chunks > cur &&
                   !g_rstats.fanout_width_max.compare_exchange_weak(
                       cur, chunks, std::memory_order_relaxed)) {
            }
          }
        } else {
          g_rstats.excerpt_fallbacks.fetch_add(1, std::memory_order_relaxed);
        }
      }
    }
  }
#endif  // DVGG_RESTART
  if (!band_ready) {
  cinfo.scale_num = (unsigned)m;
  cinfo.scale_denom = 8;
  cinfo.out_color_space = JCS_RGB;
  // Reduced-size decodes aren't byte-pinned to anything (the tolerance
  // parity suite gates them), so take the cheaper non-fancy upsampling;
  // m=8 keeps libjpeg defaults — the byte-parity anchor with the full-
  // resolution path. Set explicitly both ways: the struct is REUSED.
  cinfo.do_fancy_upsampling = (m < 8) ? FALSE : TRUE;
  jpeg_start_decompress(&cinfo);

  // Partial decode (libjpeg-turbo only, dlsym-probed): IDCT + color-convert
  // only the MCU-aligned horizontal band around the crop, and skip the IDCT
  // of the rows above it. The requested band carries a small CONTEXT MARGIN
  // on every interior edge: fancy upsampling interpolates chroma from
  // neighbor samples, and at a band edge libjpeg replicates instead — the
  // seed-era partial decode diverged from a full decode by up to ~38/255 on
  // the crop's first/last columns because of exactly this. With the margin,
  // the true crop columns/rows are interior to the decoded band and the
  // partial path is byte-identical to the full-decode fallback (pinned at
  // scale 8/8 by tests/test_native_jpeg_parity.py). Fallback (plain
  // libjpeg, or scaled decode killed): decode full-width rows and discard
  // the ones above the crop. Rows BELOW the crop are never decoded either
  // way (jpeg_abort_decompress below stops the stream early).
  const PartialApi& papi = partial_api();
  const bool partial = use_scaled && papi.crop != nullptr;
  if (partial) {
    constexpr int kMargin = 2;  // h2v2 fancy upsampling reads 1 chroma
                                // neighbor = 2 output pixels of context
    const int px = std::max(0, sx - kMargin);
    const int py = std::max(0, sy - kMargin);
    JDIMENSION jx = (JDIMENSION)px;
    JDIMENSION jw = (JDIMENSION)std::min(SW - px, (sx - px) + sw + kMargin);
    papi.crop(&cinfo, &jx, &jw);  // widens further to iMCU alignment
    row_stride = (int)jw * 3;
    x_off = sx - (int)jx;  // offset of the true crop inside the band
    if (py > 0) papi.skip(&cinfo, (JDIMENSION)py);
    y_off = sy - py;  // context rows decoded above the true crop
    g_stats.partial_images.fetch_add(1, std::memory_order_relaxed);
    g_stats.rows_skipped.fetch_add(py, std::memory_order_relaxed);
  } else {
    row_stride = SW * 3;
    x_off = sx;  // full-width rows: crop offsets fold into the tap plan
    if (use_scaled && DVGG_SCALED)
      g_stats.full_fallbacks.fetch_add(1, std::memory_order_relaxed);
    uint8_t* scratch = pool_ensure(ctx.discard, (size_t)row_stride);
    for (int r = 0; r < sy;) {  // decode-and-discard the rows above
      JSAMPROW row = scratch;
      r += (int)jpeg_read_scanlines(&cinfo, &row, 1);
    }
  }
  plane_rows = y_off + sh;
  plane = pool_ensure(ctx.plane, (size_t)plane_rows * row_stride);
  for (int r = 0; r < plane_rows;) {
    JSAMPROW row = plane + (size_t)r * row_stride;
    r += (int)jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_abort_decompress(&cinfo);  // skip remaining rows; struct reusable
  }  // !band_ready — sequential band decode
  g_stats.images.fetch_add(1, std::memory_order_relaxed);
  g_stats.scale_count[m - 1].fetch_add(1, std::memory_order_relaxed);
  g_stats.rows_truncated.fetch_add(SH - sy - sh, std::memory_order_relaxed);
  const int64_t t_jpeg_done = now_ns();

  // Bilinear resize (half-pixel centers) from the (sh, sw) region to
  // out_size, as two passes per output row through the runtime-dispatched
  // resample kernels above: vertical lerp over the contiguous decoded rows,
  // then horizontal pixel-pair lerp + normalize (+ bf16 round, + pack).
  // The r5 per-column tap hoist survives as the per-pixel (tap0, tap1,
  // weight) plan built once per image — flip folded into the taps, the
  // pack4 space-to-depth scatter folded into a precomputed destination-
  // offset table — so the hot loops are pure streams with no per-pixel
  // branching.
  const int out = cfg.out_size;
  const int n_el = out * 3;
  const float sxf = (float)sw / out, syf = (float)sh / out;
  const bool u8_wire = cfg.out_kind == kOutU8;
  float* f32 = nullptr;
  uint16_t* b16 = nullptr;
  uint8_t* u8 = nullptr;
  if (u8_wire)
    u8 = dst_base;
  else if (cfg.out_kind == kOutBf16)
    b16 = reinterpret_cast<uint16_t*>(dst_base);
  else
    f32 = reinterpret_cast<float*>(dst_base);
  const float inv_std[3] = {1.0f / cfg.std_[0], 1.0f / cfg.std_[1],
                            1.0f / cfg.std_[2]};
  int32_t* p0 = pool_ensure(ctx.p0, (size_t)out);
  int32_t* p1 = pool_ensure(ctx.p1, (size_t)out);
  float* w4 = u8_wire ? nullptr : pool_ensure(ctx.w4, (size_t)out * 4);
  uint32_t* w4i = u8_wire ? pool_ensure(ctx.w4i, (size_t)out * 4) : nullptr;
  for (int ox = 0; ox < out; ++ox) {
    int ox_src = flip ? (out - 1 - ox) : ox;
    float fx = ((float)ox_src + 0.5f) * sxf - 0.5f;
    int x0 = (int)std::floor(fx);
    float wx = fx - x0;
    int x1 = std::min(std::max(x0 + 1, 0), sw - 1);
    x0 = std::min(std::max(x0, 0), sw - 1);
    p0[ox] = (x_off + x0) * 3;
    p1[ox] = (x_off + x1) * 3;
    if (u8_wire) {
      // 8-bit fractional weight: the u8 wire's only precision loss vs the
      // float path (<= 1 intensity level after rounding, the pinned bound)
      const uint32_t wi = (uint32_t)std::lround(wx * 256.0f);
      for (int k = 0; k < 4; ++k) w4i[(size_t)ox * 4 + k] = wi;
    } else {
      for (int k = 0; k < 4; ++k) w4[(size_t)ox * 4 + k] = wx;
    }
  }
  const ResampleKernels& K = active_kernels();
  if (u8_wire) {
    // Whole u8 item: fixed-point vertical+horizontal passes, raw pixels
    // out. The +4-element vtmp16 pad mirrors the float path's (the AVX2
    // quad tap loads read one u16 past the last rgb triple).
    uint16_t* vtmp16 = pool_ensure(ctx.vtmp16, (size_t)row_stride + 4);
    for (int oy = 0; oy < out; ++oy) {
      float fy = ((float)oy + 0.5f) * syf - 0.5f;
      int y0 = (int)std::floor(fy);
      float wy = fy - y0;
      int y1 = std::min(std::max(y0 + 1, 0), sh - 1);
      y0 = std::min(std::max(y0, 0), sh - 1);
      const uint32_t wy8 = (uint32_t)std::lround(wy * 256.0f);
      K.v_u8(plane + (size_t)(y_off + y0) * row_stride,
             plane + (size_t)(y_off + y1) * row_stride, wy8, vtmp16,
             row_stride);
      K.h_u8(p0, p1, w4i, vtmp16, u8 + (size_t)oy * n_el, out);
    }
    g_ns_jpeg.fetch_add(t_jpeg_done - t_start, std::memory_order_relaxed);
    g_ns_resample.fetch_add(now_ns() - t_jpeg_done,
                            std::memory_order_relaxed);
    g_profiled_images.fetch_add(1, std::memory_order_relaxed);
    return true;
  }
  // +4 floats of tail: the AVX2 quad tap loads read one float past the last
  // rgb triple of the row. The tail values never survive into dst (every
  // stray lane is overwritten or handled scalar — see the kernel comments),
  // but the loads must land in owned memory.
  float* vtmp = pool_ensure(ctx.vtmp, (size_t)row_stride + 4);
  float* row_f32 = cfg.pack4 && !b16
                       ? pool_ensure(ctx.row_f32, (size_t)n_el) : nullptr;
  uint16_t* row_b16 = cfg.pack4 && b16
                          ? pool_ensure(ctx.row_b16, (size_t)n_el) : nullptr;
  for (int oy = 0; oy < out; ++oy) {
    float fy = ((float)oy + 0.5f) * syf - 0.5f;
    int y0 = (int)std::floor(fy);
    float wy = fy - y0;
    int y1 = std::min(std::max(y0 + 1, 0), sh - 1);
    y0 = std::min(std::max(y0, 0), sh - 1);
    K.vlerp(plane + (size_t)(y_off + y0) * row_stride,
            plane + (size_t)(y_off + y1) * row_stride, wy, vtmp, row_stride);
    if (!cfg.pack4) {
      if (b16)
        K.h_bf16(p0, p1, w4, cfg.mean, inv_std,
                 vtmp, b16 + (size_t)oy * n_el, out);
      else
        K.h_f32(p0, p1, w4, cfg.mean, inv_std,
                vtmp, f32 + (size_t)oy * n_el, out);
    } else {
      // space-to-depth destination, channel order (dy, dx, c) — matches
      // tf.nn.space_to_depth and models/vggf.py Conv1SpaceToDepth. Within
      // one row, each 4-pixel group's 12 elements land CONTIGUOUS at
      // element offset 48·g from the row's (oy-dependent) base, so the
      // repack is out/4 straight 12-element copies, not a per-element
      // scatter (pack4 guarantees out % 4 == 0).
      const size_t base =
          (((size_t)(oy >> 2) * (out >> 2)) * 16 + (size_t)(oy & 3) * 4) * 3;
      if (b16) {
        K.h_bf16(p0, p1, w4, cfg.mean, inv_std, vtmp, row_b16, out);
        for (int g = 0; g < out / 4; ++g)
          std::memcpy(b16 + base + 48 * (size_t)g, row_b16 + 12 * g,
                      12 * sizeof(uint16_t));
      } else {
        K.h_f32(p0, p1, w4, cfg.mean, inv_std, vtmp, row_f32, out);
        for (int g = 0; g < out / 4; ++g)
          std::memcpy(f32 + base + 48 * (size_t)g, row_f32 + 12 * g,
                      12 * sizeof(float));
      }
    }
  }
  g_ns_jpeg.fetch_add(t_jpeg_done - t_start, std::memory_order_relaxed);
  g_ns_resample.fetch_add(now_ns() - t_jpeg_done, std::memory_order_relaxed);
  g_profiled_images.fetch_add(1, std::memory_order_relaxed);
  return true;
}

// ---------------------------------------------------------------- loader
class JpegLoader {
 public:
  explicit JpegLoader(Config cfg)
      : cfg_(std::move(cfg)),
        item_bytes_((size_t)cfg_.out_size * cfg_.out_size * 3 *
                    out_kind_bytes(cfg_.out_kind)),
        slots_(kDepth) {
    for (auto& s : slots_) {
      s.images.resize(item_bytes_ * cfg_.batch);
      s.labels.resize(cfg_.batch);
    }
    if (cfg_.finite) {
      total_batches_ =
          ((int64_t)cfg_.items.size() + cfg_.batch - 1) / cfg_.batch;
    }
    // workers start lazily on the first next(): seek() must be able to set
    // the stream position before any item is claimed.
  }

  ~JpegLoader() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      stop_ = true;
    }
    cv_prod_.notify_all();
    cv_cons_.notify_all();
    for (auto& t : workers_) t.join();
  }

  void seek(int64_t batch_index) {
    // only valid before the first next() (workers have not started yet); the
    // stream is a pure function of (seed, batch_index), so this IS exact
    // deterministic resume.
    std::lock_guard<std::mutex> lk(mu_);
    if (!workers_.empty()) return;  // too late — position already consumed
    consume_index_ = batch_index;
    next_item_ = batch_index * cfg_.batch;
  }

  // Flip ownership (ABI v9): 0 = the host never flips (on-device
  // augmentation owns it). Mirror of seek()'s race contract — only valid
  // BEFORE the first next() (workers read cfg_.hflip without a lock once
  // they run); returns the now-active value, or -1 when workers already
  // started (callers must treat -1 as "too late", never as success).
  int set_hflip(int enabled) {
    std::lock_guard<std::mutex> lk(mu_);
    if (!workers_.empty()) return -1;
    cfg_.hflip = enabled ? 1 : 0;
    return cfg_.hflip;
  }

  int hflip() {
    std::lock_guard<std::mutex> lk(mu_);
    return cfg_.hflip;
  }

  // Returns 0 with *valid in (0, batch] on success (< batch only on the final
  // partial batch of a finite pass), 1 on end-of-stream, 2 on shutdown.
  int next(uint8_t* out_images, int32_t* out_labels, int32_t* valid) {
    std::unique_lock<std::mutex> lk(mu_);
    if (cfg_.finite && consume_index_ >= total_batches_) return 1;
    if (workers_.empty() && !stop_)
      for (int t = 0; t < std::max(1, cfg_.num_threads); ++t)
        workers_.emplace_back([this] { worker(); });
    Slot& s = slots_[(size_t)(consume_index_ % kDepth)];
    cv_cons_.wait(lk, [&] {
      return stop_ || (s.target_batch == consume_index_ && s.remaining == 0);
    });
    if (stop_) return 2;
    // The slot is exclusively ours while target_batch == consume_index_ (no
    // producer touches it until consume_index_ advances), so the big copy
    // runs with the lock RELEASED — holding mu_ across a multi-hundred-MB
    // memcpy would stall every decode worker each batch.
    int32_t n_valid = s.valid;
    lk.unlock();
    std::memcpy(out_images, s.images.data(), s.images.size());
    std::memcpy(out_labels, s.labels.data(),
                s.labels.size() * sizeof(int32_t));
    lk.lock();
    s.target_batch = -1;  // slot free
    ++consume_index_;
    cv_prod_.notify_all();
    if (valid) *valid = n_valid;
    return 0;
  }

  int64_t decode_errors() const { return decode_errors_.load(); }

  // Runtime pool resize (r11, ABI v8): grow spawns fresh workers that join
  // the item-claim loop immediately; shrink posts exit requests that idle
  // workers consume at their next wakeup — BEFORE claiming an item, so no
  // half-produced slot is ever abandoned. The stream is untouched either
  // way (items are claimed under mu_ in global order; determinism is a
  // function of (seed, batch index), not worker count). Finished
  // std::thread objects stay in workers_ (inert; joined in the
  // destructor). Returns the now-active target.
  int set_threads(int n) {
    n = clamp_threads(n);
    std::lock_guard<std::mutex> lk(mu_);
    cfg_.num_threads = n;  // also the lazy-start width
    if (workers_.empty() || stop_) return n;
    int active = (int)workers_.size() - exited_ - exit_requests_;
    if (n > active) {
      for (int i = 0; i < n - active; ++i)
        workers_.emplace_back([this] { worker(); });
    } else if (n < active) {
      exit_requests_ += active - n;
      cv_prod_.notify_all();
    }
    return n;
  }

  int num_threads() {
    std::lock_guard<std::mutex> lk(mu_);
    return std::max(1, cfg_.num_threads);
  }

 private:
  // 3 batch slots regardless of thread count: one being consumed, two in
  // flight. Workers share batches at ITEM granularity, so a single slot's
  // batch is decoded by all threads in parallel (first-batch latency) and
  // host RAM stays at 3 batch buffers (a whole-batch-per-worker design costs
  // (threads+1) buffers — ~11 GB at local_batch 2048 f32 with 8 threads).
  static constexpr int kDepth = 3;

  struct Slot {
    std::vector<uint8_t> images;
    std::vector<int32_t> labels;
    int64_t target_batch = -1;  // -1 = free
    int remaining = 0;          // items not yet decoded into this slot
    int32_t valid = 0;          // items actually present (finite final batch)
  };

  // Number of items in batch b (only the final batch of a finite pass is
  // short; infinite streams always fill the batch).
  int batch_items(int64_t b) const {
    if (!cfg_.finite) return cfg_.batch;
    int64_t n = (int64_t)cfg_.items.size();
    return (int)std::min<int64_t>(cfg_.batch, n - b * cfg_.batch);
  }

  void worker() {
    std::vector<uint8_t> bytes;
    DecodeCtx ctx;  // per-thread: reused jpeg struct + pooled decode buffers
    // per-thread single-file cache: TFRecord items cluster by file, so most
    // claims reuse the already-open container
    FILE* cached_f = nullptr;
    int32_t cached_path = -1;
    std::vector<int64_t> order;
    int64_t cached_epoch = -1;
    while (true) {
      int64_t g, b;
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_prod_.wait(lk, [&] {
          if (stop_ || exit_requests_ > 0) return true;
          if (cfg_.finite &&
              next_item_ >= (int64_t)cfg_.items.size()) return false;
          return next_item_ / cfg_.batch - consume_index_ < kDepth;
        });
        if (stop_) break;
        if (exit_requests_ > 0) {
          // shrink: consume one request and retire — checked before any
          // item claim, so the slot accounting never sees a dead producer
          --exit_requests_;
          ++exited_;
          break;
        }
        g = next_item_++;
        b = g / cfg_.batch;
        Slot& s = slots_[(size_t)(b % kDepth)];
        if (s.target_batch != b) {
          // first item claimed for this batch initializes the slot (claims
          // are serialized under mu_, and the gate above guarantees the slot
          // is free: its previous batch was consumed)
          s.target_batch = b;
          s.valid = batch_items(b);
          s.remaining = s.valid;
          if (cfg_.finite && s.valid < cfg_.batch) {
            std::memset(s.images.data() + (size_t)s.valid * item_bytes_, 0,
                        (size_t)(cfg_.batch - s.valid) * item_bytes_);
            std::fill(s.labels.begin() + s.valid, s.labels.end(), 0);
          }
        }
      }
      produce_item(g, bytes, ctx, cached_f, cached_path, order, cached_epoch);
      {
        std::lock_guard<std::mutex> lk(mu_);
        Slot& s = slots_[(size_t)(g / cfg_.batch % kDepth)];
        if (--s.remaining == 0) cv_cons_.notify_all();
      }
    }
    if (cached_f) std::fclose(cached_f);
  }

  // index of the global item `g` in the (epoch-shuffled unless eval) order
  int64_t item_index(int64_t g, std::vector<int64_t>& order,
                     int64_t& cached_epoch) {
    const int64_t n = (int64_t)cfg_.items.size();
    int64_t epoch = g / n, pos = g % n;
    if (cfg_.eval_mode || cfg_.finite) return pos;  // identity, in order
    if (epoch != cached_epoch) {
      if ((int64_t)order.size() != n) order.resize(n);
      for (int64_t i = 0; i < n; ++i) order[i] = i;
      shuffle_indices(order, cfg_.seed, (uint64_t)epoch);
      cached_epoch = epoch;
    }
    return order[pos];
  }

  void produce_item(int64_t g, std::vector<uint8_t>& bytes, DecodeCtx& ctx,
                    FILE*& cached_f, int32_t& cached_path,
                    std::vector<int64_t>& order, int64_t& cached_epoch) {
    Slot& s = slots_[(size_t)(g / cfg_.batch % kDepth)];
    int j = (int)(g % cfg_.batch);
    int64_t idx = item_index(g, order, cached_epoch);
    const Item& it = cfg_.items[(size_t)idx];
    s.labels[(size_t)j] = cfg_.labels[(size_t)idx];
    SplitMix64 rng(mix(cfg_.seed, 0xA0A0ULL + (uint64_t)g));
    uint8_t* dst = s.images.data() + (size_t)j * item_bytes_;
    if (it.path != cached_path) {
      if (cached_f) std::fclose(cached_f);
      cached_f = std::fopen(cfg_.paths[(size_t)it.path].c_str(), "rb");
      cached_path = it.path;
    }
    bool ok = false;
    FILE* f = cached_f;
    if (f) {
      int64_t off = it.offset, len = it.length;
      if (off < 0) {  // whole file
        std::fseek(f, 0, SEEK_END);
        len = std::ftell(f);
        off = 0;
      }
      if (len > 0 && std::fseek(f, (long)off, SEEK_SET) == 0) {
        bytes.resize((size_t)len);
        if (std::fread(bytes.data(), 1, (size_t)len, f) == (size_t)len)
          ok = decode_one(cfg_, bytes.data(), bytes.size(), rng, dst, ctx);
      }
    }
    if (!ok) {
      fill_failed_item(dst);
      decode_errors_.fetch_add(1);
    }
  }

  // Corrupt-image fallback. Host wires (f32/bf16) zero-fill POST-normalize
  // values — the failed item reads as a mean-colored image downstream. On
  // the u8 wire a raw 0 would device-normalize to (0-mean)/std ~ -2 sigma
  // (a black image), i.e. the SAME failing input would yield materially
  // different training data per wire. Fill with the rounded per-channel
  // mean instead: the device finish lands within half an intensity level
  // of the host wires' zero — inside the wire's pinned quantization bound.
  void fill_failed_item(uint8_t* dst) const {
    if (cfg_.out_kind != kOutU8) {
      std::memset(dst, 0, item_bytes_);
      return;
    }
    uint8_t m[3];
    for (int c = 0; c < 3; ++c) {
      float v = cfg_.mean[c];
      v = v < 0.0f ? 0.0f : (v > 255.0f ? 255.0f : v);
      m[c] = (uint8_t)std::lround(v);
    }
    const size_t px = item_bytes_ / 3;
    for (size_t i = 0; i < px; ++i) {
      dst[3 * i + 0] = m[0];
      dst[3 * i + 1] = m[1];
      dst[3 * i + 2] = m[2];
    }
  }

  Config cfg_;
  size_t item_bytes_;
  std::vector<Slot> slots_;
  std::vector<std::thread> workers_;
  std::mutex mu_;
  std::condition_variable cv_prod_, cv_cons_;
  int64_t next_item_ = 0;    // next global item to claim (guarded by mu_)
  int64_t consume_index_ = 0;
  int64_t total_batches_ = -1;  // finite mode only
  int exit_requests_ = 0;    // shrink requests not yet consumed (mu_)
  int exited_ = 0;           // workers retired by resize (mu_)
  bool stop_ = false;
  std::atomic<int64_t> decode_errors_{0};
};

Config base_config(const char* paths_blob, const int64_t* path_offsets,
                   int64_t n_paths, const int32_t* labels, int64_t n_items,
                   int batch, int out_size, uint64_t seed, const float* mean,
                   const float* stddev, int num_threads, int out_kind,
                   double area_min, double area_max) {
  Config cfg;
  cfg.paths.reserve((size_t)n_paths);
  for (int64_t i = 0; i < n_paths; ++i)
    cfg.paths.emplace_back(paths_blob + path_offsets[i],
                           (size_t)(path_offsets[i + 1] - path_offsets[i]));
  cfg.labels.assign(labels, labels + n_items);
  cfg.batch = batch;
  cfg.out_size = out_size;
  cfg.seed = seed;
  for (int c = 0; c < 3; ++c) {
    cfg.mean[c] = mean[c];
    cfg.std_[c] = stddev[c];
  }
  cfg.num_threads = std::max(1, num_threads);
  cfg.out_kind = out_kind;
  cfg.area_min = area_min;
  cfg.area_max = area_max;
  cfg.eval_mode = 0;
  cfg.finite = 0;
  cfg.pack4 = 0;
  return cfg;
}

// Output-kind gate shared by every creation surface: 0/1 always valid;
// 2 (u8 wire) only when compiled in AND not kill-switched — a refused kind
// fails creation so the caller falls back ABOVE the ABI (format decisions
// cannot be absorbed transparently down here). pack4 stays host-normalize-
// only: the u8 wire's space-to-depth belongs to the device-finish prologue.
bool out_kind_ok(int out_kind, int pack4) {
  if (out_kind == kOutF32 || out_kind == kOutBf16) return true;
  if (out_kind != kOutU8) return false;
  return active_wire_u8() == 1 && !pack4;
}

}  // namespace

extern "C" {

// Bumped on EVERY C-ABI change; the Python binding refuses (and force-
// rebuilds) a library whose version doesn't match. Guards against a stale
// cached .so whose mtime check passed (tar/rsync/cp -p timestamp ties): a
// signature mismatch would otherwise be silently absorbed by cdecl and
// corrupt batches instead of failing.
// v4: SIMD resample dispatch (simd_supported/kind/set) + phase profile.
// v5: scaled-decode dispatch (scaled_supported/kind/set), partial-decode
//     probe, scale chooser export, decode stats (scale histogram, skipped/
//     truncated scanlines, buffer-pool hit rate).
// v6: uint8 wire mode — the loaders' `bf16_out` int becomes the 3-state
//     `out_kind` (0 f32, 1 bf16, 2 u8 raw pixels; 0/1 unchanged), plus the
//     wire_u8_supported/kind/set dispatch triple (DVGGF_WIRE_U8 env
//     kill-switch, -DDVGGF_NO_WIRE_U8 compile-out). Creation with kind 2
//     FAILS when the u8 wire is compiled out or killed — callers fall back
//     to the host-normalize wire above the ABI.
// v7: restart-marker-parallel entropy decode — the
//     restart_supported/kind/set dispatch triple (DVGGF_DECODE_RESTART env
//     kill-switch, -DDVGGF_NO_RESTART compile-out), the fan-out width pair
//     (restart_fanout/set_restart_fanout; DVGGF_RESTART_FANOUT env),
//     restart_stats receipts, and dvgg_jpeg_reencode_restart (lossless
//     coefficient-domain transcode injecting RSTn markers — the offline
//     dataset-indexing tool's engine, compiled in regardless of
//     -DDVGGF_NO_RESTART because it is encode-side machinery).
// v8: runtime thread-pool grow/shrink — per-loader
//     dvgg_jpeg_loader_set_threads / dvgg_jpeg_loader_num_threads (the
//     closed-loop ingest autotuner's decode-worker knob, data/autotune.py)
//     plus the resize_supported/kind/set dispatch triple
//     (DVGGF_THREAD_RESIZE env kill-switch, -DDVGGF_NO_RESIZE compile-out).
//     Resize never changes pixels: the stream stays a pure function of
//     (seed, batch index) at any worker count.
// v9: flip ownership — per-loader dvgg_jpeg_loader_set_hflip /
//     dvgg_jpeg_loader_hflip (0 = the fused on-device augmentation stage,
//     data/augment.py, owns the horizontal flip; the host never flips) and
//     an `hflip` argument on dvgg_jpeg_decode_single (the snapshot cache's
//     repair path must reproduce flips-disabled crops). The flip bit is
//     drawn from the per-item RNG either way, so crop geometry is
//     bit-identical at both settings.
int64_t dvgg_jpeg_loader_abi_version() { return 9; }

// 1 iff AVX2+FMA kernels are compiled in AND the running CPU supports them.
int dvgg_jpeg_simd_supported() { return simd_supported(); }

// Active resample path: 0 scalar, 1 avx2. First call resolves cpuid + the
// DVGGF_DECODE_SIMD env kill-switch.
int dvgg_jpeg_simd_kind() { return active_simd_kind(); }

// Force the resample path at runtime (enable=0 → scalar; nonzero → SIMD if
// supported). Returns the now-active kind — the parity tests decode the
// same bytes through both paths in one process with this.
int dvgg_jpeg_set_simd(int enable) {
  g_simd_kind.store(enable ? simd_supported() : 0,
                    std::memory_order_relaxed);
  return active_simd_kind();
}

// 1 unless the DCT-scaled + partial decode machinery was compiled out
// (-DDVGGF_NO_SCALED).
int dvgg_jpeg_scaled_supported() { return scaled_supported(); }

// Active decode strategy: 0 full-resolution, 1 DCT-scaled + partial. First
// call resolves the DVGGF_DECODE_SCALED env kill-switch.
int dvgg_jpeg_scaled_kind() { return active_scaled_kind(); }

// Force the decode strategy at runtime (enable=0 → full resolution;
// nonzero → scaled when compiled in). Returns the now-active kind — the
// tolerance-parity suite decodes the same bytes through both strategies in
// one process with this.
int dvgg_jpeg_set_scaled(int enable) {
  g_scaled_kind.store(enable ? scaled_supported() : 0,
                      std::memory_order_relaxed);
  return active_scaled_kind();
}

// 1 iff the running libjpeg provides the partial-decode pair
// (jpeg_crop_scanline + jpeg_skip_scanlines — libjpeg-turbo extensions,
// dlsym-probed). 0 means the scaled path falls back to full-width decode.
int dvgg_jpeg_partial_supported() { return partial_supported(); }

// 1 unless the u8 wire mode was compiled out (-DDVGGF_NO_WIRE_U8).
int dvgg_jpeg_wire_u8_supported() { return wire_u8_supported(); }

// Active u8-wire availability: 0 = refused (loader creation with the u8
// output kind fails), 1 = available. First call resolves the DVGGF_WIRE_U8
// env kill-switch.
int dvgg_jpeg_wire_u8_kind() { return active_wire_u8(); }

// Force the u8-wire availability at runtime (enable=0 → refuse; nonzero →
// available when compiled in). Returns the now-active kind — how the
// parity/fallback tests exercise both wires in one process. Only affects
// loaders created AFTER the call; live loaders keep their output kind.
int dvgg_jpeg_set_wire_u8(int enable) {
  g_wire_u8.store(enable ? wire_u8_supported() : 0,
                  std::memory_order_relaxed);
  return active_wire_u8();
}

// 1 unless the restart-marker excerpt decode was compiled out
// (-DDVGGF_NO_RESTART).
int dvgg_jpeg_restart_supported() { return restart_supported(); }

// Active entropy-decode strategy: 0 sequential only, 1 restart-marker
// excerpt decode when the stream carries usable RSTn structure. First call
// resolves the DVGGF_DECODE_RESTART env kill-switch.
int dvgg_jpeg_restart_kind() { return active_restart_kind(); }

// Force the entropy strategy at runtime (enable=0 → sequential; nonzero →
// restart excerpts when compiled in). Returns the now-active kind — the
// parity suite decodes the same marker-bearing bytes through both entropy
// paths in one process with this.
int dvgg_jpeg_set_restart(int enable) {
  g_restart_kind.store(enable ? restart_supported() : 0,
                       std::memory_order_relaxed);
  return active_restart_kind();
}

// Active intra-image fan-out width (1 = no fan-out; resolves the
// DVGGF_RESTART_FANOUT env default on first call).
int dvgg_jpeg_restart_fanout() { return active_restart_fanout(); }

// Set the fan-out width at runtime (clamped to [1, 64]). Returns the
// now-active width. Fan-out trades cores for latency — per-core throughput
// (the provisioning metric) is served by width 1.
int dvgg_jpeg_set_restart_fanout(int n) {
  g_restart_fanout.store(clamp_fanout(n), std::memory_order_relaxed);
  return active_restart_fanout();
}

// 1 unless the runtime thread-pool resize was compiled out
// (-DDVGGF_NO_RESIZE).
int dvgg_jpeg_resize_supported() { return resize_supported(); }

// Active resize availability: 0 = refused (set_threads is a no-op
// returning -1), 1 = live grow/shrink allowed. First call resolves the
// DVGGF_THREAD_RESIZE env kill-switch.
int dvgg_jpeg_resize_kind() { return active_resize_kind(); }

// Force the resize availability at runtime (enable=0 → refuse; nonzero →
// allowed when compiled in). Returns the now-active kind — how the
// kill-switch tests exercise both behaviors in one process.
int dvgg_jpeg_set_resize(int enable) {
  g_resize_kind.store(enable ? resize_supported() : 0,
                      std::memory_order_relaxed);
  return active_resize_kind();
}

// Cumulative restart-path receipts since load/reset (process-wide):
// out[0]  images decoded via excerpts
// out[1]  marker_absent (no DRI / zero interval)
// out[2]  unsupported (progressive/arithmetic/multi-scan/non-interleaved)
// out[3]  misaligned (interval incompatible with the MCU row)
// out[4]  scan_failures (bogus RSTn order, count mismatch, truncation)
// out[5]  excerpt_fallbacks (excerpt decode failed → sequential retry)
// out[6]  segments entropy-decoded by the excerpt path
// out[7]  segments never parsed (the skipped entropy work)
// out[8]  images split across threads (fan-out > 1)
// out[9]  max fan-out width observed
// out[10] chunk jobs run by pool threads
// out[11] no_gain (band covered every segment; sequential used)
// out[12..15] reserved (0)
void dvgg_jpeg_restart_stats(int64_t* out) {
  if (!out) return;
  out[0] = g_rstats.images.load(std::memory_order_relaxed);
  out[1] = g_rstats.marker_absent.load(std::memory_order_relaxed);
  out[2] = g_rstats.unsupported.load(std::memory_order_relaxed);
  out[3] = g_rstats.misaligned.load(std::memory_order_relaxed);
  out[4] = g_rstats.scan_failures.load(std::memory_order_relaxed);
  out[5] = g_rstats.excerpt_fallbacks.load(std::memory_order_relaxed);
  out[6] = g_rstats.segments_used.load(std::memory_order_relaxed);
  out[7] = g_rstats.segments_skipped.load(std::memory_order_relaxed);
  out[8] = g_rstats.fanout_images.load(std::memory_order_relaxed);
  out[9] = g_rstats.fanout_width_max.load(std::memory_order_relaxed);
  out[10] = g_rstats.chunk_jobs_pooled.load(std::memory_order_relaxed);
  out[11] = g_rstats.no_gain.load(std::memory_order_relaxed);
  out[12] = out[13] = out[14] = out[15] = 0;
}

void dvgg_jpeg_restart_stats_reset() {
  g_rstats.images.store(0, std::memory_order_relaxed);
  g_rstats.marker_absent.store(0, std::memory_order_relaxed);
  g_rstats.unsupported.store(0, std::memory_order_relaxed);
  g_rstats.misaligned.store(0, std::memory_order_relaxed);
  g_rstats.scan_failures.store(0, std::memory_order_relaxed);
  g_rstats.excerpt_fallbacks.store(0, std::memory_order_relaxed);
  g_rstats.segments_used.store(0, std::memory_order_relaxed);
  g_rstats.segments_skipped.store(0, std::memory_order_relaxed);
  g_rstats.fanout_images.store(0, std::memory_order_relaxed);
  g_rstats.fanout_width_max.store(0, std::memory_order_relaxed);
  g_rstats.chunk_jobs_pooled.store(0, std::memory_order_relaxed);
  g_rstats.no_gain.store(0, std::memory_order_relaxed);
}

// Lossless restart-marker injection (the offline re-encode/indexing tool's
// engine, benchmarks/reencode_restart.py): decode to DCT coefficients,
// re-entropy-code with `interval_mcus` restart markers (0 = one marker per
// MCU row — the row-trimmable layout the excerpt decoder likes best).
// TRANSCODE, not re-compress: the quantized coefficients are copied bit-
// exact, so the decoded pixels are identical to the source's (progressive
// sources additionally normalize to baseline sequential — a decode-speed
// win in itself). optimize_coding is forced so the output always carries
// Huffman tables valid for sequential emission.
// Returns: bytes written to `out` on success; -needed when out_cap is too
// small (call again with a bigger buffer); -1 on decode/encode failure;
// -2 on bad arguments.
int64_t dvgg_jpeg_reencode_restart(const uint8_t* in, int64_t in_size,
                                   int interval_mcus, uint8_t* out,
                                   int64_t out_cap) {
  if (!in || in_size <= 0 || interval_mcus < 0 || !out || out_cap < 0)
    return -2;
  jpeg_decompress_struct src;
  jpeg_compress_struct dst;
  JerrMgr serr, derr;
  // thread_local, not automatic: jpeg_mem_dest rewrites outbuf through its
  // stored pointer inside longjmp-capable calls, and an automatic local
  // modified between setjmp and longjmp is indeterminate at `done:` (the
  // free would leak or crash on every corrupt input). Thread storage
  // duration is exempt from that rule; no recursion reaches here.
  static thread_local unsigned char* outbuf;
  static thread_local unsigned long outsize;
  outbuf = nullptr;
  outsize = 0;
  jvirt_barray_ptr* coefs = nullptr;
  long interval = 0;
  int hmax = 1;
  long mcus_per_row = 0;
  int64_t ret = -1;

  src.err = jpeg_std_error(&serr.pub);
  serr.pub.error_exit = jerr_exit;
  dst.err = jpeg_std_error(&derr.pub);
  derr.pub.error_exit = jerr_exit;
  jpeg_create_decompress(&src);
  jpeg_create_compress(&dst);
  if (setjmp(serr.jb)) goto done;
  if (setjmp(derr.jb)) goto done;
  jpeg_mem_src(&src, in, (unsigned long)in_size);
  if (jpeg_read_header(&src, TRUE) != JPEG_HEADER_OK) goto done;
  coefs = jpeg_read_coefficients(&src);
  if (!coefs) goto done;
  jpeg_copy_critical_parameters(&src, &dst);
  for (int c = 0; c < src.num_components; ++c)
    hmax = std::max(hmax, src.comp_info[c].h_samp_factor);
  mcus_per_row = ((long)src.image_width + 8 * hmax - 1) / (8 * hmax);
  interval = interval_mcus > 0 ? interval_mcus : mcus_per_row;
  if (interval > 65535) interval = 65535;
  dst.restart_interval = (unsigned)interval;
  dst.optimize_coding = TRUE;
  jpeg_mem_dest(&dst, &outbuf, &outsize);
  jpeg_write_coefficients(&dst, coefs);
  jpeg_finish_compress(&dst);
  jpeg_finish_decompress(&src);
  if (out_cap >= (int64_t)outsize) {
    std::memcpy(out, outbuf, outsize);
    ret = (int64_t)outsize;
  } else {
    ret = -(int64_t)outsize;  // caller retries with a buffer this big
  }
done:
  jpeg_destroy_compress(&dst);
  jpeg_destroy_decompress(&src);
  if (outbuf) free(outbuf);
  return ret;
}

// The scale chooser as a pure function: scale_num (denom 8) the scaled
// path picks for a (crop_w, crop_h) region resized to out_size. Exported
// for the Python mirror test (tests/test_native_jpeg.py) — the never-
// upscale invariant and the power-of-two preference are pinned against
// this, not against a re-derivation.
int dvgg_jpeg_choose_scale(int crop_w, int crop_h, int out_size) {
  if (crop_w < 1 || crop_h < 1 || out_size < 1) return 8;
  return choose_scale_m(crop_w, crop_h, out_size);
}

// Cumulative decode receipts since load/reset (process-wide, all threads):
// out[0]  images decoded
// out[1..8]  chosen-scale histogram (count of images decoded at m/8,
//            m = index)
// out[9]  scanlines skipped above the crop (partial path: entropy-parsed,
//         IDCT skipped)
// out[10] scanlines truncated below the crop (never decoded)
// out[11] buffer-pool hits   (reuse with capacity already held)
// out[12] buffer-pool misses (cold start or growth)
// out[13] images decoded through the partial (crop+skip) path
// out[14] images that wanted partial decode but fell back to full-width
//         (libjpeg without the turbo API)
// out[15] reserved (0)
void dvgg_jpeg_decode_stats(int64_t* out) {
  if (!out) return;
  out[0] = g_stats.images.load(std::memory_order_relaxed);
  for (int m = 1; m <= 8; ++m)
    out[m] = g_stats.scale_count[m - 1].load(std::memory_order_relaxed);
  out[9] = g_stats.rows_skipped.load(std::memory_order_relaxed);
  out[10] = g_stats.rows_truncated.load(std::memory_order_relaxed);
  out[11] = g_stats.pool_hits.load(std::memory_order_relaxed);
  out[12] = g_stats.pool_misses.load(std::memory_order_relaxed);
  out[13] = g_stats.partial_images.load(std::memory_order_relaxed);
  out[14] = g_stats.full_fallbacks.load(std::memory_order_relaxed);
  out[15] = 0;
}

void dvgg_jpeg_decode_stats_reset() {
  g_stats.images.store(0, std::memory_order_relaxed);
  for (auto& c : g_stats.scale_count) c.store(0, std::memory_order_relaxed);
  g_stats.rows_skipped.store(0, std::memory_order_relaxed);
  g_stats.rows_truncated.store(0, std::memory_order_relaxed);
  g_stats.pool_hits.store(0, std::memory_order_relaxed);
  g_stats.pool_misses.store(0, std::memory_order_relaxed);
  g_stats.partial_images.store(0, std::memory_order_relaxed);
  g_stats.full_fallbacks.store(0, std::memory_order_relaxed);
}

// Cumulative successful-decode phase split since load/reset:
// out[0] = libjpeg ns (header+entropy+IDCT+color), out[1] = resample ns
// (the kernels above), out[2] = images. Process-wide, all threads.
void dvgg_jpeg_profile_ns(int64_t* out) {
  if (!out) return;
  out[0] = g_ns_jpeg.load(std::memory_order_relaxed);
  out[1] = g_ns_resample.load(std::memory_order_relaxed);
  out[2] = g_profiled_images.load(std::memory_order_relaxed);
}

void dvgg_jpeg_profile_reset() {
  g_ns_jpeg.store(0, std::memory_order_relaxed);
  g_ns_resample.store(0, std::memory_order_relaxed);
  g_profiled_images.store(0, std::memory_order_relaxed);
}

// Stateless single-image decode for external pipeline frameworks (the Grain
// backend's per-record transform, data/grain_imagenet.py): same crop/
// resize/normalize math as the batch loader, with the per-item RNG seeded
// explicitly by the caller (derive from (seed, record index) for a stream
// that is a pure function of position). Returns 0 ok, 1 decode failure
// (caller zero-fills), 2 bad args.
int dvgg_jpeg_decode_single(const uint8_t* data, int64_t size, int out_size,
                            const float* mean, const float* stddev,
                            int out_kind, int pack4, int eval_mode, int hflip,
                            double area_min, double area_max,
                            uint64_t rng_seed, void* out) {
  if (!data || size <= 0 || out_size <= 0 || !out) return 2;
  if (pack4 && out_size % 4 != 0) return 2;
  if (!out_kind_ok(out_kind, pack4)) return 2;
  Config cfg;
  cfg.batch = 1;
  cfg.out_size = out_size;
  cfg.seed = 0;
  for (int c = 0; c < 3; ++c) {
    cfg.mean[c] = mean[c];
    cfg.std_[c] = stddev[c];
  }
  cfg.num_threads = 1;
  cfg.out_kind = out_kind;
  cfg.area_min = area_min;
  cfg.area_max = area_max;
  cfg.eval_mode = eval_mode;
  cfg.finite = 0;
  cfg.pack4 = pack4;
  // ABI v9 flip ownership: hflip=0 reproduces a crop from a flips-disabled
  // stream (the snapshot cache's repair path under device-side
  // augmentation). The flip bit is still drawn — same RNG stream.
  cfg.hflip = hflip ? 1 : 0;
  SplitMix64 rng(rng_seed);
  // Per-thread reusable context, same as the batch workers: the Grain
  // per-record transform calls this on a hot path too.
  static thread_local DecodeCtx ctx;
  return decode_one(cfg, data, (size_t)size, rng,
                    reinterpret_cast<uint8_t*>(out), ctx) ? 0 : 1;
}

// Whole-file items: one path per item (the raw-JPEG directory layout).
void* dvgg_jpeg_loader_create(const char* paths_blob,
                              const int64_t* path_offsets,  // n+1 offsets
                              const int32_t* labels, int64_t n, int batch,
                              int out_size, uint64_t seed, const float* mean,
                              const float* stddev, int num_threads,
                              int out_kind, double area_min, double area_max) {
  if (n <= 0 || batch <= 0 || out_size <= 0) return nullptr;
  if (!out_kind_ok(out_kind, 0)) return nullptr;
  Config cfg = base_config(paths_blob, path_offsets, n, labels, n, batch,
                           out_size, seed, mean, stddev, num_threads, out_kind,
                           area_min, area_max);
  cfg.items.resize((size_t)n);
  for (int64_t i = 0; i < n; ++i)
    cfg.items[(size_t)i] = Item{(int32_t)i, -1, 0};
  try {
    return new JpegLoader(std::move(cfg));
  } catch (...) {
    return nullptr;
  }
}

// Ranged items: `n_items` byte ranges (item_path[i], item_offset[i],
// item_length[i]) into a table of `n_paths` files — the TFRecord layout
// (tfrecord_index.cc emits these), or any mix with offset<0 = whole file.
// eval_mode: deterministic center crop, identity order. finite: one pass,
// then next() returns 1; the final batch's tail is zero-filled with
// valid < batch.
void* dvgg_jpeg_loader_create_ranged(
    const char* paths_blob, const int64_t* path_offsets, int64_t n_paths,
    const int32_t* item_path, const int64_t* item_offset,
    const int64_t* item_length, const int32_t* labels, int64_t n_items,
    int batch, int out_size, uint64_t seed, const float* mean,
    const float* stddev, int num_threads, int out_kind, double area_min,
    double area_max, int eval_mode, int finite, int pack4) {
  if (n_paths <= 0 || n_items <= 0 || batch <= 0 || out_size <= 0)
    return nullptr;
  if (pack4 && out_size % 4 != 0) return nullptr;
  if (!out_kind_ok(out_kind, pack4)) return nullptr;
  Config cfg = base_config(paths_blob, path_offsets, n_paths, labels, n_items,
                           batch, out_size, seed, mean, stddev, num_threads,
                           out_kind, area_min, area_max);
  cfg.items.resize((size_t)n_items);
  for (int64_t i = 0; i < n_items; ++i) {
    if (item_path[i] < 0 || item_path[i] >= n_paths) return nullptr;
    cfg.items[(size_t)i] = Item{item_path[i], item_offset[i], item_length[i]};
  }
  cfg.eval_mode = eval_mode;
  cfg.finite = finite;
  cfg.pack4 = pack4;
  try {
    return new JpegLoader(std::move(cfg));
  } catch (...) {
    return nullptr;
  }
}

int dvgg_jpeg_loader_next(void* handle, void* out_images,
                          int32_t* out_labels) {
  if (!handle) return 2;
  return static_cast<JpegLoader*>(handle)->next(
      reinterpret_cast<uint8_t*>(out_images), out_labels, nullptr);
}

int dvgg_jpeg_loader_next_valid(void* handle, void* out_images,
                                int32_t* out_labels, int32_t* valid) {
  if (!handle) return 2;
  return static_cast<JpegLoader*>(handle)->next(
      reinterpret_cast<uint8_t*>(out_images), out_labels, valid);
}

void dvgg_jpeg_loader_seek(void* handle, int64_t batch_index) {
  if (handle) static_cast<JpegLoader*>(handle)->seek(batch_index);
}

int64_t dvgg_jpeg_loader_decode_errors(void* handle) {
  return handle ? static_cast<JpegLoader*>(handle)->decode_errors() : -1;
}

// Runtime pool resize (v8): grow spawns workers into the live claim loop,
// shrink retires idle workers at their next wakeup (never mid-item). The
// batch stream is byte-identical at any width. Returns the now-active
// target, or -1 when refused (null handle, compiled out with
// -DDVGGF_NO_RESIZE, or killed via DVGGF_THREAD_RESIZE=0 /
// dvgg_jpeg_set_resize(0)) — the autotuner treats -1 as "knob
// unavailable", never as success.
int dvgg_jpeg_loader_set_threads(void* handle, int n) {
  if (!handle || active_resize_kind() != 1) return -1;
  return static_cast<JpegLoader*>(handle)->set_threads(n);
}

// Current worker-count target (creation value until the first resize).
// Readable regardless of the resize kill-switch; -1 on a null handle.
int dvgg_jpeg_loader_num_threads(void* handle) {
  return handle ? static_cast<JpegLoader*>(handle)->num_threads() : -1;
}

// Flip ownership (v9): enable=0 disables the loader's horizontal flip so
// the fused on-device augmentation stage (data/augment.py) can own it —
// leaving both on would double-flip. Per-LOADER (not process-wide: mixed
// augment configs in one process keep independent streams) and only valid
// before the first next(), mirroring seek()'s race contract. Returns the
// now-active value, or -1 when refused (null handle / workers already
// started) — callers treat -1 as "too late", never as success. The
// per-item flip bit is still drawn either way, so crops are bit-identical
// at both settings.
int dvgg_jpeg_loader_set_hflip(void* handle, int enable) {
  if (!handle) return -1;
  return static_cast<JpegLoader*>(handle)->set_hflip(enable);
}

// Current flip-ownership state (1 = host flips, the default; 0 = device
// owns flips); -1 on a null handle.
int dvgg_jpeg_loader_hflip(void* handle) {
  return handle ? static_cast<JpegLoader*>(handle)->hflip() : -1;
}

void dvgg_jpeg_loader_destroy(void* handle) {
  delete static_cast<JpegLoader*>(handle);
}

}  // extern "C"
