// Native ImageNet JPEG loader for distributed_vgg_f_tpu.
//
// Role (SURVEY.md §2.2 native layer, §7 input-pipeline hard part): the host
// JPEG decode path is the measured end-to-end bottleneck (README: one vCPU
// decodes ~370 img/s through tf.data vs ~20k img/s/chip device demand). This
// library is the framework's own native decode path. Items are byte ranges
// `(path, offset, length)` — a standalone .JPEG file (offset<0) or an
// encoded-JPEG value inside a container such as a TFRecord file (see
// tfrecord_index.cc, which emits exactly these ranges) — so BOTH ImageNet
// layouts ride the same decoder:
//
//   TRAIN: sample random-resized crop in ORIGINAL coords (area 8-100%, aspect
//   3/4-4/3, 10 attempts — the standard Inception crop the tf.data path also
//   uses) → libjpeg-turbo DCT-SCALED decode (scale M/8 chosen so the scaled
//   crop still covers the output size — decoding 1/4-1/2 of the pixels costs
//   a fraction of a full-res decode; tf.image.decode_and_crop_jpeg always
//   decodes the crop window at FULL resolution)
//   → jpeg_crop_scanline + jpeg_skip_scanlines (decode only the crop rows/MCU
//   columns) → bilinear resize to out_size → optional h-flip → mean/std
//   normalize → float32 or bfloat16 batch buffer.
//
//   EVAL (eval_mode=1): deterministic center crop — the centered region that
//   "resize short side to 256 → center-crop 224" maps back to in original
//   coordinates (side = min(W,H) * out/256), DCT-scale-decoded and bilinearly
//   resized to out_size in ONE resampling step. No RNG, no flip; a finite
//   in-order pass whose final partial batch reports a valid count (the
//   exact-eval pad-and-mask protocol, data/eval_pad.py).
//
// Threading: N workers share a fixed ring of 3 batch slots at ITEM
// granularity — each worker claims the next global item index under the lock
// and decodes it directly into its slot position, so first-batch latency and
// intra-batch work are spread across all threads and host RAM is 3 batch
// buffers regardless of thread count. Determinism: per-item RNG is derived
// from (seed, global item index) with splitmix64 and the epoch shuffle from
// (seed, epoch) — the stream is a pure function of (seed, position)
// regardless of thread count, which makes `seek(batch)` an O(1) exact resume
// (no iterator snapshot files needed).
//
// C ABI (ctypes, no pybind11 in this image):
//   dvgg_jpeg_loader_create(...)                 -> handle (0 on error)
//   dvgg_jpeg_loader_create_ranged(...)          -> handle; items are byte
//       ranges into a path table, plus eval_mode/finite flags
//   dvgg_jpeg_loader_next(handle, imgs, labels)  -> 0 ok, 1 end-of-stream
//   dvgg_jpeg_loader_next_valid(handle, imgs, labels, &valid) -> 0 ok;
//       valid < batch on the final partial batch of a finite pass
//   dvgg_jpeg_loader_seek(handle, batch_index)   (call before first next)
//   dvgg_jpeg_loader_decode_errors(handle)       -> corrupt-image fallbacks
//   dvgg_jpeg_loader_destroy(handle)

#include <cstdio>  // jpeglib.h needs FILE declared first

#include <jpeglib.h>

#include <algorithm>
#include <atomic>
#include <cmath>
#include <condition_variable>
#include <csetjmp>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

struct SplitMix64 {
  uint64_t s;
  explicit SplitMix64(uint64_t seed) : s(seed) {}
  uint64_t next() {
    uint64_t z = (s += 0x9e3779b97f4a7c15ULL);
    z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ULL;
    z = (z ^ (z >> 27)) * 0x94d049bb133111ebULL;
    return z ^ (z >> 31);
  }
  double uniform() { return (next() >> 11) * (1.0 / 9007199254740992.0); }
};

inline uint64_t mix(uint64_t a, uint64_t b) {
  SplitMix64 r(a * 0x9e3779b97f4a7c15ULL + b);
  r.next();
  return r.next();
}

void shuffle_indices(std::vector<int64_t>& idx, uint64_t seed, uint64_t epoch) {
  SplitMix64 r(mix(seed, 0x5eedULL + epoch));
  for (int64_t i = (int64_t)idx.size() - 1; i > 0; --i) {
    int64_t j = (int64_t)(r.next() % (uint64_t)(i + 1));
    std::swap(idx[i], idx[j]);
  }
}

inline uint16_t f32_to_bf16(float v) {
  uint32_t bits;
  std::memcpy(&bits, &v, 4);
  // round-to-nearest-even
  uint32_t lsb = (bits >> 16) & 1;
  return (uint16_t)((bits + 0x7fffu + lsb) >> 16);
}

// ---------------------------------------------------------------- jpeg error
struct JerrMgr {
  jpeg_error_mgr pub;
  std::jmp_buf jb;
};

void jerr_exit(j_common_ptr cinfo) {
  JerrMgr* j = reinterpret_cast<JerrMgr*>(cinfo->err);
  std::longjmp(j->jb, 1);
}

// ---------------------------------------------------------------- config
struct Item {
  int32_t path;    // index into Config::paths
  int64_t offset;  // byte offset of the JPEG within the file; <0 = whole file
  int64_t length;  // byte length of the JPEG (ignored when offset < 0)
};

struct Config {
  std::vector<std::string> paths;
  std::vector<Item> items;
  std::vector<int32_t> labels;  // one per item
  int batch;
  int out_size;
  uint64_t seed;
  float mean[3];
  float std_[3];
  int num_threads;
  int bf16_out;
  double area_min, area_max;
  int eval_mode;  // 1: deterministic center crop, no flip, identity order
  int finite;     // 1: one pass over items, then end-of-stream
  int pack4;      // 1: emit 4x4 space-to-depth layout (out/4, out/4, 48) —
                  // same bytes, packed destination indexing (the host side of
                  // the VGG-F stem contract; requires out_size % 4 == 0)
};

// Decode `bytes`, crop per mode, write normalized pixels for one item into
// `dst_base` (float32 or bf16). Train mode samples the Inception crop + flip
// from `rng`; eval mode (cfg.eval_mode) uses the deterministic center crop.
// Returns false on decode failure (caller zero-fills).
bool decode_one(const Config& cfg, const uint8_t* data, size_t size,
                SplitMix64& rng, uint8_t* dst_base) {
  jpeg_decompress_struct cinfo;
  JerrMgr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = jerr_exit;
  std::vector<uint8_t> scaled;   // decoded crop region (rows x stride)
  if (setjmp(jerr.jb)) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, data, size);
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  const int W = (int)cinfo.image_width, H = (int)cinfo.image_height;
  if (W < 1 || H < 1) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }

  int cx = 0, cy = 0, cw = W, ch = H;
  bool flip = false;
  if (cfg.eval_mode) {
    // Center crop: the original-coordinate preimage of "resize short side to
    // 256 → center-crop out_size": a centered square of side
    // min(W,H)*out/256, then one bilinear resample to out_size.
    int side = std::max(1, (int)std::lround(
        (double)std::min(W, H) * cfg.out_size / 256.0));
    side = std::min(side, std::min(W, H));
    cw = ch = side;
    cx = (W - side) / 2;
    cy = (H - side) / 2;
  } else {
    // Inception-style crop sampled in original coordinates.
    for (int attempt = 0; attempt < 10; ++attempt) {
      double area = (double)W * H *
          (cfg.area_min + rng.uniform() * (cfg.area_max - cfg.area_min));
      double log_lo = std::log(3.0 / 4.0), log_hi = std::log(4.0 / 3.0);
      double aspect = std::exp(log_lo + rng.uniform() * (log_hi - log_lo));
      int w = (int)std::lround(std::sqrt(area * aspect));
      int h = (int)std::lround(std::sqrt(area / aspect));
      if (w > 0 && h > 0 && w <= W && h <= H) {
        cx = (int)(rng.next() % (uint64_t)(W - w + 1));
        cy = (int)(rng.next() % (uint64_t)(H - h + 1));
        cw = w;
        ch = h;
        break;
      }
    }
    flip = (rng.next() & 1) != 0;
  }

  // DCT-scaled decode: smallest M/8 (M in 1..8) whose scaled crop still
  // covers out_size in both dims — never decode more pixels than needed.
  int m = 8;
  for (int cand = 1; cand <= 8; ++cand) {
    if ((int64_t)cw * cand / 8 >= cfg.out_size &&
        (int64_t)ch * cand / 8 >= cfg.out_size) {
      m = cand;
      break;
    }
  }
  cinfo.scale_num = (unsigned)m;
  cinfo.scale_denom = 8;
  cinfo.out_color_space = JCS_RGB;
  jpeg_start_decompress(&cinfo);
  const int SW = (int)cinfo.output_width, SH = (int)cinfo.output_height;
  // crop coords in scaled space
  int sx = std::min((int)((int64_t)cx * SW / W), SW - 1);
  int sy = std::min((int)((int64_t)cy * SH / H), SH - 1);
  int sw = std::max(1, std::min((int)((int64_t)cw * SW / W), SW - sx));
  int sh = std::max(1, std::min((int)((int64_t)ch * SH / H), SH - sy));

  // horizontal MCU-aligned crop; libjpeg widens [sx, sw] to alignment
  JDIMENSION jx = (JDIMENSION)sx, jw = (JDIMENSION)sw;
  jpeg_crop_scanline(&cinfo, &jx, &jw);
  const int row_stride = (int)jw * 3;
  const int x_off = sx - (int)jx;  // offset of the true crop inside the band
  if (sy > 0) jpeg_skip_scanlines(&cinfo, (JDIMENSION)sy);
  scaled.resize((size_t)sh * row_stride);
  for (int r = 0; r < sh;) {
    JSAMPROW row = scaled.data() + (size_t)r * row_stride;
    r += (int)jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_abort_decompress(&cinfo);  // skip remaining rows without error
  jpeg_destroy_decompress(&cinfo);

  // bilinear resize (half-pixel centers) from the (sh, sw) region to out_size
  const int out = cfg.out_size;
  const float sxf = (float)sw / out, syf = (float)sh / out;
  float* f32 = nullptr;
  uint16_t* b16 = nullptr;
  if (cfg.bf16_out)
    b16 = reinterpret_cast<uint16_t*>(dst_base);
  else
    f32 = reinterpret_cast<float*>(dst_base);
  // Loop-invariant hoists (measured on the host bench, r5): the x-axis
  // bilinear taps are identical for every row — precompute the (p00, p01,
  // wx) column tables once per image instead of 224× — and the per-channel
  // normalize divide becomes a multiply (3 divides/pixel ≈ 150k/image was
  // a visible slice of the ~1.8 ms/image budget).
  const float inv_std[3] = {1.0f / cfg.std_[0], 1.0f / cfg.std_[1],
                            1.0f / cfg.std_[2]};
  std::vector<int> xt0(out), xt1(out);
  std::vector<float> xtw(out);
  for (int ox = 0; ox < out; ++ox) {
    int ox_src = flip ? (out - 1 - ox) : ox;
    float fx = ((float)ox_src + 0.5f) * sxf - 0.5f;
    int x0 = (int)std::floor(fx);
    xtw[ox] = fx - x0;
    int x1 = std::min(std::max(x0 + 1, 0), sw - 1);
    x0 = std::min(std::max(x0, 0), sw - 1);
    xt0[ox] = (x_off + x0) * 3;
    xt1[ox] = (x_off + x1) * 3;
  }
  for (int oy = 0; oy < out; ++oy) {
    float fy = ((float)oy + 0.5f) * syf - 0.5f;
    int y0 = (int)std::floor(fy);
    float wy = fy - y0;
    int y1 = std::min(std::max(y0 + 1, 0), sh - 1);
    y0 = std::min(std::max(y0, 0), sh - 1);
    const uint8_t* r0 = scaled.data() + (size_t)y0 * row_stride;
    const uint8_t* r1 = scaled.data() + (size_t)y1 * row_stride;
    for (int ox = 0; ox < out; ++ox) {
      const float wx = xtw[ox];
      const int p00 = xt0[ox], p01 = xt1[ox];
      size_t o;
      if (cfg.pack4) {
        // destination channel order (dy, dx, c) — matches
        // tf.nn.space_to_depth and models/vggf.py Conv1SpaceToDepth
        o = (((size_t)(oy >> 2) * (out >> 2) + (ox >> 2)) * 16 +
             (oy & 3) * 4 + (ox & 3)) * 3;
      } else {
        o = ((size_t)oy * out + ox) * 3;
      }
      for (int c = 0; c < 3; ++c) {
        float top = r0[p00 + c] + wx * (r0[p01 + c] - r0[p00 + c]);
        float bot = r1[p00 + c] + wx * (r1[p01 + c] - r1[p00 + c]);
        float v = (top + wy * (bot - top) - cfg.mean[c]) * inv_std[c];
        if (b16)
          b16[o + c] = f32_to_bf16(v);
        else
          f32[o + c] = v;
      }
    }
  }
  return true;
}

// ---------------------------------------------------------------- loader
class JpegLoader {
 public:
  explicit JpegLoader(Config cfg)
      : cfg_(std::move(cfg)),
        item_bytes_((size_t)cfg_.out_size * cfg_.out_size * 3 *
                    (cfg_.bf16_out ? 2 : 4)),
        slots_(kDepth) {
    for (auto& s : slots_) {
      s.images.resize(item_bytes_ * cfg_.batch);
      s.labels.resize(cfg_.batch);
    }
    if (cfg_.finite) {
      total_batches_ =
          ((int64_t)cfg_.items.size() + cfg_.batch - 1) / cfg_.batch;
    }
    // workers start lazily on the first next(): seek() must be able to set
    // the stream position before any item is claimed.
  }

  ~JpegLoader() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      stop_ = true;
    }
    cv_prod_.notify_all();
    cv_cons_.notify_all();
    for (auto& t : workers_) t.join();
  }

  void seek(int64_t batch_index) {
    // only valid before the first next() (workers have not started yet); the
    // stream is a pure function of (seed, batch_index), so this IS exact
    // deterministic resume.
    std::lock_guard<std::mutex> lk(mu_);
    if (!workers_.empty()) return;  // too late — position already consumed
    consume_index_ = batch_index;
    next_item_ = batch_index * cfg_.batch;
  }

  // Returns 0 with *valid in (0, batch] on success (< batch only on the final
  // partial batch of a finite pass), 1 on end-of-stream, 2 on shutdown.
  int next(uint8_t* out_images, int32_t* out_labels, int32_t* valid) {
    std::unique_lock<std::mutex> lk(mu_);
    if (cfg_.finite && consume_index_ >= total_batches_) return 1;
    if (workers_.empty() && !stop_)
      for (int t = 0; t < std::max(1, cfg_.num_threads); ++t)
        workers_.emplace_back([this] { worker(); });
    Slot& s = slots_[(size_t)(consume_index_ % kDepth)];
    cv_cons_.wait(lk, [&] {
      return stop_ || (s.target_batch == consume_index_ && s.remaining == 0);
    });
    if (stop_) return 2;
    // The slot is exclusively ours while target_batch == consume_index_ (no
    // producer touches it until consume_index_ advances), so the big copy
    // runs with the lock RELEASED — holding mu_ across a multi-hundred-MB
    // memcpy would stall every decode worker each batch.
    int32_t n_valid = s.valid;
    lk.unlock();
    std::memcpy(out_images, s.images.data(), s.images.size());
    std::memcpy(out_labels, s.labels.data(),
                s.labels.size() * sizeof(int32_t));
    lk.lock();
    s.target_batch = -1;  // slot free
    ++consume_index_;
    cv_prod_.notify_all();
    if (valid) *valid = n_valid;
    return 0;
  }

  int64_t decode_errors() const { return decode_errors_.load(); }

 private:
  // 3 batch slots regardless of thread count: one being consumed, two in
  // flight. Workers share batches at ITEM granularity, so a single slot's
  // batch is decoded by all threads in parallel (first-batch latency) and
  // host RAM stays at 3 batch buffers (a whole-batch-per-worker design costs
  // (threads+1) buffers — ~11 GB at local_batch 2048 f32 with 8 threads).
  static constexpr int kDepth = 3;

  struct Slot {
    std::vector<uint8_t> images;
    std::vector<int32_t> labels;
    int64_t target_batch = -1;  // -1 = free
    int remaining = 0;          // items not yet decoded into this slot
    int32_t valid = 0;          // items actually present (finite final batch)
  };

  // Number of items in batch b (only the final batch of a finite pass is
  // short; infinite streams always fill the batch).
  int batch_items(int64_t b) const {
    if (!cfg_.finite) return cfg_.batch;
    int64_t n = (int64_t)cfg_.items.size();
    return (int)std::min<int64_t>(cfg_.batch, n - b * cfg_.batch);
  }

  void worker() {
    std::vector<uint8_t> bytes;
    // per-thread single-file cache: TFRecord items cluster by file, so most
    // claims reuse the already-open container
    FILE* cached_f = nullptr;
    int32_t cached_path = -1;
    std::vector<int64_t> order;
    int64_t cached_epoch = -1;
    while (true) {
      int64_t g, b;
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_prod_.wait(lk, [&] {
          if (stop_) return true;
          if (cfg_.finite &&
              next_item_ >= (int64_t)cfg_.items.size()) return false;
          return next_item_ / cfg_.batch - consume_index_ < kDepth;
        });
        if (stop_) break;
        g = next_item_++;
        b = g / cfg_.batch;
        Slot& s = slots_[(size_t)(b % kDepth)];
        if (s.target_batch != b) {
          // first item claimed for this batch initializes the slot (claims
          // are serialized under mu_, and the gate above guarantees the slot
          // is free: its previous batch was consumed)
          s.target_batch = b;
          s.valid = batch_items(b);
          s.remaining = s.valid;
          if (cfg_.finite && s.valid < cfg_.batch) {
            std::memset(s.images.data() + (size_t)s.valid * item_bytes_, 0,
                        (size_t)(cfg_.batch - s.valid) * item_bytes_);
            std::fill(s.labels.begin() + s.valid, s.labels.end(), 0);
          }
        }
      }
      produce_item(g, bytes, cached_f, cached_path, order, cached_epoch);
      {
        std::lock_guard<std::mutex> lk(mu_);
        Slot& s = slots_[(size_t)(g / cfg_.batch % kDepth)];
        if (--s.remaining == 0) cv_cons_.notify_all();
      }
    }
    if (cached_f) std::fclose(cached_f);
  }

  // index of the global item `g` in the (epoch-shuffled unless eval) order
  int64_t item_index(int64_t g, std::vector<int64_t>& order,
                     int64_t& cached_epoch) {
    const int64_t n = (int64_t)cfg_.items.size();
    int64_t epoch = g / n, pos = g % n;
    if (cfg_.eval_mode || cfg_.finite) return pos;  // identity, in order
    if (epoch != cached_epoch) {
      if ((int64_t)order.size() != n) order.resize(n);
      for (int64_t i = 0; i < n; ++i) order[i] = i;
      shuffle_indices(order, cfg_.seed, (uint64_t)epoch);
      cached_epoch = epoch;
    }
    return order[pos];
  }

  void produce_item(int64_t g, std::vector<uint8_t>& bytes, FILE*& cached_f,
                    int32_t& cached_path, std::vector<int64_t>& order,
                    int64_t& cached_epoch) {
    Slot& s = slots_[(size_t)(g / cfg_.batch % kDepth)];
    int j = (int)(g % cfg_.batch);
    int64_t idx = item_index(g, order, cached_epoch);
    const Item& it = cfg_.items[(size_t)idx];
    s.labels[(size_t)j] = cfg_.labels[(size_t)idx];
    SplitMix64 rng(mix(cfg_.seed, 0xA0A0ULL + (uint64_t)g));
    uint8_t* dst = s.images.data() + (size_t)j * item_bytes_;
    if (it.path != cached_path) {
      if (cached_f) std::fclose(cached_f);
      cached_f = std::fopen(cfg_.paths[(size_t)it.path].c_str(), "rb");
      cached_path = it.path;
    }
    bool ok = false;
    FILE* f = cached_f;
    if (f) {
      int64_t off = it.offset, len = it.length;
      if (off < 0) {  // whole file
        std::fseek(f, 0, SEEK_END);
        len = std::ftell(f);
        off = 0;
      }
      if (len > 0 && std::fseek(f, (long)off, SEEK_SET) == 0) {
        bytes.resize((size_t)len);
        if (std::fread(bytes.data(), 1, (size_t)len, f) == (size_t)len)
          ok = decode_one(cfg_, bytes.data(), bytes.size(), rng, dst);
      }
    }
    if (!ok) {
      std::memset(dst, 0, item_bytes_);
      decode_errors_.fetch_add(1);
    }
  }

  Config cfg_;
  size_t item_bytes_;
  std::vector<Slot> slots_;
  std::vector<std::thread> workers_;
  std::mutex mu_;
  std::condition_variable cv_prod_, cv_cons_;
  int64_t next_item_ = 0;    // next global item to claim (guarded by mu_)
  int64_t consume_index_ = 0;
  int64_t total_batches_ = -1;  // finite mode only
  bool stop_ = false;
  std::atomic<int64_t> decode_errors_{0};
};

Config base_config(const char* paths_blob, const int64_t* path_offsets,
                   int64_t n_paths, const int32_t* labels, int64_t n_items,
                   int batch, int out_size, uint64_t seed, const float* mean,
                   const float* stddev, int num_threads, int bf16_out,
                   double area_min, double area_max) {
  Config cfg;
  cfg.paths.reserve((size_t)n_paths);
  for (int64_t i = 0; i < n_paths; ++i)
    cfg.paths.emplace_back(paths_blob + path_offsets[i],
                           (size_t)(path_offsets[i + 1] - path_offsets[i]));
  cfg.labels.assign(labels, labels + n_items);
  cfg.batch = batch;
  cfg.out_size = out_size;
  cfg.seed = seed;
  for (int c = 0; c < 3; ++c) {
    cfg.mean[c] = mean[c];
    cfg.std_[c] = stddev[c];
  }
  cfg.num_threads = std::max(1, num_threads);
  cfg.bf16_out = bf16_out;
  cfg.area_min = area_min;
  cfg.area_max = area_max;
  cfg.eval_mode = 0;
  cfg.finite = 0;
  cfg.pack4 = 0;
  return cfg;
}

}  // namespace

extern "C" {

// Bumped on EVERY C-ABI change; the Python binding refuses (and force-
// rebuilds) a library whose version doesn't match. Guards against a stale
// cached .so whose mtime check passed (tar/rsync/cp -p timestamp ties): a
// signature mismatch would otherwise be silently absorbed by cdecl and
// corrupt batches instead of failing.
int64_t dvgg_jpeg_loader_abi_version() { return 3; }

// Stateless single-image decode for external pipeline frameworks (the Grain
// backend's per-record transform, data/grain_imagenet.py): same crop/
// resize/normalize math as the batch loader, with the per-item RNG seeded
// explicitly by the caller (derive from (seed, record index) for a stream
// that is a pure function of position). Returns 0 ok, 1 decode failure
// (caller zero-fills), 2 bad args.
int dvgg_jpeg_decode_single(const uint8_t* data, int64_t size, int out_size,
                            const float* mean, const float* stddev,
                            int bf16_out, int pack4, int eval_mode,
                            double area_min, double area_max,
                            uint64_t rng_seed, void* out) {
  if (!data || size <= 0 || out_size <= 0 || !out) return 2;
  if (pack4 && out_size % 4 != 0) return 2;
  Config cfg;
  cfg.batch = 1;
  cfg.out_size = out_size;
  cfg.seed = 0;
  for (int c = 0; c < 3; ++c) {
    cfg.mean[c] = mean[c];
    cfg.std_[c] = stddev[c];
  }
  cfg.num_threads = 1;
  cfg.bf16_out = bf16_out;
  cfg.area_min = area_min;
  cfg.area_max = area_max;
  cfg.eval_mode = eval_mode;
  cfg.finite = 0;
  cfg.pack4 = pack4;
  SplitMix64 rng(rng_seed);
  return decode_one(cfg, data, (size_t)size, rng,
                    reinterpret_cast<uint8_t*>(out)) ? 0 : 1;
}

// Whole-file items: one path per item (the raw-JPEG directory layout).
void* dvgg_jpeg_loader_create(const char* paths_blob,
                              const int64_t* path_offsets,  // n+1 offsets
                              const int32_t* labels, int64_t n, int batch,
                              int out_size, uint64_t seed, const float* mean,
                              const float* stddev, int num_threads,
                              int bf16_out, double area_min, double area_max) {
  if (n <= 0 || batch <= 0 || out_size <= 0) return nullptr;
  Config cfg = base_config(paths_blob, path_offsets, n, labels, n, batch,
                           out_size, seed, mean, stddev, num_threads, bf16_out,
                           area_min, area_max);
  cfg.items.resize((size_t)n);
  for (int64_t i = 0; i < n; ++i)
    cfg.items[(size_t)i] = Item{(int32_t)i, -1, 0};
  try {
    return new JpegLoader(std::move(cfg));
  } catch (...) {
    return nullptr;
  }
}

// Ranged items: `n_items` byte ranges (item_path[i], item_offset[i],
// item_length[i]) into a table of `n_paths` files — the TFRecord layout
// (tfrecord_index.cc emits these), or any mix with offset<0 = whole file.
// eval_mode: deterministic center crop, identity order. finite: one pass,
// then next() returns 1; the final batch's tail is zero-filled with
// valid < batch.
void* dvgg_jpeg_loader_create_ranged(
    const char* paths_blob, const int64_t* path_offsets, int64_t n_paths,
    const int32_t* item_path, const int64_t* item_offset,
    const int64_t* item_length, const int32_t* labels, int64_t n_items,
    int batch, int out_size, uint64_t seed, const float* mean,
    const float* stddev, int num_threads, int bf16_out, double area_min,
    double area_max, int eval_mode, int finite, int pack4) {
  if (n_paths <= 0 || n_items <= 0 || batch <= 0 || out_size <= 0)
    return nullptr;
  if (pack4 && out_size % 4 != 0) return nullptr;
  Config cfg = base_config(paths_blob, path_offsets, n_paths, labels, n_items,
                           batch, out_size, seed, mean, stddev, num_threads,
                           bf16_out, area_min, area_max);
  cfg.items.resize((size_t)n_items);
  for (int64_t i = 0; i < n_items; ++i) {
    if (item_path[i] < 0 || item_path[i] >= n_paths) return nullptr;
    cfg.items[(size_t)i] = Item{item_path[i], item_offset[i], item_length[i]};
  }
  cfg.eval_mode = eval_mode;
  cfg.finite = finite;
  cfg.pack4 = pack4;
  try {
    return new JpegLoader(std::move(cfg));
  } catch (...) {
    return nullptr;
  }
}

int dvgg_jpeg_loader_next(void* handle, void* out_images,
                          int32_t* out_labels) {
  if (!handle) return 2;
  return static_cast<JpegLoader*>(handle)->next(
      reinterpret_cast<uint8_t*>(out_images), out_labels, nullptr);
}

int dvgg_jpeg_loader_next_valid(void* handle, void* out_images,
                                int32_t* out_labels, int32_t* valid) {
  if (!handle) return 2;
  return static_cast<JpegLoader*>(handle)->next(
      reinterpret_cast<uint8_t*>(out_images), out_labels, valid);
}

void dvgg_jpeg_loader_seek(void* handle, int64_t batch_index) {
  if (handle) static_cast<JpegLoader*>(handle)->seek(batch_index);
}

int64_t dvgg_jpeg_loader_decode_errors(void* handle) {
  return handle ? static_cast<JpegLoader*>(handle)->decode_errors() : -1;
}

void dvgg_jpeg_loader_destroy(void* handle) {
  delete static_cast<JpegLoader*>(handle);
}

}  // extern "C"
