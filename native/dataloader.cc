// Native host-side data loader for distributed_vgg_f_tpu.
//
// Role (SURVEY.md §2.2): the reference's native surface is linked libraries
// (NCCL/MPI/TF C++ kernels); on TPU the collectives/kernels come from XLA+libtpu,
// so the framework's own native layer sits where the real bottleneck is:
// the HOST input path. SURVEY.md §7 identifies host-side batch prep as where
// the ≥90% scaling-efficiency target is won or lost (VGG-F is compute-light).
//
// This library implements a multi-threaded, double-buffered augmenting batch
// assembler over an in-memory uint8 image dataset (CIFAR-class sizes):
//   sample (shuffled, epoch-aware) → pad-reflect → random crop → random h-flip
//   → mean/std normalize to float32
// with a background prefetch thread producing into a ring of pinned host
// buffers while the device consumes the previous batch.
//
// C ABI (used from Python via ctypes — no pybind11 in this image):
//   dvgg_loader_create(...) -> handle
//   dvgg_loader_next(handle, float* out_images, int* out_labels)
//   dvgg_loader_destroy(handle)
//
// Determinism: all randomness comes from a per-loader splitmix64/xoshiro256++
// stream seeded by `seed`; same seed → same batch sequence, regardless of
// thread count (per-item RNG is derived from (epoch, index), not thread id).

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <new>
#include <thread>
#include <vector>

namespace {

// ---------------------------------------------------------------- RNG
struct SplitMix64 {
  uint64_t s;
  explicit SplitMix64(uint64_t seed) : s(seed) {}
  uint64_t next() {
    uint64_t z = (s += 0x9e3779b97f4a7c15ULL);
    z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ULL;
    z = (z ^ (z >> 27)) * 0x94d049bb133111ebULL;
    return z ^ (z >> 31);
  }
};

inline uint64_t mix(uint64_t a, uint64_t b) {
  SplitMix64 r(a * 0x9e3779b97f4a7c15ULL + b);
  r.next();
  return r.next();
}

// Fisher-Yates over an index vector, seeded deterministically per epoch.
void shuffle_indices(std::vector<int64_t>& idx, uint64_t seed, uint64_t epoch) {
  SplitMix64 r(mix(seed, 0xabcdef1234ULL + epoch));
  for (int64_t i = (int64_t)idx.size() - 1; i > 0; --i) {
    int64_t j = (int64_t)(r.next() % (uint64_t)(i + 1));
    std::swap(idx[i], idx[j]);
  }
}

struct LoaderConfig {
  const uint8_t* images;  // (n, h, w, c) contiguous, NOT owned
  const int32_t* labels;  // (n,)          NOT owned
  int64_t n;
  int h, w, c;
  int batch;
  int pad;          // reflect-pad then random crop back to (h, w); 0 = no crop
  int train;        // train: shuffle + augment; eval: sequential, no augment
  uint64_t seed;
  float mean[3];
  float std_[3];
  int num_threads;
};

class Loader {
 public:
  explicit Loader(const LoaderConfig& cfg)
      : cfg_(cfg), order_(cfg.n), stop_(false), ready_(false) {
    for (int64_t i = 0; i < cfg_.n; ++i) order_[i] = i;
    if (cfg_.train) shuffle_indices(order_, cfg_.seed, epoch_);
    const size_t img_elems =
        (size_t)cfg_.batch * cfg_.h * cfg_.w * cfg_.c;
    staged_images_.resize(img_elems);
    staged_labels_.resize(cfg_.batch);
    // persistent worker pool (producer thread is worker #0): spawning and
    // joining threads per batch would cost as much as the batch work itself
    int nthreads = cfg_.num_threads > 0 ? cfg_.num_threads : 1;
    if (nthreads > cfg_.batch) nthreads = cfg_.batch;
    for (int t = 0; t < nthreads - 1; ++t)
      workers_.emplace_back([this] { this->worker_loop(); });
    producer_ = std::thread([this] { this->produce_loop(); });
  }

  ~Loader() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      stop_ = true;
    }
    cv_.notify_all();
    if (producer_.joinable()) producer_.join();
    {
      std::lock_guard<std::mutex> lk(pool_mu_);
      pool_stop_ = true;
    }
    pool_cv_.notify_all();
    for (auto& th : workers_) th.join();
  }

  void next(float* out_images, int32_t* out_labels) {
    std::unique_lock<std::mutex> lk(mu_);
    cv_.wait(lk, [this] { return ready_ || stop_; });
    if (stop_) return;
    std::memcpy(out_images, staged_images_.data(),
                staged_images_.size() * sizeof(float));
    std::memcpy(out_labels, staged_labels_.data(),
                staged_labels_.size() * sizeof(int32_t));
    ready_ = false;
    lk.unlock();
    cv_.notify_all();  // wake producer to stage the next batch
  }

 private:
  void produce_loop() {
    while (true) {
      // assemble one batch into the staging buffer (outside the lock: the
      // consumer only reads it between ready_=true and ready_=false)
      assemble();
      {
        std::unique_lock<std::mutex> lk(mu_);
        ready_ = true;
        cv_.notify_all();
        cv_.wait(lk, [this] { return !ready_ || stop_; });
        if (stop_) return;
      }
    }
  }

  // Deterministic item processing: RNG keyed by (seed, epoch, position).
  void process_item(int64_t pos_in_epoch, int slot) {
    const int h = cfg_.h, w = cfg_.w, c = cfg_.c, pad = cfg_.pad;
    int64_t src_idx = order_[pos_in_epoch % cfg_.n];
    SplitMix64 r(mix(cfg_.seed ^ 0x5eedf00dULL,
                     (uint64_t)(epoch_ * 1315423911ULL + pos_in_epoch)));
    int dy = 0, dx = 0;
    bool flip = false;
    if (cfg_.train && pad > 0) {
      dy = (int)(r.next() % (uint64_t)(2 * pad + 1));
      dx = (int)(r.next() % (uint64_t)(2 * pad + 1));
      flip = (r.next() & 1) != 0;
    }
    const uint8_t* src = cfg_.images + (size_t)src_idx * h * w * c;
    float* dst = staged_images_.data() + (size_t)slot * h * w * c;

    for (int y = 0; y < h; ++y) {
      // reflect-padded source row index
      int sy = y + dy - pad;
      if (sy < 0) sy = -sy;
      if (sy >= h) sy = 2 * h - 2 - sy;
      for (int x = 0; x < w; ++x) {
        int xx = flip ? (w - 1 - x) : x;
        int sx = xx + dx - pad;
        if (sx < 0) sx = -sx;
        if (sx >= w) sx = 2 * w - 2 - sx;
        const uint8_t* p = src + ((size_t)sy * w + sx) * c;
        float* q = dst + ((size_t)y * w + x) * c;
        for (int ch = 0; ch < c; ++ch) {
          float m = ch < 3 ? cfg_.mean[ch] : 0.f;
          float s = ch < 3 ? cfg_.std_[ch] : 1.f;
          q[ch] = ((float)p[ch] - m) / s;
        }
      }
    }
    staged_labels_[slot] = cfg_.labels[src_idx];
  }

  void worker_loop() {
    uint64_t seen = 0;
    while (true) {
      {
        std::unique_lock<std::mutex> lk(pool_mu_);
        pool_cv_.wait(lk, [&] { return gen_ != seen || pool_stop_; });
        if (pool_stop_) return;
        seen = gen_;
      }
      int slot;
      while ((slot = cursor_.fetch_add(1)) < cfg_.batch)
        process_item(pos_ + slot, slot);
      {
        std::lock_guard<std::mutex> lk(pool_mu_);
        if (--active_ == 0) done_cv_.notify_one();
      }
    }
  }

  void assemble() {
    const int batch = cfg_.batch;
    cursor_.store(0);
    {
      std::lock_guard<std::mutex> lk(pool_mu_);
      active_ = (int)workers_.size();
      ++gen_;
    }
    pool_cv_.notify_all();
    int slot;
    while ((slot = cursor_.fetch_add(1)) < batch)
      process_item(pos_ + slot, slot);
    {
      std::unique_lock<std::mutex> lk(pool_mu_);
      done_cv_.wait(lk, [&] { return active_ == 0; });
    }
    // pos_/epoch_/order_ are only mutated here, after all workers are idle
    pos_ += batch;
    if (pos_ + batch > cfg_.n) {  // epoch boundary: reshuffle, restart
      ++epoch_;
      pos_ = 0;
      if (cfg_.train) shuffle_indices(order_, cfg_.seed, epoch_);
    }
  }

  LoaderConfig cfg_;
  std::vector<int64_t> order_;
  std::vector<float> staged_images_;
  std::vector<int32_t> staged_labels_;
  int64_t pos_ = 0;
  uint64_t epoch_ = 0;
  std::thread producer_;
  std::mutex mu_;
  std::condition_variable cv_;
  bool stop_;
  bool ready_;
  // persistent worker pool state
  std::vector<std::thread> workers_;
  std::mutex pool_mu_;
  std::condition_variable pool_cv_;
  std::condition_variable done_cv_;
  std::atomic<int> cursor_{0};
  uint64_t gen_ = 0;
  int active_ = 0;
  bool pool_stop_ = false;
};

}  // namespace

extern "C" {

void* dvgg_loader_create(const uint8_t* images, const int32_t* labels,
                         int64_t n, int h, int w, int c, int batch, int pad,
                         int train, uint64_t seed, const float* mean3,
                         const float* std3, int num_threads) {
  if (!images || !labels || n <= 0 || batch <= 0 || batch > n) return nullptr;
  LoaderConfig cfg;
  cfg.images = images;
  cfg.labels = labels;
  cfg.n = n;
  cfg.h = h;
  cfg.w = w;
  cfg.c = c;
  cfg.batch = batch;
  cfg.pad = pad;
  cfg.train = train;
  cfg.seed = seed;
  for (int i = 0; i < 3; ++i) {
    cfg.mean[i] = mean3 ? mean3[i] : 0.f;
    cfg.std_[i] = std3 ? std3[i] : 1.f;
  }
  cfg.num_threads = num_threads;
  return new (std::nothrow) Loader(cfg);
}

void dvgg_loader_next(void* handle, float* out_images, int32_t* out_labels) {
  if (handle) static_cast<Loader*>(handle)->next(out_images, out_labels);
}

void dvgg_loader_destroy(void* handle) {
  delete static_cast<Loader*>(handle);
}

int dvgg_abi_version() { return 1; }

}  // extern "C"
