#!/usr/bin/env bash
# host_r10 measurement session — restart-marker excerpt decode + snapshot
# cache (ISSUE 6). Quiet-host protocol: min-of-6 windows x 12 batches of 64,
# threads 1, columns ALTERNATING within each round, same-session worktree
# control (r9 code = ABI v6 HEAD, built in /tmp/r9code).
set -u
cd "$(dirname "$0")/../../.."   # repo root (script lives in runs/host_r10)
REPO=/root/repo
WT=/tmp/r9code
OUT=$REPO/benchmarks/runs/host_r10
COMMON="--decode-bench --layout tfrecord --batch 64 --batches 12 --repeats 6 \
  --image-size 224 --threads 1 --wire u8 --space-to-depth --image-dtype bfloat16"

run_new() {  # name, extra args...
  local name=$1; shift
  (cd "$REPO" && timeout 1200 python benchmarks/host_pipeline_bench.py \
     $COMMON "$@" --json-out "$OUT/$name.json") \
     > "$OUT/$name.log" 2>&1
  echo "== $name rc=$?"
}
run_ctrl() {  # worktree r9 code: no restart flags exist there
  local name=$1; shift
  (cd "$WT" && timeout 1200 python benchmarks/host_pipeline_bench.py \
     $COMMON "$@" --json-out "$OUT/$name.json") \
     > "$OUT/$name.log" 2>&1
  echo "== $name rc=$?"
}

for r in 1 2 3; do
  run_ctrl decode_r9code_u8s2d_448tex_run$r --source-hw 448x448 --source-kind textured
  run_new  decode_r10_off_448tex_rst1_run$r --source-hw 448x448 --source-kind textured \
           --restart-interval 1 --decode-restart off
  run_new  decode_r10_on_448tex_rst1_run$r  --source-hw 448x448 --source-kind textured \
           --restart-interval 1 --decode-restart on
done

for r in 1 2; do
  run_new decode_r10_off_768tex_rst1_run$r --source-hw 768x768 --source-kind textured \
          --restart-interval 1 --decode-restart off
  run_new decode_r10_on_768tex_rst1_run$r  --source-hw 768x768 --source-kind textured \
          --restart-interval 1 --decode-restart on
done

# continuity basis (r4-r9): 320x256 noise, markers injected, restart auto
for r in 1 2; do
  run_new decode_r10_on_320noise_rst1_run$r --source-hw 320x256 --source-kind noise \
          --restart-interval 1 --decode-restart on
done

# snapshot warm-vs-cold, flagship-shaped config on the r10 source basis
for r in 1 2; do
  run_new decode_r10_snapshot_448tex_run$r --source-hw 448x448 --source-kind textured \
          --restart-interval 1 --decode-restart on --snapshot-cache
done

# interval ablation sidebar (single runs, non-protocol): row-mode vs columns
run_new decode_r10_on_448tex_rst0_run1 --source-hw 448x448 --source-kind textured \
        --restart-interval 0 --decode-restart on
run_new decode_r10_on_448tex_rst4_run1 --source-hw 448x448 --source-kind textured \
        --restart-interval 4 --decode-restart on
echo "SESSION DONE"
