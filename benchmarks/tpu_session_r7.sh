#!/bin/sh
# Round-7 TPU measurement session — same discipline as tpu_session_r6.sh
# (scheduled EARLY, followed by a HARD TPU FREEZE; every bench.py invocation
# watchdog-protected; unprotected phases only after the flagship bench
# proves the tunnel healthy; a wedged-tunnel flagship exits 0 with the
# stale last_committed payload as its result line).
#
# Differences from tpu_session_r6.sh:
#   - the host decode-bench phase gains the r8 WIRE COLUMNS
#     (--wire {host_f32,host_bf16,u8}): for each config of interest the
#     u8-wire row (raw uint8 pixels, device-finish prologue) is paired
#     with its host-normalize control in the SAME session, so the wire
#     comparison is drift-controlled like the scaled-decode pairs were.
#   - a u8-wire E2E device bench row (data.wire=u8) captures the
#     device-side half of the wire win — the device_put bytes/img drop
#     and the fused normalize/cast/s2d cost — which no host-only bench
#     can see. This is the receipt the next TPU grant owes host_r9.
#
# Usage: sh benchmarks/tpu_session_r7.sh [outdir] [run_label]

set -u
OUT=${1:-/tmp/tpu_session_r7}
RUN=${2:-benchmarks/runs/tpu_r7}
mkdir -p "$OUT"
cd "$(dirname "$0")/.."

echo "== flagship device bench =="
DVGGF_BENCH_ARTIFACT="$RUN/vggf_device.json" \
python bench.py --steps 30 --warmup 5 --budget 1500 \
    | tee "$OUT/vggf_device.json"
if grep -q '"error"' "$OUT/vggf_device.json"; then
    echo "tunnel unhealthy (stale or null result) — stopping before" \
         "unprotected phases" >&2
    exit 1
fi

echo "== model zoo benches =="
DVGGF_BENCH_ARTIFACT="$RUN/vgg16_device.json" \
python bench.py --model vgg16 --batch-size 128 --steps 20 --budget 1500 \
    | tee "$OUT/vgg16_device.json"
DVGGF_BENCH_ARTIFACT="$RUN/resnet50_device.json" \
python bench.py --model resnet50 --batch-size 256 --steps 20 --budget 1500 \
    | tee "$OUT/resnet50_device.json"
DVGGF_BENCH_ARTIFACT="$RUN/vit_s16_device.json" \
python bench.py --model vit_s16 --batch-size 256 --steps 20 --budget 1500 \
    | tee "$OUT/vit_s16_device.json"

echo "== end-to-end pipeline bench: host wire vs u8 wire (min-of-6) =="
DVGGF_BENCH_ARTIFACT="$RUN/vggf_e2e.json" \
python bench.py --pipeline imagenet --repeats 6 --budget 3600 \
    | tee "$OUT/vggf_e2e.json"
# the u8-wire e2e row: raw uint8 pixels through device_put, the finish
# fused into the step — THE device-side receipt of the r8 wire rework
DVGGF_BENCH_ARTIFACT="$RUN/vggf_e2e_wire_u8.json" \
python bench.py --pipeline imagenet --repeats 6 --budget 3600 \
    --wire u8 \
    | tee "$OUT/vggf_e2e_wire_u8.json"

echo "== host decode contract line (host-only, no TPU client) =="
python benchmarks/host_pipeline_bench.py --layout tfrecord --batches 12 \
    2>/dev/null | tee "$OUT/host_decode.json"

echo "== host decode-bench wire columns (r8 protocol: min-of-N per-core"
echo "   rate, wire + bytes/img receipts, phase split, dispatch receipts) =="
# f32 contract config: host_f32 control + u8 wire row (the host_r9 pair)
python benchmarks/host_pipeline_bench.py --decode-bench --layout tfrecord \
    --repeats 6 --wire host_f32 \
    --json-out "$OUT/host_decode_bench_wire_f32.json" 2>/dev/null \
    | tee "$OUT/host_decode_bench_wire_f32.log"
python benchmarks/host_pipeline_bench.py --decode-bench --layout tfrecord \
    --repeats 6 --wire u8 \
    --json-out "$OUT/host_decode_bench_wire_u8.json" 2>/dev/null \
    | tee "$OUT/host_decode_bench_wire_u8.log"
# flagship continuity config (bf16 + space-to-depth) + its u8 replacement
# (u8 never packs on host — the device finish owns space-to-depth)
python benchmarks/host_pipeline_bench.py --decode-bench --layout tfrecord \
    --repeats 6 --wire host_bf16 --space-to-depth \
    --json-out "$OUT/host_decode_bench_wire_bf16s2d.json" 2>/dev/null \
    | tee "$OUT/host_decode_bench_wire_bf16s2d.log"
python benchmarks/host_pipeline_bench.py --decode-bench --layout tfrecord \
    --repeats 6 --wire u8 --space-to-depth \
    --json-out "$OUT/host_decode_bench_wire_u8_s2d.json" 2>/dev/null \
    | tee "$OUT/host_decode_bench_wire_u8_s2d.log"
# >=448px textured scaled-decode rows carry forward on the u8 wire
for HW in 448x448 768x768; do
    python benchmarks/host_pipeline_bench.py --decode-bench \
        --layout tfrecord --repeats 6 --wire u8 \
        --source-hw "$HW" --source-kind textured \
        --json-out "$OUT/host_decode_bench_wire_u8_${HW}_tex.json" \
        2>/dev/null | tee "$OUT/host_decode_bench_wire_u8_${HW}_tex.log"
done

echo "session complete: $OUT — TPU FREEZE is now in effect"
