#!/bin/sh
# Round-18 TPU measurement session — same discipline as tpu_session_r17.sh
# (STATIC GATE FIRST, hard TPU freeze after, watchdog-protected phases,
# carried debt by delegation).
#
# New in r18 (the r23 latency-tier serving round):
#   - SERVING TIER GRID ROW (device): the flagship's full ladder —
#     fp32/bf16/int8/student — under the r16 open-loop Poisson protocol,
#     one row per rung. The committed host receipts
#     (benchmarks/runs/host_r23/) already pin the CPU frontier
#     (int8 elision + the half-width student beat fp32; bf16 is
#     EMULATED on CPU and receipts within noise); the device grid measures
#     what CPU cannot: bf16 on a native-MXU part, where the cast-once
#     params + bf16 activations should finally cash the rung's latency
#     claim. Rows land on the sentinel basis's r20 `tier` axis
#     (SERVING_RPS_R18_* chains) so each rung regresses independently.
#     Trained weights are required for the accuracy-delta receipts —
#     train with tools/distill (see $WEIGHTS/$STUDENT below) before the
#     session, or the rows bench fresh-init RPS without accuracy blocks.
#   - everything r7–r17 carried (zero3 device grid + narrowed gather
#     wire, elastic downtime receipt, resume receipt, wire-escalation
#     row, serving open-loop + device serving, ingest-service grid,
#     sharding/bucket grid, zoo rows, augment pair, autotune
#     convergence, wire columns, sentinel gating, sanitizer receipts)
#     rides along by DELEGATING to tpu_session_r17.sh — one copy of the
#     debt, no drift.
#
# Usage: sh benchmarks/tpu_session_r18.sh [outdir] [run_label]

set -u
OUT=${1:-/tmp/tpu_session_r18}
RUN=${2:-benchmarks/runs/tpu_r18}
WEIGHTS=${DVGGF_TIER_WEIGHTS:-/tmp/r23_weights/vggf_fp32.npz}
STUDENT=${DVGGF_STUDENT_WEIGHTS:-/tmp/r23_weights/vggf_student.npz}
mkdir -p "$OUT"
cd "$(dirname "$0")/.."

echo "== r18 static gate: linter + ABI contract + committed receipts =="
sh tools/check.sh 2>&1 | tee "$OUT/static_gate.log"
if ! grep -q "ALL GREEN" "$OUT/static_gate.log"; then
    echo "static gate FAILED — fix the tree before spending TPU time" >&2
    exit 1
fi

echo "== r23 serving tier grid: fp32/bf16/int8/student ladder =="
ACC=""
if [ -f "$WEIGHTS" ]; then
    ACC="--weights $WEIGHTS"
else
    echo "NOTE: $WEIGHTS missing — tier rows bench fresh-init, no accuracy blocks" >&2
fi
for TIER in fp32 bf16 int8 student; do
    EXTRA="$ACC"
    if [ "$TIER" = student ] && [ -f "$STUDENT" ]; then
        EXTRA="$ACC --student-weights $STUDENT"
    elif [ "$TIER" = student ]; then
        echo "NOTE: $STUDENT missing — skipping student rung" >&2
        continue
    fi
    DVGGF_BENCH_ARTIFACT="$RUN/serving_r18_tier_${TIER}_device.json" \
    python benchmarks/serving_bench.py --tier "$TIER" \
        --image-size 32 --num-classes 10 $EXTRA \
        --json-out "$OUT/serving_r18_tier_${TIER}_device.json" 2>/dev/null \
        | tee "$OUT/serving_r18_tier_${TIER}_device.json.log"
done

echo "== carried r7-r17 debt: delegate to tpu_session_r17.sh =="
sh benchmarks/tpu_session_r17.sh "$OUT/r17_carried" "$RUN"

echo "session complete: $OUT — TPU FREEZE is now in effect"
