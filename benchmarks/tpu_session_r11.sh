#!/bin/sh
# Round-11 TPU measurement session — same discipline as tpu_session_r10.sh
# (scheduled EARLY, followed by a HARD TPU FREEZE; every bench.py invocation
# watchdog-protected; unprotected phases only after the flagship bench
# proves the tunnel healthy; a wedged-tunnel flagship exits 0 with the
# stale last_committed payload as its result line).
#
# Differences from tpu_session_r10.sh (the r14 overlapped-bucketed-exchange
# + ZeRO-2 round):
#   - STEP-TIME x (model, sharding, bucket) GRID: the r14 acceptance rows.
#     For vggf (FC-heavy — the two FC layers dominate param bytes; the
#     exchange tail is worst here) and vit_s16 (many small leaves — the
#     many-small-buckets latency caveat), device step time under
#       dp            (shard_opt_state=false)
#       zero1         (shard_opt_state=true, bucket off — the r13 row)
#       zero2         (shard_gradients=true, bucket off)
#       zero2_bucketed(shard_gradients=true, comm_bucket_mb=4 — flagship)
#     plus a 1 MB bucket column on vggf to bracket the bucket-size knob.
#     The on-device win the CPU receipts cannot show (XLA's latency-hiding
#     scheduler running bucket k's collective under the backward that
#     feeds bucket k+1) reads directly off step time bucket-on vs off.
#   - per-chip HBM columns for the same grid: ZeRO-2's gradient-state
#     O(params/N) claim on real HBM (scaling model:
#     gradient_state_bytes_per_chip; accumulator sharding needs the
#     grad_accum=2 row).
#   - everything r10 carried (zoo rows, augment pair, autotune, restart
#     columns, snapshot, exporter smoke) rides along unchanged.
#
# Usage: sh benchmarks/tpu_session_r11.sh [outdir] [run_label]

set -u
OUT=${1:-/tmp/tpu_session_r11}
RUN=${2:-benchmarks/runs/tpu_r11}
mkdir -p "$OUT"
cd "$(dirname "$0")/.."

echo "== flagship device bench (continuity row, bench-default config) =="
DVGGF_BENCH_ARTIFACT="$RUN/vggf_device.json" \
python bench.py --steps 30 --warmup 5 --budget 1500 \
    | tee "$OUT/vggf_device.json"
if grep -q '"error"' "$OUT/vggf_device.json"; then
    echo "tunnel unhealthy (stale or null result) — stopping before" \
         "unprotected phases" >&2
    exit 1
fi

echo "== r14 step-time x (model, sharding, bucket) grid: the overlapped"
echo "   bucketed exchange's device receipts (bench.py builds its own"
echo "   config, so each layout is applied explicitly via --set) =="
for MODEL in vggf vit_s16; do
    BS=2048; [ "$MODEL" = "vit_s16" ] && BS=256
    DVGGF_BENCH_ARTIFACT="$RUN/${MODEL}_device_dp.json" \
    python bench.py --model "$MODEL" --batch-size "$BS" --steps 30 \
        --warmup 5 --budget 1500 \
        --set mesh.shard_opt_state=false \
        | tee "$OUT/${MODEL}_device_dp.json"
    DVGGF_BENCH_ARTIFACT="$RUN/${MODEL}_device_zero1.json" \
    python bench.py --model "$MODEL" --batch-size "$BS" --steps 30 \
        --warmup 5 --budget 1500 \
        --set mesh.shard_opt_state=true \
        | tee "$OUT/${MODEL}_device_zero1.json"
    DVGGF_BENCH_ARTIFACT="$RUN/${MODEL}_device_zero2.json" \
    python bench.py --model "$MODEL" --batch-size "$BS" --steps 30 \
        --warmup 5 --budget 1500 \
        --set mesh.shard_opt_state=true --set mesh.shard_gradients=true \
        | tee "$OUT/${MODEL}_device_zero2.json"
    DVGGF_BENCH_ARTIFACT="$RUN/${MODEL}_device_zero2_bucket4.json" \
    python bench.py --model "$MODEL" --batch-size "$BS" --steps 30 \
        --warmup 5 --budget 1500 \
        --set mesh.shard_opt_state=true --set mesh.shard_gradients=true \
        --set mesh.comm_bucket_mb=4.0 \
        | tee "$OUT/${MODEL}_device_zero2_bucket4.json"
done

echo "== r14 bucket-size bracket on the FC-heavy stress case (vggf) =="
DVGGF_BENCH_ARTIFACT="$RUN/vggf_device_zero2_bucket1.json" \
python bench.py --steps 30 --warmup 5 --budget 1500 \
    --set mesh.shard_opt_state=true --set mesh.shard_gradients=true \
    --set mesh.comm_bucket_mb=1.0 \
    | tee "$OUT/vggf_device_zero2_bucket1.json"
DVGGF_BENCH_ARTIFACT="$RUN/vggf_device_dp_bucket4.json" \
python bench.py --steps 30 --warmup 5 --budget 1500 \
    --set mesh.shard_opt_state=false --set mesh.comm_bucket_mb=4.0 \
    | tee "$OUT/vggf_device_dp_bucket4.json"

echo "== r14 ZeRO-2 sharded-accumulator HBM row (grad_accum=2: the scan"
echo "   carry drops O(params) -> O(params/N); pair with the zero1 row"
echo "   above for the delta) =="
DVGGF_BENCH_ARTIFACT="$RUN/vggf_device_zero2_accum2.json" \
python bench.py --steps 30 --warmup 5 --budget 1500 \
    --set mesh.shard_opt_state=true --set mesh.shard_gradients=true \
    --set mesh.comm_bucket_mb=4.0 --set train.grad_accum_steps=2 \
    | tee "$OUT/vggf_device_zero2_accum2.json"

echo "== r14 bf16-wire x bucketed column (per-bucket cast through the"
echo "   single-sourced cast; clip-after-cast pinned on CPU) =="
DVGGF_BENCH_ARTIFACT="$RUN/vggf_device_zero2_bucket4_bf16.json" \
python bench.py --steps 30 --warmup 5 --budget 1500 \
    --set mesh.shard_opt_state=true --set mesh.shard_gradients=true \
    --set mesh.comm_bucket_mb=4.0 --set mesh.reduce_dtype=bfloat16 \
    | tee "$OUT/vggf_device_zero2_bucket4_bf16.json"

echo "== r14 CPU receipts carried next to the device grid (bucketing"
echo "   overhead + the lowered-HLO overlap assertion re-run on the"
echo "   session box) =="
JAX_PLATFORMS=cpu python benchmarks/comm_overlap_bench.py \
    --model vggf --sharding zero2 --image-size 64 --repeats 6 \
    --json-out "$OUT/comm_overlap_vggf_zero2.json" 2>/dev/null \
    | tee "$OUT/comm_overlap_vggf_zero2.log"
JAX_PLATFORMS=cpu python benchmarks/comm_overlap_bench.py --hlo-report \
    --model vggf --image-size 64 --batch 8 \
    --json-out "$OUT/hlo_overlap_vggf_zero2.json" 2>/dev/null \
    | tee "$OUT/hlo_overlap_vggf_zero2.log"

echo "== model zoo device benches (carried forward) =="
DVGGF_BENCH_ARTIFACT="$RUN/vgg16_device.json" \
python bench.py --model vgg16 --batch-size 128 --steps 20 --budget 1500 \
    | tee "$OUT/vgg16_device.json"
DVGGF_BENCH_ARTIFACT="$RUN/resnet50_device.json" \
python bench.py --model resnet50 --batch-size 256 --steps 20 --budget 1500 \
    | tee "$OUT/resnet50_device.json"
DVGGF_BENCH_ARTIFACT="$RUN/vit_s16_device.json" \
python bench.py --model vit_s16 --batch-size 256 --steps 20 --budget 1500 \
    | tee "$OUT/vit_s16_device.json"

echo "== end-to-end pipeline bench: u8 wire flagship (carried forward) =="
DVGGF_BENCH_ARTIFACT="$RUN/vggf_e2e_wire_u8.json" \
python bench.py --pipeline imagenet --repeats 6 --budget 3600 \
    --wire u8 \
    | tee "$OUT/vggf_e2e_wire_u8.json"

echo "== host decode contract + flagship wire column (carried forward) =="
python benchmarks/host_pipeline_bench.py --layout tfrecord --batches 12 \
    2>/dev/null | tee "$OUT/host_decode.json"
python benchmarks/host_pipeline_bench.py --decode-bench --layout tfrecord \
    --repeats 6 --wire u8 --space-to-depth \
    --json-out "$OUT/host_decode_bench_wire_u8_s2d.json" 2>/dev/null \
    | tee "$OUT/host_decode_bench_wire_u8_s2d.log"

echo "== r13 zoo host rows (carried forward) =="
for MODEL in vggf vgg16 resnet50 vit_s16; do
    python benchmarks/host_pipeline_bench.py --decode-bench \
        --layout tfrecord --repeats 6 --model "$MODEL" \
        --restart-interval 1 --decode-restart on \
        --json-out "$OUT/host_decode_bench_zoo_${MODEL}.json" 2>/dev/null \
        | tee "$OUT/host_decode_bench_zoo_${MODEL}.log"
done

echo "== r13 augment-on host column (carried forward) =="
python benchmarks/host_pipeline_bench.py --decode-bench --layout tfrecord \
    --repeats 6 --model vggf --augment on --augment-receipt \
    --restart-interval 1 --decode-restart on \
    --json-out "$OUT/host_decode_bench_augment_on.json" 2>/dev/null \
    | tee "$OUT/host_decode_bench_augment_on.log"

echo "== r11 autotune convergence pair (carried forward) =="
python benchmarks/host_pipeline_bench.py --decode-bench --layout tfrecord \
    --repeats 6 --wire u8 --space-to-depth --autotune on \
    --json-out "$OUT/host_decode_bench_autotune_u8_s2d.json" 2>/dev/null \
    | tee "$OUT/host_decode_bench_autotune_u8_s2d.log"

echo "== regression sentinel: gate the flagship + zoo + augment rows"
echo "   against their pinned bases =="
# no pipe to tee here: POSIX sh has no pipefail, so '|| ...' after a pipe
# would test tee's exit status and the failure branch could never fire
python benchmarks/regression_sentinel.py --check-committed \
    --check "$OUT"/host_decode_bench_wire_u8_s2d.json \
            "$OUT"/host_decode_bench_autotune_u8_s2d.json \
            "$OUT"/host_decode_bench_zoo_vgg16.json \
            "$OUT"/host_decode_bench_zoo_resnet50.json \
            "$OUT"/host_decode_bench_zoo_vit_s16.json \
            "$OUT"/host_decode_bench_augment_on.json \
    > "$OUT/regression_sentinel.log" 2>&1
SENTINEL_RC=$?
cat "$OUT/regression_sentinel.log"
if [ "$SENTINEL_RC" -ne 0 ]; then
    echo "SENTINEL FAILED — do not commit these rows as a new pin" \
         "without same-session worktree controls" >&2
fi

echo "session complete: $OUT — TPU FREEZE is now in effect"
