#!/bin/sh
# Round-10 TPU measurement session — same discipline as tpu_session_r9.sh
# (scheduled EARLY, followed by a HARD TPU FREEZE; every bench.py invocation
# watchdog-protected; unprotected phases only after the flagship bench
# proves the tunnel healthy; a wedged-tunnel flagship exits 0 with the
# stale last_committed payload as its result line).
#
# Differences from tpu_session_r9.sh (the r13 fused-augment + one-contract
# round):
#   - the flagship E2E device row now runs vggf_imagenet_dp with BOTH
#     data.augment (fused on-device flips+mixup — the device step-time
#     confirmation of the CPU augment_step_bench.py receipt) and
#     mesh.shard_opt_state (ZeRO-1): its JSONL carries the augment blocks,
#     and the per-chip HBM delta vs --set mesh.shard_opt_state=false is
#     the queued ROADMAP item 4 receipt.
#   - an augment on/off DEVICE step pair: the same preset with
#     data.augment.enabled=false — fused-augment step overhead on real
#     hardware (<2% acceptance, CPU receipt in benchmarks/runs/host_r13/).
#   - ZOO HOST ROWS: all four presets' ingest configs through
#     host_pipeline_bench.py --model (wire/space-to-depth from the
#     models/ingest.py descriptor) — the per-model basis keys the
#     regression sentinel now gates independently of the VGG-F line.
#   - the r13 augment-overhead HOST receipt (--augment-receipt):
#     alternating augment-off/on windows proving host img/s/core and wire
#     bytes/image unchanged with augmentation on.
#   - everything r9 carried (autotune pair + wire escalation + overhead,
#     restart columns, snapshot row, exporter smoke, u8 e2e) rides along.
#
# Usage: sh benchmarks/tpu_session_r10.sh [outdir] [run_label]

set -u
OUT=${1:-/tmp/tpu_session_r10}
RUN=${2:-benchmarks/runs/tpu_r10}
mkdir -p "$OUT"
cd "$(dirname "$0")/.."

echo "== flagship device bench (continuity row, bench-default config) =="
DVGGF_BENCH_ARTIFACT="$RUN/vggf_device.json" \
python bench.py --steps 30 --warmup 5 --budget 1500 \
    | tee "$OUT/vggf_device.json"
if grep -q '"error"' "$OUT/vggf_device.json"; then
    echo "tunnel unhealthy (stale or null result) — stopping before" \
         "unprotected phases" >&2
    exit 1
fi

echo "== r13 augment on/off device step pair (fused-stage overhead on"
echo "   real hardware; CPU receipt: host_r13/augment_step_overhead —"
echo "   bench.py builds its own config, so the PRESET recipe is applied"
echo "   explicitly via --set) =="
DVGGF_BENCH_ARTIFACT="$RUN/vggf_device_augment_on.json" \
python bench.py --steps 30 --warmup 5 --budget 1500 \
    --set data.augment.enabled=true --set data.augment.mixup_alpha=0.2 \
    | tee "$OUT/vggf_device_augment_on.json"

echo "== r13 ZeRO-1 on/off per-chip HBM + step-time pair (ROADMAP item 4"
echo "   device receipt; the preset ships mesh.shard_opt_state=true) =="
DVGGF_BENCH_ARTIFACT="$RUN/vggf_device_zero1_on.json" \
python bench.py --steps 30 --warmup 5 --budget 1500 \
    --set mesh.shard_opt_state=true \
    | tee "$OUT/vggf_device_zero1_on.json"

echo "== model zoo device benches (one u8 ingest contract for all four) =="
DVGGF_BENCH_ARTIFACT="$RUN/vgg16_device.json" \
python bench.py --model vgg16 --batch-size 128 --steps 20 --budget 1500 \
    | tee "$OUT/vgg16_device.json"
DVGGF_BENCH_ARTIFACT="$RUN/resnet50_device.json" \
python bench.py --model resnet50 --batch-size 256 --steps 20 --budget 1500 \
    | tee "$OUT/resnet50_device.json"
DVGGF_BENCH_ARTIFACT="$RUN/vit_s16_device.json" \
python bench.py --model vit_s16 --batch-size 256 --steps 20 --budget 1500 \
    | tee "$OUT/vit_s16_device.json"

echo "== end-to-end pipeline bench: host wire vs u8 wire (min-of-6) =="
DVGGF_BENCH_ARTIFACT="$RUN/vggf_e2e.json" \
python bench.py --pipeline imagenet --repeats 6 --budget 3600 \
    | tee "$OUT/vggf_e2e.json"
DVGGF_BENCH_ARTIFACT="$RUN/vggf_e2e_wire_u8.json" \
python bench.py --pipeline imagenet --repeats 6 --budget 3600 \
    --wire u8 \
    | tee "$OUT/vggf_e2e_wire_u8.json"

echo "== host decode contract line (host-only, no TPU client) =="
python benchmarks/host_pipeline_bench.py --layout tfrecord --batches 12 \
    2>/dev/null | tee "$OUT/host_decode.json"

echo "== host decode-bench flagship wire column (carried forward) =="
python benchmarks/host_pipeline_bench.py --decode-bench --layout tfrecord \
    --repeats 6 --wire u8 --space-to-depth \
    --json-out "$OUT/host_decode_bench_wire_u8_s2d.json" 2>/dev/null \
    | tee "$OUT/host_decode_bench_wire_u8_s2d.log"

echo "== r13 zoo host rows: every preset's ingest config through the"
echo "   bench, layout/wire from the per-model descriptor =="
for MODEL in vggf vgg16 resnet50 vit_s16; do
    python benchmarks/host_pipeline_bench.py --decode-bench \
        --layout tfrecord --repeats 6 --model "$MODEL" \
        --restart-interval 1 --decode-restart on \
        --json-out "$OUT/host_decode_bench_zoo_${MODEL}.json" 2>/dev/null \
        | tee "$OUT/host_decode_bench_zoo_${MODEL}.log"
done

echo "== r13 augment-on host column + alternating overhead receipt"
echo "   (host rate and wire bytes/img unchanged with augmentation on) =="
python benchmarks/host_pipeline_bench.py --decode-bench --layout tfrecord \
    --repeats 6 --model vggf --augment on --augment-receipt \
    --restart-interval 1 --decode-restart on \
    --json-out "$OUT/host_decode_bench_augment_on.json" 2>/dev/null \
    | tee "$OUT/host_decode_bench_augment_on.log"

echo "== r13 fused-augment CPU step receipt (carried next to the device"
echo "   pair above) =="
python benchmarks/augment_step_bench.py --model vggf --image-size 128 \
    --batch 32 --repeats 6 \
    --json-out "$OUT/augment_step_overhead.json" 2>/dev/null \
    | tee "$OUT/augment_step_overhead.log"

echo "== r11 autotune convergence pair (carried forward) =="
python benchmarks/host_pipeline_bench.py --decode-bench --layout tfrecord \
    --repeats 6 --wire u8 --space-to-depth --autotune on \
    --json-out "$OUT/host_decode_bench_autotune_u8_s2d.json" 2>/dev/null \
    | tee "$OUT/host_decode_bench_autotune_u8_s2d.log"

echo "== r11 wire-escalation run (carried forward) =="
python benchmarks/host_pipeline_bench.py --decode-bench --layout tfrecord \
    --repeats 6 --wire u8 --space-to-depth --autotune on \
    --autotune-start-wire host \
    --json-out "$OUT/host_decode_bench_autotune_wire_esc.json" 2>/dev/null \
    | tee "$OUT/host_decode_bench_autotune_wire_esc.log"

echo "== r9 restart columns (carried forward): >=448px textured =="
for HW in 448x448 768x768; do
    for RST in off on; do
        python benchmarks/host_pipeline_bench.py --decode-bench \
            --layout tfrecord --repeats 6 --wire u8 --space-to-depth \
            --source-hw "$HW" --source-kind textured \
            --restart-interval 1 --decode-restart "$RST" \
            --json-out "$OUT/host_decode_bench_rst1_${RST}_${HW}_tex.json" \
            2>/dev/null \
            | tee "$OUT/host_decode_bench_rst1_${RST}_${HW}_tex.log"
    done
done

echo "== r9 snapshot warm-vs-cold row (carried forward) =="
python benchmarks/host_pipeline_bench.py --decode-bench --layout tfrecord \
    --repeats 6 --wire u8 --space-to-depth \
    --source-hw 448x448 --source-kind textured \
    --restart-interval 1 --decode-restart on --snapshot-cache \
    --json-out "$OUT/host_decode_bench_snapshot_448tex.json" 2>/dev/null \
    | tee "$OUT/host_decode_bench_snapshot_448tex.log"

echo "== exporter smoke row (carried forward) =="
python benchmarks/host_pipeline_bench.py --decode-bench --layout tfrecord \
    --repeats 6 --wire u8 --space-to-depth --exporter-receipt \
    --json-out "$OUT/host_decode_bench_exporter_u8_s2d.json" 2>/dev/null \
    | tee "$OUT/host_decode_bench_exporter_u8_s2d.log"

echo "== regression sentinel: gate the flagship rows AND the r13 zoo +"
echo "   augment rows against their own pinned bases =="
# no pipe to tee here: POSIX sh has no pipefail, so '|| ...' after a pipe
# would test tee's exit status and the failure branch could never fire
python benchmarks/regression_sentinel.py --check-committed \
    --check "$OUT"/host_decode_bench_wire_u8_s2d.json \
            "$OUT"/host_decode_bench_autotune_u8_s2d.json \
            "$OUT"/host_decode_bench_zoo_vgg16.json \
            "$OUT"/host_decode_bench_zoo_resnet50.json \
            "$OUT"/host_decode_bench_zoo_vit_s16.json \
            "$OUT"/host_decode_bench_augment_on.json \
    > "$OUT/regression_sentinel.log" 2>&1
SENTINEL_RC=$?
cat "$OUT/regression_sentinel.log"
if [ "$SENTINEL_RC" -ne 0 ]; then
    echo "SENTINEL FAILED — do not commit these rows as a new pin" \
         "without same-session worktree controls" >&2
fi

echo "session complete: $OUT — TPU FREEZE is now in effect"
