#!/usr/bin/env python
"""N-worker ingest-service scaling bench (r16 acceptance receipt).

Measures the disaggregated-ingest plane end-to-end on one box: N decode-
worker PROCESSES (real `python -m distributed_vgg_f_tpu.data.ingest_service`
children, 1 decode thread each — the per-core discipline of every committed
decode receipt) serving one ServiceIngestClient, against the local native
iterator as the same-session control column. Two receipts per run:

1. **Scaling**: aggregate img/s for N ∈ {1, 2, 4} workers vs the local
   single-core rate, min-of-R ALTERNATING windows (each repeat cycles
   local → service_1w → service_2w → service_4w, so box drift lands evenly
   across columns — the r8+ alternating-window protocol). The acceptance
   bar is service_4w ≥ 0.85 × 4 × service_1w.
2. **Verdict flip**: a simulated trainer (fixed per-batch compute budget,
   calibrated to `--compute-factor` × the measured single-worker service
   rate) classified per window by the REAL stall attributor
   (telemetry/stall.classify): starved at N=1 → `infeed_bound`, fed at
   N=4 → `compute_bound` — the live signal that tells an operator "add
   decode workers" and then "stop adding".

Sources are generated noise JPEGs at --source-hw (default 320x256, the
frozen contract protocol); the artifact's layout rows carry
`ingest_mode` (`local` | `service_<N>w`) — the r16 Basis key — so service
rows gate independently of the single-host pins.

Usage:
  python benchmarks/ingest_service_bench.py --repeats 6 \
      --json-out benchmarks/runs/host_r15/ingest_service_scaling_run1.json
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from distributed_vgg_f_tpu import telemetry  # noqa: E402
from distributed_vgg_f_tpu.config import (apply_overrides,  # noqa: E402
                                          get_config)
from distributed_vgg_f_tpu.telemetry import schema, stall  # noqa: E402

HOST_METRIC = "host_native_decode_images_per_sec_per_core"


def generate_sources(root: str, n: int, hw, quality: int = 90) -> float:
    """Noise JPEGs in the imagefolder layout; returns bytes/pixel."""
    from PIL import Image
    rs = np.random.RandomState(0)
    total = 0
    for cls in range(2):
        d = os.path.join(root, "train", f"c{cls:02d}")
        os.makedirs(d, exist_ok=True)
        for i in range(n // 2):
            p = os.path.join(d, f"{i:05d}.jpg")
            Image.fromarray(
                (rs.rand(hw[0], hw[1], 3) * 255).astype(np.uint8)).save(
                p, "JPEG", quality=quality)
            total += os.path.getsize(p)
    return total / (n * hw[0] * hw[1])


def bench_cfg(data_dir: str, batch: int, image_size: int):
    """The bench's stream config: flagship-style u8 wire, augment and
    autotune off (hand-pinned 1-thread columns, like every committed
    decode row), snapshot tier off (this measures DECODE scaling, not the
    cache)."""
    return apply_overrides(get_config("vggf_imagenet_dp"), {
        "data.data_dir": data_dir,
        "data.global_batch_size": batch,
        "data.image_size": image_size,
        "data.native_threads": 1,
        "data.autotune.enabled": False,
        "data.augment.enabled": False,
        "data.snapshot_cache.enabled": False,
        "data.space_to_depth": False,
        "train.seed": 0,
    })


def spawn_workers(cfg_args, n: int, timeout_s: float = 60.0):
    """n real worker processes; returns (procs, endpoints) after scraping
    each child's bound-port line (the port-0 contract)."""
    procs, endpoints = [], []
    for i in range(n):
        cmd = [sys.executable, "-m",
               "distributed_vgg_f_tpu.data.ingest_service",
               "--host", "127.0.0.1", "--port", "0",
               "--worker-index", str(i), "--num-workers", str(n),
               "--threads", "1"] + cfg_args
        proc = subprocess.Popen(cmd, cwd=REPO, stdout=subprocess.PIPE,
                                stderr=subprocess.DEVNULL, text=True)
        procs.append(proc)
    deadline = time.monotonic() + timeout_s
    for proc in procs:
        line = ""
        while time.monotonic() < deadline:
            line = proc.stdout.readline()
            if "serving on" in line:
                break
        if "serving on" not in line:
            raise RuntimeError(f"worker did not report its port: {line!r}")
        endpoints.append(line.rsplit("serving on ", 1)[1].strip())
    return procs, endpoints


def drain_rate(it, batches: int, batch: int, warmup: int = 3) -> float:
    """Steady-state drain: the warmup draws ramp the pipeline (native
    worker threads on the local column; the fetch-ahead window and
    per-link connections on the service columns) outside the timed
    region, the same discipline as host_pipeline_bench's windows."""
    for _ in range(warmup):
        next(it)
    t0 = time.monotonic()
    for _ in range(batches):
        next(it)
    return batches * batch / (time.monotonic() - t0)


def simulated_train_verdict(it, batches: int, batch: int,
                            target_rate: float, warmup: int = 3) -> dict:
    """One simulated-trainer window: per batch, block on the pipeline then
    burn a fixed compute budget (batch/target_rate seconds); classify the
    window with the production stall attributor. Warmup draws ramp the
    pipeline outside the classified window (a trainer's first steps are
    compile time anyway)."""
    budget = batch / target_rate
    for _ in range(warmup):
        next(it)
    wait_s = 0.0
    t_start = time.monotonic()
    for _ in range(batches):
        t0 = time.monotonic()
        next(it)
        wait_s += time.monotonic() - t0
        t_done = time.monotonic() + budget
        while time.monotonic() < t_done:  # busy-wait: a device never sleeps
            pass
    wall = time.monotonic() - t_start
    record = stall.classify(wall, infeed_wait_s=wait_s)
    record["images_per_sec"] = round(batches * batch / wall, 2)
    return record


def column_stats(samples) -> dict:
    best = max(samples)
    med = float(np.median(samples))
    return {"images_per_sec": round(best, 2),
            "repeats": len(samples),
            "median": round(med, 2),
            "spread": round((max(samples) - min(samples)) / med, 4)}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--batches", type=int, default=12)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--repeats", type=int, default=6)
    ap.add_argument("--workers-grid", default="1,2,4")
    ap.add_argument("--source-images", type=int, default=256)
    ap.add_argument("--source-hw", default="320x256")
    ap.add_argument("--verdict-batches", type=int, default=8)
    ap.add_argument("--compute-factor", type=float, default=2.2,
                    help="simulated device rate = factor x measured "
                         "single-worker service rate (between 2 and 4 "
                         "workers' throughput, so the verdict flips "
                         "inside the grid)")
    ap.add_argument("--json-out", default="")
    ap.add_argument("--keep-sources", default="")
    args = ap.parse_args(argv)

    grid = [int(x) for x in args.workers_grid.split(",") if x.strip()]
    hw = tuple(int(x) for x in args.source_hw.split("x"))
    root = args.keep_sources or tempfile.mkdtemp(prefix="svc_bench_")
    print(f"generating {args.source_images} noise JPEGs at "
          f"{hw[0]}x{hw[1]} under {root} ...", flush=True)
    bpp = generate_sources(root, args.source_images, hw)
    cfg = bench_cfg(root, args.batch, args.image_size)
    cfg_args = ["--config", "vggf_imagenet_dp",
                "--set", f"data.data_dir={root}",
                "--set", f"data.global_batch_size={args.batch}",
                "--set", f"data.image_size={args.image_size}",
                "--set", "data.autotune.enabled=false",
                "--set", "data.augment.enabled=false",
                "--set", "data.snapshot_cache.enabled=false",
                "--set", "data.space_to_depth=false",
                "--set", "train.seed=0"]

    from distributed_vgg_f_tpu.data import build_dataset
    from distributed_vgg_f_tpu.data.service_client import ServiceIngestClient

    fleets = {}
    try:
        for n in grid:
            print(f"spawning {n}-worker fleet ...", flush=True)
            fleets[n] = spawn_workers(cfg_args, n)

        def service_client(n):
            # routing epoch = ImageNet-scale (~1.28M/batch), NOT the tiny
            # generated source set's: ownership must stay static across a
            # window (the production shape) — re-keying every
            # source_images/batch cursors would randomize assignment and
            # measure load-imbalance, not scaling
            return ServiceIngestClient(
                fleets[n][1], seed=0,
                batches_per_epoch=max(1, 1_281_167 // args.batch),
                expect={"seed": 0})

        # warmup every column once (page cache, lazy pools, sockets)
        for n in grid:
            c = service_client(n)
            drain_rate(c, 2, args.batch)
            c.close()
        local_warm = build_dataset(cfg.data, "train", seed=0,
                                   num_classes=1000)
        drain_rate(local_warm, 2, args.batch)
        local_warm.close()

        samples = {"local": []}
        for n in grid:
            samples[f"service_{n}w"] = []
        for r in range(args.repeats):
            # ALTERNATING columns inside each repeat: drift lands evenly
            local = build_dataset(cfg.data, "train", seed=0,
                                  num_classes=1000)
            rate = drain_rate(local, args.batches, args.batch)
            local.close()
            samples["local"].append(rate)
            print(f"[r{r}] local: {rate:.1f} img/s/core", flush=True)
            for n in grid:
                c = service_client(n)
                # warmup must EXCEED the fetch-ahead window (3n): the
                # ramp leaves up to fetch_ahead batches buffered, and a
                # timed region that starts by draining them reads ~25%
                # above steady state — the warmup consumes the surplus so
                # the window is purely producer-limited
                rate = drain_rate(c, args.batches, args.batch,
                                  warmup=3 * n + 2)
                c.close()
                samples[f"service_{n}w"].append(rate)
                print(f"[r{r}] service_{n}w: {rate:.1f} img/s aggregate",
                      flush=True)

        # verdict-flip pass: simulated trainer at a rate between the 2- and
        # 4-worker aggregate, so the grid crosses the flip
        svc1 = max(samples["service_1w"]) if "service_1w" in samples \
            else max(samples["local"])
        target = args.compute_factor * svc1
        verdicts = {}
        local = build_dataset(cfg.data, "train", seed=0, num_classes=1000)
        verdicts["local"] = simulated_train_verdict(
            local, args.verdict_batches, args.batch, target)
        local.close()
        for n in grid:
            c = service_client(n)
            verdicts[f"service_{n}w"] = simulated_train_verdict(
                c, args.verdict_batches, args.batch, target,
                warmup=3 * n + 2)
            c.close()
        for col, v in verdicts.items():
            print(f"verdict[{col}]: {v['verdict']} "
                  f"(infeed_fraction={v['infeed_fraction']})", flush=True)
    finally:
        for procs, _ in fleets.values():
            for p in procs:
                p.terminate()
        for procs, _ in fleets.values():
            for p in procs:
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    p.kill()
        if not args.keep_sources:
            shutil.rmtree(root, ignore_errors=True)

    src = {"source_hw": [hw[0], hw[1]], "source_kind": "noise",
           "bytes_per_pixel": round(bpp, 4)}
    protocol = (f"min-of-{args.repeats} alternating windows "
                f"(local -> service_1w -> service_2w -> service_4w per "
                f"repeat), {args.batches} batches of {args.batch} at "
                f"image_size {args.image_size}; workers are separate "
                f"processes, 1 decode thread each; sources noise "
                f"{hw[0]}x{hw[1]}")
    wire_bytes = args.image_size * args.image_size * 3
    rows = []
    local_stats = column_stats(samples["local"])
    rows.append({
        "layout": "imagefolder", "mode": "decode_bench",
        "ingest_mode": "local",
        "images_per_sec_per_core": local_stats["images_per_sec"],
        "threads": 1, "image_dtype": "float32", "space_to_depth": False,
        "wire": "u8", "wire_bytes_per_image": wire_bytes,
        "repeats": local_stats["repeats"], "median": local_stats["median"],
        "spread": local_stats["spread"], "model": "vggf",
        "source": src, "verdict": verdicts["local"]})
    scaling = {}
    svc1_best = column_stats(samples[f"service_{grid[0]}w"])[
        "images_per_sec"] if grid else None
    for n in grid:
        st = column_stats(samples[f"service_{n}w"])
        vs_local = round(st["images_per_sec"]
                         / local_stats["images_per_sec"], 3)
        linearity = round(st["images_per_sec"] / (n * svc1_best), 3)
        rows.append({
            "layout": "imagefolder", "mode": "decode_bench",
            "ingest_mode": f"service_{n}w",
            "images_per_sec_per_core": round(st["images_per_sec"] / n, 2),
            "images_per_sec_aggregate": st["images_per_sec"],
            "workers": n, "threads": 1, "image_dtype": "float32",
            "space_to_depth": False, "wire": "u8",
            "wire_bytes_per_image": wire_bytes,
            "repeats": st["repeats"], "median": st["median"],
            "spread": st["spread"], "model": "vggf", "source": src,
            "vs_local": vs_local, "linearity_vs_1w": linearity,
            "verdict": verdicts[f"service_{n}w"]})
        scaling[f"service_{n}w"] = {
            "aggregate_images_per_sec": st["images_per_sec"],
            "vs_local": vs_local, "linearity_vs_1w": linearity,
            "verdict": verdicts[f"service_{n}w"]["verdict"]}
    artifact = {
        "schema_version": schema.SCHEMA_VERSION,
        "metric": HOST_METRIC,
        "value": local_stats["images_per_sec"],
        "unit": "images/sec/core",
        "protocol": protocol,
        "host_vcpus": os.cpu_count(),
        "layouts": rows,
        "ingest_scaling": {
            "grid": grid,
            "local_images_per_sec_per_core": local_stats["images_per_sec"],
            "compute_factor": args.compute_factor,
            "simulated_device_rate": round(target, 2),
            "columns": scaling,
            "verdict_flip": {k: v["verdict"] for k, v in verdicts.items()},
        },
    }
    errors = schema.validate_bench_artifact(artifact)
    if errors:
        print("SCHEMA ERRORS:", errors, file=sys.stderr)
        return 1
    out = json.dumps(artifact, indent=1)
    print(out)
    if args.json_out:
        os.makedirs(os.path.dirname(args.json_out) or ".", exist_ok=True)
        with open(args.json_out, "w") as f:
            f.write(out + "\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
