"""Profile the flagship device bench and attribute step time to ops.

Runs the jitted VGG-F DP train step under a `jax.profiler` trace window
(utils/profiling.py), then parses the chrome-trace output and prints the top
time sinks — the trace-backed breakdown behind README's performance notes
(VERDICT r1: attribute the gap to peak, don't guess).

Usage:
    python benchmarks/profile_bench.py [--batch-size N] [--top K]

Prints JSON lines: one per top op group, then a summary line.
"""

from __future__ import annotations

import argparse
import collections
import glob
import gzip
import io
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def capture_trace(args, logdir: str) -> dict:
    import jax

    from distributed_vgg_f_tpu.config import (
        DataConfig, ExperimentConfig, ModelConfig, OptimConfig, TrainConfig,
        supports_space_to_depth)
    from distributed_vgg_f_tpu.data.synthetic import SyntheticDataset
    from distributed_vgg_f_tpu.train.trainer import Trainer
    from distributed_vgg_f_tpu.utils.logging import MetricLogger
    from distributed_vgg_f_tpu.utils.profiling import StepProfiler

    num_chips = jax.device_count()
    batch = args.batch_size * max(1, num_chips)
    cfg = ExperimentConfig(
        name="profile_bench",
        model=ModelConfig(name=args.model, num_classes=1000,
                          compute_dtype="bfloat16"),
        optim=OptimConfig(base_lr=0.01, reference_batch_size=batch),
        data=DataConfig(name="synthetic", image_size=args.image_size,
                        global_batch_size=batch,
                        space_to_depth=supports_space_to_depth(
                            args.model, args.image_size)),
        train=TrainConfig(steps=args.steps, log_every=10_000, seed=0),
    )
    trainer = Trainer(cfg, logger=MetricLogger(stream=io.StringIO()))
    state = trainer.init_state()
    rng = trainer.base_rng()
    ds = SyntheticDataset(batch_size=batch, image_size=args.image_size,
                          num_classes=1000, seed=0, fixed=True,
                          image_dtype="bfloat16",
                          space_to_depth=cfg.data.space_to_depth)
    sharded = trainer.shard(next(ds))

    for _ in range(args.warmup):
        state, metrics = trainer.train_step(state, sharded, rng)
    if args.warmup:
        float(jax.device_get(metrics["loss"]))

    profiler = StepProfiler(logdir, start_step=2, num_steps=args.trace_steps)
    t0 = time.monotonic()
    for step in range(args.steps):
        profiler.step(step, sync=lambda: jax.device_get(state.step))
        state, metrics = trainer.train_step(state, sharded, rng)
    float(jax.device_get(metrics["loss"]))
    elapsed = time.monotonic() - t0
    profiler.stop()
    return {
        "images_per_sec_per_chip": batch * args.steps / elapsed / num_chips,
        "step_ms": elapsed / args.steps * 1e3,
        "batch": batch,
    }


def analyze_trace(logdir: str, top: int):
    """Aggregate the device "XLA Ops" lane by semantic op path (`tf_op`) and
    by `hlo_category` — the trace-backed time attribution."""
    paths = sorted(glob.glob(
        os.path.join(logdir, "plugins/profile/*/*.trace.json.gz")),
        key=os.path.getmtime)
    if not paths:
        raise FileNotFoundError(f"no trace.json.gz under {logdir}")
    with gzip.open(paths[-1], "rt") as f:
        trace = json.load(f)
    events = trace.get("traceEvents", [])
    op_lanes = {
        (e["pid"], e["tid"])
        for e in events
        if e.get("ph") == "M" and e.get("name") == "thread_name"
        and e.get("args", {}).get("name") == "XLA Ops"}
    by_op: dict = collections.defaultdict(float)
    by_cat: dict = collections.defaultdict(float)
    counts: dict = collections.defaultdict(int)
    for e in events:
        if e.get("ph") != "X" or (e.get("pid"), e.get("tid")) not in op_lanes:
            continue
        args = e.get("args") or {}
        dur = e.get("dur", 0.0)
        op = args.get("tf_op") or e.get("name", "?")
        by_op[op] += dur
        counts[op] += 1
        by_cat[args.get("hlo_category", "?")] += dur
    grand = sum(by_op.values()) or 1.0
    ops = [{"op": name.rstrip(":"), "total_us": round(dur, 1),
            "count": counts[name], "fraction": round(dur / grand, 4)}
           for name, dur in sorted(by_op.items(), key=lambda kv: -kv[1])[:top]]
    cats = [{"hlo_category": c, "total_us": round(d, 1),
             "fraction": round(d / grand, 4)}
            for c, d in sorted(by_cat.items(), key=lambda kv: -kv[1])]
    return ops, cats


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--batch-size", type=int, default=2048)
    parser.add_argument("--image-size", type=int, default=224)
    parser.add_argument("--model", default="vggf")
    parser.add_argument("--steps", type=int, default=12)
    parser.add_argument("--warmup", type=int, default=5)
    parser.add_argument("--trace-steps", type=int, default=4)
    parser.add_argument("--top", type=int, default=15)
    parser.add_argument("--logdir", default="/tmp/dvggf_profile_bench")
    args = parser.parse_args()

    perf = capture_trace(args, args.logdir)
    ops, cats = analyze_trace(args.logdir, args.top)
    for row in ops:
        print(json.dumps(row))
    for row in cats:
        print(json.dumps(row))
    print(json.dumps({"summary": perf, "logdir": args.logdir}))


if __name__ == "__main__":
    main()
