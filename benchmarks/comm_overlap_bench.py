"""CPU receipts for the bucketed, overlapped gradient exchange (r14).

Two receipts, one harness:

1. **Step-time overhead** (default): the bucketed exchange re-groups the
   gradient collectives — on CPU (where no latency-hiding scheduler can
   cash the overlap in) its cost must be ~zero, so the min-of-N
   ALTERNATING-window protocol of every r7+ receipt times the jitted
   train step bucketing-OFF vs bucketing-ON at the same sharding basis.
   CPU is the honest qualifier for the OVERHEAD half of the claim; the
   overlap WIN is device-side and rides tpu_session_r11.sh.

2. **Lowered-HLO overlap evidence** (`--hlo-report`): the committed
   ASSERTION that bucketing produces an overlap-capable exchange
   (ISSUE 11 acceptance: evidence in lowered HLO, not prose). For the
   sharded bases it lowers the step both ways and checks, via
   parallel/buckets.hlo_overlap_report:
     - monolithic: exactly 1 reduce-scatter whose ancestors include the
       ENTIRE backward (the serial tail this PR deletes);
     - bucketed: >= 2 gradient collectives AND a (collective, conv/dot)
       pair with no dependency path either way — the structural license
       for XLA's latency-hiding scheduler to run them concurrently;
     - zero3 (r21, mesh.shard_params): one param all-gather PER BUCKET
       (gathers == buckets; monolithic: exactly 1) plus the committed
       GATHER witness — an (all_gather, conv/dot) pair with no path
       either way, the overlap license for the just-in-time gather.
   Exit 1 if any assertion fails.

    JAX_PLATFORMS=cpu python benchmarks/comm_overlap_bench.py \
        --sharding zero2 --bucket-mb 0.25 --repeats 6 \
        --json-out benchmarks/runs/host_r14/comm_overlap_zero2.json
    JAX_PLATFORMS=cpu python benchmarks/comm_overlap_bench.py \
        --hlo-report --json-out benchmarks/runs/host_r14/hlo_overlap.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

METRIC = "cpu_train_step_images_per_sec"


def _stats(rates):
    med = sorted(rates)[len(rates) // 2]
    return {"repeats": len(rates), "best": round(max(rates), 2),
            "median": round(med, 2),
            "spread": round((max(rates) - min(rates)) / med, 4) if med else 0}


def main() -> int:
    parser = argparse.ArgumentParser(
        description="bucketed gradient-exchange receipts (CPU)")
    parser.add_argument("--model", default="vggf",
                        choices=("vggf", "vgg16", "resnet50", "vit_s16"))
    parser.add_argument("--image-size", type=int, default=64)
    parser.add_argument("--batch", type=int, default=16)
    parser.add_argument("--num-classes", type=int, default=100)
    parser.add_argument("--devices", type=int, default=8,
                        help="virtual CPU mesh size (collectives need > 1)")
    parser.add_argument("--sharding", default="zero2",
                        choices=("dp", "zero1", "zero2", "zero3"))
    parser.add_argument("--bucket-mb", type=float, default=0.25,
                        help="comm_bucket_mb for the bucketed column")
    parser.add_argument("--grad-accum", type=int, default=1)
    parser.add_argument("--steps-per-window", type=int, default=4)
    parser.add_argument("--warmup-steps", type=int, default=2)
    parser.add_argument("--repeats", type=int, default=6,
                        help="alternating window pairs (min-of-N)")
    parser.add_argument("--hlo-report", action="store_true",
                        help="emit + assert the lowered-HLO overlap "
                             "evidence instead of timing windows")
    parser.add_argument("--json-out", default=None)
    args = parser.parse_args()

    # the virtual device count must be pinned before jax initializes
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count="
            f"{args.devices}").strip()

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distributed_vgg_f_tpu.config import ModelConfig
    from distributed_vgg_f_tpu.models import build_model
    from distributed_vgg_f_tpu.parallel.buckets import (
        build_bucket_layout,
        hlo_overlap_report,
    )
    from distributed_vgg_f_tpu.parallel.mesh import (
        MeshSpec,
        build_mesh,
        shard_host_batch,
    )
    from distributed_vgg_f_tpu.parallel.zero import (
        flat_param_count,
        padded_flat_size,
        train_state_specs,
    )
    from distributed_vgg_f_tpu.train.state import TrainState
    from distributed_vgg_f_tpu.train.step import build_train_step

    n_dev = len(jax.devices())
    model = build_model(ModelConfig(name=args.model,
                                    num_classes=args.num_classes,
                                    compute_dtype="float32",
                                    dropout_rate=0.0))
    mesh = build_mesh(MeshSpec(("data",), (n_dev,)))
    tx = optax.sgd(0.01, momentum=0.9)
    zero = args.sharding in ("zero1", "zero2", "zero3")
    zero3 = args.sharding == "zero3"
    sample = jnp.zeros((1, args.image_size, args.image_size, 3), jnp.float32)

    def make(bucket_mb: float):
        layout = None
        specs = None
        p_struct = None
        if zero:
            shapes = jax.eval_shape(
                lambda r: TrainState.create(model, tx, r, sample,
                                            zero1_shards=n_dev),
                jax.random.key(0))
            p_struct = shapes.params  # the params TREE geometry (zero3)
            if bucket_mb > 0:
                layout = build_bucket_layout(
                    shapes.params, n_dev, int(bucket_mb * 1024 * 1024))
                padded = layout.total_padded
            else:
                padded = padded_flat_size(
                    flat_param_count(shapes.params), n_dev)

            def create(r):
                return TrainState.create(model, tx, r, sample,
                                         zero1_shards=n_dev,
                                         bucket_layout=layout,
                                         shard_params=zero3)

            shapes = jax.eval_shape(create, jax.random.key(0))
            specs = train_state_specs(shapes, padded, "data",
                                      shard_params=zero3)
            shardings = jax.tree.map(
                lambda s: NamedSharding(mesh, s), specs,
                is_leaf=lambda x: isinstance(x, P))
            state = jax.jit(create,
                            out_shardings=shardings)(jax.random.key(0))
        else:
            state = TrainState.create(model, tx, jax.random.key(0), sample)
        step = build_train_step(
            model, tx, mesh, weight_decay=5e-4, zero1=zero,
            state_specs=specs, grad_accum_steps=args.grad_accum,
            shard_gradients=args.sharding in ("zero2", "zero3"),
            shard_params=zero3,
            params_struct=p_struct if zero3 else None,
            comm_bucket_mb=bucket_mb)
        return state, step

    rng0 = np.random.default_rng(0)
    batch = shard_host_batch(
        {"image": rng0.standard_normal(
            (args.batch, args.image_size, args.image_size, 3)
        ).astype(np.float32),
         "label": rng0.integers(0, args.num_classes,
                                (args.batch,)).astype(np.int32)}, mesh)
    base = jax.jit(lambda: jax.random.key(1))()

    from distributed_vgg_f_tpu.telemetry.schema import SCHEMA_VERSION

    if args.hlo_report and args.grad_accum != 1:
        # the HLO parser reads TOP-LEVEL instructions only; with grad
        # accumulation the per-bucket scatters live inside the scan's
        # while body, so every assertion below would fail spuriously
        parser.error("--hlo-report requires --grad-accum 1 (accumulated "
                     "collectives lower inside the scan body, invisible "
                     "to the top-level overlap analysis)")

    if args.hlo_report:
        failures = []
        rows = []
        for bucket_mb in (0.0, args.bucket_mb):
            state, step = make(bucket_mb)
            text = step.lower(state, batch, base).as_text()
            rep = hlo_overlap_report(text)
            bucketed = bucket_mb > 0
            label = args.sharding + ("_bucketed" if bucketed else "")
            rows.append({"mode": "hlo_overlap", "sharding": label,
                         "model": args.model, "bucket_mb": bucket_mb,
                         "comm": dict(step.comm_meta), **rep})
            scatters = rep["collective_counts"].get("reduce_scatter", 0)
            if zero and not bucketed:
                # the monolithic serial tail this PR exists to break
                if scatters != 1:
                    failures.append(f"{label}: expected exactly 1 "
                                    f"reduce_scatter, saw {scatters}")
                if rep["serial_tail_collectives"] < 1:
                    failures.append(f"{label}: flat scatter should depend "
                                    "on the whole backward")
            if bucketed:
                want = step.comm_meta["buckets"]
                if zero and scatters != want:
                    failures.append(f"{label}: {scatters} reduce_scatters "
                                    f"!= {want} buckets")
                if rep["grad_collectives"] < 2:
                    failures.append(f"{label}: < 2 gradient collectives")
                if not rep["overlap_capable"]:
                    failures.append(f"{label}: no overlap witness — every "
                                    "collective depends on the full "
                                    "backward")
            if zero3:
                # r21 acceptance: one param all-gather per bucket, plus
                # the dependency-free (all_gather, conv/dot) pair — the
                # just-in-time gather's own overlap license
                want_g = step.comm_meta["gathers"]
                if rep["gathers"] != want_g:
                    failures.append(f"{label}: {rep['gathers']} all_gathers "
                                    f"!= {want_g} expected")
                if bucketed and not rep["gather_overlap_capable"]:
                    failures.append(f"{label}: no gather witness — every "
                                    "param all-gather blocks all compute")
        artifact = {"schema_version": SCHEMA_VERSION,
                    "mode": "hlo_overlap_report", "model": args.model,
                    "sharding": args.sharding, "devices": n_dev,
                    "layouts": rows, "failures": failures}
        print(json.dumps({k: v for k, v in artifact.items()
                          if k != "schema_version"}, indent=1))
        if args.json_out:
            os.makedirs(os.path.dirname(args.json_out) or ".",
                        exist_ok=True)
            with open(args.json_out, "w") as f:
                json.dump(artifact, f, indent=1)
        if failures:
            print("HLO OVERLAP ASSERTION FAILED:", *failures,
                  sep="\n  ", file=sys.stderr)
            return 1
        return 0

    def window(state, step):
        t0 = time.monotonic()
        for _ in range(args.steps_per_window):
            state, metrics = step(state, batch, base)
        jax.block_until_ready(metrics["loss"])
        dt = time.monotonic() - t0
        return state, args.steps_per_window * args.batch / dt

    cols = {0.0: make(0.0), args.bucket_mb: make(args.bucket_mb)}
    for k in cols:
        for _ in range(max(1, args.warmup_steps)):
            st, _ = window(*cols[k])
            cols[k] = (st, cols[k][1])
    off_rates, on_rates = [], []
    for _ in range(max(1, args.repeats)):
        st, r = window(*cols[0.0])
        cols[0.0] = (st, cols[0.0][1])
        off_rates.append(r)
        st, r = window(*cols[args.bucket_mb])
        cols[args.bucket_mb] = (st, cols[args.bucket_mb][1])
        on_rates.append(r)

    on_best, off_best = max(on_rates), max(off_rates)
    overhead_pct = round((1.0 - on_best / off_best) * 100.0, 2)
    comm_on = dict(cols[args.bucket_mb][1].comm_meta)
    artifact = {
        "schema_version": SCHEMA_VERSION,
        "metric": METRIC,
        "value": round(on_best, 2),
        "unit": "images/sec",
        "model": args.model,
        "image_size": args.image_size,
        "batch": args.batch,
        "devices": n_dev,
        "layouts": [
            {"mode": "comm_overlap_bench",
             "sharding": args.sharding + "_bucketed",
             "model": args.model, "comm": comm_on,
             "images_per_sec": round(on_best, 2), **_stats(on_rates)},
            {"mode": "comm_overlap_bench", "sharding": args.sharding,
             "model": args.model,
             "comm": dict(cols[0.0][1].comm_meta),
             "images_per_sec": round(off_best, 2), **_stats(off_rates)},
        ],
        "comm_overlap": {
            "mode": "comm_bucketing_overhead",
            "bucketed_images_per_sec": round(on_best, 2),
            "monolithic_images_per_sec": round(off_best, 2),
            "overhead_pct": overhead_pct,
            "buckets": comm_on["buckets"],
            "bucket_mb": args.bucket_mb,
            "on": _stats(on_rates), "off": _stats(off_rates),
            "protocol": f"min-of-{args.repeats} ALTERNATING "
                        f"monolithic/bucketed windows x "
                        f"{args.steps_per_window} jitted steps of batch "
                        f"{args.batch} at {args.image_size}px "
                        f"({args.model}, {args.sharding}, f32, "
                        f"{n_dev}-device CPU mesh); CPU pays the "
                        f"bucketing bookkeeping WITHOUT the overlap win "
                        f"— the upper bound for the stage's relative "
                        f"cost",
        },
        "host_vcpus": os.cpu_count(),
    }
    print(json.dumps({k: v for k, v in artifact.items()
                      if k != "schema_version"}))
    if args.json_out:
        os.makedirs(os.path.dirname(args.json_out) or ".", exist_ok=True)
        with open(args.json_out, "w") as f:
            json.dump(artifact, f, indent=1)
    budget = 2.0
    if overhead_pct > budget:
        print(f"OVER BUDGET: bucketed-exchange CPU step overhead "
              f"{overhead_pct}% > {budget}% (acceptance)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
