#!/bin/sh
# Round-9 TPU measurement session — same discipline as tpu_session_r8.sh
# (scheduled EARLY, followed by a HARD TPU FREEZE; every bench.py invocation
# watchdog-protected; unprotected phases only after the flagship bench
# proves the tunnel healthy; a wedged-tunnel flagship exits 0 with the
# stale last_committed payload as its result line — which now also cites
# the cited run's autotune settled-state, r11 staleness hygiene).
#
# Differences from tpu_session_r8.sh:
#   - the r11 AUTOTUNE COLUMN PAIR: --autotune on runs the closed-loop
#     convergence protocol (crippled start: 1 decode thread, host prefetch
#     depth 1) next to the hand-pinned 'off' column through the same
#     harness — the actuation log + settled rate land in the artifact, and
#     the artifact carries the settled-state receipt the regression
#     sentinel requires before gating.
#   - a wire-escalation run (--autotune-start-wire host): the controller
#     starts on the host-normalize wire and must actuate the u8 downgrade
#     itself (the wire knob's receipt).
#   - the controller-overhead receipt (--autotune-receipt): alternating
#     no-controller/controller windows with rails pinned — the <2% budget
#     proof, same protocol as the r8 telemetry / r11 exporter receipts.
#   - the flagship E2E device row runs the vggf_imagenet_dp preset, which
#     now ships data.autotune.enabled=true: its JSONL carries the autotune
#     blocks, and the last-good registry entry records the settled state.
#   - everything r8 carried (restart columns, snapshot row, exporter
#     smoke, u8 e2e) rides along unchanged.
#
# Usage: sh benchmarks/tpu_session_r9.sh [outdir] [run_label]

set -u
OUT=${1:-/tmp/tpu_session_r9}
RUN=${2:-benchmarks/runs/tpu_r9}
mkdir -p "$OUT"
cd "$(dirname "$0")/.."

echo "== flagship device bench =="
DVGGF_BENCH_ARTIFACT="$RUN/vggf_device.json" \
python bench.py --steps 30 --warmup 5 --budget 1500 \
    | tee "$OUT/vggf_device.json"
if grep -q '"error"' "$OUT/vggf_device.json"; then
    echo "tunnel unhealthy (stale or null result) — stopping before" \
         "unprotected phases" >&2
    exit 1
fi

echo "== model zoo benches =="
DVGGF_BENCH_ARTIFACT="$RUN/vgg16_device.json" \
python bench.py --model vgg16 --batch-size 128 --steps 20 --budget 1500 \
    | tee "$OUT/vgg16_device.json"
DVGGF_BENCH_ARTIFACT="$RUN/resnet50_device.json" \
python bench.py --model resnet50 --batch-size 256 --steps 20 --budget 1500 \
    | tee "$OUT/resnet50_device.json"
DVGGF_BENCH_ARTIFACT="$RUN/vit_s16_device.json" \
python bench.py --model vit_s16 --batch-size 256 --steps 20 --budget 1500 \
    | tee "$OUT/vit_s16_device.json"

echo "== end-to-end pipeline bench: host wire vs u8 wire (min-of-6) =="
DVGGF_BENCH_ARTIFACT="$RUN/vggf_e2e.json" \
python bench.py --pipeline imagenet --repeats 6 --budget 3600 \
    | tee "$OUT/vggf_e2e.json"
DVGGF_BENCH_ARTIFACT="$RUN/vggf_e2e_wire_u8.json" \
python bench.py --pipeline imagenet --repeats 6 --budget 3600 \
    --wire u8 \
    | tee "$OUT/vggf_e2e_wire_u8.json"

echo "== host decode contract line (host-only, no TPU client) =="
python benchmarks/host_pipeline_bench.py --layout tfrecord --batches 12 \
    2>/dev/null | tee "$OUT/host_decode.json"

echo "== host decode-bench wire columns (r8 protocol, carried forward) =="
python benchmarks/host_pipeline_bench.py --decode-bench --layout tfrecord \
    --repeats 6 --wire u8 --space-to-depth \
    --json-out "$OUT/host_decode_bench_wire_u8_s2d.json" 2>/dev/null \
    | tee "$OUT/host_decode_bench_wire_u8_s2d.log"

echo "== r11 autotune convergence pair: crippled start vs hand-pinned"
echo "   (actuation log + settled-state receipt in the artifact) =="
python benchmarks/host_pipeline_bench.py --decode-bench --layout tfrecord \
    --repeats 6 --wire u8 --space-to-depth --autotune on \
    --json-out "$OUT/host_decode_bench_autotune_u8_s2d.json" 2>/dev/null \
    | tee "$OUT/host_decode_bench_autotune_u8_s2d.log"

echo "== r11 wire-escalation run: controller starts on the host wire and"
echo "   must actuate the u8 downgrade itself =="
python benchmarks/host_pipeline_bench.py --decode-bench --layout tfrecord \
    --repeats 6 --wire u8 --space-to-depth --autotune on \
    --autotune-start-wire host \
    --json-out "$OUT/host_decode_bench_autotune_wire_esc.json" 2>/dev/null \
    | tee "$OUT/host_decode_bench_autotune_wire_esc.log"

echo "== r11 controller-overhead receipt (alternating windows, rails"
echo "   pinned — the <2% budget proof) =="
python benchmarks/host_pipeline_bench.py --decode-bench --layout tfrecord \
    --repeats 6 --wire u8 --space-to-depth --autotune-receipt \
    --json-out "$OUT/host_decode_bench_autotune_overhead.json" 2>/dev/null \
    | tee "$OUT/host_decode_bench_autotune_overhead.log"

echo "== r9 restart columns (carried forward): >=448px textured,"
echo "   marker-per-MCU sources, on/off pairs in the same session =="
for HW in 448x448 768x768; do
    for RST in off on; do
        python benchmarks/host_pipeline_bench.py --decode-bench \
            --layout tfrecord --repeats 6 --wire u8 --space-to-depth \
            --source-hw "$HW" --source-kind textured \
            --restart-interval 1 --decode-restart "$RST" \
            --json-out "$OUT/host_decode_bench_rst1_${RST}_${HW}_tex.json" \
            2>/dev/null \
            | tee "$OUT/host_decode_bench_rst1_${RST}_${HW}_tex.log"
    done
done

echo "== r9 snapshot warm-vs-cold row (carried forward) =="
python benchmarks/host_pipeline_bench.py --decode-bench --layout tfrecord \
    --repeats 6 --wire u8 --space-to-depth \
    --source-hw 448x448 --source-kind textured \
    --restart-interval 1 --decode-restart on --snapshot-cache \
    --json-out "$OUT/host_decode_bench_snapshot_448tex.json" 2>/dev/null \
    | tee "$OUT/host_decode_bench_snapshot_448tex.log"

echo "== exporter smoke row (carried forward) =="
python benchmarks/host_pipeline_bench.py --decode-bench --layout tfrecord \
    --repeats 6 --wire u8 --space-to-depth --exporter-receipt \
    --json-out "$OUT/host_decode_bench_exporter_u8_s2d.json" 2>/dev/null \
    | tee "$OUT/host_decode_bench_exporter_u8_s2d.log"

echo "== regression sentinel: gate this session's flagship-basis rows"
echo "   against the pinned HOST_DECODE_RATE_R* trajectory (the autotune"
echo "   artifact is ALSO gated — its settled-state receipt must hold) =="
# no pipe to tee here: POSIX sh has no pipefail, so '|| ...' after a pipe
# would test tee's exit status and the failure branch could never fire
python benchmarks/regression_sentinel.py --check-committed \
    --check "$OUT"/host_decode_bench_wire_u8_s2d.json \
            "$OUT"/host_decode_bench_autotune_u8_s2d.json \
    > "$OUT/regression_sentinel.log" 2>&1
SENTINEL_RC=$?
cat "$OUT/regression_sentinel.log"
if [ "$SENTINEL_RC" -ne 0 ]; then
    echo "SENTINEL FAILED — do not commit these rows as a new pin" \
         "without same-session worktree controls" >&2
fi

echo "session complete: $OUT — TPU FREEZE is now in effect"
