#!/bin/sh
# Round-5 TPU measurement session — scheduled EARLY in the round and followed
# by a HARD TPU FREEZE (VERDICT r4 next-#1: the judged driver bench has been
# starved three rounds by late-session TPU work; nothing TPU-touching may
# start after this script completes).
#
# Differences from tpu_session.sh (the r4 protocol):
#   - e2e runs min-of-6 windows (VERDICT r4 next-#2: N>=6 or prove the
#     variance floor), budget raised accordingly.
#   - long-context flash rows at T=6144 and 16384 incl. causal dma-skip
#     (VERDICT r4 next-#6), flash impls ONLY: the xla_einsum side is past its
#     measured compile wall (T=6144 hung ~2.5 h in compile in r4 and killing
#     the grant-holder wedged the tunnel; T=8192 is a reproduced service-side
#     compile failure). The einsum 6144/16384 rows are recorded as documented
#     skips, not attempted.
#   - the r4 one-off sweeps (ResNet batch/stem, ViT flash b512) are NOT
#     repeated — their questions are answered and every extra minute of
#     session is wedge exposure.
#
# Safe to run blind: every bench.py invocation is watchdog-protected (budget
# expiry -> machine-readable failure JSON, waiting child left alive). The
# unprotected microbench runs only after the flagship bench proves the
# tunnel healthy.
#
# Usage: sh benchmarks/tpu_session_r5.sh [outdir] [run_label]

set -u
OUT=${1:-/tmp/tpu_session_r5}
RUN=${2:-benchmarks/runs/tpu_r5}
mkdir -p "$OUT"
cd "$(dirname "$0")/.."

echo "== flagship device bench =="
DVGGF_BENCH_ARTIFACT="$RUN/vggf_device.json" \
python bench.py --steps 30 --warmup 5 --budget 1500 \
    | tee "$OUT/vggf_device.json"
if grep -q '"error"' "$OUT/vggf_device.json"; then
    echo "tunnel unhealthy — stopping before unprotected phases" >&2
    exit 1
fi

echo "== model zoo benches =="
DVGGF_BENCH_ARTIFACT="$RUN/vgg16_device.json" \
python bench.py --model vgg16 --batch-size 128 --steps 20 --budget 1500 \
    | tee "$OUT/vgg16_device.json"
DVGGF_BENCH_ARTIFACT="$RUN/resnet50_device.json" \
python bench.py --model resnet50 --batch-size 256 --steps 20 --budget 1500 \
    | tee "$OUT/resnet50_device.json"
DVGGF_BENCH_ARTIFACT="$RUN/vit_s16_device.json" \
python bench.py --model vit_s16 --batch-size 256 --steps 20 --budget 1500 \
    | tee "$OUT/vit_s16_device.json"

echo "== end-to-end pipeline bench (min-of-6 windows — VERDICT r4 #2) =="
DVGGF_BENCH_ARTIFACT="$RUN/vggf_e2e.json" \
python bench.py --pipeline imagenet --repeats 6 --budget 3600 \
    | tee "$OUT/vggf_e2e.json"

echo "== long-context flash rows (flash impls only; see header) =="
python benchmarks/flash_attention_bench.py --seqs 6144,16384 \
    --impls flash_pallas --iters 6 --warmup 2 \
    | tee "$OUT/flash_longctx.json"
python benchmarks/flash_attention_bench.py --seqs 6144,16384 \
    --impls flash_pallas,flash_pallas_dma_skip --causal --iters 6 --warmup 2 \
    | tee "$OUT/flash_longctx_causal.json"

echo "== host decode contract line (host-only, no TPU client) =="
python benchmarks/host_pipeline_bench.py --layout tfrecord --batches 12 \
    2>/dev/null | tee "$OUT/host_decode.json"

echo "== host decode-bench artifact (r6 protocol: min-of-N per-core rate,"
echo "   simd dispatch receipt, libjpeg/resample profile split) =="
# flagship ingest config (bf16 + space-to-depth) — the provisioning basis
# (utils/scaling_model.py HOST_DECODE_RATE_R6); plus the f32 contract-
# continuity row. Lower committed value re-derives the constant.
python benchmarks/host_pipeline_bench.py --decode-bench --layout tfrecord \
    --repeats 6 --image-dtype bfloat16 --space-to-depth \
    --json-out "$OUT/host_decode_bench_bf16s2d.json" 2>/dev/null \
    | tee "$OUT/host_decode_bench_bf16s2d.log"
python benchmarks/host_pipeline_bench.py --decode-bench --layout tfrecord \
    --repeats 6 \
    --json-out "$OUT/host_decode_bench_f32.json" 2>/dev/null \
    | tee "$OUT/host_decode_bench_f32.log"

echo "session complete: $OUT — TPU FREEZE is now in effect"
