"""Generate a small CLASS-SEPARABLE fake ImageNet (TFRecord layout) for
end-to-end learning demonstrations through the real ImageNet input path
(native shard index → ranged libjpeg decode → packed space-to-depth →
train → exact eval → checkpoint).

Each class is a distinct base color plus per-pixel noise. The default is
trivially learnable; `--color-strength/--noise` harden it (the committed
`benchmarks/runs/imagenet_path_smoke` artifact used --color-strength 0.35
--noise 70 so the accuracy curve is visible instead of saturating before
the first eval). Classic layout: `train-*-of-*` / `validation-*-of-*`,
1-based int64 labels.

Usage: python benchmarks/separable_imagenet.py <out_dir>
           [--classes 10] [--per-class 160]
           [--color-strength 1.0] [--noise 40]
"""

from __future__ import annotations

import argparse
import os

import numpy as np


def class_color(c: int, classes: int) -> np.ndarray:
    """A well-separated RGB base color per class (coarse HSV ring)."""
    h = c / classes * 6.0
    i = int(h) % 6
    f = h - int(h)
    v, p, q, t = 220.0, 30.0, 220.0 - 190.0 * f, 30.0 + 190.0 * f
    rgb = [(v, t, p), (q, v, p), (p, v, t), (p, q, v), (t, p, v),
           (v, p, q)][i]
    return np.asarray(rgb, np.float32)


def write_dataset(out_dir: str, *, classes: int = 10, per_class: int = 160,
                  val_per_class: int = 16, hw=(160, 128), seed: int = 0,
                  train_shards: int = 4, color_strength: float = 1.0,
                  noise: float = 40.0) -> None:
    """`color_strength` < 1 attenuates the class color toward mid-gray and
    `noise` is the per-pixel Gaussian sigma — together they set difficulty."""
    import tensorflow as tf
    rng = np.random.default_rng(seed)
    os.makedirs(out_dir, exist_ok=True)
    h, w = hw

    def example(c: int) -> bytes:
        base = class_color(c, classes)
        img = (color_strength * base + (1.0 - color_strength) * 140.0
               + rng.normal(0.0, noise, size=(h, w, 3)))
        img = np.clip(img, 0, 255).astype(np.uint8)
        jpeg = tf.io.encode_jpeg(img, quality=85).numpy()
        ex = tf.train.Example(features=tf.train.Features(feature={
            "image/encoded": tf.train.Feature(
                bytes_list=tf.train.BytesList(value=[jpeg])),
            "image/class/label": tf.train.Feature(
                int64_list=tf.train.Int64List(value=[c + 1])),  # 1-based
        }))
        return ex.SerializeToString()

    train = [c for c in range(classes) for _ in range(per_class)]
    rng.shuffle(train)
    per_shard = (len(train) + train_shards - 1) // train_shards
    for s in range(train_shards):
        path = os.path.join(out_dir, f"train-{s:05d}-of-{train_shards:05d}")
        with tf.io.TFRecordWriter(path) as wtr:
            for c in train[s * per_shard:(s + 1) * per_shard]:
                wtr.write(example(c))
    with tf.io.TFRecordWriter(
            os.path.join(out_dir, "validation-00000-of-00001")) as wtr:
        for c in range(classes):
            for _ in range(val_per_class):
                wtr.write(example(c))
    print(f"wrote {len(train)} train / {classes * val_per_class} val "
          f"examples to {out_dir}")


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("out_dir")
    parser.add_argument("--classes", type=int, default=10)
    parser.add_argument("--per-class", type=int, default=160)
    parser.add_argument("--color-strength", type=float, default=1.0)
    parser.add_argument("--noise", type=float, default=40.0)
    args = parser.parse_args()
    write_dataset(args.out_dir, classes=args.classes,
                  per_class=args.per_class,
                  color_strength=args.color_strength, noise=args.noise)
