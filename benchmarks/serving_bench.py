#!/usr/bin/env python
"""Open-loop serving load generator (r17 acceptance receipt).

Drives the always-on predict server (serving/) with OPEN-LOOP traffic —
Poisson arrivals at a configurable RPS ramp, arrivals independent of
completions (the load a population of users actually offers; a closed loop
would politely slow down exactly when the server struggles, hiding the
overload behavior this receipt exists to pin). Per ramp stage the artifact
records offered vs admitted RPS, shed rate, and the latency quantiles of
ADMITTED requests; the overload segment is the acceptance claim:

    bounded queue + shed-not-collapse — as offered load passes capacity,
    the shed rate RISES while the p99 of admitted requests stays within
    the SLO budget (the budget is what the bounded queue buys: worst
    admitted wait <= queue_limit/capacity + window + batch time).

The engine serves a freshly-initialized vggf head (serving throughput is
weight-agnostic — the machinery under test is admission + batching + HTTP,
and the checkpoint restore path is pinned separately in tests); payloads
are raw u8 pixels, the serving wire contract. The admission controller is
OFF by default (hand-pinned window — the committed-receipt discipline, the
same reason decode rows refuse to gate mid-autotune); `--controller` turns
it on for exploration runs that are not meant to gate.

Contract value (`serving_admitted_rps`): peak admitted RPS among stages
whose admitted p99 stayed within the SLO — throughput actually served
within latency, not offered load. The row carries the r17 sentinel basis
(`serving_mode: openloop_b<max_batch>`), gated by SERVING_PINS.

Usage:
  python benchmarks/serving_bench.py \
      --json-out benchmarks/runs/host_r16/serving_openloop_run1.json
"""

from __future__ import annotations

import argparse
import concurrent.futures
import http.client
import json
import os
import sys
import threading
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from distributed_vgg_f_tpu.telemetry import schema  # noqa: E402
from distributed_vgg_f_tpu.telemetry.regress import SERVING_METRIC  # noqa: E402


def build_engine(model_name: str, image_size: int, num_classes: int,
                 buckets, max_batch: int, weights: str = ""):
    import jax

    from distributed_vgg_f_tpu.config import ModelConfig
    from distributed_vgg_f_tpu.data.device_ingest import make_device_finish
    from distributed_vgg_f_tpu.models.ingest import ingest_descriptor
    from distributed_vgg_f_tpu.models.registry import build_model
    from distributed_vgg_f_tpu.serving.engine import PredictEngine
    model = build_model(ModelConfig(name=model_name,
                                    num_classes=num_classes,
                                    compute_dtype="float32"))
    if weights:
        # trained weights (train/distill.py npz) — REQUIRED for tier
        # receipts: the accuracy deltas and the int8 elision structure
        # are properties of trained networks, not of fresh init
        from distributed_vgg_f_tpu.train.distill import load_params
        params, batch_stats = load_params(weights), {}
    else:
        desc = ingest_descriptor(model_name)
        finish = make_device_finish(desc.mean_rgb, desc.stddev_rgb)
        x0 = jax.numpy.zeros((1, image_size, image_size, 3),
                             jax.numpy.uint8)
        variables = model.init(jax.random.PRNGKey(0), finish(x0),
                               train=False)
        params = variables["params"]
        batch_stats = variables.get("batch_stats", {})
    return PredictEngine(
        model_name=model_name, model=model, params=params,
        batch_stats=batch_stats,
        image_size=image_size, num_classes=num_classes,
        buckets=buckets, max_batch=max_batch)


def build_tier_engine(base, tier: str, tiers_cfg, student_weights: str):
    """Derive the benched tier's engine from the fp32 base (the same
    builders the server's tier ladder uses — the bench measures the
    serving artifact, not a bench-local reimplementation)."""
    from distributed_vgg_f_tpu.serving import tiers as tiers_mod
    if tier == "fp32":
        return base
    if tier == "bf16":
        return tiers_mod.build_bf16_engine(base)
    if tier == "int8":
        return tiers_mod.build_int8_engine(base, tiers_cfg=tiers_cfg)
    if tier == "student":
        if not student_weights:
            raise SystemExit("--tier student needs --student-weights "
                             "(train/distill.py output)")
        from distributed_vgg_f_tpu.config import ModelConfig
        from distributed_vgg_f_tpu.models.registry import build_model
        from distributed_vgg_f_tpu.train.distill import load_params
        smodel = build_model(ModelConfig(
            name="vggf_student", num_classes=base.num_classes,
            compute_dtype="float32"))
        return tiers_mod.build_student_engine(
            base, student_model=smodel,
            student_params=load_params(student_weights))
    raise SystemExit(f"unknown --tier {tier!r}")


def offline_top1(engine, images, labels) -> float:
    """Top-1 vs teacher labels through engine.run — the OFFLINE half of
    the per-tier parity pair, so the accuracy receipt measures exactly
    the executables the server routes to."""
    step = engine.buckets[-1]
    hits = 0
    for i in range(0, len(images), step):
        probs, _ = engine.run(images[i:i + step])
        hits += int(np.sum(np.argmax(probs, axis=1)
                           == labels[i:i + step]))
    return hits / len(images)


def accuracy_block(base, engine, tier: str, tiers_cfg, *,
                   eval_examples: int) -> dict:
    """The per-tier accuracy-delta receipt: top-1 on the fixed teacher
    eval shard (train/distill.teacher_eval_shard — disjoint from train
    and calibration indices), delta vs the fp32 base, bound from
    serving.tiers config. Schema-validated; delta > bound fails the
    run."""
    from distributed_vgg_f_tpu.train.distill import teacher_eval_shard
    images, labels = teacher_eval_shard(
        base.image_size, base.num_classes, eval_examples)
    fp32_top1 = offline_top1(base, images, labels)
    top1 = fp32_top1 if tier == "fp32" \
        else offline_top1(engine, images, labels)
    bound = {"fp32": 0.0,
             "bf16": tiers_cfg.max_top1_delta_bf16,
             "int8": tiers_cfg.max_top1_delta_int8,
             "student": tiers_cfg.max_top1_delta_student}[tier]
    return {"top1": round(top1, 4),
            "fp32_top1": round(fp32_top1, 4),
            "delta": round(fp32_top1 - top1, 4),
            "bound": bound,
            "eval_examples": int(len(images))}


def probe_capacity(engine, batches: int = 12) -> float:
    """Engine-only throughput at the top bucket (img/s == requests/s) —
    the load the open-loop ramp is scaled against."""
    top = engine.buckets[-1]
    rng = np.random.default_rng(0)
    batch = rng.integers(0, 256, (top, engine.image_size,
                                  engine.image_size, 3)).astype(np.uint8)
    engine.run(batch)  # compile outside the timed region
    t0 = time.monotonic()
    for _ in range(batches):
        engine.run(batch)
    return batches * top / (time.monotonic() - t0)


def run_stage(port: str | int, model: str, payload: bytes, *,
              offered_rps: float, duration_s: float, seed: int,
              client_threads: int) -> dict:
    """One open-loop ramp stage: Poisson arrivals at `offered_rps` for
    `duration_s`. Workers hold PERSISTENT keep-alive connections (an LB's
    connection pool, and without per-request TCP churn the client stays
    out of the measurement); latency is measured from the SCHEDULED
    arrival instant, so any client-side queueing counts against the
    number instead of hiding in it. Returns the stage row."""
    rng = np.random.default_rng(seed)
    results = []
    results_lock = threading.Lock()
    t_start = time.monotonic()

    def post(t_sched: float, conn_box: list):
        # HTTPException alongside OSError: a truncated/torn response
        # raises BadStatusLine (NOT an OSError), and an uncaught one
        # would both vanish from the accounting and leave the poisoned
        # keep-alive connection in conn_box, cascading CannotSendRequest
        # onto every later request of this worker thread
        for attempt in (0, 1):
            if not conn_box:
                conn_box.append(http.client.HTTPConnection(
                    "127.0.0.1", int(port), timeout=60))
            conn = conn_box[0]
            try:
                conn.request("POST", f"/v1/predict/{model}", body=payload)
                resp = conn.getresponse()
                resp.read()
                status = resp.status
                break
            except (OSError, http.client.HTTPException):
                # stale keep-alive — rebuild once, then report the failure
                try:
                    conn.close()
                except OSError:
                    pass
                conn_box.clear()
                status = -1
        with results_lock:
            results.append((status,
                            (time.monotonic() - t_start - t_sched) * 1e3,
                            t_sched))

    # one persistent connection per worker thread
    local = threading.local()

    def task(t_sched: float):
        if not hasattr(local, "box"):
            local.box = []
        post(t_sched, local.box)

    pool = concurrent.futures.ThreadPoolExecutor(max_workers=client_threads)
    t_next = t_start
    n_offered = 0
    while True:
        t_next += float(rng.exponential(1.0 / offered_rps))
        if t_next - t_start > duration_s:
            break
        delay = t_next - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        pool.submit(task, t_next - t_start)
        n_offered += 1
    pool.shutdown(wait=True)
    wall = time.monotonic() - t_start
    admitted = [(lat, t) for status, lat, t in results if status == 200]
    shed = sum(1 for status, _, _ in results if status == 503)
    errors = sum(1 for status, _, _ in results
                 if status not in (200, 503))
    lat = np.asarray([x[0] for x in admitted], np.float64)
    row = {
        "offered_rps": round(n_offered / wall, 2),
        "target_rps": round(offered_rps, 2),
        "duration_s": round(wall, 2),
        "requests": n_offered,
        "admitted": len(admitted),
        "admitted_rps": round(len(admitted) / wall, 2),
        "shed": shed,
        "shed_rate": round(shed / max(1, n_offered), 4),
        "errors": errors,
    }
    if len(lat):
        row.update({"p50_ms": round(float(np.percentile(lat, 50)), 2),
                    "p95_ms": round(float(np.percentile(lat, 95)), 2),
                    "p99_ms": round(float(np.percentile(lat, 99)), 2)})
    # three equal sub-windows of admitted completions -> the spread the
    # sentinel derives its tolerance band from (the decode rows' window
    # discipline, adapted to one timed stage)
    if admitted:
        thirds = [0, 0, 0]
        for _, t in admitted:
            thirds[min(2, int(3 * t / duration_s))] += 1
        rates = [3 * c / duration_s for c in thirds]
        med = float(np.median(rates))
        if med > 0:
            row["spread"] = round((max(rates) - min(rates)) / med, 4)
    return row


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--model", default="vggf")
    ap.add_argument("--tier", default="fp32",
                    choices=["fp32", "bf16", "int8", "student"],
                    help="which rung of the serving ladder to drive; "
                         "non-fp32 engines are derived through the SAME "
                         "builders the server uses (serving/tiers.py)")
    ap.add_argument("--weights", default="",
                    help="trained fp32 weights npz (train/distill.py); "
                         "REQUIRED for gating tier receipts — enables the "
                         "accuracy-delta block, and int8's calibrated "
                         "elision is a trained-network property")
    ap.add_argument("--student-weights", default="",
                    help="distilled vggf_student weights npz "
                         "(--tier student only)")
    ap.add_argument("--eval-examples", type=int, default=512,
                    help="teacher eval shard size for the accuracy block")
    # 128: pins engine capacity ~200-300 rps on this host class, so the
    # whole ramp (overload included) stays well under the stdlib front
    # end's ~1k req/s handling ceiling — the overload segment must
    # saturate the ENGINE, not Python's request parsing
    ap.add_argument("--image-size", type=int, default=128)
    ap.add_argument("--num-classes", type=int, default=100)
    ap.add_argument("--max-batch", type=int, default=8)
    ap.add_argument("--window-ms", type=float, default=20.0)
    # 32: the bounded-latency sweet spot on this front end — the SLO
    # budget is queue_limit/drain-rate-bound, and the effective drain under
    # HTTP load sits below the synchronous engine probe, so a deeper queue
    # spends its depth on latency the budget has to absorb
    ap.add_argument("--queue-limit", type=int, default=32)
    ap.add_argument("--stage-seconds", type=float, default=6.0)
    ap.add_argument("--rps-factors", default="0.4,0.8,1.2,1.8",
                    help="offered-load ramp as multiples of the probed "
                         "engine capacity; >1 stages are the overload "
                         "segment. Keep absolute rates under the stdlib "
                         "front end's ~1k req/s handling ceiling: past it "
                         "the measurement saturates PYTHON, not the "
                         "admission machinery under test")
    ap.add_argument("--client-threads", type=int, default=128)
    ap.add_argument("--slo-ms", type=float, default=0.0,
                    help="admitted-p99 budget; 0 = derive from the bounded "
                         "queue: 1.5 * (queue_limit/capacity + window + "
                         "2*top-bucket time)")
    ap.add_argument("--controller", action="store_true",
                    help="enable the admission controller (exploration "
                         "only — a gating receipt keeps the window "
                         "hand-pinned)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--json-out", default="")
    args = ap.parse_args(argv)

    from distributed_vgg_f_tpu.config import (ServingConfig,
                                              ServingTiersConfig)
    from distributed_vgg_f_tpu.serving.server import PredictServer

    buckets = tuple(sorted({1 << i for i in
                            range(args.max_batch.bit_length())}
                           | {args.max_batch}))
    buckets = tuple(b for b in buckets if b <= args.max_batch)
    tiers_cfg = ServingTiersConfig(enabled=(args.tier != "fp32"))
    base = build_engine(args.model, args.image_size, args.num_classes,
                        buckets, args.max_batch, weights=args.weights)
    engine = build_tier_engine(base, args.tier, tiers_cfg,
                               args.student_weights)
    accuracy = None
    if args.weights:
        accuracy = accuracy_block(base, engine, args.tier, tiers_cfg,
                                  eval_examples=args.eval_examples)
        print(f"accuracy[{args.tier}]: top1 {accuracy['top1']} "
              f"(fp32 {accuracy['fp32_top1']}, delta "
              f"{accuracy['delta']}, bound {accuracy['bound']})",
              flush=True)
    print(f"probing engine capacity (top bucket {buckets[-1]}) ...",
          flush=True)
    # The ramp and the SLO budget derive from the BASE (fp32) engine's
    # capacity for EVERY tier: the frontier comparison is "the same
    # offered traffic under the same latency budget — how much does each
    # rung serve within it". Deriving per-rung would hand a fast rung a
    # proportionally tighter SLO and push its offered rates past the
    # stdlib front end's ~1k req/s ceiling — benching Python, not the
    # ladder. The rung's own engine-only capacity still ships in the row
    # (tier_capacity_images_per_sec) as the raw-speed receipt.
    capacity = probe_capacity(base)
    tier_capacity = capacity if engine is base else probe_capacity(engine)
    top_bucket_s = buckets[-1] / capacity
    slo_ms = args.slo_ms or 1.5e3 * (args.queue_limit / capacity
                                     + args.window_ms / 1e3
                                     + 2 * top_bucket_s)
    print(f"capacity ~{capacity:.1f} img/s; SLO budget {slo_ms:.0f} ms",
          flush=True)

    cfg = ServingConfig(
        enabled=True, max_batch=args.max_batch, buckets=buckets,
        max_latency_ms=args.window_ms, queue_limit=args.queue_limit,
        controller=bool(args.controller),
        window_max_ms=max(100.0, args.window_ms),
        controller_interval_s=1.0, warmup=True,
        # the benched tier answers the plain route: same open-loop
        # protocol for every rung, only the engine differs
        tier_default=args.tier, tiers=tiers_cfg)
    server = PredictServer(cfg)
    server.add_engine(engine)
    port = server.start()
    payload = np.random.default_rng(1).integers(
        0, 256, (args.image_size, args.image_size, 3)) \
        .astype(np.uint8).tobytes()

    factors = [float(x) for x in args.rps_factors.split(",") if x.strip()]
    stages = []
    try:
        for i, factor in enumerate(factors):
            rps = factor * capacity
            print(f"stage {i}: offered {rps:.1f} rps "
                  f"({factor:.2f}x capacity) for {args.stage_seconds}s ...",
                  flush=True)
            row = run_stage(port, args.model, payload,
                            offered_rps=rps,
                            duration_s=args.stage_seconds,
                            seed=args.seed * 1000 + i,
                            client_threads=args.client_threads)
            row["capacity_factor"] = factor
            row["within_slo"] = bool(row.get("p99_ms", float("inf"))
                                     <= slo_ms)
            stages.append(row)
            print(f"  admitted {row['admitted_rps']} rps, shed_rate "
                  f"{row['shed_rate']}, p99 {row.get('p99_ms')} ms",
                  flush=True)
        model_row = server.servingz_payload()["models"][args.model]
        if "admission" not in model_row:  # non-fp32-only ladder
            model_row = model_row["tiers"][args.tier]
        admission = model_row["admission"]
    finally:
        server.close()

    in_slo = [s["admitted_rps"] for s in stages
              if s["within_slo"] and s["admitted"] > 0]
    value = max(in_slo) if in_slo else None
    overload = [s for s in stages if s["capacity_factor"] > 1.0]
    max_shed = max((s["shed_rate"] for s in overload), default=0.0)
    shed_ok = bool(overload and max_shed > 0.05
                   and all(s["within_slo"] for s in overload
                           if s["admitted"] > 0))
    # A rung faster than the ramp's top never reaches ITS overload under
    # the common fp32-capacity traffic: it ABSORBS the flagship's
    # overload segment whole. Essentially-shed-free + every overload
    # stage in-SLO + admitted tracking offered is that claim, receipted
    # — not a missing demonstration.
    absorbed = bool(overload and max_shed <= 0.05
                    and all(s["within_slo"] for s in overload)
                    and all(s["admitted_rps"] >= 0.9 * s["offered_rps"]
                            for s in overload))
    ok_overload = shed_ok or absorbed
    contract = max((s for s in stages if s["within_slo"]
                    and s["admitted"] > 0),
                   key=lambda s: s["admitted_rps"], default=None)
    row = {
        "layout": "openloop", "mode": "serving_bench",
        "serving_mode": f"openloop_b{args.max_batch}",
        "model": args.model, "tier": args.tier,
        "served_by": getattr(engine, "served_by", args.model),
        "wire": "u8", "space_to_depth": False,
        "image_dtype": "float32",
        "wire_bytes_per_image": args.image_size * args.image_size * 3,
        "source": {"source_kind": "u8_payload",
                   "source_hw": [args.image_size, args.image_size]},
        "admitted_rps": value,
        "spread": (contract or {}).get("spread"),
        "queue_peak": int(admission["queue_peak"]),
        "capacity_images_per_sec": round(capacity, 2),
        "tier_capacity_images_per_sec": round(tier_capacity, 2),
        "slo_ms": round(slo_ms, 1),
        "serving": {"buckets": list(buckets),
                    "max_batch": args.max_batch,
                    "window_ms": args.window_ms,
                    "queue_limit": args.queue_limit,
                    "controller": bool(args.controller),
                    "tier": args.tier},
        "stages": stages,
        "bucket_occupancy": admission["bucket_occupancy"],
        "overload": {
            "stages": [s["capacity_factor"] for s in overload],
            "max_shed_rate": max_shed,
            "admitted_p99_within_slo": ok_overload,
            "absorbed": absorbed,
            "queue_peak": int(admission["queue_peak"]),
            "queue_limit": args.queue_limit,
        },
    }
    if accuracy is not None:
        row["accuracy"] = accuracy
    calib = getattr(engine, "calibration", None)
    if calib is not None:
        # the committed activation-range receipt: scales + kept-channel
        # counts — a re-run reproduces the exact quantization from this
        row["calibration"] = calib.receipt()
    artifact = {
        "schema_version": schema.SCHEMA_VERSION,
        "metric": SERVING_METRIC,
        "value": value,
        "unit": "admitted requests/sec within SLO",
        "protocol": (f"open-loop Poisson ramp {args.rps_factors} x probed "
                     f"fp32-base capacity (common offered load + SLO "
                     f"budget across tiers), "
                     f"{args.stage_seconds}s/stage, u8 payloads "
                     f"{args.image_size}px, window {args.window_ms}ms, "
                     f"queue_limit {args.queue_limit}, buckets "
                     f"{list(buckets)}, controller "
                     f"{'on' if args.controller else 'off'}, "
                     f"tier {args.tier}"
                     + (", trained weights" if args.weights else "")),
        "host_vcpus": os.cpu_count(),
        "layouts": [row],
    }
    if value is None:
        artifact["error"] = "no_stage_within_slo"
    errors = schema.validate_bench_artifact(artifact)
    if errors:
        print("SCHEMA ERRORS:", errors, file=sys.stderr)
        return 1
    out = json.dumps(artifact, indent=1)
    print(out)
    if args.json_out:
        os.makedirs(os.path.dirname(args.json_out) or ".", exist_ok=True)
        with open(args.json_out, "w") as f:
            f.write(out + "\n")
    if not ok_overload:
        print("OVERLOAD SEGMENT INCOMPLETE: shed-not-collapse not "
              "demonstrated (need a >1x stage with shed_rate > 0.05 and "
              "admitted p99 within SLO, or the rung to absorb the whole "
              "ramp shed-free within SLO)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
