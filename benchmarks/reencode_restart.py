"""Offline restart-marker injection for JPEG datasets (r9).

The restart-marker-parallel entropy decoder (native/jpeg_loader.cc, ABI v7)
engages only on streams that carry RSTn markers with a row-compatible DRI
interval — which stock ImageNet JPEGs (and most camera output) do not.
This tool walks a dataset once and LOSSLESSLY transcodes every JPEG in the
coefficient domain (jpeg_read/write_coefficients — the jpegtran move:
quantized DCT coefficients copied bit-exact, decoded pixels identical, and
progressive sources normalized to baseline sequential), injecting a
restart marker every `--interval` MCUs. Size cost is typically 1-3 %
(marker bytes + per-segment Huffman-state flushes); decode benefit is the
r10 restart column: the decoder entropy-parses only the segments covering
each crop band instead of every row above it.

Layouts:
  imagefolder — every *.JPEG/*.jpg under --src is transcoded into the
      mirrored tree under --dst (or in place with --in-place).
  tfrecord    — every train-*-of-* shard under --src is rewritten under
      --dst with the image/encoded features transcoded and every other
      feature carried through untouched.

Usage:
  python benchmarks/reencode_restart.py --src /data/imagenet --dst /data/imagenet_rst
  python benchmarks/reencode_restart.py --src shards/ --dst shards_rst/ --layout tfrecord
  python benchmarks/reencode_restart.py --src /data/imagenet --in-place --interval 0

--interval 0 (default) = one marker per MCU row, the row-trimmable layout;
a positive value that divides the MCU row additionally enables column
trimming (e.g. --interval 7 on 448px 4:2:0 sources = 4 segments/row).
Files that fail to decode are copied through unchanged and counted.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

JPEG_EXTS = (".jpeg", ".jpg")


def _transcode(data: bytes, interval: int, stats: dict) -> bytes:
    from distributed_vgg_f_tpu.data.native_jpeg import reencode_restart
    out = reencode_restart(data, interval)
    if out is None:
        stats["failed"] += 1
        return data
    stats["images"] += 1
    stats["bytes_in"] += len(data)
    stats["bytes_out"] += len(out)
    return out


def run_imagefolder(src: str, dst: str, interval: int, stats: dict) -> None:
    for root, _dirs, names in os.walk(src):
        rel = os.path.relpath(root, src)
        out_dir = os.path.join(dst, rel) if rel != "." else dst
        os.makedirs(out_dir, exist_ok=True)
        for name in sorted(names):
            sp = os.path.join(root, name)
            dp = os.path.join(out_dir, name)
            if name.lower().endswith(JPEG_EXTS):
                with open(sp, "rb") as f:
                    data = _transcode(f.read(), interval, stats)
                tmp = f"{dp}.tmp.{os.getpid()}"
                with open(tmp, "wb") as f:
                    f.write(data)
                os.replace(tmp, dp)  # atomic: safe for --in-place
            elif os.path.abspath(sp) != os.path.abspath(dp):
                shutil.copy2(sp, dp)


def run_tfrecord(src: str, dst: str, interval: int, stats: dict) -> None:
    import tensorflow as tf
    os.makedirs(dst, exist_ok=True)
    shards = sorted(n for n in os.listdir(src)
                    if "-of-" in n and not n.startswith("."))
    if not shards:
        raise SystemExit(f"no TFRecord shards (train-*-of-*) under {src!r}")
    for name in shards:
        out_path = os.path.join(dst, name)
        tmp = f"{out_path}.tmp.{os.getpid()}"
        with tf.io.TFRecordWriter(tmp) as writer:
            for rec in tf.data.TFRecordDataset(os.path.join(src, name)):
                ex = tf.train.Example()
                ex.ParseFromString(rec.numpy())
                feat = ex.features.feature
                if "image/encoded" in feat \
                        and feat["image/encoded"].bytes_list.value:
                    enc = feat["image/encoded"].bytes_list.value
                    enc[0] = _transcode(bytes(enc[0]), interval, stats)
                writer.write(ex.SerializeToString())
        os.replace(tmp, out_path)
        stats["shards"] = stats.get("shards", 0) + 1


def main() -> None:
    parser = argparse.ArgumentParser(
        description="Losslessly inject JPEG restart markers into a dataset "
                    "(coefficient-domain transcode; pixels unchanged)")
    parser.add_argument("--src", required=True, help="dataset root")
    parser.add_argument("--dst", default=None,
                        help="output root (mirrored tree); required unless "
                             "--in-place")
    parser.add_argument("--in-place", action="store_true",
                        help="rewrite files where they are (atomic per-file "
                             "replace; imagefolder layout only)")
    parser.add_argument("--layout", choices=("imagefolder", "tfrecord"),
                        default="imagefolder")
    parser.add_argument("--interval", type=int, default=0, metavar="MCUS",
                        help="restart interval in MCUs; 0 = one marker per "
                             "MCU row (default — the row-trimmable layout)")
    args = parser.parse_args()
    if args.interval < 0:
        raise SystemExit("--interval must be >= 0")
    if args.in_place:
        if args.layout != "imagefolder":
            raise SystemExit("--in-place supports the imagefolder layout "
                             "only (shards are rewritten whole)")
        args.dst = args.src
    if not args.dst:
        raise SystemExit("--dst is required (or pass --in-place)")

    stats = {"images": 0, "failed": 0, "bytes_in": 0, "bytes_out": 0}
    if args.layout == "imagefolder":
        run_imagefolder(args.src, args.dst, args.interval, stats)
    else:
        run_tfrecord(args.src, args.dst, args.interval, stats)
    if stats["bytes_in"]:
        stats["size_ratio"] = round(stats["bytes_out"] / stats["bytes_in"],
                                    4)
    stats["interval"] = args.interval
    print(json.dumps(stats))
    if stats["images"] == 0:
        raise SystemExit("no JPEGs transcoded — wrong --src or --layout?")


if __name__ == "__main__":
    main()
