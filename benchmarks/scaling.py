"""Scaling-efficiency benchmark (BASELINE.json north_star: >=90% linear
images/sec/chip from v4-8 to v4-128; SURVEY.md §5 distributed backend).

Weak scaling: fixed per-chip batch, mesh sizes 1..N over the visible devices.
Reports images/sec/chip at each size and efficiency relative to the smallest
mesh, tagged with the ICI vs ICI+DCN regime from the mesh topology report.

On this machine only one real TPU chip is visible, so multi-chip points run on
virtual CPU devices (`--fake-devices N`) — that validates the harness and the
collective layout, not silicon performance; on a real slice the same command
measures the judged metric.

Usage:
    python benchmarks/scaling.py                      # real devices
    python benchmarks/scaling.py --fake-devices 8     # 8 virtual CPU devices
"""

from __future__ import annotations

import argparse
import io
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def parse_args():
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="vggf")
    p.add_argument("--per-chip-batch", type=int, default=64)
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--warmup", type=int, default=3)
    p.add_argument("--fake-devices", type=int, default=0,
                   help="force N virtual CPU devices (multi-chip dry run)")
    p.add_argument("--sizes", type=int, nargs="*", default=None,
                   help="mesh sizes to measure (default: powers of 2 up to N)")
    return p.parse_args()


def main() -> None:
    args = parse_args()
    if args.fake_devices:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count"
                        f"={args.fake_devices}").strip()

    import jax

    if args.fake_devices:
        jax.config.update("jax_platforms", "cpu")

    from distributed_vgg_f_tpu.config import (
        DataConfig, ExperimentConfig, MeshConfig, ModelConfig, OptimConfig,
        TrainConfig)
    from distributed_vgg_f_tpu.data.synthetic import SyntheticDataset
    from distributed_vgg_f_tpu.parallel.mesh import (
        MeshSpec, build_mesh, mesh_topology_report)
    from distributed_vgg_f_tpu.train.trainer import Trainer
    from distributed_vgg_f_tpu.utils.logging import MetricLogger

    devices = jax.devices()
    n = len(devices)
    sizes = args.sizes or [s for s in (1, 2, 4, 8, 16, 32, 64, 128) if s <= n]

    results = []
    for k in sizes:
        mesh = build_mesh(MeshSpec(("data",), (k,)), devices=devices[:k])
        batch = args.per_chip_batch * k
        cfg = ExperimentConfig(
            name=f"scaling_{args.model}_{k}",
            model=ModelConfig(name=args.model, num_classes=1000,
                              compute_dtype="bfloat16" if not args.fake_devices
                              else "float32"),
            optim=OptimConfig(base_lr=0.01, reference_batch_size=batch),
            data=DataConfig(name="synthetic", image_size=args.image_size,
                            global_batch_size=batch),
            mesh=MeshConfig(num_data=k),
            train=TrainConfig(steps=args.steps, seed=0),
        )
        trainer = Trainer(cfg, mesh=mesh, logger=MetricLogger(stream=io.StringIO()))
        state = trainer.init_state()
        rng = trainer.base_rng()
        # match bench.py's judged-metric methodology: bf16 batches for the bf16
        # model (real hardware); f32 on the fake-device CPU dry run.
        ds = SyntheticDataset(batch_size=batch, image_size=args.image_size,
                              num_classes=1000, seed=0, fixed=True,
                              image_dtype="float32" if args.fake_devices
                              else "bfloat16")
        sharded = trainer.shard(next(ds))
        for _ in range(args.warmup):
            state, metrics = trainer.train_step(state, sharded, rng)
        int(jax.device_get(state.step))  # sync (see bench.py note)
        t0 = time.monotonic()
        for _ in range(args.steps):
            state, metrics = trainer.train_step(state, sharded, rng)
        float(jax.device_get(metrics["loss"]))
        elapsed = time.monotonic() - t0
        per_chip = batch * args.steps / elapsed / k
        results.append({"mesh_size": k, "images_per_sec_per_chip": round(per_chip, 2),
                        **{kk: vv for kk, vv in mesh_topology_report(mesh).items()
                           if kk in ("regime", "num_processes", "platform")}})
        print(json.dumps(results[-1]), flush=True)

    if len(results) > 1:
        base = results[0]["images_per_sec_per_chip"]
        summary = {
            "metric": f"{args.model}_weak_scaling_efficiency",
            "sizes": [r["mesh_size"] for r in results],
            "efficiency": [round(r["images_per_sec_per_chip"] / base, 4)
                           for r in results],
            "target": ">=0.90 linear (BASELINE.json north_star)",
        }
        print(json.dumps(summary))


if __name__ == "__main__":
    main()
