"""Time ViT-S/16 train-step variants of the attention sublayer on the chip.

The r3 TPU trace (VERDICT r2 #2) attributed ~15.5% of the ViT step to
`data formatting` HLOs (attention layout transposes) and ~10% to
rng-bit-generator + per-block uniforms (attention-weight dropout masks over
(B,H,197,197) ×12 blocks). This harness measures each lever independently,
plus the round-2 flax `nn.MultiHeadDotProductAttention` build as the
regression reference, all in ONE process (single-grant TPU: clients queue,
so serial in-process variants are the only safe sweep).

Usage:
    python benchmarks/vit_attention_variants.py [--batch-size 256] [--steps 20]

Prints one JSON line per variant: {"variant": ..., "images_per_sec_per_chip": ...}
"""

from __future__ import annotations

import argparse
import io
import json
import os
import sys
import time
from typing import Any

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _register_mha_reference() -> None:
    """Round-2 ViT build: per-block flax MHA (three separate projection GEMMs,
    dropout_rate applied to attention weights) — the 1,866 img/s/chip r2
    baseline, kept here as the regression reference."""
    import flax.linen as nn
    import jax.numpy as jnp

    from distributed_vgg_f_tpu.config import ModelConfig
    from distributed_vgg_f_tpu.models.registry import _dtype, register
    from distributed_vgg_f_tpu.models.vit import MlpBlock, ViT

    class MhaEncoderBlock(nn.Module):
        num_heads: int
        mlp_dim: int
        dropout_rate: float
        compute_dtype: Any
        attention_dropout_rate: float = 0.0
        attention_layout: str = "unused"

        @nn.compact
        def __call__(self, x, *, train: bool):
            y = nn.LayerNorm(dtype=jnp.float32, name="ln1")(x)
            y = nn.MultiHeadDotProductAttention(
                num_heads=self.num_heads, dtype=self.compute_dtype,
                param_dtype=jnp.float32,
                dropout_rate=self.attention_dropout_rate,
                deterministic=not train, name="attn")(y, y)
            x = x + nn.Dropout(self.dropout_rate, deterministic=not train)(y)
            y = nn.LayerNorm(dtype=jnp.float32, name="ln2")(x)
            y = MlpBlock(self.mlp_dim, self.dropout_rate, self.compute_dtype,
                         name="mlp")(y, train=train)
            return x + y

    class MhaViT(ViT):
        @nn.compact
        def __call__(self, x, *, train: bool = False):
            import jax.numpy as jnp
            B = x.shape[0]
            x = x.astype(self.compute_dtype)
            x = nn.Conv(self.hidden_dim,
                        (self.patch_size, self.patch_size),
                        strides=(self.patch_size, self.patch_size),
                        padding="VALID", dtype=self.compute_dtype,
                        param_dtype=jnp.float32, name="patch_embed")(x)
            x = x.reshape(B, -1, self.hidden_dim)
            cls_tok = self.param("cls", nn.initializers.zeros,
                                 (1, 1, self.hidden_dim), jnp.float32)
            x = jnp.concatenate(
                [jnp.broadcast_to(cls_tok.astype(self.compute_dtype),
                                  (B, 1, self.hidden_dim)), x], axis=1)
            pos = self.param("pos_embed",
                             nn.initializers.normal(stddev=0.02),
                             (1, x.shape[1], self.hidden_dim), jnp.float32)
            x = x + pos.astype(self.compute_dtype)
            x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
            for i in range(self.depth):
                x = MhaEncoderBlock(
                    self.num_heads, self.mlp_dim, self.dropout_rate,
                    self.compute_dtype,
                    attention_dropout_rate=self.attention_dropout_rate,
                    name=f"block{i}")(x, train=train)
            x = nn.LayerNorm(dtype=jnp.float32, name="ln_final")(x)
            x = x[:, 0]
            x = nn.Dense(self.num_classes, dtype=self.compute_dtype,
                         param_dtype=jnp.float32, name="head")(x)
            return x.astype(jnp.float32)

    @register("vit_s16_mha_ref")
    def _build(cfg: ModelConfig):
        return MhaViT(num_classes=cfg.num_classes,
                      dropout_rate=cfg.dropout_rate,
                      compute_dtype=_dtype(cfg), **cfg.extra)


def time_variant(name: str, model_name: str, extra: dict, args) -> dict:
    import jax

    from distributed_vgg_f_tpu.config import (
        DataConfig, ExperimentConfig, ModelConfig, OptimConfig, TrainConfig)
    from distributed_vgg_f_tpu.data.synthetic import SyntheticDataset
    from distributed_vgg_f_tpu.train.trainer import Trainer
    from distributed_vgg_f_tpu.utils.logging import MetricLogger

    num_chips = jax.device_count()
    batch = args.batch_size * max(1, num_chips)
    cfg = ExperimentConfig(
        name=f"vit_variant_{name}",
        model=ModelConfig(name=model_name, num_classes=1000,
                          dropout_rate=0.1, compute_dtype="bfloat16",
                          extra=extra),
        optim=OptimConfig(base_lr=0.01, reference_batch_size=batch),
        data=DataConfig(name="synthetic", image_size=224,
                        global_batch_size=batch),
        train=TrainConfig(steps=args.steps, log_every=10_000, seed=0),
    )
    trainer = Trainer(cfg, logger=MetricLogger(stream=io.StringIO()))
    state = trainer.init_state()
    rng = trainer.base_rng()
    ds = SyntheticDataset(batch_size=batch, image_size=224, num_classes=1000,
                          seed=0, fixed=True, image_dtype="bfloat16")
    sharded = trainer.shard(next(ds))

    for _ in range(args.warmup):
        state, metrics = trainer.train_step(state, sharded, rng)
    if args.warmup:
        float(jax.device_get(metrics["loss"]))

    t0 = time.monotonic()
    for _ in range(args.steps):
        state, metrics = trainer.train_step(state, sharded, rng)
    float(jax.device_get(metrics["loss"]))
    elapsed = time.monotonic() - t0
    return {
        "variant": name,
        "images_per_sec_per_chip": round(batch * args.steps / elapsed / num_chips, 1),
        "step_ms": round(elapsed / args.steps * 1e3, 2),
        "batch": batch,
    }


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--batch-size", type=int, default=256)
    parser.add_argument("--steps", type=int, default=20)
    parser.add_argument("--warmup", type=int, default=5)
    args = parser.parse_args()

    _register_mha_reference()

    variants = [
        # (name, model, extra)
        ("mha_attndrop0.1_r2ref", "vit_s16_mha_ref",
         {"attention_dropout_rate": 0.1}),
        ("mha_attndrop0.0", "vit_s16_mha_ref", {}),
        ("fused_token_major_attndrop0.1_r3asmeasured", "vit_s16",
         {"attention_layout": "token_major", "attention_dropout_rate": 0.1}),
        ("fused_token_major_attndrop0.0", "vit_s16",
         {"attention_layout": "token_major"}),
        ("fused_head_major_attndrop0.1", "vit_s16",
         {"attention_layout": "head_major", "attention_dropout_rate": 0.1}),
        ("fused_head_major_attndrop0.0_proposed", "vit_s16",
         {"attention_layout": "head_major"}),
        ("fused_flash_pallas", "vit_s16",
         {"attention_layout": "flash"}),
    ]
    for name, model_name, extra in variants:
        row = time_variant(name, model_name, extra, args)
        print(json.dumps(row), flush=True)


if __name__ == "__main__":
    main()
