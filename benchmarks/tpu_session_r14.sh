#!/bin/sh
# Round-14 TPU measurement session — same discipline as tpu_session_r13.sh
# (STATIC GATE FIRST, hard TPU freeze after, watchdog-protected bench.py
# phases, sanitizer receipts last; a wedged-tunnel flagship exits 0 with
# the stale last_committed payload as its result line).
#
# New in r14 (the r17 production-serving round):
#   - SERVING OPEN-LOOP RECEIPT (host-side, no tunnel needed):
#     benchmarks/serving_bench.py re-runs the committed host_r16 protocol
#     — Poisson RPS ramp vs probed capacity, u8 payloads, hand-pinned
#     admission window, bounded queue — including the overload segment
#     (shed-not-collapse: shed rate rises, admitted p99 inside the SLO
#     budget, queue peak <= queue_limit). Gated by the sentinel on the
#     r17 `serving` basis (SERVING_PINS chain; serving rows never touch
#     the decode pins).
#   - DEVICE SERVING ROW (device phase, queued debt): the same open-loop
#     protocol against an engine whose bucket executables are AOT-lowered
#     for the TPU — the device half of the r17 acceptance (per-bucket
#     step time + HBM for the executable set; the CPU receipt pins only
#     the admission machinery).
#   - everything r13 carried (r16 ingest-service grid + service-on e2e,
#     r14 sharding/bucket grid, zoo rows, augment pair, autotune, wire
#     columns, sentinel gating, sanitizer receipts) rides along unchanged.
#
# Usage: sh benchmarks/tpu_session_r14.sh [outdir] [run_label]

set -u
OUT=${1:-/tmp/tpu_session_r14}
RUN=${2:-benchmarks/runs/tpu_r14}
mkdir -p "$OUT"
cd "$(dirname "$0")/.."

echo "== r15 static gate: linter + ABI contract + committed receipts =="
sh tools/check.sh 2>&1 | tee "$OUT/static_gate.log"
if ! grep -q "ALL GREEN" "$OUT/static_gate.log"; then
    echo "static gate FAILED — fix the tree before spending TPU time" >&2
    exit 1
fi

echo "== r17 serving open-loop receipt (host-side, committed protocol ="
echo "   host_r16: Poisson ramp, bounded queue, overload segment) =="
JAX_PLATFORMS=cpu python benchmarks/serving_bench.py \
    --json-out "$OUT/serving_openloop.json" 2>/dev/null \
    | tee "$OUT/serving_openloop.log"

echo "== r16 ingest-service scaling grid (carried; host-side) =="
python benchmarks/ingest_service_bench.py --repeats 6 --batches 36 \
    --source-images 256 --verdict-batches 16 \
    --json-out "$OUT/ingest_service_scaling.json" 2>/dev/null \
    | tee "$OUT/ingest_service_scaling.log"

echo "== flagship device bench (continuity row, bench-default config) =="
DVGGF_BENCH_ARTIFACT="$RUN/vggf_device.json" \
python bench.py --steps 30 --warmup 5 --budget 1500 \
    | tee "$OUT/vggf_device.json"
if grep -q '"error"' "$OUT/vggf_device.json"; then
    echo "tunnel unhealthy (stale or null result) — stopping before" \
         "unprotected phases" >&2
    exit 1
fi

echo "== r17 DEVICE serving row: open-loop protocol against TPU-lowered"
echo "   bucket executables (the device half of the serving acceptance) =="
python benchmarks/serving_bench.py --image-size 224 --num-classes 1000 \
    --max-batch 32 --stage-seconds 8 \
    --json-out "$RUN/serving_openloop_device.json" \
    | tee "$OUT/serving_openloop_device.json"

echo "== r16 service-on e2e row (carried): local 4-worker fleet feeding"
echo "   the trainer (kill-switch column first) =="
DVGGF_BENCH_ARTIFACT="$RUN/vggf_e2e_ingest_local.json" \
python bench.py --pipeline imagenet --repeats 6 --budget 3600 --wire u8 \
    | tee "$OUT/vggf_e2e_ingest_local.json"
SVC_PIDS=""
SVC_EPS=""
i=0
while [ $i -lt 4 ]; do
    python -m distributed_vgg_f_tpu.data.ingest_service \
        --config vggf_imagenet_dp --set data.data_dir="$DVGGF_DATA_DIR" \
        --worker-index $i --num-workers 4 --threads 1 \
        > "$OUT/svc_worker_$i.log" 2>&1 &
    SVC_PIDS="$SVC_PIDS $!"
    i=$((i + 1))
done
sleep 5
for f in "$OUT"/svc_worker_*.log; do
    EP=$(sed -n 's/.*serving on //p' "$f" | head -1)
    SVC_EPS="$SVC_EPS,$EP"
done
SVC_EPS=${SVC_EPS#,}
DVGGF_BENCH_ARTIFACT="$RUN/vggf_e2e_ingest_service_4w.json" \
python bench.py --pipeline imagenet --repeats 6 --budget 3600 --wire u8 \
    --set data.service.enabled=true \
    --set data.service.workers="$SVC_EPS" \
    | tee "$OUT/vggf_e2e_ingest_service_4w.json"
for pid in $SVC_PIDS; do kill "$pid" 2>/dev/null; done

echo "== r14 step-time x (model, sharding, bucket) grid (carried) =="
for MODEL in vggf vit_s16; do
    BS=2048; [ "$MODEL" = "vit_s16" ] && BS=256
    DVGGF_BENCH_ARTIFACT="$RUN/${MODEL}_device_dp.json" \
    python bench.py --model "$MODEL" --batch-size "$BS" --steps 30 \
        --warmup 5 --budget 1500 \
        --set mesh.shard_opt_state=false \
        | tee "$OUT/${MODEL}_device_dp.json"
    DVGGF_BENCH_ARTIFACT="$RUN/${MODEL}_device_zero2_bucket4.json" \
    python bench.py --model "$MODEL" --batch-size "$BS" --steps 30 \
        --warmup 5 --budget 1500 \
        --set mesh.shard_opt_state=true --set mesh.shard_gradients=true \
        --set mesh.comm_bucket_mb=4.0 \
        | tee "$OUT/${MODEL}_device_zero2_bucket4.json"
done

echo "== model zoo device benches (carried forward) =="
DVGGF_BENCH_ARTIFACT="$RUN/vgg16_device.json" \
python bench.py --model vgg16 --batch-size 128 --steps 20 --budget 1500 \
    | tee "$OUT/vgg16_device.json"
DVGGF_BENCH_ARTIFACT="$RUN/resnet50_device.json" \
python bench.py --model resnet50 --batch-size 256 --steps 20 --budget 1500 \
    | tee "$OUT/resnet50_device.json"
DVGGF_BENCH_ARTIFACT="$RUN/vit_s16_device.json" \
python bench.py --model vit_s16 --batch-size 256 --steps 20 --budget 1500 \
    | tee "$OUT/vit_s16_device.json"

echo "== host decode contract + flagship wire column (carried forward) =="
python benchmarks/host_pipeline_bench.py --layout tfrecord --batches 12 \
    2>/dev/null | tee "$OUT/host_decode.json"
python benchmarks/host_pipeline_bench.py --decode-bench --layout tfrecord \
    --repeats 6 --wire u8 --space-to-depth \
    --json-out "$OUT/host_decode_bench_wire_u8_s2d.json" 2>/dev/null \
    | tee "$OUT/host_decode_bench_wire_u8_s2d.log"

echo "== r13 zoo host rows + augment column (carried forward) =="
for MODEL in vggf vgg16 resnet50 vit_s16; do
    python benchmarks/host_pipeline_bench.py --decode-bench \
        --layout tfrecord --repeats 6 --model "$MODEL" \
        --restart-interval 1 --decode-restart on \
        --json-out "$OUT/host_decode_bench_zoo_${MODEL}.json" 2>/dev/null \
        | tee "$OUT/host_decode_bench_zoo_${MODEL}.log"
done
python benchmarks/host_pipeline_bench.py --decode-bench --layout tfrecord \
    --repeats 6 --model vggf --augment on --augment-receipt \
    --restart-interval 1 --decode-restart on \
    --json-out "$OUT/host_decode_bench_augment_on.json" 2>/dev/null \
    | tee "$OUT/host_decode_bench_augment_on.log"

echo "== r11 autotune convergence pair (carried forward) =="
python benchmarks/host_pipeline_bench.py --decode-bench --layout tfrecord \
    --repeats 6 --wire u8 --space-to-depth --autotune on \
    --json-out "$OUT/host_decode_bench_autotune_u8_s2d.json" 2>/dev/null \
    | tee "$OUT/host_decode_bench_autotune_u8_s2d.log"

echo "== regression sentinel: gate every gateable row =="
python benchmarks/regression_sentinel.py --check-committed \
    --check "$OUT"/serving_openloop.json \
            "$OUT"/host_decode_bench_wire_u8_s2d.json \
            "$OUT"/host_decode_bench_autotune_u8_s2d.json \
            "$OUT"/host_decode_bench_zoo_vgg16.json \
            "$OUT"/host_decode_bench_zoo_resnet50.json \
            "$OUT"/host_decode_bench_zoo_vit_s16.json \
            "$OUT"/host_decode_bench_augment_on.json \
            "$OUT"/ingest_service_scaling.json \
    > "$OUT/regression_sentinel.log" 2>&1
SENTINEL_RC=$?
cat "$OUT/regression_sentinel.log"
if [ "$SENTINEL_RC" -ne 0 ]; then
    echo "SENTINEL FAILED — do not commit these rows as a new pin" \
         "without same-session worktree controls" >&2
fi

echo "== r15 sanitizer receipts (host-only, AFTER every measurement"
echo "   phase; includes the r16 ingest-service socket stress) =="
JAX_PLATFORMS=cpu python -m pytest tests/test_sanitizers.py -m "" -q -rs \
    -p no:cacheprovider > "$OUT/sanitizer_receipts.log" 2>&1
SAN_RC=$?
cat "$OUT/sanitizer_receipts.log"
if [ "$SAN_RC" -ne 0 ]; then
    echo "SANITIZER SUITE FAILED — a finding in the native layer; fix or" \
         "add a per-entry justified suppression before committing" >&2
fi

echo "session complete: $OUT — TPU FREEZE is now in effect"
