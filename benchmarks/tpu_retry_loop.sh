#!/bin/sh
# Unattended retry loop for the TPU measurement session during a service
# outage (r4: service-side UNAVAILABLE since ~14:50 UTC). One tpu_session.sh
# attempt per cool-down period; on the first healthy attempt, copy the
# artifacts into the repo run dir (the driver commits uncommitted work at
# round end) and stop. Every attempt is watchdog-protected and leaves no
# killed clients behind (bench.py discipline); observed: children blocked
# in backend init die on their own when the service refuses.
#
# Usage: sh benchmarks/tpu_retry_loop.sh [max_attempts] [cooldown_s]

set -u
MAX=${1:-10}
COOLDOWN=${2:-2100}
cd "$(dirname "$0")/.."
RUN_DIR=benchmarks/runs/tpu_r4

i=1
while [ "$i" -le "$MAX" ]; do
    OUT="/tmp/tpu_session_loop_$i"
    echo "[retry-loop] attempt $i/$MAX $(date -u +%H:%M:%S)"
    sh benchmarks/tpu_session.sh "$OUT" "$RUN_DIR"
    rc=$?
    if [ "$rc" -eq 0 ] && [ -f "$OUT/vggf_device.json" ] \
       && ! grep -q '"error"' "$OUT/vggf_device.json"; then
        echo "[retry-loop] HEALTHY session on attempt $i — copying artifacts"
        mkdir -p "$RUN_DIR"
        cp "$OUT"/*.json "$RUN_DIR"/ 2>/dev/null
        echo "[retry-loop] artifacts in $RUN_DIR (uncommitted on purpose:"
        echo "  builder or driver commits them with analysis)"
        exit 0
    fi
    echo "[retry-loop] attempt $i unhealthy (rc=$rc); cooling down ${COOLDOWN}s"
    i=$((i + 1))
    [ "$i" -le "$MAX" ] && sleep "$COOLDOWN"
done
echo "[retry-loop] exhausted $MAX attempts without a healthy session"
exit 1
