#!/bin/sh
# Unattended retry loop for the TPU measurement session during a service
# outage (r4: service-side UNAVAILABLE since ~14:50 UTC). One tpu_session.sh
# attempt per cool-down period; on the first healthy attempt, copy the
# artifacts into the repo run dir (the driver commits uncommitted work at
# round end) and stop. Every attempt is watchdog-protected and leaves no
# killed clients behind (bench.py discipline); observed: children blocked
# in backend init die on their own when the service refuses.
#
# Usage: sh benchmarks/tpu_retry_loop.sh [max_attempts] [cooldown_s] \
#            [session_script] [run_dir]

set -u
MAX=${1:-10}
COOLDOWN=${2:-2100}
SESSION=${3:-benchmarks/tpu_session_r5.sh}
cd "$(dirname "$0")/.."
RUN_DIR=${4:-benchmarks/runs/tpu_r5}

i=1
while [ "$i" -le "$MAX" ]; do
    OUT="/tmp/tpu_session_loop_$i"
    echo "[retry-loop] attempt $i/$MAX $(date -u +%H:%M:%S)"
    sh "$SESSION" "$OUT" "$RUN_DIR"
    rc=$?
    # POSITIVE health gate: the flagship bench printed a real number.
    # (tpu_session.sh's pipeline rc is tee's, so rc==0 proves nothing; an
    # init crash leaves an EMPTY vggf_device.json that a no-"error" grep
    # would bless — code-review r4.) Parsed as JSON, top-level "value"
    # only: a bare 'grep "value": [0-9]' is fooled by the failure record's
    # embedded last_committed.value (caught live in r5 attempt 1 — the
    # stale-labeling feature of r4 broke r4's grep-based gate).
    if [ -s "$OUT/vggf_device.json" ] \
       && python -c '
import json, sys
with open(sys.argv[1]) as f:
    rec = json.load(f)
sys.exit(0 if isinstance(rec.get("value"), (int, float)) else 1)
' "$OUT/vggf_device.json"; then
        echo "[retry-loop] flagship bench HEALTHY on attempt $i"
        mkdir -p "$RUN_DIR"
        bad=0
        for f in "$OUT"/*.json; do
            base=$(basename "$f")
            if grep -q '"error"' "$f"; then
                # a mid-session tunnel drop: ship the failure record under
                # its honest name, never as a measured result
                cp "$f" "$RUN_DIR/${base%.json}_FAILED.json"
                bad=$((bad + 1))
            else
                cp "$f" "$RUN_DIR/$base"
            fi
        done
        echo "[retry-loop] artifacts in $RUN_DIR ($bad failed mid-session;"
        echo "  uncommitted on purpose: builder/driver commits with analysis)"
        [ "$bad" -gt 0 ] && exit 2
        exit 0
    fi
    echo "[retry-loop] attempt $i unhealthy (rc=$rc); cooling down ${COOLDOWN}s"
    i=$((i + 1))
    [ "$i" -le "$MAX" ] && sleep "$COOLDOWN"
done
echo "[retry-loop] exhausted $MAX attempts without a healthy session"
exit 1
