"""Render the per-op achievable-MFU bounds (utils/mxu_model.py) — the
committed derivation of the ResNet-50 ≈0.36 / ViT-S/16 ≈0.27 ceilings
(VERDICT r4 #3: "turn the MFU ceilings into arithmetic").

Usage: python benchmarks/mxu_bounds.py [--json PATH] [--markdown]

Pure host-side arithmetic — no jax import, safe with the TPU tunnel in any
state. Measured numbers quoted from the committed r4 session artifacts
(benchmarks/runs/tpu_r4/): device benches for MFU, profiler traces for the
matmul step fraction.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributed_vgg_f_tpu.utils.mxu_model import (  # noqa: E402
    INVENTORIES, achievable_mfu, ceiling_bracket, headroom_table,
    mxu_fill_bound, serial_mfu, train_views)

#: (model, bench batch, measured analytic MFU, measured matmul step
#: fraction, sources). matmul_fraction: the profiler's matmul-bearing HLO
#: category share — "convolution fusion" covers conv AND dot fusions on
#: this backend (the ViT trace's 0.5687 "convolution fusion" is its GEMMs).
#: VGG-F/VGG-16 traces were not captured in r4 (both are above 0.5 MFU —
#: not ceiling suspects); their rows carry the roofline bracket only.
MEASURED = [
    ("resnet50", 256, 0.364, 0.802,
     "runs/tpu_r4/resnet50_device.json + resnet50_trace.json"),
    ("vit_s16", 256, 0.267, 0.5687,
     "runs/tpu_r4/vit_s16_device.json + vit_s16_trace.json"),
    ("vggf", 2048, 0.508, None, "runs/tpu_r4/vggf_device.json"),
    ("vgg16", 128, 0.656, None, "runs/tpu_r4/vgg16_device.json"),
]


def model_report(name: str, batch: int, measured: float,
                 matmul_fraction: float | None, source: str) -> dict:
    views = train_views(INVENTORIES[name](batch))
    fill = mxu_fill_bound(views)
    roof = achievable_mfu(views)
    serial = serial_mfu(views)
    rep = {
        "model": name, "batch": batch,
        "mxu_fill_bound": round(fill, 4),
        "roofline_overlap_bound": round(roof, 4),
        "roofline_serial_bound": round(serial, 4),
        "measured_mfu": measured,
        "measured_source": source,
        # every view's wall and time share; the top rows ARE the ceiling
        "top_ops": headroom_table(views)[:8],
    }
    if matmul_fraction is not None:
        lo, hi = ceiling_bracket(views, matmul_fraction)
        rep.update({
            "matmul_step_fraction": matmul_fraction,
            "ceiling_bracket": [round(lo, 4), round(hi, 4)],
            "measured_inside_bracket": bool(lo <= measured <= hi),
            # headroom per the arithmetic: distance from measurement to the
            # bracket's optimistic edge — what perfect intra-op overlap
            # could still buy at the measured non-matmul fraction
            "headroom_to_upper_edge": round(hi / measured - 1.0, 4),
        })
    else:
        # no trace captured for this model (not a ceiling suspect): the
        # only claim the arithmetic makes is the upper bound — the
        # measurement must not EXCEED the perfect-overlap roofline (a
        # violation would mean the model undercounts achievable work)
        rep["measured_inside_bracket"] = bool(measured <= roof)
    return rep


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--json", default=None)
    ap.add_argument("--markdown", action="store_true")
    args = ap.parse_args()

    reports = [model_report(*row) for row in MEASURED]
    doc = {
        "chip": "TPU v5e",
        "model_doc": "utils/mxu_model.py — per-op roofline: time_i = "
                     "max(flops/(peak*mxu_fill), bytes/hbm_bw) [overlap "
                     "edge] or their sum [serial edge]; ceiling bracket = "
                     "bound x measured matmul step fraction",
        "reports": reports,
    }
    for rep in reports:
        # the judged claim: the measured MFU must sit inside its derived
        # bracket, otherwise the model (or the measurement) is wrong and
        # this artifact must not be committed silently green
        if not rep["measured_inside_bracket"]:
            limit = rep.get("ceiling_bracket",
                            [rep["roofline_serial_bound"],
                             rep["roofline_overlap_bound"]])
            raise RuntimeError(
                f"{rep['model']}: measured {rep['measured_mfu']} outside "
                f"derived bound {limit}")
    print(json.dumps(doc, indent=1))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(doc, f, indent=1)
    if args.markdown:
        print("\n| model | fill bound | roofline [serial, overlap] | "
              "x matmul frac | measured |")
        print("|---|---|---|---|---|")
        for r in reports:
            print(f"| {r['model']} b{r['batch']} | {r['mxu_fill_bound']} | "
                  f"[{r['roofline_serial_bound']}, "
                  f"{r['roofline_overlap_bound']}] | "
                  f"{r.get('ceiling_bracket', '—')} | "
                  f"{r['measured_mfu']} |")


if __name__ == "__main__":
    main()
