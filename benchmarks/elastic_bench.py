"""Elastic-resize downtime receipt (r19, ISSUE 16 satellite): measure what
surviving a k-of-N preemption actually costs under the two recovery
semantics —

- **elastic** (parallel/elastic.py, `mesh.elastic.enabled=true`): the
  trainer keeps running. Survivors shrink the mesh in place, reshard
  params/opt-state through the retopology converter, and take over the
  data stream through the r18 cursor blob. Downtime = the trainer's own
  `elastic_downtime` receipt: preemption consensus → first completed step
  on the survivor mesh, recompile included. Replayed batches MUST be 0
  (the cursor-handoff contract — enforced by the artifact schema,
  telemetry/schema.validate_elastic_row).
- **restart** (the r18-era control): the process dies at the forced
  preempt checkpoint and a FRESH interpreter comes up on the survivor
  mesh — python + jax import, trainer construction, checkpoint restore,
  recompile, first step. Timed as a real subprocess because that is what
  a restart is; in-process timing would flatter it by the whole runtime
  warm-up.

Both paths share one persistent XLA compilation cache (set up before
jax initializes, inherited by the restart subprocess): a preempted fleet
has a warm compile cache, and min-of-N timings therefore compare the
warm path on BOTH sides — without it the receipt would mostly race two
cold compiles of the same survivor-mesh program.

The artifact (--json-out) carries `metric:
elastic_resize_downtime_seconds` with `value` = the elastic row's min
downtime, one `mode: elastic_bench` layout row (the r19 regression-
sentinel basis rides its `topology` key, telemetry/regress.Basis). It is
schema-gated, never pin-gated: zero replay and the >= 3x bar are
correctness claims, not rates to band (regress.check_artifact routes it
accordingly; validate_elastic_row fails any committed receipt below 3x).

Committed receipts: benchmarks/runs/host_r18/.
"""

from __future__ import annotations

import argparse
import io
import json
import os
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from distributed_vgg_f_tpu.config import (  # noqa: E402
    DataConfig, ElasticConfig, ExperimentConfig, MeshConfig, ModelConfig,
    OptimConfig, TrainConfig)
from distributed_vgg_f_tpu.telemetry import schema  # noqa: E402
from distributed_vgg_f_tpu.telemetry.regress import ELASTIC_METRIC  # noqa: E402

DEVICES = 4


def _spread(values) -> float:
    med = sorted(values)[len(values) // 2]
    return (max(values) - min(values)) / max(med, 1e-9)


def _cfg(ckpt_dir: str, *, batch: int, image_size: int, steps: int,
         preempt_at: int, elastic: bool, faults: str) -> ExperimentConfig:
    return ExperimentConfig(
        name="elastic_bench",
        model=ModelConfig(name="vggf", num_classes=10,
                          compute_dtype="float32", dropout_rate=0.0),
        optim=OptimConfig(base_lr=0.05, reference_batch_size=batch,
                          momentum=0.9, weight_decay=1e-4),
        data=DataConfig(name="synthetic", image_size=image_size,
                        global_batch_size=batch,
                        num_train_examples=4 * batch),
        mesh=MeshConfig(num_data=0,
                        elastic=ElasticConfig(enabled=elastic)),
        train=TrainConfig(steps=steps, seed=0, log_every=1,
                          checkpoint_dir=ckpt_dir,
                          checkpoint_every_steps=100,
                          eval_every_steps=10_000,
                          fault_injection=faults),
    )


def _build_trainer(cfg, mesh_size: int, jsonl_path: str | None = None):
    import jax
    from distributed_vgg_f_tpu.parallel.mesh import MeshSpec, build_mesh
    from distributed_vgg_f_tpu.train.trainer import Trainer
    from distributed_vgg_f_tpu.utils.logging import MetricLogger
    cache = os.environ.get("JAX_COMPILATION_CACHE_DIR")
    if cache:  # robust to jax having initialized before the env was set
        jax.config.update("jax_compilation_cache_dir", cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          0.5)
    mesh = build_mesh(MeshSpec(("data",), (mesh_size,)),
                      devices=jax.devices()[:mesh_size])
    logger = MetricLogger(jsonl_path=jsonl_path, stream=io.StringIO())
    return Trainer(cfg, mesh=mesh, logger=logger)


def elastic_once(args, workdir: str) -> dict:
    """One full elastic run; returns the resize + downtime receipts."""
    jsonl = os.path.join(workdir, "elastic.jsonl")
    cfg = _cfg(os.path.join(workdir, "ck_el"),
               batch=args.batch, image_size=args.image_size,
               steps=args.steps, preempt_at=args.preempt_at, elastic=True,
               faults=f"preempt@rank1:{args.preempt_at}")
    trainer = _build_trainer(cfg, DEVICES, jsonl_path=jsonl)
    trainer.fit()
    trainer.logger.close()
    records = [json.loads(ln) for ln in open(jsonl)]
    resize = next(r for r in records if r.get("event") == "elastic_resize")
    downtime = next(r for r in records
                    if r.get("event") == "elastic_downtime")
    assert resize["cursor"]["replayed_batches"] == 0, resize
    return {"downtime_seconds": downtime["downtime_ns"] / 1e9,
            "topology": resize["topology"],
            "batch_policy": resize["batch_policy"]}


def restart_control_once(args, workdir: str, fresh_checkpoint: bool) -> float:
    """Time the r18 path: a fresh interpreter from launch to the first
    completed step on the survivor mesh. The stop-run (elastic off, forced
    preempt checkpoint) is re-created per repeat only when asked — its
    cost is NOT part of the restart (the elastic path pays the same forced
    save before resizing)."""
    ck = os.path.join(workdir, "ck_ctl")
    if fresh_checkpoint:
        cfg = _cfg(ck, batch=args.batch, image_size=args.image_size,
                   steps=args.steps, preempt_at=args.preempt_at,
                   elastic=False,
                   faults=f"preempt@rank1:{args.preempt_at}")
        trainer = _build_trainer(cfg, DEVICES)
        trainer.fit()
        trainer.logger.close()
    child_steps = args.preempt_at + 1  # restore at k, run exactly one step
    t0 = time.perf_counter()
    subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--_child-restart",
         "--ckpt-dir", ck, "--batch", str(args.batch),
         "--image-size", str(args.image_size),
         "--steps", str(child_steps),
         "--preempt-at", str(args.preempt_at),
         "--survivors", str(DEVICES - 1)],
        check=True, env={**os.environ, "JAX_PLATFORMS": "cpu"},
        stdout=subprocess.DEVNULL)
    return time.perf_counter() - t0


def child_restart(args) -> int:
    """The subprocess body: survivor-mesh trainer, restore, one step."""
    cfg = _cfg(args.ckpt_dir, batch=args.batch,
               image_size=args.image_size, steps=args.steps,
               preempt_at=args.preempt_at, elastic=False, faults="")
    trainer = _build_trainer(cfg, args.survivors)
    state = trainer.fit()
    import jax
    final = int(jax.device_get(state.step))
    if final != args.steps:
        raise SystemExit(f"restart control ran to step {final}, "
                         f"expected {args.steps} — not a restore")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--batch", type=int, default=12,
                    help="global batch; must divide by 4 and 3 "
                         "(keep_global across the 4->3 resize)")
    ap.add_argument("--image-size", type=int, default=32)
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--preempt-at", type=int, default=2)
    ap.add_argument("--repeats", type=int, default=2)
    ap.add_argument("--json-out", default="")
    # subprocess plumbing (restart_control_once)
    ap.add_argument("--_child-restart", action="store_true",
                    help=argparse.SUPPRESS)
    ap.add_argument("--ckpt-dir", default="", help=argparse.SUPPRESS)
    ap.add_argument("--survivors", type=int, default=3,
                    help=argparse.SUPPRESS)
    args = ap.parse_args(argv)

    # the virtual device count must be pinned before jax initializes
    # (CPU receipt: 4 virtual devices, resize 4->3 on rank-1 preemption)
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count="
            f"{DEVICES}").strip()

    if args._child_restart:
        return child_restart(args)

    elastic_runs, restart_s = [], []
    with tempfile.TemporaryDirectory(prefix="elastic_bench_") as workdir:
        # one warm compilation cache for BOTH paths (subprocess inherits
        # the env) — see the module docstring for why this is the honest
        # comparison
        os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                              os.path.join(workdir, "xla_cache"))
        os.environ.setdefault(
            "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")
        for i in range(args.repeats):
            run_dir = os.path.join(workdir, f"r{i}")
            os.makedirs(run_dir)
            elastic_runs.append(elastic_once(args, run_dir))
            restart_s.append(restart_control_once(
                args, run_dir, fresh_checkpoint=True))

    elastic_s = [r["downtime_seconds"] for r in elastic_runs]
    downtime = min(elastic_s)
    restart = min(restart_s)
    row = {
        "mode": "elastic_bench",
        "topology": elastic_runs[0]["topology"],
        "batch_policy": elastic_runs[0]["batch_policy"],
        "downtime_seconds": round(downtime, 4),
        "downtime_seconds_median": round(
            sorted(elastic_s)[len(elastic_s) // 2], 4),
        "restart_seconds": round(restart, 4),
        "restart_seconds_median": round(
            sorted(restart_s)[len(restart_s) // 2], 4),
        "speedup_vs_restart": round(restart / max(downtime, 1e-9), 3),
        "replayed_batches": 0,
        "resizes": 1,
        "spread": round(_spread(elastic_s), 4),
        "repeats": args.repeats,
        "preempt_at": args.preempt_at, "steps": args.steps,
        "devices": DEVICES, "survivors": DEVICES - 1,
        "batch": args.batch, "image_size": args.image_size,
        "model": "vggf", "dataset": "synthetic",
    }
    artifact = {
        "schema_version": schema.SCHEMA_VERSION,
        "metric": ELASTIC_METRIC,
        "value": row["downtime_seconds"],
        "unit": "seconds",
        "layouts": [row],
    }
    errors = schema.validate_bench_artifact(artifact)
    if errors:
        print(json.dumps(artifact, indent=1), file=sys.stderr)
        print("SCHEMA ERRORS:", errors, file=sys.stderr)
        return 1
    print(json.dumps(artifact, indent=1))
    print(f"\nelastic resize: {downtime:7.2f} s downtime "
          f"(0 replayed batches)")
    print(f"restart control:{restart:7.2f} s (fresh interpreter + restore)"
          f" -> elastic is {row['speedup_vs_restart']}x faster")
    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump(artifact, f, indent=1)
        print(f"wrote {args.json_out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
