"""Render the analytic scaling-model table (utils/scaling_model.py) —
the committed artifact for the ≥90 % v4-8 → v4-128 north star.

Usage: python benchmarks/scaling_model.py [--json PATH] [--markdown]

Pure host-side arithmetic: no jax import, no device work — safe to run with
the TPU tunnel in any state.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributed_vgg_f_tpu.utils.scaling_model import (  # noqa: E402
    ASSUMPTIONS, HOST_DECODE_RATE_R5, HOST_DECODE_RATE_R6,
    HOST_DECODE_RATE_R7, HOST_DECODE_RATE_R8, HOST_DECODE_RATE_R9,
    MEASURED, V4, V5E,
    host_provisioning_requirement,
    host_provisioning_table, north_star_summary, predict, predict_table,
    ring_attention_comm_model, ulysses_comm_model)


def sp_layout_comparison(n_chips: int = 8,
                         t_locals=(512, 1024, 1910, 3820, 8192)) -> dict:
    """The committed ring-vs-ulysses layout table (parallel/ring_attention
    vs parallel/ulysses): per T_local, the ring's EXPOSED comm (what its
    pipeline fails to hide under block compute) against the ulysses
    all-to-all wire time (charged fully exposed). The rule the numbers
    show: ulysses wins below ≈ half the ring's break-even length; from
    there up the ring's exposure shrinks to zero while the all-to-alls
    remain. Indivisible head counts no longer disqualify ulysses — they
    are zero-padded (parallel/ulysses.py) and charged ceil(H/n)·n/H here."""
    rows = []
    for t in t_locals:
        r = ring_attention_comm_model(t, n_chips)
        u = ulysses_comm_model(t, n_chips)
        ring_exposed = r.comm_exposed_fraction * r.ring_time_s
        rows.append({
            "t_local": t,
            "ring_exposed_comm_s": ring_exposed,
            "ulysses_wire_s": u.comm_time_s,
            "ulysses_wire_bytes_vs_ring": round(1 / u.bytes_ratio_vs_ring, 4),
            "preferred": "ulysses" if u.comm_time_s < ring_exposed
                         else "ring",
        })
        # same invariant the unit tests pin: per-chip attention FLOPs are
        # layout-independent (n hops × one block == full T over H/n heads)
        # up to ulysses's head-padding overhead. A real exception (not a
        # -O-stripped assert — ADVICE r4): artifact generation must fail
        # LOUDLY if the two comm models ever drift apart.
        if abs(u.compute_s - n_chips * r.hop_compute_s * u.padding_overhead) \
                > 1e-9 * u.compute_s:
            raise RuntimeError(
                f"SP comm models drifted: ulysses compute_s {u.compute_s} "
                f"!= ring total {n_chips * r.hop_compute_s} x padding "
                f"{u.padding_overhead} at t_local={t}")
    return {
        "n_chips": n_chips,
        "ring_break_even_t_local": ring_attention_comm_model(
            1024, n_chips).min_t_local_to_hide,
        "rows": rows,
        "rule": "prefer ulysses while its padding-adjusted wire time "
                "(ceil(H/n)*n/H overhead when H doesn't divide) beats the "
                "ring's exposed comm — for divisible H, t_local < ~half "
                "the ring break-even; the ring above (zero exposure, "
                "O(T/n^2) memory, any n)",
    }




def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--json", default=None,
                    help="also write the full table as JSON")
    ap.add_argument("--markdown", action="store_true",
                    help="print the README-ready markdown table")
    args = ap.parse_args()

    rows = predict_table()
    worst_no_overlap = [predict(p, 128, overlap_fraction=0.0)
                        for p in MEASURED]
    ns = north_star_summary()

    if args.markdown:
        print("| model | layout | chips | step ms | comm ms (wire) | "
              "exposed ms | efficiency | img/s/chip (device) | "
              "host ceiling | binds |")
        print("|---|---|---|---|---|---|---|---|---|---|")
        for r in rows:
            print(f"| {r.model} | {r.layout} | {r.n_chips} "
                  f"| {r.step_time_s * 1e3:.1f} "
                  f"| {r.comm_time_s * 1e3:.2f} "
                  f"| {r.exposed_comm_s * 1e3:.2f} "
                  f"| {r.efficiency:.4f} "
                  f"| {r.images_per_sec_per_chip:,.0f} "
                  f"| {r.host_bound_images_per_sec_per_chip:,.0f} "
                  f"| {r.binding_constraint} |")
        print()
        print("no-overlap worst case at 128 chips "
              "(overlap_fraction=0 — every wire byte exposed):")
        print("| model | efficiency | exposed ms |")
        print("|---|---|---|")
        for r in worst_no_overlap:
            print(f"| {r.model} | {r.efficiency:.4f} "
                  f"| {r.exposed_comm_s * 1e3:.2f} |")
        print()
        import inspect
        default_rate = inspect.signature(
            host_provisioning_requirement).parameters[
                "decode_per_core"].default
        print(f"host provisioning (cores/chip at the measured "
              f"{default_rate:.1f} img/s/core decode rate, 1.2x headroom):")
        print("| chip | model | device img/s/chip | cores/chip bare | "
              "with margin | stock | sufficient |")
        print("|---|---|---|---|---|---|---|")
        for chip in (V4, V5E):
            for r in host_provisioning_table(chip=chip):
                print(f"| {r.chip} | {r.model} "
                      f"| {r.device_rate_img_s_chip:,.0f} "
                      f"| {r.cores_per_chip_required:.1f} "
                      f"| {r.cores_per_chip_with_margin:.1f} "
                      f"| {r.stock_cores_per_chip:.0f} "
                      f"| {'yes' if r.stock_sufficient else 'NO'} |")

    payload = {
        "north_star": {
            "target": ">=0.90 scaling efficiency v4-8 -> v4-128",
            "model": ns["model"],
            "predicted_efficiency_8_to_128": round(
                ns["efficiency_8_to_128"], 4),
            "host_bound_ceiling_img_s_chip": round(
                ns["host_bound_ceiling_img_s_chip"], 1),
            "note": ns["note"],
        },
        "worst_case_no_overlap_128": {
            r.model: round(r.efficiency, 4) for r in worst_no_overlap},
        "worst_case_no_overlap_128_bf16_reduce": {
            p.name: round(predict(p, 128, overlap_fraction=0.0,
                                  grad_bytes_per_param=2).efficiency, 4)
            for p in MEASURED},
        "table": [dataclasses.asdict(r) for r in rows],
        "sp_layouts": sp_layout_comparison(),
        # the deployable host spec (VERDICT r4 #8): cores/chip each model
        # needs at the measured decode rate, with the sensitivity rows the
        # number is only honest with (decode rate ±20 % spans the measured
        # host variance; headroom 1.0 = no-margin bare minimum)
        "host_provisioning": {
            chip.name: [dataclasses.asdict(r)
                        for r in host_provisioning_table(chip=chip)]
            for chip in (V4, V5E)},
        "host_provisioning_sensitivity": {
            # HOST_DECODE_RATE_R9 = the r9 measured default (restart-marker
            # excerpt entropy decode on the u8 wire — assumes the dataset
            # carries interval-1 markers, reencode_restart.py);
            # HOST_DECODE_RATE_R8 = the r8 uint8-wire rate (also what a
            # marker-ABSENT dataset decodes at, modulo drift);
            # HOST_DECODE_RATE_R7 = the r7 host-bf16+s2d-wire rate;
            # HOST_DECODE_RATE_R6 = the r6 SIMD-resample point value (the
            # r6→r7 gap is committed box drift — host_r7/README.md);
            # HOST_DECODE_RATE_R5 = the r5 scalar-hoist rate; 556.34 = the
            # frozen r4 baseline; ±20% brackets host variance
            f"decode_{int(rate)}": {
                r.model: round(r.cores_per_chip_with_margin, 1)
                for r in host_provisioning_table(decode_per_core=rate)}
            for rate in (556.34, HOST_DECODE_RATE_R5, HOST_DECODE_RATE_R6,
                         HOST_DECODE_RATE_R7, HOST_DECODE_RATE_R8,
                         HOST_DECODE_RATE_R9 * 0.8, HOST_DECODE_RATE_R9,
                         HOST_DECODE_RATE_R9 * 1.2)},
        "assumptions": dict(ASSUMPTIONS),
    }
    if args.json:
        os.makedirs(os.path.dirname(args.json) or ".", exist_ok=True)
        with open(args.json, "w") as f:
            json.dump(payload, f, indent=1)
    print(json.dumps({"metric": "predicted_scaling_efficiency_v4_8_to_128",
                      "value": round(ns["efficiency_8_to_128"], 4),
                      "unit": "ratio",
                      "vs_baseline": round(ns["efficiency_8_to_128"] / 0.90,
                                           4)}))


if __name__ == "__main__":
    main()
