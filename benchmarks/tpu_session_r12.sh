#!/bin/sh
# Round-12 TPU measurement session — same discipline as tpu_session_r11.sh
# (scheduled EARLY, followed by a HARD TPU FREEZE; every bench.py invocation
# watchdog-protected; unprotected phases only after the flagship bench
# proves the tunnel healthy; a wedged-tunnel flagship exits 0 with the
# stale last_committed payload as its result line).
#
# Differences from tpu_session_r11.sh (the r15 correctness-tooling round):
#   - STATIC GATE FIRST: tools/check.sh (invariant linter + ctypes<->ABI
#     contract checker + committed-receipt sentinel) runs BEFORE anything
#     touches the tunnel — a session on scarce hardware must not start on
#     a tree that fails its own invariants. Gate failure aborts the
#     session outright.
#   - SANITIZER RECEIPTS LAST: the ASan+UBSan byte-parity re-run and the
#     TSan concurrency stress suite (tests/test_sanitizers.py, `-m
#     sanitizer`) execute on the HOST after every measurement phase — they
#     are CPU-heavy and must not pollute the host-sensitive decode
#     windows, and they need no tunnel. The pytest log is the committed
#     "zero unjustified findings" receipt; skips (missing sanitizer
#     runtimes) land in the log with their reason.
#   - everything r11 carried (r14 sharding/bucket grid, zoo rows, augment
#     pair, autotune, wire columns, sentinel gating) rides along
#     unchanged.
#
# Usage: sh benchmarks/tpu_session_r12.sh [outdir] [run_label]

set -u
OUT=${1:-/tmp/tpu_session_r12}
RUN=${2:-benchmarks/runs/tpu_r12}
mkdir -p "$OUT"
cd "$(dirname "$0")/.."

echo "== r15 static gate: linter + ABI contract + committed receipts =="
sh tools/check.sh 2>&1 | tee "$OUT/static_gate.log"
# capture the gate's status from its log tail (POSIX sh: no pipefail)
if ! grep -q "ALL GREEN" "$OUT/static_gate.log"; then
    echo "static gate FAILED — fix the tree before spending TPU time" >&2
    exit 1
fi

echo "== flagship device bench (continuity row, bench-default config) =="
DVGGF_BENCH_ARTIFACT="$RUN/vggf_device.json" \
python bench.py --steps 30 --warmup 5 --budget 1500 \
    | tee "$OUT/vggf_device.json"
if grep -q '"error"' "$OUT/vggf_device.json"; then
    echo "tunnel unhealthy (stale or null result) — stopping before" \
         "unprotected phases" >&2
    exit 1
fi

echo "== r14 step-time x (model, sharding, bucket) grid (carried) =="
for MODEL in vggf vit_s16; do
    BS=2048; [ "$MODEL" = "vit_s16" ] && BS=256
    DVGGF_BENCH_ARTIFACT="$RUN/${MODEL}_device_dp.json" \
    python bench.py --model "$MODEL" --batch-size "$BS" --steps 30 \
        --warmup 5 --budget 1500 \
        --set mesh.shard_opt_state=false \
        | tee "$OUT/${MODEL}_device_dp.json"
    DVGGF_BENCH_ARTIFACT="$RUN/${MODEL}_device_zero2_bucket4.json" \
    python bench.py --model "$MODEL" --batch-size "$BS" --steps 30 \
        --warmup 5 --budget 1500 \
        --set mesh.shard_opt_state=true --set mesh.shard_gradients=true \
        --set mesh.comm_bucket_mb=4.0 \
        | tee "$OUT/${MODEL}_device_zero2_bucket4.json"
done

echo "== model zoo device benches (carried forward) =="
DVGGF_BENCH_ARTIFACT="$RUN/vgg16_device.json" \
python bench.py --model vgg16 --batch-size 128 --steps 20 --budget 1500 \
    | tee "$OUT/vgg16_device.json"
DVGGF_BENCH_ARTIFACT="$RUN/resnet50_device.json" \
python bench.py --model resnet50 --batch-size 256 --steps 20 --budget 1500 \
    | tee "$OUT/resnet50_device.json"
DVGGF_BENCH_ARTIFACT="$RUN/vit_s16_device.json" \
python bench.py --model vit_s16 --batch-size 256 --steps 20 --budget 1500 \
    | tee "$OUT/vit_s16_device.json"

echo "== end-to-end pipeline bench: u8 wire flagship (carried forward) =="
DVGGF_BENCH_ARTIFACT="$RUN/vggf_e2e_wire_u8.json" \
python bench.py --pipeline imagenet --repeats 6 --budget 3600 \
    --wire u8 \
    | tee "$OUT/vggf_e2e_wire_u8.json"

echo "== host decode contract + flagship wire column (carried forward) =="
python benchmarks/host_pipeline_bench.py --layout tfrecord --batches 12 \
    2>/dev/null | tee "$OUT/host_decode.json"
python benchmarks/host_pipeline_bench.py --decode-bench --layout tfrecord \
    --repeats 6 --wire u8 --space-to-depth \
    --json-out "$OUT/host_decode_bench_wire_u8_s2d.json" 2>/dev/null \
    | tee "$OUT/host_decode_bench_wire_u8_s2d.log"

echo "== r13 zoo host rows (carried forward) =="
for MODEL in vggf vgg16 resnet50 vit_s16; do
    python benchmarks/host_pipeline_bench.py --decode-bench \
        --layout tfrecord --repeats 6 --model "$MODEL" \
        --restart-interval 1 --decode-restart on \
        --json-out "$OUT/host_decode_bench_zoo_${MODEL}.json" 2>/dev/null \
        | tee "$OUT/host_decode_bench_zoo_${MODEL}.log"
done

echo "== r13 augment-on host column (carried forward) =="
python benchmarks/host_pipeline_bench.py --decode-bench --layout tfrecord \
    --repeats 6 --model vggf --augment on --augment-receipt \
    --restart-interval 1 --decode-restart on \
    --json-out "$OUT/host_decode_bench_augment_on.json" 2>/dev/null \
    | tee "$OUT/host_decode_bench_augment_on.log"

echo "== r11 autotune convergence pair (carried forward) =="
python benchmarks/host_pipeline_bench.py --decode-bench --layout tfrecord \
    --repeats 6 --wire u8 --space-to-depth --autotune on \
    --json-out "$OUT/host_decode_bench_autotune_u8_s2d.json" 2>/dev/null \
    | tee "$OUT/host_decode_bench_autotune_u8_s2d.log"

echo "== regression sentinel: gate the flagship + zoo + augment rows"
echo "   against their pinned bases =="
# no pipe to tee here: POSIX sh has no pipefail, so '|| ...' after a pipe
# would test tee's exit status and the failure branch could never fire
python benchmarks/regression_sentinel.py --check-committed \
    --check "$OUT"/host_decode_bench_wire_u8_s2d.json \
            "$OUT"/host_decode_bench_autotune_u8_s2d.json \
            "$OUT"/host_decode_bench_zoo_vgg16.json \
            "$OUT"/host_decode_bench_zoo_resnet50.json \
            "$OUT"/host_decode_bench_zoo_vit_s16.json \
            "$OUT"/host_decode_bench_augment_on.json \
    > "$OUT/regression_sentinel.log" 2>&1
SENTINEL_RC=$?
cat "$OUT/regression_sentinel.log"
if [ "$SENTINEL_RC" -ne 0 ]; then
    echo "SENTINEL FAILED — do not commit these rows as a new pin" \
         "without same-session worktree controls" >&2
fi

echo "== r15 sanitizer receipts (host-only, AFTER every measurement"
echo "   phase: CPU-heavy by design, needs no tunnel; skips carry their"
echo "   reason into the committed log) =="
JAX_PLATFORMS=cpu python -m pytest tests/test_sanitizers.py -m "" -q -rs \
    -p no:cacheprovider > "$OUT/sanitizer_receipts.log" 2>&1
SAN_RC=$?
cat "$OUT/sanitizer_receipts.log"
if [ "$SAN_RC" -ne 0 ]; then
    echo "SANITIZER SUITE FAILED — a finding in the native layer; fix or" \
         "add a per-entry justified suppression before committing" >&2
fi

echo "session complete: $OUT — TPU FREEZE is now in effect"
