"""CPU train-step bench for the fused on-device augmentation stage (r13).

The acceptance claim this receipt backs: the fused augment stage
(data/augment.py — flip/jitter/mixup/RandAugment-lite INSIDE the jitted
step) costs < 2% step time. The host-pipeline half of the claim (host
rate and wire bytes unchanged) is host_pipeline_bench.py
--augment-receipt; THIS harness times the jitted train step itself,
augment-on vs augment-off, with the same min-of-N ALTERNATING-window
protocol as every r7+ receipt (both columns sample the same box drift, so
the min-of-N difference isolates the stage).

CPU is the honest qualifier: on a TPU the elementwise augment ops fuse
into memory-bound kernels XLA was already emitting, so the CPU number —
where the same ops compete for the cores running everything else — is the
UPPER bound for the stage's relative cost. The device-side confirmation
row rides tpu_session_r10.sh.

    JAX_PLATFORMS=cpu python benchmarks/augment_step_bench.py \
        --model vggf --image-size 128 --batch 16 --repeats 6 \
        --json-out benchmarks/runs/host_r13/augment_step_overhead.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

METRIC = "cpu_train_step_images_per_sec"


def _stats(rates):
    med = sorted(rates)[len(rates) // 2]
    return {"repeats": len(rates), "best": round(max(rates), 2),
            "median": round(med, 2),
            "spread": round((max(rates) - min(rates)) / med, 4) if med else 0}


def main() -> int:
    parser = argparse.ArgumentParser(
        description="fused-augment step-time overhead receipt (CPU)")
    parser.add_argument("--model", default="vggf",
                        choices=("vggf", "vgg16", "resnet50", "vit_s16"))
    parser.add_argument("--image-size", type=int, default=128)
    parser.add_argument("--batch", type=int, default=16)
    parser.add_argument("--num-classes", type=int, default=100)
    parser.add_argument("--steps-per-window", type=int, default=4)
    parser.add_argument("--warmup-steps", type=int, default=2)
    parser.add_argument("--repeats", type=int, default=6,
                        help="alternating window pairs (min-of-N)")
    parser.add_argument("--json-out", default=None)
    args = parser.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from distributed_vgg_f_tpu.config import AugmentConfig, ModelConfig
    from distributed_vgg_f_tpu.data.augment import make_device_augment
    from distributed_vgg_f_tpu.data.device_ingest import make_device_finish
    from distributed_vgg_f_tpu.models import build_model
    from distributed_vgg_f_tpu.models.ingest import (
        IMAGENET_MEAN_RGB,
        IMAGENET_STDDEV_RGB,
        ingest_descriptor,
    )
    from distributed_vgg_f_tpu.parallel.mesh import (
        MeshSpec,
        build_mesh,
        shard_host_batch,
    )
    from distributed_vgg_f_tpu.train.state import TrainState
    from distributed_vgg_f_tpu.train.step import build_train_step

    desc = ingest_descriptor(args.model)
    s2d = desc.space_to_depth and args.image_size % 4 == 0
    # float32 on CPU: bf16 emulation noise would swamp a 2% budget
    model = build_model(ModelConfig(name=args.model,
                                    num_classes=args.num_classes,
                                    compute_dtype="float32"))
    mesh = build_mesh(MeshSpec(("data",), (0,)))
    tx = optax.sgd(0.01, momentum=0.9)
    finish = make_device_finish(IMAGENET_MEAN_RGB, IMAGENET_STDDEV_RGB,
                                space_to_depth=False)
    aug_cfg = AugmentConfig(enabled=True, hflip=True, mixup_alpha=0.2)
    augment = make_device_augment(aug_cfg, IMAGENET_MEAN_RGB,
                                  IMAGENET_STDDEV_RGB, space_to_depth=s2d)
    finish_s2d = make_device_finish(IMAGENET_MEAN_RGB, IMAGENET_STDDEV_RGB,
                                    space_to_depth=s2d)

    rng = np.random.default_rng(0)
    # the u8 wire's batch, exactly as production ships it
    pixels = rng.integers(0, 256, size=(args.batch, args.image_size,
                                        args.image_size, 3)).astype(np.uint8)
    labels = rng.integers(0, args.num_classes,
                          size=(args.batch,)).astype(np.int32)
    batch = shard_host_batch({"image": pixels, "label": labels}, mesh)
    base = jax.jit(lambda: jax.random.key(1))()

    def make(with_augment: bool):
        state = TrainState.create(
            model, tx, jax.random.key(0),
            jnp.zeros((1, args.image_size, args.image_size, 3), jnp.float32))
        # augment-on defers the pack behind the stage; augment-off packs in
        # the finish — each column runs ITS production configuration
        step = build_train_step(
            model, tx, mesh, weight_decay=5e-4,
            device_finish=finish if with_augment else finish_s2d,
            device_augment=augment if with_augment else None)
        return state, step

    def window(state, step):
        t0 = time.monotonic()
        for _ in range(args.steps_per_window):
            state, metrics = step(state, batch, base)
        jax.block_until_ready(metrics["loss"])
        dt = time.monotonic() - t0
        return state, args.steps_per_window * args.batch / dt

    # one persistent (state, step) per column: compile once, then windows
    # only pay the step. Alternate columns so both sample the same drift.
    cols = {False: make(False), True: make(True)}
    for k in cols:
        for _ in range(max(1, args.warmup_steps)):  # warmup/compile
            st, _ = window(*cols[k])
            cols[k] = (st, cols[k][1])
    off_rates, on_rates = [], []
    for _ in range(max(1, args.repeats)):
        st, r = window(*cols[False])
        cols[False] = (st, cols[False][1])
        off_rates.append(r)
        st, r = window(*cols[True])
        cols[True] = (st, cols[True][1])
        on_rates.append(r)

    on_best, off_best = max(on_rates), max(off_rates)
    overhead_pct = round((1.0 - on_best / off_best) * 100.0, 2)
    from distributed_vgg_f_tpu.telemetry.schema import SCHEMA_VERSION
    artifact = {
        "schema_version": SCHEMA_VERSION,
        "metric": METRIC,
        "value": round(on_best, 2),
        "unit": "images/sec",
        "model": args.model,
        "image_size": args.image_size,
        "batch": args.batch,
        "space_to_depth": s2d,
        "augment_overhead": {
            "mode": "augment_step_overhead",
            "augment_on_images_per_sec": round(on_best, 2),
            "augment_off_images_per_sec": round(off_best, 2),
            "overhead_pct": overhead_pct,
            "on": _stats(on_rates), "off": _stats(off_rates),
            "augment": aug_cfg.describe(),
            "protocol": f"min-of-{args.repeats} ALTERNATING augment-off/on "
                        f"windows x {args.steps_per_window} jitted steps of "
                        f"batch {args.batch} at {args.image_size}px "
                        f"({args.model}, f32 compute, u8-wire batch, CPU); "
                        f"'on' = flagship recipe (flips+mixup) fused into "
                        f"the step, pack deferred behind the stage",
        },
        "host_vcpus": os.cpu_count(),
    }
    print(json.dumps({k: v for k, v in artifact.items()
                      if k != "schema_version"}))
    if args.json_out:
        os.makedirs(os.path.dirname(args.json_out) or ".", exist_ok=True)
        with open(args.json_out, "w") as f:
            json.dump(artifact, f, indent=1)
    budget = 2.0
    if overhead_pct > budget:
        print(f"OVER BUDGET: fused-augment step overhead {overhead_pct}% "
              f"> {budget}% (acceptance)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
