#!/bin/sh
# Round-6 TPU measurement session — same discipline as tpu_session_r5.sh
# (scheduled EARLY, followed by a HARD TPU FREEZE; every bench.py invocation
# watchdog-protected; unprotected phases only after the flagship bench
# proves the tunnel healthy). A wedged-tunnel flagship bench now exits 0
# with the stale last_committed payload as its result line (bench.py r7),
# so the health gate below checks for a MEASURED value, not just rc.
#
# Differences from tpu_session_r5.sh:
#   - host decode-bench rows carry the r7 protocol forward: scaled-decode
#     receipts (scale histogram, skipped scanlines, pool hit rate, source
#     bytes/pixel) land in every artifact, and the >=448px textured rows
#     measure DCT-scaled decode in the same min-of-N protocol as host_r6/
#     host_r7 — with a --decode-scaled off control column per source.
#   - the f32 contract-continuity row stays on the frozen 320x256-noise
#     basis (vs_baseline only means something there).
#
# Usage: sh benchmarks/tpu_session_r6.sh [outdir] [run_label]

set -u
OUT=${1:-/tmp/tpu_session_r6}
RUN=${2:-benchmarks/runs/tpu_r6}
mkdir -p "$OUT"
cd "$(dirname "$0")/.."

echo "== flagship device bench =="
DVGGF_BENCH_ARTIFACT="$RUN/vggf_device.json" \
python bench.py --steps 30 --warmup 5 --budget 1500 \
    | tee "$OUT/vggf_device.json"
if grep -q '"error"' "$OUT/vggf_device.json"; then
    echo "tunnel unhealthy (stale or null result) — stopping before" \
         "unprotected phases" >&2
    exit 1
fi

echo "== model zoo benches =="
DVGGF_BENCH_ARTIFACT="$RUN/vgg16_device.json" \
python bench.py --model vgg16 --batch-size 128 --steps 20 --budget 1500 \
    | tee "$OUT/vgg16_device.json"
DVGGF_BENCH_ARTIFACT="$RUN/resnet50_device.json" \
python bench.py --model resnet50 --batch-size 256 --steps 20 --budget 1500 \
    | tee "$OUT/resnet50_device.json"
DVGGF_BENCH_ARTIFACT="$RUN/vit_s16_device.json" \
python bench.py --model vit_s16 --batch-size 256 --steps 20 --budget 1500 \
    | tee "$OUT/vit_s16_device.json"

echo "== end-to-end pipeline bench (min-of-6 windows) =="
DVGGF_BENCH_ARTIFACT="$RUN/vggf_e2e.json" \
python bench.py --pipeline imagenet --repeats 6 --budget 3600 \
    | tee "$OUT/vggf_e2e.json"

echo "== host decode contract line (host-only, no TPU client) =="
python benchmarks/host_pipeline_bench.py --layout tfrecord --batches 12 \
    2>/dev/null | tee "$OUT/host_decode.json"

echo "== host decode-bench artifacts (r7 protocol: min-of-N per-core rate,"
echo "   simd+scaled dispatch receipts, scale histogram, pool hit rate,"
echo "   libjpeg/resample profile split, source bytes/pixel) =="
# flagship ingest config (bf16 + space-to-depth) on the continuity source —
# the provisioning basis (utils/scaling_model.py HOST_DECODE_RATE_R7);
# lower committed value re-derives the constant.
python benchmarks/host_pipeline_bench.py --decode-bench --layout tfrecord \
    --repeats 6 --image-dtype bfloat16 --space-to-depth \
    --json-out "$OUT/host_decode_bench_bf16s2d.json" 2>/dev/null \
    | tee "$OUT/host_decode_bench_bf16s2d.log"
# >=448px scaled-decode rows (textured = natural-image-class entropy), with
# the full-decode control column — the same-session pair that isolates what
# DCT-scaled + partial decode buys at 2x-resolution sources.
for HW in 448x448 768x768; do
    python benchmarks/host_pipeline_bench.py --decode-bench \
        --layout tfrecord --repeats 6 --image-dtype bfloat16 \
        --space-to-depth --source-hw "$HW" --source-kind textured \
        --json-out "$OUT/host_decode_bench_bf16s2d_${HW}_tex.json" \
        2>/dev/null | tee "$OUT/host_decode_bench_bf16s2d_${HW}_tex.log"
    python benchmarks/host_pipeline_bench.py --decode-bench \
        --layout tfrecord --repeats 6 --image-dtype bfloat16 \
        --space-to-depth --source-hw "$HW" --source-kind textured \
        --decode-scaled off \
        --json-out "$OUT/host_decode_bench_bf16s2d_${HW}_tex_off.json" \
        2>/dev/null | tee "$OUT/host_decode_bench_bf16s2d_${HW}_tex_off.log"
done
# f32 contract-continuity row (vs_baseline is defined on this basis only)
python benchmarks/host_pipeline_bench.py --decode-bench --layout tfrecord \
    --repeats 6 \
    --json-out "$OUT/host_decode_bench_f32.json" 2>/dev/null \
    | tee "$OUT/host_decode_bench_f32.log"

echo "session complete: $OUT — TPU FREEZE is now in effect"
