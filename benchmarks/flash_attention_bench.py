"""Flash (Pallas) vs XLA-einsum attention on the chip, fwd+bwd, long T.

The claim under test (ops/flash_attention.py): XLA's einsum attention
materializes (B, H, T, T) probs in HBM — O(T²) bandwidth and memory — while
the Pallas kernel streams K/V blocks through VMEM. At ViT scale (T=197) the
probs tensor is ~95 MB/block and XLA hides much of it; by T=8k it is
gigabytes and dominates. This bench measures both implementations' full
train-relevant path (fwd + grads wrt q, k, v) across sequence lengths on
identical inputs, plus the largest T where each still fits.

One process, variants serial (single-grant TPU discipline).

Usage:
    python benchmarks/flash_attention_bench.py [--seqs 512,2048,8192]

JSON line per (T, impl): {"seq": T, "impl": ..., "ms_per_iter": ...}
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--seqs", default="512,2048,4096,8192")
    parser.add_argument("--batch", type=int, default=4)
    parser.add_argument("--heads", type=int, default=8)
    parser.add_argument("--head-dim", type=int, default=64)
    parser.add_argument("--iters", type=int, default=10)
    parser.add_argument("--warmup", type=int, default=3)
    parser.add_argument("--causal", action="store_true")
    parser.add_argument("--impls", default="",
                        help="comma list of impl names to run (default all): "
                             "flash_pallas, flash_pallas_dma_skip, "
                             "xla_einsum. The r5 long-context rows use this "
                             "to skip xla_einsum past its measured compile "
                             "wall (r4: T=6144 einsum hung ~2.5 h in "
                             "compile; killing the grant-holding client "
                             "wedged the tunnel — benchmarks/runs/tpu_r4/"
                             "README.md 'Post-session attempts')")
    parser.add_argument("--interpret", action="store_true",
                        help="CPU debugging only")
    parser.add_argument("--platform", default="",
                        help="force a jax platform (use 'cpu' with "
                             "--interpret: this machine's sitecustomize "
                             "otherwise queues the process on the TPU "
                             "tunnel at first jit)")
    args = parser.parse_args()

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    import jax.numpy as jnp

    from distributed_vgg_f_tpu.ops.flash_attention import flash_self_attention
    from distributed_vgg_f_tpu.parallel.ring_attention import (
        full_attention_reference)

    def naive(q, k, v):
        return full_attention_reference(q, k, v, causal=args.causal)

    def flash(q, k, v):
        # pinned to the rectangular grids so the flash vs flash_dma_skip
        # comparison stays meaningful now that the production default is
        # causal_skip="auto" (which would pick "dma" itself at long T)
        return flash_self_attention(q, k, v, causal=args.causal,
                                    causal_skip="mxu",
                                    interpret=args.interpret)

    def flash_dma_skip(q, k, v):
        # causal only: the jagged forward grid — masked blocks never DMA
        # (VERDICT r3 weak #6; expected to matter most at long T)
        return flash_self_attention(q, k, v, causal=True,
                                    causal_skip="dma",
                                    interpret=args.interpret)

    def time_impl(fn, q, k, v):
        @jax.jit
        def step(q, k, v):
            def loss(q, k, v):
                return jnp.sum(fn(q, k, v).astype(jnp.float32) ** 2)
            l, grads = jax.value_and_grad(loss, argnums=(0, 1, 2))(q, k, v)
            return l, grads
        # at least one un-timed call: compile + cache before the window
        # (--warmup 0 used to hit `l` unbound here)
        for _ in range(max(args.warmup, 1)):
            l, grads = step(q, k, v)
        jax.device_get(l)
        t0 = time.monotonic()
        for _ in range(args.iters):
            l, grads = step(q, k, v)
        jax.device_get(l)
        return (time.monotonic() - t0) / args.iters * 1e3

    for t in [int(s) for s in args.seqs.split(",")]:
        shape = (args.batch, t, args.heads, args.head_dim)
        kq, kk, kv = jax.random.split(jax.random.key(0), 3)
        q = jax.random.normal(kq, shape, jnp.bfloat16)
        k = jax.random.normal(kk, shape, jnp.bfloat16)
        v = jax.random.normal(kv, shape, jnp.bfloat16)
        probs_gib = (args.batch * args.heads * t * t * 2) / 2**30
        impls = [("flash_pallas", flash), ("xla_einsum", naive)]
        if args.causal:
            impls.insert(1, ("flash_pallas_dma_skip", flash_dma_skip))
        if args.impls:
            wanted = {s.strip() for s in args.impls.split(",") if s.strip()}
            unknown = wanted - {name for name, _ in impls}
            if unknown:
                raise SystemExit(f"--impls unknown: {sorted(unknown)}")
            impls = [(n, f) for n, f in impls if n in wanted]
        for name, fn in impls:
            try:
                ms = time_impl(fn, q, k, v)
                row = {"seq": t, "impl": name, "ms_per_iter": round(ms, 2),
                       "xla_probs_gib_per_materialization": round(probs_gib, 3)}
            except Exception as e:  # OOM at long T is a RESULT here
                row = {"seq": t, "impl": name,
                       "error": type(e).__name__,
                       "detail": str(e).splitlines()[0][:200]}
            print(json.dumps(row), flush=True)


if __name__ == "__main__":
    main()
