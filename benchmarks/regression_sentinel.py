"""Perf regression sentinel CLI (telemetry/regress.py engine).

The machine-checked half of the r5–r10 receipt discipline:

    # tier-1 / CI consistency: pins == committed receipts, trajectory
    # monotone-or-receipted, trajectory.json fresh
    python benchmarks/regression_sentinel.py --check-committed

    # regenerate the machine-readable trajectory after committing a new
    # receipt round or moving a pin
    python benchmarks/regression_sentinel.py --write-trajectory

    # pre-commit gate for a fresh bench artifact (non-zero exit on
    # regression past the tolerance band):
    python benchmarks/host_pipeline_bench.py --decode-bench --layout \
        tfrecord --repeats 6 --wire u8 --space-to-depth --json-out /tmp/a.json
    python benchmarks/regression_sentinel.py --check /tmp/a.json

Exit code: 0 = green, 1 = any check failed. One JSON line per finding on
stdout plus a final summary line — greppable in CI logs, parseable by the
session scripts.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributed_vgg_f_tpu.telemetry import regress  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="receipt-driven perf regression sentinel")
    parser.add_argument("--repo", default=REPO,
                        help="repository root (default: this checkout)")
    parser.add_argument("--check-committed", action="store_true",
                        help="verify pins vs committed receipts, monotone-"
                             "or-receipted trajectory, and trajectory.json "
                             "freshness")
    parser.add_argument("--write-trajectory", nargs="?", const="",
                        default=None, metavar="PATH",
                        help="(re)generate the machine-readable trajectory "
                             "(default path: benchmarks/runs/"
                             "trajectory.json)")
    parser.add_argument("--check", nargs="*", default=[], metavar="ARTIFACT",
                        help="gate new --json-out artifacts against the "
                             "pinned trajectory with noise-aware tolerance "
                             "bands")
    parser.add_argument("--require-pin", action="store_true",
                        help="--check: an artifact whose basis matches no "
                             "gating pin is an ERROR, not a note")
    args = parser.parse_args(argv)
    if not (args.check_committed or args.check
            or args.write_trajectory is not None):
        parser.error("nothing to do: pass --check-committed, "
                     "--write-trajectory, and/or --check ARTIFACT...")

    errors = []
    if args.write_trajectory is not None:
        path = args.write_trajectory or os.path.join(
            args.repo, "benchmarks", "runs", "trajectory.json")
        trajectory = regress.build_trajectory(args.repo)
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        with open(path, "w") as f:
            json.dump(trajectory, f, indent=1)
            f.write("\n")
        print(json.dumps({"wrote": os.path.relpath(path, args.repo),
                          "rounds": len(trajectory["host_decode"]),
                          "device_rows": len(trajectory["device"])}))

    if args.check_committed:
        found = regress.check_committed(args.repo)
        found += regress.check_trajectory_file(args.repo)
        for e in found:
            print(json.dumps({"check": "committed", "error": e}))
        if not found:
            pins = {p.name: regress.pin_value(p)
                    for p in regress.PINS + regress.SERVING_PINS}
            print(json.dumps({"check": "committed", "ok": True,
                              "pins": pins}))
        errors += found

    for artifact in args.check:
        found, report = regress.check_artifact(
            artifact, args.repo, require_pin=args.require_pin)
        print(json.dumps({"check": "artifact", **report,
                          "errors": found or None}))
        errors += found

    print(json.dumps({"sentinel": "fail" if errors else "pass",
                      "errors": len(errors)}))
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
