#!/bin/sh
# Round-8 TPU measurement session — same discipline as tpu_session_r7.sh
# (scheduled EARLY, followed by a HARD TPU FREEZE; every bench.py invocation
# watchdog-protected; unprotected phases only after the flagship bench
# proves the tunnel healthy; a wedged-tunnel flagship exits 0 with the
# stale last_committed payload as its result line).
#
# Differences from tpu_session_r7.sh:
#   - the >=448px textured decode-bench rows gain the r9 RESTART COLUMNS:
#     sources transcoded to carry an RSTn marker per MCU
#     (--restart-interval 1, the committed host_r10 layout) with
#     --decode-restart on/off pairs in the SAME session, so the
#     entropy-excerpt win is drift-controlled like the r8 wire pairs were.
#   - a SNAPSHOT warm-vs-cold row (--snapshot-cache) on the flagship
#     source config receipts the decoded-crop cache on TPU-VM host
#     hardware (hit rate, warm/cold split — the host_r10 protocol's
#     acceptance row, re-run where the cores actually live).
#   - the u8-wire E2E device row carries forward unchanged — still the
#     device-side receipt the next grant owes host_r9 (BENCH_r05's
#     tpu_unavailable payload is r7-vintage and pre-wire).
#
# Usage: sh benchmarks/tpu_session_r8.sh [outdir] [run_label]

set -u
OUT=${1:-/tmp/tpu_session_r8}
RUN=${2:-benchmarks/runs/tpu_r8}
mkdir -p "$OUT"
cd "$(dirname "$0")/.."

echo "== flagship device bench =="
DVGGF_BENCH_ARTIFACT="$RUN/vggf_device.json" \
python bench.py --steps 30 --warmup 5 --budget 1500 \
    | tee "$OUT/vggf_device.json"
if grep -q '"error"' "$OUT/vggf_device.json"; then
    echo "tunnel unhealthy (stale or null result) — stopping before" \
         "unprotected phases" >&2
    exit 1
fi

echo "== model zoo benches =="
DVGGF_BENCH_ARTIFACT="$RUN/vgg16_device.json" \
python bench.py --model vgg16 --batch-size 128 --steps 20 --budget 1500 \
    | tee "$OUT/vgg16_device.json"
DVGGF_BENCH_ARTIFACT="$RUN/resnet50_device.json" \
python bench.py --model resnet50 --batch-size 256 --steps 20 --budget 1500 \
    | tee "$OUT/resnet50_device.json"
DVGGF_BENCH_ARTIFACT="$RUN/vit_s16_device.json" \
python bench.py --model vit_s16 --batch-size 256 --steps 20 --budget 1500 \
    | tee "$OUT/vit_s16_device.json"

echo "== end-to-end pipeline bench: host wire vs u8 wire (min-of-6) =="
DVGGF_BENCH_ARTIFACT="$RUN/vggf_e2e.json" \
python bench.py --pipeline imagenet --repeats 6 --budget 3600 \
    | tee "$OUT/vggf_e2e.json"
# the u8-wire e2e row: raw uint8 pixels through device_put, the finish
# fused into the step — THE device-side receipt of the r8 wire rework
DVGGF_BENCH_ARTIFACT="$RUN/vggf_e2e_wire_u8.json" \
python bench.py --pipeline imagenet --repeats 6 --budget 3600 \
    --wire u8 \
    | tee "$OUT/vggf_e2e_wire_u8.json"

echo "== host decode contract line (host-only, no TPU client) =="
python benchmarks/host_pipeline_bench.py --layout tfrecord --batches 12 \
    2>/dev/null | tee "$OUT/host_decode.json"

echo "== host decode-bench wire columns (r8 protocol, carried forward) =="
python benchmarks/host_pipeline_bench.py --decode-bench --layout tfrecord \
    --repeats 6 --wire host_f32 \
    --json-out "$OUT/host_decode_bench_wire_f32.json" 2>/dev/null \
    | tee "$OUT/host_decode_bench_wire_f32.log"
python benchmarks/host_pipeline_bench.py --decode-bench --layout tfrecord \
    --repeats 6 --wire u8 \
    --json-out "$OUT/host_decode_bench_wire_u8.json" 2>/dev/null \
    | tee "$OUT/host_decode_bench_wire_u8.log"
python benchmarks/host_pipeline_bench.py --decode-bench --layout tfrecord \
    --repeats 6 --wire host_bf16 --space-to-depth \
    --json-out "$OUT/host_decode_bench_wire_bf16s2d.json" 2>/dev/null \
    | tee "$OUT/host_decode_bench_wire_bf16s2d.log"
python benchmarks/host_pipeline_bench.py --decode-bench --layout tfrecord \
    --repeats 6 --wire u8 --space-to-depth \
    --json-out "$OUT/host_decode_bench_wire_u8_s2d.json" 2>/dev/null \
    | tee "$OUT/host_decode_bench_wire_u8_s2d.log"

echo "== r9 restart columns: >=448px textured, marker-per-MCU sources,"
echo "   on/off pairs in the same session (host_r10 protocol) =="
for HW in 448x448 768x768; do
    for RST in off on; do
        python benchmarks/host_pipeline_bench.py --decode-bench \
            --layout tfrecord --repeats 6 --wire u8 --space-to-depth \
            --source-hw "$HW" --source-kind textured \
            --restart-interval 1 --decode-restart "$RST" \
            --json-out "$OUT/host_decode_bench_rst1_${RST}_${HW}_tex.json" \
            2>/dev/null \
            | tee "$OUT/host_decode_bench_rst1_${RST}_${HW}_tex.log"
    done
done

echo "== r9 snapshot warm-vs-cold row (flagship source config) =="
python benchmarks/host_pipeline_bench.py --decode-bench --layout tfrecord \
    --repeats 6 --wire u8 --space-to-depth \
    --source-hw 448x448 --source-kind textured \
    --restart-interval 1 --decode-restart on --snapshot-cache \
    --json-out "$OUT/host_decode_bench_snapshot_448tex.json" 2>/dev/null \
    | tee "$OUT/host_decode_bench_snapshot_448tex.log"

echo "== exporter smoke row: live /metrics scraped at 1 Hz under the"
echo "   flagship decode config (ISSUE 8 observability plane) =="
python benchmarks/host_pipeline_bench.py --decode-bench --layout tfrecord \
    --repeats 6 --wire u8 --space-to-depth --exporter-receipt \
    --json-out "$OUT/host_decode_bench_exporter_u8_s2d.json" 2>/dev/null \
    | tee "$OUT/host_decode_bench_exporter_u8_s2d.log"

echo "== regression sentinel: gate this session's flagship-basis rows"
echo "   against the pinned HOST_DECODE_RATE_R* trajectory =="
# no pipe to tee here: POSIX sh has no pipefail, so '|| ...' after a pipe
# would test tee's exit status and the failure branch could never fire
python benchmarks/regression_sentinel.py --check-committed \
    --check "$OUT"/host_decode_bench_wire_u8_s2d.json \
    > "$OUT/regression_sentinel.log" 2>&1
SENTINEL_RC=$?
cat "$OUT/regression_sentinel.log"
if [ "$SENTINEL_RC" -ne 0 ]; then
    echo "SENTINEL FAILED — do not commit these rows as a new pin" \
         "without same-session worktree controls" >&2
fi

echo "session complete: $OUT — TPU FREEZE is now in effect"
