#!/bin/sh
# Round-15 TPU measurement session — same discipline as tpu_session_r14.sh
# (STATIC GATE FIRST, hard TPU freeze after, watchdog-protected bench.py
# phases, sanitizer receipts last).
#
# New in r15 (the r18 position-exact-resume round):
#   - RESUME RECEIPT (host-side): benchmarks/resume_bench.py re-runs the
#     committed host_r17 protocol — kill-at-window-k mid-epoch, blob
#     restore vs epoch-boundary replay control. Exact mode MUST replay 0
#     batches (schema-enforced); the receipt is never pin-gated.
#   - WIRE-ESCALATION-IN-TRAINER ROW (device phase): a LIVE flagship
#     train run started on the host_f32 wire with every cheaper autotune
#     knob railed, so the controller's first escalation actuates the
#     trainer-side wire knob (r18: bound through the ResumableIngest
#     position-exact rebuild — the r11 "deliberately unbound" carve-out
#     is retired). The receipt is the `wire_u8` actuation in the run's
#     autotune JSONL block plus the iterator_state block flipping its
#     wire to u8 mid-epoch; the device-rate delta against the u8-from-
#     start column is the payoff number.
#   - everything r7–r14 carried (serving open-loop + device serving,
#     ingest-service grid + service-on e2e, sharding/bucket grid, zoo
#     rows, augment pair, autotune convergence, wire columns, sentinel
#     gating, sanitizer receipts) rides along by DELEGATING to
#     tpu_session_r14.sh — one copy of the debt, no drift.
#
# Usage: sh benchmarks/tpu_session_r15.sh [outdir] [run_label]

set -u
OUT=${1:-/tmp/tpu_session_r15}
RUN=${2:-benchmarks/runs/tpu_r15}
mkdir -p "$OUT"
cd "$(dirname "$0")/.."

echo "== r15 static gate: linter + ABI contract + committed receipts =="
sh tools/check.sh 2>&1 | tee "$OUT/static_gate.log"
if ! grep -q "ALL GREEN" "$OUT/static_gate.log"; then
    echo "static gate FAILED — fix the tree before spending TPU time" >&2
    exit 1
fi

echo "== r18 resume receipt (host-side; committed host_r17 protocol) =="
JAX_PLATFORMS=cpu python benchmarks/resume_bench.py \
    --items 240 --batch 8 --image-size 224 --source-hw 320 256 \
    --repeats 6 --json-out "$OUT/resume_receipt.json" 2>/dev/null \
    | tee "$OUT/resume_receipt.log"

echo "== r18 wire-escalation-in-trainer row: flagship starts on host_f32"
echo "   with threads/depths railed; the controller's first escalation"
echo "   must actuate the trainer-side wire knob (grep the receipt) =="
DVGGF_BENCH_ARTIFACT="$RUN/vggf_device_wire_escalation.json" \
python bench.py --pipeline imagenet --steps 60 --warmup 5 --budget 1800 \
    --wire host_f32 \
    --set data.autotune.enabled=true \
    --set data.autotune.k_windows=2 \
    --set data.autotune.cooldown_windows=0 \
    --set data.autotune.min_threads=1 --set data.autotune.max_threads=1 \
    --set data.autotune.min_prefetch=1 --set data.autotune.max_prefetch=1 \
    --set data.autotune.min_prefetch_to_device=1 \
    --set data.autotune.max_prefetch_to_device=1 \
    | tee "$OUT/vggf_device_wire_escalation.json"
if grep -q '"knob": *"wire_u8"' "$OUT"/vggf_device_wire_escalation* \
        2>/dev/null; then
    echo "wire-escalation receipt: trainer actuated host_f32 -> u8"
else
    echo "NO wire_u8 actuation found — the trainer-side knob did not" \
         "fire; inspect the autotune JSONL before committing this row" >&2
fi

echo "== carried r7-r14 debt: delegate to tpu_session_r14.sh =="
sh benchmarks/tpu_session_r14.sh "$OUT/r14_carried" "$RUN"

echo "session complete: $OUT — TPU FREEZE is now in effect"
