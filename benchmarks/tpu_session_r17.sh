#!/bin/sh
# Round-17 TPU measurement session — same discipline as tpu_session_r16.sh
# (STATIC GATE FIRST, hard TPU freeze after, watchdog-protected bench.py
# phases, sanitizer receipts last).
#
# New in r17 (the r21 ZeRO-3 parameter-sharding round):
#   - ZERO3 SHARDING GRID ROW (device): the flagship + the many-leaves
#     stress case at the full ZeRO ladder's top —
#     mesh.shard_params=true over the bucketed zero2 frame. The CPU
#     equality grid (tests/test_zero3.py) already pins the math bitwise
#     vs zero2; the device row measures what CPU cannot: whether XLA's
#     latency-hiding scheduler actually cashes the per-bucket
#     just-in-time param gathers under forward compute (the committed
#     structural license: benchmarks/runs/host_r19/
#     hlo_gather_{vggf,vit_s16}_zero3.json — gathers == buckets and a
#     dependency-free (all_gather, conv/dot) pair). Rows land on their
#     OWN sentinel basis key (sharding=zero3_bucketed) so they never
#     band against the zero2 line.
#   - ZERO3 NARROWED GATHER WIRE ROW: zero3 + mesh.reduce_dtype=bfloat16
#     — the one basis where the param-gather leg narrows (zero1/2 keep
#     the re-sync gather fp32 by the replica-sync contract; under zero3
#     every replica re-gathers THROUGH the wire each step, so the cast
#     trades gather bytes against the bf16 rounding the clip-after-cast
#     pin already bounds). Wire bytes drop 37.5 % vs fp32 zero3
#     (scaling_model.exchange_bytes_per_chip with narrowed param_bytes).
#   - everything r7–r16 carried (elastic downtime receipt, resume
#     receipt, wire-escalation row, serving open-loop + device serving,
#     ingest-service grid, sharding/bucket grid, zoo rows, augment pair,
#     autotune convergence, wire columns, sentinel gating, sanitizer
#     receipts) rides along by DELEGATING to tpu_session_r16.sh — one
#     copy of the debt, no drift.
#
# Usage: sh benchmarks/tpu_session_r17.sh [outdir] [run_label]

set -u
OUT=${1:-/tmp/tpu_session_r17}
RUN=${2:-benchmarks/runs/tpu_r17}
mkdir -p "$OUT"
cd "$(dirname "$0")/.."

echo "== r17 static gate: linter + ABI contract + committed receipts =="
sh tools/check.sh 2>&1 | tee "$OUT/static_gate.log"
if ! grep -q "ALL GREEN" "$OUT/static_gate.log"; then
    echo "static gate FAILED — fix the tree before spending TPU time" >&2
    exit 1
fi

echo "== r21 zero3 device grid: flagship + many-leaves stress case =="
for MODEL in vggf vit_s16; do
    DVGGF_BENCH_ARTIFACT="$RUN/${MODEL}_device_zero3_bucket4.json" \
    python benchmarks/bench.py --config "${MODEL}_imagenet"* \
        --set mesh.shard_params=true \
        --json-out "$OUT/${MODEL}_device_zero3_bucket4.json" 2>/dev/null \
        | tee "$OUT/${MODEL}_device_zero3_bucket4.json.log"
done

echo "== r21 zero3 narrowed gather wire (bf16 wire, both legs) =="
DVGGF_BENCH_ARTIFACT="$RUN/vggf_device_zero3_bucket4_bf16.json" \
python benchmarks/bench.py --config vggf_imagenet_dp \
    --set mesh.shard_params=true --set mesh.reduce_dtype=bfloat16 \
    --json-out "$OUT/vggf_device_zero3_bucket4_bf16.json" 2>/dev/null \
    | tee "$OUT/vggf_device_zero3_bucket4_bf16.json.log"

echo "== carried r7-r16 debt: delegate to tpu_session_r16.sh =="
sh benchmarks/tpu_session_r16.sh "$OUT/r16_carried" "$RUN"

echo "session complete: $OUT — TPU FREEZE is now in effect"
