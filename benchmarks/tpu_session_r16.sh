#!/bin/sh
# Round-16 TPU measurement session — same discipline as tpu_session_r15.sh
# (STATIC GATE FIRST, hard TPU freeze after, watchdog-protected bench.py
# phases, sanitizer receipts last).
#
# New in r16 (the r19 elastic-resize round):
#   - ELASTIC DOWNTIME RECEIPT (host-side): benchmarks/elastic_bench.py
#     re-runs the committed host_r18 protocol — preempt rank 1 of 4 on a
#     live run, resize in place to 3 survivors, race the trainer's own
#     downtime_ns receipt against a REAL fresh-interpreter restart
#     subprocess. Zero replayed batches and >= 3x vs the restart control
#     are schema-enforced (telemetry/schema.validate_elastic_row); the
#     receipt is never pin-gated, and it rides the sentinel's new
#     `topology` basis (static | elastic_<N>to<M>) so elastic numbers
#     never band against static ones.
#   - DEVICE ELASTIC RESIZE ROW (queued): the same preempt-k-of-N on a
#     real multi-chip mesh, where the reshard moves actual HBM shards
#     and the recompile is the dominant downtime term. QUEUED until a
#     multi-chip allocation lands (single-chip v5e cannot shrink a
#     1-device data axis; mesh.elastic.min_survivors=2 refuses by
#     design — the refusal receipt IS the single-chip row). When it
#     runs: bench.py --set mesh.elastic.enabled=true
#     --set train.fault_injection="preempt@rank1:40", commit the run's
#     elastic JSONL block + downtime_ns next to this receipt.
#   - everything r7–r15 carried (resume receipt, wire-escalation row,
#     serving open-loop + device serving, ingest-service grid, sharding/
#     bucket grid, zoo rows, augment pair, autotune convergence, wire
#     columns, sentinel gating, sanitizer receipts) rides along by
#     DELEGATING to tpu_session_r15.sh — one copy of the debt, no drift.
#
# Usage: sh benchmarks/tpu_session_r16.sh [outdir] [run_label]

set -u
OUT=${1:-/tmp/tpu_session_r16}
RUN=${2:-benchmarks/runs/tpu_r16}
mkdir -p "$OUT"
cd "$(dirname "$0")/.."

echo "== r16 static gate: linter + ABI contract + committed receipts =="
sh tools/check.sh 2>&1 | tee "$OUT/static_gate.log"
if ! grep -q "ALL GREEN" "$OUT/static_gate.log"; then
    echo "static gate FAILED — fix the tree before spending TPU time" >&2
    exit 1
fi

echo "== r19 elastic downtime receipt (host-side; committed host_r18"
echo "   protocol: 4 virtual devices, preempt rank 1, resize to 3) =="
JAX_PLATFORMS=cpu python benchmarks/elastic_bench.py \
    --repeats 2 --json-out "$OUT/elastic_receipt.json" 2>/dev/null \
    | tee "$OUT/elastic_receipt.log"

echo "== r19 device elastic resize row: QUEUED (multi-chip only) =="
echo "   single-chip v5e has no rank to lose: a 1-device data axis"
echo "   cannot shrink below mesh.elastic.min_survivors=2, and the"
echo "   typed ElasticDegraded(too_few_survivors) refusal is the"
echo "   correct single-chip receipt. The live-HBM reshard + recompile"
echo "   downtime row runs with the first multi-chip allocation (see"
echo "   the bench.py invocation in this script's header)."

echo "== carried r7-r15 debt: delegate to tpu_session_r15.sh =="
sh benchmarks/tpu_session_r15.sh "$OUT/r15_carried" "$RUN"

echo "session complete: $OUT — TPU FREEZE is now in effect"
