"""Offline generalization run (VERDICT r2 #3): train VGG-F on the teacher
task (data/teacher.py) through the FULL fit/eval loop and record the curve.

The claim being demonstrated: this framework's optimization generalizes —
val top-1 on a DISJOINT clean split lands well above chance (1/10) and below
the train-batch top-1 (whose ceiling is capped by 10 % label noise +
augmentation) — retiring "every committed run saturates at ~1.0" as the only
learning evidence. tests/test_teacher_generalization.py regression-pins the
band; this script commits the full curve to benchmarks/runs/teacher_gen/.

Usage: python benchmarks/teacher_generalization.py [--steps 640]
       [--out benchmarks/runs/teacher_gen]
Prints one JSON summary line; writes metrics.jsonl + summary.json.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=None,
                        help="override train.steps; default: derived from "
                             "the preset's epoch count and the (possibly "
                             "overridden) train-set size, so a "
                             "--train-examples sweep keeps the SAME "
                             "epoch-based schedule per arm (code-review r4: "
                             "pinning steps while doubling data silently "
                             "halves the epochs)")
    parser.add_argument("--train-examples", type=int, default=None,
                        help="override data.num_train_examples (the r4 "
                             "train-size sweep: 2x data at the same "
                             "epoch-based schedule — the known-good lever "
                             "that should narrow the train/val gap)")
    parser.add_argument("--eval-examples", type=int, default=None,
                        help="override data.num_eval_examples (4096 in the "
                             "controlled sweep: halves the ±1.5%% top-1 "
                             "sampling noise of the 1024-example split)")
    parser.add_argument("--eval-index-base", type=int, default=0,
                        help="fixed index base for the val split (default "
                             "0 = legacy 'starts at num_train_examples'). "
                             "The sweep uses one far-offset base (65536) "
                             "for every arm so all arms score IDENTICAL "
                             "held-out examples — otherwise the val set "
                             "itself changes with the train size and the "
                             "gap comparison is confounded")
    parser.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "runs", "teacher_gen"))
    parser.add_argument("--platform", default="",
                        help="force a jax platform (e.g. cpu); default: the "
                             "machine's default backend")
    args = parser.parse_args()

    if args.platform:
        import jax
        jax.config.update("jax_platforms", args.platform)

    from distributed_vgg_f_tpu.config import get_config
    from distributed_vgg_f_tpu.data import build_dataset
    from distributed_vgg_f_tpu.train.trainer import Trainer
    from distributed_vgg_f_tpu.utils.logging import MetricLogger

    os.makedirs(args.out, exist_ok=True)
    jsonl = os.path.join(args.out, "metrics.jsonl")
    if os.path.exists(jsonl):
        os.remove(jsonl)

    cfg = get_config("vggf_teacher")
    data_over = {}
    if args.train_examples:
        data_over["num_train_examples"] = args.train_examples
    if args.eval_examples:
        data_over["num_eval_examples"] = args.eval_examples
    if args.eval_index_base:
        data_over["eval_index_base"] = args.eval_index_base
    if data_over:
        cfg = dataclasses.replace(
            cfg, data=dataclasses.replace(cfg.data, **data_over))
    if args.steps is not None:
        cfg = dataclasses.replace(
            cfg, train=dataclasses.replace(cfg.train, steps=args.steps))
    trainer = Trainer(cfg, logger=MetricLogger(jsonl_path=jsonl))
    eval_ds = build_dataset(cfg.data, "eval", seed=cfg.train.seed)
    state = trainer.fit(eval_dataset=eval_ds)
    final_eval = trainer.evaluate(state, eval_ds)
    # the memorization-side number: the TRAIN split under the eval protocol
    # (clean images, clean teacher labels) — the noisy-augmented in-training
    # top1 reads LOWER than val and is the wrong gap baseline
    clean_train = trainer.evaluate(
        state, build_dataset(cfg.data, "train_clean", seed=cfg.train.seed))

    with open(jsonl) as f:
        events = [json.loads(l) for l in f if l.strip()]
    train_top1 = [e["top1"] for e in events if e["event"] == "train"]
    # the trailing logged eval is the clean-TRAIN evaluation above, not a
    # val point — keep it out of the val curve
    evals = [e for e in events if e["event"] == "eval"][:-1]
    val_final = final_eval["eval_top1"]
    summary = {
        "steps": cfg.total_steps,
        "epochs": round(cfg.total_steps / cfg.steps_per_epoch, 2),
        "eval_index_base": cfg.data.eval_index_base or
        cfg.data.num_train_examples,
        "train_noisy_batch_top1_final": round(train_top1[-1], 4),
        "train_clean_top1_final": round(clean_train["eval_top1"], 4),
        "val_top1_final": round(val_final, 4),
        "val_top5_final": round(final_eval["eval_top5"], 4),
        "val_top1_curve": [round(e["eval_top1"], 4) for e in evals],
        "chance": 0.1,
        "label_noise": 0.1,
        "num_train_examples": cfg.data.num_train_examples,
        "num_eval_examples": cfg.data.num_eval_examples,
        # generalizes = far above chance on the DISJOINT split, while below
        # the train split's clean score (a real, finite train/val gap)
        "generalizes": (val_final > 0.3
                        and val_final < clean_train["eval_top1"]),
    }
    with open(os.path.join(args.out, "summary.json"), "w") as f:
        json.dump(summary, f, indent=1)
    print(json.dumps(summary))


if __name__ == "__main__":
    main()
