"""Kill-at-window-k / resume receipt (r18, ISSUE 15 satellite): measure
what a mid-epoch restart actually costs under the two restart semantics —

- **exact** (data/iterator_state.py): capture the iterator-state blob at
  cursor k, tear the stack down (the "kill"), rebuild a fresh native
  pipeline, restore through the blob, and time until the first batch is
  in hand. Replayed batches MUST be 0 (the position-exact contract —
  enforced by the artifact schema, telemetry/schema.validate_resume_row),
  and the first delivered batch must byte-match the uninterrupted
  stream's batch k.
- **replay** (the r17-era control): rebuild, seek only to the EPOCH
  BOUNDARY below k, and burn `k mod batches_per_epoch` full decodes
  re-reaching the cursor — the decode+wall cost the blob deletes.

The artifact (--json-out) carries `metric: resume_replayed_batches` with
`value` = the exact row's replayed count (0), one layout row per mode
(`resume_mode: exact|replay` — the r18 regression-sentinel basis,
telemetry/regress.Basis.resume), and min-of-N timings with the window
spread. It is schema-gated, never pin-gated: zero replay is a correctness
claim, not a rate to band (regress.check_artifact routes it accordingly).

Committed receipts: benchmarks/runs/host_r17/.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from distributed_vgg_f_tpu.config import DataConfig  # noqa: E402
from distributed_vgg_f_tpu.data import build_dataset  # noqa: E402
from distributed_vgg_f_tpu.data.iterator_state import (  # noqa: E402
    ResumableIngest, epoch_of, restore_from_blob)
from distributed_vgg_f_tpu.telemetry import schema  # noqa: E402


def _generate_dataset(root: str, items: int, hw) -> None:
    from PIL import Image
    rs = np.random.RandomState(0)
    classes = 4
    for c in range(classes):
        d = os.path.join(root, "train", f"cls{c:02d}")
        os.makedirs(d, exist_ok=True)
        for i in range(items // classes):
            Image.fromarray(
                (rs.rand(hw[0], hw[1], 3) * 255).astype(np.uint8)) \
                .save(os.path.join(d, f"{i}.jpg"), "JPEG", quality=90)


def _spread(values) -> float:
    med = sorted(values)[len(values) // 2]
    return (max(values) - min(values)) / max(med, 1e-9)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--data-dir", default="",
                    help="imagefolder ImageNet layout; '' generates a "
                         "synthetic JPEG set in a temp dir")
    ap.add_argument("--items", type=int, default=80)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--image-size", type=int, default=64)
    ap.add_argument("--source-hw", type=int, nargs=2, default=(72, 80))
    ap.add_argument("--wire", default="u8",
                    choices=("host_f32", "host_bf16", "u8"))
    ap.add_argument("--kill-cursor", type=int, default=0,
                    help="cursor to kill at; 0 = mid epoch 1 "
                         "(bpe + bpe//2)")
    ap.add_argument("--repeats", type=int, default=5)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--json-out", default="")
    args = ap.parse_args(argv)

    tmp = None
    data_dir = args.data_dir
    # the basis label must name what was actually decoded: the generated
    # synthetic set is 'noise'; a user-supplied layout is its own basis
    # (a real-data receipt keyed as noise would cross-compare against
    # synthetic numbers — the drift the sentinel Basis exists to prevent)
    source_kind = "user_data" if data_dir else "noise"
    if not data_dir:
        tmp = tempfile.TemporaryDirectory(prefix="resume_bench_")
        data_dir = tmp.name
        _generate_dataset(data_dir, args.items, tuple(args.source_hw))

    bpe = max(1, args.items // args.batch)
    kill = args.kill_cursor or (bpe + bpe // 2)
    if kill % bpe == 0:
        raise SystemExit("--kill-cursor must be MID-epoch (k mod "
                         f"batches_per_epoch != 0), got {kill} with "
                         f"bpe={bpe}")
    cfg = DataConfig(name="imagenet", data_dir=data_dir,
                     image_size=args.image_size,
                     global_batch_size=args.batch,
                     num_train_examples=args.items, wire=args.wire)

    def factory(dc):
        return build_dataset(dc, "train", seed=args.seed, num_classes=10)

    def ingest():
        return ResumableIngest(factory, cfg, seed=args.seed,
                               batches_per_epoch=bpe)

    # ---- uninterrupted reference: the batch the resumed stack must emit
    ref = ingest()
    for _ in range(kill):
        next(ref)
    blob = ref.capture_state(kill)
    ref_batch = {k: np.array(v, copy=True) for k, v in next(ref).items()}
    ref.close()

    def exact_once():
        t0 = time.perf_counter()
        ing = ingest()
        receipt = restore_from_blob(
            ing, blob, step=kill,
            expect={"seed": args.seed, "batches_per_epoch": bpe,
                    "ingest": "local"})
        if receipt is None:
            raise SystemExit("blob restore refused — not a resume bench")
        batch = next(ing)
        dt = time.perf_counter() - t0
        ok = (np.array_equal(batch["image"], ref_batch["image"])
              and np.array_equal(batch["label"], ref_batch["label"]))
        ing.close()
        return dt, receipt["replayed_batches"], ok

    def replay_once():
        boundary = (kill // bpe) * bpe
        t0 = time.perf_counter()
        ing = ingest()
        if not ing.restore_state(boundary):
            raise SystemExit("epoch-boundary seek refused")
        for _ in range(kill - boundary):   # the burned decodes
            next(ing)
        batch = next(ing)
        dt = time.perf_counter() - t0
        ok = (np.array_equal(batch["image"], ref_batch["image"])
              and np.array_equal(batch["label"], ref_batch["label"]))
        ing.close()
        return dt, kill - boundary, ok

    exact = [exact_once() for _ in range(args.repeats)]
    replay = [replay_once() for _ in range(args.repeats)]
    exact_s = [e[0] for e in exact]
    replay_s = [r[0] for r in replay]

    def row(mode, times, replayed, matched):
        return {
            "mode": "resume_bench", "resume_mode": mode,
            "replayed_batches": int(replayed),
            "resume_seconds": round(min(times), 6),
            "resume_seconds_median": round(
                sorted(times)[len(times) // 2], 6),
            "spread": round(_spread(times), 4),
            "repeats": args.repeats,
            "kill_cursor": kill, "batches_per_epoch": bpe,
            "kill_epoch": epoch_of(kill, bpe),
            "first_batch_matches": bool(matched),
            "wire": args.wire, "space_to_depth": False,
            "model": "vggf", "ingest_mode": "local",
            "source": {"source_kind": source_kind,
                       "source_hw": list(args.source_hw)},
            "batch": args.batch, "image_size": args.image_size,
            "items": args.items,
        }

    exact_row = row("exact", exact_s, exact[0][1],
                    all(e[2] for e in exact))
    replay_row = row("replay", replay_s, replay[0][1],
                     all(r[2] for r in replay))
    exact_row["vs_replay"] = round(min(replay_s) / max(min(exact_s), 1e-9),
                                   3)
    artifact = {
        "schema_version": schema.SCHEMA_VERSION,
        "metric": "resume_replayed_batches",
        "value": int(exact[0][1]),
        "unit": "batches",
        "layouts": [exact_row, replay_row],
    }
    errors = schema.validate_bench_artifact(artifact)
    if errors:
        print("SCHEMA ERRORS:", errors, file=sys.stderr)
        return 1
    print(json.dumps(artifact, indent=1))
    print(f"\nexact resume:  {min(exact_s) * 1e3:8.1f} ms "
          f"(0 replayed batches)")
    print(f"replay resume: {min(replay_s) * 1e3:8.1f} ms "
          f"({replay[0][1]} replayed batches) -> exact is "
          f"{exact_row['vs_replay']}x faster")
    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump(artifact, f, indent=1)
        print(f"wrote {args.json_out}")
    if tmp is not None:
        tmp.cleanup()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
