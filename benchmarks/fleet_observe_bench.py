#!/usr/bin/env python
"""Fleet observability overhead + stitched-trace receipts (r22).

Two receipts from one rig — a trainer-shaped consumer pulling batches from
2 in-process ingest workers over the real service wire, every process
serving its own telemetry exporter:

1. **Overhead** (`--json-out`): min-of-R ALTERNATING collector-off /
   collector-on windows (the r8+ protocol — box drift lands evenly on
   both columns). The ON column runs a live FleetCollector scraping all
   three exporters (/metrics + /stallz + /healthz per endpoint) at 1 Hz
   and writing fleet JSONL, i.e. the full fleet read path. The budget is
   the observability plane's standing bar: <2% end-to-end throughput.
2. **Stitched trace** (`--stitch-dir`): one traced window with client
   trace ids on, plus one served predict request against a stub engine,
   merged by telemetry/stitch.py into ONE multi-process trace. The
   receipt is the trace + its schema-validated manifest, with the two
   acceptance flow links asserted before anything is written: client
   `service_get` → the OWNING worker's `service_decode`, and
   `serving_request` → `serving_flush_<model>`.

Usage:
  python benchmarks/fleet_observe_bench.py --repeats 6 \
      --json-out benchmarks/runs/host_r22/fleet_observe_overhead.json \
      --stitch-dir benchmarks/runs/host_r22
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import urllib.request

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from distributed_vgg_f_tpu import telemetry  # noqa: E402
from distributed_vgg_f_tpu.config import (apply_overrides,  # noqa: E402
                                          get_config)
from distributed_vgg_f_tpu.data import build_dataset  # noqa: E402
from distributed_vgg_f_tpu.data.ingest_service import (  # noqa: E402
    IngestWorker, SequentialReplayProducer)
from distributed_vgg_f_tpu.data.service_client import (  # noqa: E402
    ServiceIngestClient)
from distributed_vgg_f_tpu.telemetry import flight as flight_mod  # noqa: E402
from distributed_vgg_f_tpu.telemetry import schema, stall  # noqa: E402
from distributed_vgg_f_tpu.telemetry import stitch as stitch_mod  # noqa: E402
from distributed_vgg_f_tpu.telemetry.collector import (  # noqa: E402
    FleetCollector)
from distributed_vgg_f_tpu.telemetry.exporter import (  # noqa: E402
    TelemetryExporter)
from distributed_vgg_f_tpu.telemetry.flight import FlightRecorder  # noqa: E402
from distributed_vgg_f_tpu.telemetry.registry import (  # noqa: E402
    TelemetryRegistry)
from distributed_vgg_f_tpu.telemetry.spans import SpanRecorder  # noqa: E402


def bench_cfg(batch: int, image_size: int):
    return apply_overrides(get_config("vggf_synthetic"), {
        "data.global_batch_size": batch,
        "data.image_size": image_size,
    })


class _Fleet:
    """2 replay workers + 1 trainer-role exporter, each process-alike
    serving its own registry/recorder/flight — the scrape targets."""

    def __init__(self, data_cfg, seed=3):
        factory = lambda: build_dataset(  # noqa: E731
            data_cfg, "train", seed=seed, num_classes=1000)
        self.worker_recs = [SpanRecorder(), SpanRecorder()]
        self.workers = [
            IngestWorker(SequentialReplayProducer(factory),
                         worker_index=i, num_workers=2,
                         receipt={"seed": seed, "shard_index": 0,
                                  "num_shards": 1},
                         recorder=self.worker_recs[i])
            for i in range(2)]
        self.exporters = []
        for i in range(2):
            reg, fl = TelemetryRegistry(), FlightRecorder()
            fl.record_window(step=1, wall_s=1.0,
                             stall=stall.classify(1.0),
                             counters={}, spans={})
            exp = TelemetryExporter(registry=reg,
                                    recorder=self.worker_recs[i],
                                    flight=fl, role=f"ingest_worker{i}")
            exp.start()
            exp.heartbeat(1)
            self.exporters.append(exp)
        telemetry.set_process_label("trainer_rank0")
        flight_mod.get_flight().record_window(
            step=1, wall_s=1.0, stall=stall.classify(1.0),
            counters={}, spans={})
        trainer_exp = TelemetryExporter(role="trainer_rank0")
        trainer_exp.start()
        trainer_exp.heartbeat(1)
        self.exporters.append(trainer_exp)

    @property
    def endpoints(self):
        return ([f"ingest_worker[{i}]@127.0.0.1:{self.exporters[i].port}"
                 for i in range(2)]
                + [f"trainer_rank0[2]@127.0.0.1:{self.exporters[2].port}"])

    def client(self, seed=3):
        return ServiceIngestClient([w.endpoint for w in self.workers],
                                   seed=seed, batches_per_epoch=10 ** 9)

    def close(self):
        for e in self.exporters:
            e.stop()
        for w in self.workers:
            w.close()


def run_window(fleet, steps, compute_dim, compute_iters, warmup=4):
    """Trainer-shaped consumer: each step pulls one batch off the service
    wire then runs a fixed numpy compute budget — the prefetching client
    hides wire jitter exactly as it does under a real trainer, so the
    column measures what the fleet plane can actually steal: time from
    the step loop. (The bare wire-bound loop is ±3x jagged on this box
    and would drown any <2% effect.)"""
    a = (np.random.RandomState(0).rand(compute_dim, compute_dim)
         .astype(np.float32)) / compute_dim
    client = fleet.client()
    try:
        for _ in range(warmup):
            next(client)
        t0 = time.perf_counter()
        for _ in range(steps):
            next(client)
            b = a
            for _ in range(compute_iters):
                b = a @ b
        dt = time.perf_counter() - t0
    finally:
        client.close()
    return steps / dt


def overhead_receipt(args):
    cfg = bench_cfg(args.batch, args.image_size)
    off, on, cycles_per_on, errors_per_on = [], [], [], []
    for rep in range(args.repeats):
        for mode in ("off", "on"):
            fleet = _Fleet(cfg.data)
            collector = None
            fleet_log = ""
            try:
                if mode == "on":
                    fleet_log = os.path.join(
                        args.tmp_dir, f"fleet_{rep}.jsonl")
                    collector = FleetCollector(
                        endpoints=fleet.endpoints,
                        interval_s=args.interval,
                        fleet_log=fleet_log)
                    collector.start()
                rate = run_window(fleet, args.steps, args.compute_dim,
                                  args.compute_iters)
            finally:
                if collector is not None:
                    cycles_per_on.append(
                        collector.registry.counter_value(
                            "fleet/windows", 0))
                    errors_per_on.append(
                        collector.registry.counter_value(
                            "collector/scrape_errors", 0))
                    if schema.validate_fleet_jsonl(fleet_log):
                        raise SystemExit(
                            f"fleet JSONL invalid: {fleet_log}")
                    collector.close()
                fleet.close()
                telemetry.reset()
                flight_mod.get_flight().clear()
                telemetry.configure(enabled=True)
            (off if mode == "off" else on).append(rate)
            print(f"  rep {rep} collector_{mode}: {rate:.1f} steps/s",
                  flush=True)
    best_off, best_on = max(off), max(on)
    overhead_pct = (best_off - best_on) / best_off * 100.0
    receipt = {
        "schema_version": schema.SCHEMA_VERSION,
        "metric": "fleet_collector_overhead_pct",
        "value": round(overhead_pct, 3),
        "unit": "% of trainer-shaped steps/s (negative = noise)",
        "budget_pct": 2.0,
        "within_budget": overhead_pct < 2.0,
        "protocol": f"min-of-{args.repeats} alternating collector-off/on "
                    f"windows of {args.steps} steps (each = 1 batch of "
                    f"{args.batch} x {args.image_size}px over the live "
                    f"service wire from 2 ingest workers + "
                    f"{args.compute_iters} {args.compute_dim}^2 matmuls); "
                    f"ON column scrapes 3 exporters every "
                    f"{args.interval}s + fleet JSONL",
        "columns": {
            "collector_off": {"best": round(best_off, 2),
                              "windows": [round(r, 2) for r in off],
                              "median": round(float(np.median(off)), 2)},
            "collector_on": {"best": round(best_on, 2),
                             "windows": [round(r, 2) for r in on],
                             "median": round(float(np.median(on)), 2)},
        },
        "collector": {
            "endpoints": 3,
            "interval_s": args.interval,
            "fleet_cycles_per_on_window": cycles_per_on,
            "scrape_errors": sum(errors_per_on),
        },
        "host_vcpus": os.cpu_count(),
    }
    if not receipt["within_budget"]:
        print(f"FAIL: overhead {overhead_pct:.2f}% exceeds the 2% budget",
              flush=True)
    return receipt


class _StubEngine:
    """Numpy-only engine so the serving leg of the trace needs no jax."""

    model_name = "vggf"
    image_size = 8
    num_classes = 4
    buckets = (1, 2)

    def warmup(self):
        return None

    def run(self, images):
        n = images.shape[0]
        return (np.full((n, self.num_classes), 1.0 / self.num_classes,
                        dtype=np.float32), self.buckets[-1])


def stitched_receipt(args):
    """One traced window + one served request → the committed stitched
    trace. Raises if either acceptance flow link is missing."""
    from distributed_vgg_f_tpu.config import ServingConfig
    from distributed_vgg_f_tpu.serving.server import PredictServer
    cfg = bench_cfg(args.batch, args.image_size)
    os.makedirs(args.stitch_dir, exist_ok=True)
    paths = []

    # leg 1: trainer + 2 workers over the service wire, ids on the frames
    fleet = _Fleet(cfg.data)
    client = fleet.client()
    try:
        for _ in range(args.trace_batches):
            next(client)
    finally:
        client.close()
        fleet.close()
    trainer_trace = telemetry.get_recorder().to_chrome_trace()
    worker_traces = [
        rec.to_chrome_trace(process_name=f"ingest_worker{i}")
        for i, rec in enumerate(fleet.worker_recs)]
    telemetry.reset()
    telemetry.configure(enabled=True)

    # leg 2: a served predict request in its own "process"
    telemetry.set_process_label("serving_frontend")
    server = PredictServer(ServingConfig(enabled=True, max_batch=2,
                                         buckets=(1, 2), controller=False,
                                         warmup=False))
    server.add_engine(_StubEngine())
    port = server.start()
    try:
        image = np.zeros((8, 8, 3), np.uint8)
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/predict/vggf",
            data=image.tobytes(), method="POST",
            headers={"X-DVGGF-Trace-Id": "req-fleetbench0001"})
        with urllib.request.urlopen(req, timeout=30) as r:
            assert r.status == 200
    finally:
        server.close()
    serving_trace = telemetry.get_recorder().to_chrome_trace()

    for name, trace in (("trainer_rank0", trainer_trace),
                        ("ingest_worker0", worker_traces[0]),
                        ("ingest_worker1", worker_traces[1]),
                        ("serving_frontend", serving_trace)):
        p = os.path.join(args.tmp_dir, f"{name}.trace.json")
        with open(p, "w") as f:
            json.dump(trace, f)
        paths.append(p)

    out = os.path.join(args.stitch_dir, "fleet_stitched.trace.json")
    manifest_path = os.path.join(args.stitch_dir,
                                 "fleet_stitched.manifest.json")
    manifest = stitch_mod.stitch_to_files(paths, out, manifest_path)
    errs = schema.validate_stitch_manifest(manifest)
    errs += schema.validate_chrome_trace(json.load(open(out)))
    if errs:
        raise SystemExit(f"stitched artifacts invalid: {errs}")

    names = {i["process_name"]: i["pid"] for i in manifest["inputs"]}
    get_flows = [f for f in manifest["flows"]
                 if f["src"]["name"] == "service_get"
                 and f["src"]["pid"] == names["trainer_rank0"]
                 and all(d["name"] == "service_decode" for d in f["dst"])]
    serve_flows = [f for f in manifest["flows"]
                   if f["src"]["name"] == "serving_request"
                   and [d["name"] for d in f["dst"]] ==
                   ["serving_flush_vggf"]]
    if not get_flows:
        raise SystemExit("no client get → worker decode flow in manifest")
    if {f["dst"][0]["pid"] for f in get_flows} != \
            {names["ingest_worker0"], names["ingest_worker1"]}:
        raise SystemExit("get flows did not reach BOTH workers' decodes")
    if not serve_flows:
        raise SystemExit("no serving request → engine flush flow")
    print(f"stitched {len(paths)} traces: {len(manifest['flows'])} flows "
          f"({len(get_flows)} get→decode across 2 workers, "
          f"{len(serve_flows)} request→flush) → {out}", flush=True)
    return {"trace": out, "manifest": manifest_path,
            "inputs": names, "flows": len(manifest["flows"]),
            "get_to_decode_flows": len(get_flows),
            "request_to_flush_flows": len(serve_flows)}


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--repeats", type=int, default=6)
    ap.add_argument("--steps", type=int, default=160,
                    help="timed trainer-shaped steps per window (~4s at "
                         "the default compute budget)")
    ap.add_argument("--compute-dim", type=int, default=384,
                    help="per-step matmul operand size")
    ap.add_argument("--compute-iters", type=int, default=24,
                    help="per-step matmul count")
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--image-size", type=int, default=64)
    ap.add_argument("--interval", type=float, default=1.0,
                    help="collector scrape interval (s)")
    ap.add_argument("--trace-batches", type=int, default=8,
                    help="batches in the stitched-trace window")
    ap.add_argument("--json-out", default="",
                    help="overhead receipt path (skip when empty)")
    ap.add_argument("--stitch-dir", default="",
                    help="directory for the stitched trace + manifest "
                         "(skip when empty)")
    ap.add_argument("--tmp-dir", default="/tmp/fleet_observe_bench")
    args = ap.parse_args()
    os.makedirs(args.tmp_dir, exist_ok=True)
    telemetry.configure(enabled=True)

    stitch_summary = None
    if args.stitch_dir:
        stitch_summary = stitched_receipt(args)
        telemetry.reset()
        flight_mod.get_flight().clear()
        telemetry.configure(enabled=True)
    if args.json_out:
        receipt = overhead_receipt(args)
        if stitch_summary is not None:
            receipt["stitched"] = stitch_summary
        os.makedirs(os.path.dirname(os.path.abspath(args.json_out)),
                    exist_ok=True)
        with open(args.json_out, "w") as f:
            json.dump(receipt, f, indent=1, allow_nan=False)
        print(json.dumps({k: receipt[k] for k in
                          ("metric", "value", "within_budget")}),
              flush=True)
        if not receipt["within_budget"]:
            return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
