#!/bin/sh
# Full TPU measurement session — the per-config perf protocol (BASELINE
# `configs`: every config carries the perf bar, VERDICT r2 #2/#4, r3 #1).
#
# Safe to run blind: every bench.py invocation is watchdog-protected (budget
# expiry → machine-readable failure JSON, waiting child left alive — see
# bench.py _run_with_watchdog). The UNPROTECTED profilers only run after the
# first bench proves the tunnel healthy.
#
# DVGGF_BENCH_ARTIFACT names the repo path each number will be committed
# under — bench.py records it in benchmarks/last_good.json so later
# failure records cite real run provenance, not the registry itself.
#
# Usage: sh benchmarks/tpu_session.sh [outdir] [run_label]
#        (defaults: /tmp/tpu_session benchmarks/runs/tpu_r4)

set -u
OUT=${1:-/tmp/tpu_session}
RUN=${2:-benchmarks/runs/tpu_r4}
mkdir -p "$OUT"
cd "$(dirname "$0")/.."

echo "== flagship device bench =="
DVGGF_BENCH_ARTIFACT="$RUN/vggf_device.json" \
python bench.py --steps 30 --warmup 5 --budget 1500 \
    | tee "$OUT/vggf_device.json"
if grep -q '"error"' "$OUT/vggf_device.json"; then
    echo "tunnel unhealthy — stopping before unprotected profilers" >&2
    exit 1
fi

echo "== model zoo benches =="
DVGGF_BENCH_ARTIFACT="$RUN/vgg16_device.json" \
python bench.py --model vgg16 --batch-size 128 --steps 20 --budget 1500 \
    | tee "$OUT/vgg16_device.json"
DVGGF_BENCH_ARTIFACT="$RUN/resnet50_device.json" \
python bench.py --model resnet50 --batch-size 256 --steps 20 --budget 1500 \
    | tee "$OUT/resnet50_device.json"
DVGGF_BENCH_ARTIFACT="$RUN/vit_s16_device.json" \
python bench.py --model vit_s16 --batch-size 256 --steps 20 --budget 1500 \
    | tee "$OUT/vit_s16_device.json"

echo "== r3/r4 additions: ViT flash full-model, ResNet batch sweep =="
DVGGF_BENCH_ARTIFACT="$RUN/vit_s16_flash.json" \
python bench.py --model vit_s16 --batch-size 256 --steps 20 --budget 1500 \
    --model-extra attention_layout=flash \
    | tee "$OUT/vit_s16_flash.json"
DVGGF_BENCH_ARTIFACT="$RUN/vit_s16_flash_batch512.json" \
python bench.py --model vit_s16 --batch-size 512 --steps 20 --budget 1500 \
    --model-extra attention_layout=flash \
    | tee "$OUT/vit_s16_flash_batch512.json"
DVGGF_BENCH_ARTIFACT="$RUN/resnet50_batch512.json" \
python bench.py --model resnet50 --batch-size 512 --steps 20 --budget 1500 \
    | tee "$OUT/resnet50_batch512.json"
DVGGF_BENCH_ARTIFACT="$RUN/resnet50_batch1024.json" \
python bench.py --model resnet50 --batch-size 1024 --steps 20 --budget 1500 \
    | tee "$OUT/resnet50_batch1024.json"
DVGGF_BENCH_ARTIFACT="$RUN/resnet50_s2d_stem.json" \
python bench.py --model resnet50 --batch-size 256 --steps 20 --budget 1500 \
    --model-extra stem=space_to_depth \
    | tee "$OUT/resnet50_s2d_stem.json"

echo "== end-to-end pipeline bench (min-of-3 windows) =="
DVGGF_BENCH_ARTIFACT="$RUN/vggf_e2e.json" \
python bench.py --pipeline imagenet --budget 2400 \
    | tee "$OUT/vggf_e2e.json"

echo "== flash kernel microbench =="
python benchmarks/flash_attention_bench.py --seqs 512,2048,4096,8192 \
    --iters 8 --warmup 2 | tee "$OUT/flash_attention.json"
python benchmarks/flash_attention_bench.py --seqs 512,2048,4096,8192 \
    --iters 8 --warmup 2 --causal \
    | tee "$OUT/flash_attention_causal.json"

echo "== traces: the two sub-0.4-MFU configs (VERDICT r2 #2) =="
python benchmarks/profile_bench.py --model resnet50 --batch-size 256 \
    --logdir "$OUT/profile_resnet50" | tee "$OUT/resnet50_trace.json"
python benchmarks/profile_bench.py --model vit_s16 --batch-size 256 \
    --logdir "$OUT/profile_vit" | tee "$OUT/vit_s16_trace.json"

echo "session complete: $OUT"
