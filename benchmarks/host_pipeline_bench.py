"""Host input-path shootout: native loader vs tf.data, on both ImageNet
layouts (raw-JPEG imagefolder and TFRecord shards).

Generates local fake sources once, then times the train pipelines (same
sources, same crop distribution, same normalize) at a fixed thread count.
The host path bounds end-to-end training (README: the measured infeed
stall), so per-core decode rate is the number that matters.

Usage: python benchmarks/host_pipeline_bench.py [--layout both]
       [--threads 1] [--batches 12]
Prints one JSON line per (layout, pipeline) plus a ratio line per layout.

--decode-bench runs the native-loader-only per-core decode-rate protocol
(min-of-N windows, the r5 quiet-host methodology) and — with --json-out —
writes the committed artifact the provisioning model's measured constant is
re-derived from (utils/scaling_model.py HOST_DECODE_RATE_*): per-core rate
with median/spread, WHICH resample path ran (simd_kind — the runtime-
dispatch receipt), and the libjpeg-vs-resample phase split that says where
the remaining time goes. --force-scalar pins the scalar kernels and
--decode-scaled {on,off} pins the libjpeg strategy for before/after pairs
(both fail fast when the request can't be honored on this build). r7 adds
the decode receipts (chosen-scale histogram, skipped/truncated scanlines,
decode-buffer-pool hit rate) and the source dials: --source-hw for >=448px
sources — where DCT-scaled decode has pixels to discard — and
--source-kind {noise,textured}, with the realized bytes/pixel recorded in
the artifact so a rate is never read without its entropy-decode difficulty.

r8 adds --wire {host_f32,host_bf16,u8}: the host→device ingest wire the
timed pipeline ships. The u8 rows run the native fixed-point resample
kernels (raw uint8 HWC out — normalize/cast/space-to-depth move to the
device-finish prologue, so the host's resample+pack phase shrinks and
device_put moves 1 B/px), with `wire` and `wire_bytes_per_image` recorded
in every decode row so a rate is never read without its wire format.

r9 adds the entropy-path dials: --restart-interval N losslessly transcodes
the generated sources to carry RSTn restart markers every N MCUs (0 = one
per MCU row; keyed into the source cache + sentinel), --decode-restart
{on,off} pins the restart-marker excerpt decode vs the sequential entropy
path (fail-fast like the other pins — 'on' additionally refuses markerless
sources, which would measure sequential wearing a restart label), and
every decode row carries a restart_receipt (engagement fraction, entropy
segments used vs skipped, fallback causes). --snapshot-cache appends the
decoded-crop snapshot warm-vs-cold row: cold fill pass over a fresh cache,
then min-of-N warm windows served from the store (libjpeg never runs),
with hit/miss/bytes receipts from the prefetch/snapshot_* counters.

r10 adds --exporter-receipt: the live-observability scrape-under-load
receipt (telemetry/exporter.py) — alternating no-exporter/exporter windows
with a 1 Hz /metrics poll (full registry sweep per scrape) riding the 'on'
column, the proof the live endpoint fits the <2% telemetry budget. Every
--json-out artifact now carries `schema_version` (telemetry/schema.py);
gate fresh artifacts with benchmarks/regression_sentinel.py --check.

The tfrecord-layout native per-core rate is also emitted as a contract line
(`host_native_decode_images_per_sec_per_core`, with `vs_baseline` against
benchmarks/baseline.json; freeze with --update-baseline). This is the frozen
e2e-tracking metric (VERDICT r2 #6): on this 1-vCPU host the full-path e2e
bench is ~entirely host-bound (infeed stall ≈ 0.99), so its ratio tracks
host noise; the per-core decode rate is the signal-bearing number that
transfers to real many-core TPU-VM hosts.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def _generated(root: str) -> bool:
    # generation writes a sentinel LAST: a dir without one is a partial
    # (interrupted) generation and must be rebuilt, not silently reused
    return os.path.exists(os.path.join(root, ".complete"))


def _finish(root: str, meta: dict | None = None) -> None:
    with open(os.path.join(root, ".complete"), "w") as f:
        json.dump(meta or {}, f)


def source_meta(root: str) -> dict:
    """Generation-time metadata from the sentinel (source kind/hw and the
    realized compressed density in bytes/pixel — a decode rate must never
    be read without knowing how hard its sources were to entropy-decode).
    {} for pre-r7 caches whose sentinel predates the metadata."""
    try:
        with open(os.path.join(root, ".complete")) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def _source_image(rng, h: int, w: int, kind: str) -> np.ndarray:
    """One fake source image. 'noise': i.i.d. uniform pixels — the r4-r6
    protocol, but an adversarial WORST CASE for entropy decode (every DCT
    coefficient carries energy: a 448px noise JPEG is ~0.9 B/px where
    natural ≥448px ImageNet-class photos re-encode at ~0.3-0.6 B/px, so
    noise over-weights the un-skippable huffman phase ~2x). 'textured':
    gaussian-filtered noise (sigma 1.0) — ~0.4 B/px at q90, the honest
    stand-in for natural-image entropy when benchmarking what DCT-scaled
    decode can and cannot save. The generated artifact records the
    realized bytes/pixel either way."""
    if kind == "noise":
        return rng.integers(0, 256, size=(h, w, 3)).astype(np.uint8)
    if kind != "textured":
        raise ValueError(f"unknown source kind {kind!r}")
    img = rng.normal(128.0, 60.0, size=(h, w, 3))
    try:
        from scipy import ndimage
        img = ndimage.gaussian_filter(img, sigma=(1.0, 1.0, 0))
    except ImportError:  # crude separable box blur ~ the same spectrum cut
        k = np.array([1.0, 4.0, 6.0, 4.0, 1.0]) / 16.0
        img = np.apply_along_axis(
            lambda v: np.convolve(v, k, mode="same"), 0, img)
        img = np.apply_along_axis(
            lambda v: np.convolve(v, k, mode="same"), 1, img)
    return np.clip(img, 0, 255).astype(np.uint8)


def _maybe_mark(data: bytes, restart_interval: int) -> bytes:
    """Post-encode lossless restart-marker injection (r9 sources): the
    generated JPEG is transcoded in the coefficient domain
    (native reencode_restart — decoded pixels unchanged) so the restart-
    parallel entropy path has structure to engage on. -1 = leave plain."""
    if restart_interval < 0:
        return data
    from distributed_vgg_f_tpu.data.native_jpeg import reencode_restart
    marked = reencode_restart(data, restart_interval)
    if marked is None:
        raise SystemExit("source generation: reencode_restart failed on a "
                         "freshly encoded JPEG — native library broken?")
    return marked


def ensure_imagefolder(root: str, *, classes: int = 8, per_class: int = 64,
                       source_hw=(320, 256), source_kind="noise",
                       restart_interval: int = -1) -> None:
    if _generated(root):
        return
    import tensorflow as tf
    rng = np.random.default_rng(0)
    h, w = source_hw
    jpeg_bytes = images = 0
    for c in range(classes):
        d = os.path.join(root, "train", f"n{c:08d}")
        os.makedirs(d, exist_ok=True)
        for i in range(per_class):
            img = _source_image(rng, h, w, source_kind)
            data = _maybe_mark(tf.io.encode_jpeg(img, quality=90).numpy(),
                               restart_interval)
            jpeg_bytes += len(data)
            images += 1
            with open(os.path.join(d, f"{c}_{i}.JPEG"), "wb") as f:
                f.write(data)
    _finish(root, {"source_hw": [h, w], "source_kind": source_kind,
                   "restart_interval": restart_interval,
                   "bytes_per_pixel": round(jpeg_bytes / (images * h * w),
                                            4)})


def ensure_tfrecords(root: str, *, num_files: int = 8, per_file: int = 64,
                     source_hw=(320, 256), source_kind="noise",
                     restart_interval: int = -1) -> None:
    if _generated(root):
        return
    import tensorflow as tf
    rng = np.random.default_rng(0)
    h, w = source_hw
    os.makedirs(root, exist_ok=True)
    jpeg_bytes = images = 0
    for i in range(num_files):
        path = os.path.join(root, f"train-{i:05d}-of-{num_files:05d}")
        with tf.io.TFRecordWriter(path) as writer:
            for _ in range(per_file):
                img = _source_image(rng, h, w, source_kind)
                jpeg = _maybe_mark(
                    tf.io.encode_jpeg(img, quality=90).numpy(),
                    restart_interval)
                jpeg_bytes += len(jpeg)
                images += 1
                ex = tf.train.Example(features=tf.train.Features(feature={
                    "image/encoded": tf.train.Feature(
                        bytes_list=tf.train.BytesList(value=[jpeg])),
                    "image/class/label": tf.train.Feature(
                        int64_list=tf.train.Int64List(
                            value=[int(rng.integers(1, 1001))])),
                }))
                writer.write(ex.SerializeToString())
    _finish(root, {"source_hw": [h, w], "source_kind": source_kind,
                   "restart_interval": restart_interval,
                   "bytes_per_pixel": round(jpeg_bytes / (images * h * w),
                                            4)})


def time_pipeline(ds, batch: int, batches: int, warmup: int = 2,
                  repeats: int = 1, window_hook=None) -> list[float]:
    """N independent timed windows (min-of-N-time methodology, VERDICT r3
    #4): on a shared 1-vCPU host the best window is the least-contaminated
    sample and the spread is the error bar. `window_hook` (if given) runs
    INSIDE each timed window after its batches — the telemetry receipt uses
    it to charge the per-log-window registry pull to the 'on' column."""
    for _ in range(warmup):
        next(ds)
    rates = []
    for _ in range(max(1, repeats)):
        t0 = time.monotonic()
        for _ in range(batches):
            next(ds)
        if window_hook is not None:
            window_hook()
        rates.append(batch * batches / (time.monotonic() - t0))
    return rates


def _raw_stats(rates: list[float]) -> dict:
    """Full-precision min-of-N statistics — the ONE implementation every
    consumer (display lines, frozen baseline, contract line) derives from;
    rounding is a presentation decision at each call site."""
    import statistics
    out = {"images_per_sec": max(rates)}
    if len(rates) > 1:
        med = statistics.median(rates)
        out["repeats"] = len(rates)
        out["median"] = med
        out["spread"] = (max(rates) - min(rates)) / med
    return out


def _stats(rates: list[float]) -> dict:
    """Display-rounded form of _raw_stats for the per-pipeline lines."""
    s = _raw_stats(rates)
    for k, nd in (("images_per_sec", 1), ("median", 1), ("spread", 4)):
        if k in s:
            s[k] = round(s[k], nd)
    return s


REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
HOST_METRIC = "host_native_decode_images_per_sec_per_core"


def emit_contract(native_rates: list[float], threads: int,
                  update_baseline: bool) -> None:
    """The judged-style contract line for the frozen host metric — best of
    N windows, with median/spread recorded (and frozen alongside the value
    on --update-baseline, so later ratios have an error bar to read).
    Statistics come from the same _raw_stats used for the per-pipeline
    lines — one methodology, one implementation; the FROZEN value keeps
    full precision (rounding it would make re-runs of identical rates read
    vs_baseline != 1.0 — code-review r4)."""
    s = _raw_stats([r / max(1, threads) for r in native_rates])  # per-core
    per_core = s.pop("images_per_sec")
    path = os.path.join(REPO, "benchmarks", "baseline.json")
    baselines = {}
    if os.path.exists(path):
        with open(path) as f:
            baselines = json.load(f)
    vs = 1.0
    if update_baseline:
        baselines[HOST_METRIC] = {
            "metric": HOST_METRIC, "value": per_core,
            **{k: (round(v, 4) if isinstance(v, float) else v)
               for k, v in s.items()},
            "platform": "host-cpu", "host_vcpus": os.cpu_count(),
            "threads": threads}
        with open(path, "w") as f:
            json.dump(baselines, f)
    elif baselines.get(HOST_METRIC, {}).get("value"):
        vs = per_core / baselines[HOST_METRIC]["value"]
    try:  # the dispatch receipt: which resample path produced this number
        from distributed_vgg_f_tpu.data.native_jpeg import simd_kind
        kind = simd_kind()
    except Exception:
        kind = None
    print(json.dumps({"metric": HOST_METRIC, "value": round(per_core, 2),
                      "unit": "images/sec/core",
                      "vs_baseline": round(vs, 4),
                      "simd_kind": kind,
                      **{k: (round(v, 4) if isinstance(v, float) else v)
                         for k, v in s.items()}}))


def resolve_wire(args) -> None:
    """Fold --wire and --image-dtype into one consistent pair (r8): the
    wire names the full host→device format contract, the dtype is its
    host-batch half. 'auto' keeps the pre-r8 CLI surface (--image-dtype
    decides); an explicit host_* wire overrides the dtype; 'u8' ships raw
    uint8 pixels and the recorded image_dtype says which host wire the
    device finish reproduces (the comparison column's dtype)."""
    from distributed_vgg_f_tpu.data.dtypes import resolve_wire_dtype

    if args.wire == "auto":
        args.wire = ("host_bf16" if args.image_dtype == "bfloat16"
                     else "host_f32")
    else:
        # host_* wires override the dtype; 'u8' keeps it (the comparison
        # column's host dtype) — the single mapping in data/dtypes.py
        args.image_dtype = resolve_wire_dtype(args.wire, args.image_dtype)


def flagship_augment_cfg():
    """The FLAGSHIP augmentation recipe — read from the production preset
    itself (config.py vggf_imagenet_dp), so the bench can never measure a
    recipe that drifted from what production ships."""
    from distributed_vgg_f_tpu.config import get_config
    return get_config("vggf_imagenet_dp").data.augment


def bench_augment_cfg(args):
    """The AugmentConfig a `--augment on` column runs under: the flagship
    recipe (flips + mixup). Only the flip half touches the host (the
    loader's ABI v9 switch); mixup/jitter/photometric live entirely in
    the jitted step."""
    from distributed_vgg_f_tpu.config import AugmentConfig
    if getattr(args, "augment", "off") != "on":
        return AugmentConfig()
    return flagship_augment_cfg()


def _model_descriptor(model_name: str):
    """The per-model ingest descriptor (models/ingest.py) — the zoo rows'
    layout/wire source, so a bench row can never claim a layout the
    model's stem does not consume."""
    from distributed_vgg_f_tpu.models.ingest import ingest_descriptor
    return ingest_descriptor(model_name)


def apply_model_descriptor(args) -> None:
    """--model: derive wire and space-to-depth from the model's ingest
    descriptor (models/ingest.py), exactly as the preset does via
    config.zoo_data — the row then measures the layout production trains
    that model with. Explicit --wire/--space-to-depth must not
    contradict the descriptor (a mismatched override would print a
    mislabeled zoo row)."""
    if not args.model:
        return
    d = _model_descriptor(args.model)
    if args.wire == "auto":
        args.wire = d.wire
    elif args.wire != d.wire:
        raise SystemExit(
            f"--model {args.model} ships the {d.wire!r} wire "
            f"(models/ingest.py) but --wire {args.wire!r} was forced — a "
            "zoo row must measure the model's own ingest contract")
    want_s2d = d.space_to_depth and args.image_size % 4 == 0
    if args.space_to_depth and not want_s2d:
        raise SystemExit(
            f"--model {args.model} --space-to-depth: "
            + (f"image_size {args.image_size} is not a multiple of 4 — "
               "the 4x4 packing needs one"
               if d.space_to_depth else
               "its stem does not consume the packed 4x4 layout — drop "
               "--space-to-depth"))
    args.space_to_depth = want_s2d


def apply_decode_dispatch(args) -> None:
    """Pin the requested decode dispatch BEFORE any timed window, failing
    fast with a specific message when the request cannot be honored on this
    build/host — a receipt row that silently ran a different configuration
    than the one asked for is a wrong number wearing a right label."""
    from distributed_vgg_f_tpu.data import native_jpeg
    from distributed_vgg_f_tpu.data.native_build import toolchain_missing

    if native_jpeg.load_native_jpeg() is None:
        raise SystemExit("native jpeg library unavailable — the decode "
                         "bench has nothing to measure (toolchain: "
                         f"{toolchain_missing() or 'present, build failed'})")
    if args.wire == "u8" and not native_jpeg.wire_u8_enabled():
        raise SystemExit(
            "--wire u8: the uint8 wire is refused by this build "
            "(compiled out with -DDVGGF_NO_WIRE_U8, or killed via "
            "DVGGF_WIRE_U8=0) — a u8 column from the fallback path would "
            "be a host_f32 number wearing a u8 label")
    if args.force_scalar:
        if native_jpeg.set_simd(False) != "scalar":
            raise SystemExit("--force-scalar could not pin the scalar "
                             "resample path")
    if args.decode_scaled == "on":
        if not native_jpeg.scaled_supported():
            raise SystemExit(
                "--decode-scaled on: this libdvgg_jpeg.so was built with "
                "-DDVGGF_NO_SCALED (scaled decode compiled out) — rebuild "
                "without the flag or drop --decode-scaled on")
        if native_jpeg.set_scaled(True) != "scaled":
            raise SystemExit("--decode-scaled on could not enable the "
                             "scaled decode path (DVGGF_DECODE_SCALED=0 "
                             "in the environment?)")
    elif args.decode_scaled == "off":
        if native_jpeg.set_scaled(False) != "full":
            raise SystemExit("--decode-scaled off could not pin the "
                             "full-resolution decode path")
    if args.decode_restart == "on":
        if not native_jpeg.restart_supported():
            raise SystemExit(
                "--decode-restart on: this libdvgg_jpeg.so was built with "
                "-DDVGGF_NO_RESTART (restart decode compiled out) — rebuild "
                "without the flag or drop --decode-restart on")
        if native_jpeg.set_restart(True) != "restart":
            raise SystemExit("--decode-restart on could not enable the "
                             "restart entropy path (DVGGF_DECODE_RESTART=0 "
                             "in the environment?)")
        if args.restart_interval < 0:
            raise SystemExit(
                "--decode-restart on without --restart-interval: the "
                "generated sources carry no RSTn markers, so the column "
                "would measure the sequential path wearing a restart label "
                "— add --restart-interval 0 (one marker per MCU row)")
    elif args.decode_restart == "off":
        if native_jpeg.set_restart(False) != "sequential":
            raise SystemExit("--decode-restart off could not pin the "
                             "sequential entropy path")
    if args.restart_fanout != 1:
        if native_jpeg.set_restart_fanout(args.restart_fanout) \
                != args.restart_fanout:
            raise SystemExit(f"--restart-fanout {args.restart_fanout} "
                             "could not be pinned")


def decode_bench_layout(layout: str, data_dir: str, args) -> dict:
    """Native-loader-only per-core decode rate for one layout: min-of-N
    independent windows (the r5 quiet-host protocol), plus the runtime-
    dispatch receipts (which resample path AND which decode strategy
    actually ran, what scales the chooser picked, the scanlines it never
    IDCT'd, the decode-buffer-pool hit rate) and the per-image
    libjpeg-vs-resample phase split over the timed windows — the committed
    'where does the remaining time go' profile."""
    from distributed_vgg_f_tpu.config import DataConfig
    from distributed_vgg_f_tpu.data import build_dataset
    from distributed_vgg_f_tpu.data import native_jpeg
    from distributed_vgg_f_tpu.data.native_jpeg import NativeJpegTrainIterator

    apply_decode_dispatch(args)
    cfg = DataConfig(name="imagenet", data_dir=data_dir,
                     image_size=args.image_size,
                     global_batch_size=args.batch, shuffle_buffer=512,
                     native_threads=args.threads,
                     image_dtype=args.image_dtype,
                     space_to_depth=args.space_to_depth,
                     wire=args.wire,
                     augment=bench_augment_cfg(args))
    ds = build_dataset(cfg, "train", seed=0)
    if not isinstance(ds, NativeJpegTrainIterator):
        raise SystemExit(f"native loader unavailable for layout {layout} — "
                         "decode bench needs it")
    if args.wire == "u8" and ds.image_dtype != "uint8":
        # the ingest layer fell back (e.g. a kill-switch flipped between
        # the dispatch pin and loader creation) — same fail-fast contract
        raise SystemExit("--wire u8: the ingest layer fell back to the "
                         f"host-normalize {ds.image_dtype} wire — refusing "
                         "to print a mislabeled u8 column")
    # synchronous bench loop: recycle the output batch arrays instead of
    # paying a multi-MB numpy allocation + page-fault per batch (part of
    # the r7 buffer-pool surface; refused by device prefetch — see
    # data/native_jpeg.py ownership contract)
    ds.enable_output_buffer_reuse(3)
    prof0 = native_jpeg.decode_profile()
    st0 = native_jpeg.decode_stats()
    rst0 = native_jpeg.restart_stats()
    rates = time_pipeline(ds, args.batch, args.batches, repeats=args.repeats)
    prof1 = native_jpeg.decode_profile()
    st1 = native_jpeg.decode_stats()
    rst1 = native_jpeg.restart_stats()
    kind = native_jpeg.simd_kind()
    ds.close()
    s = _raw_stats([r / max(1, args.threads) for r in rates])
    per_core = s.pop("images_per_sec")
    from distributed_vgg_f_tpu.data.dtypes import wire_bytes_per_pixel
    row = {"layout": layout, "mode": "decode_bench",
           "images_per_sec_per_core": per_core, "threads": args.threads,
           "simd_kind": kind, "image_dtype": args.image_dtype,
           "space_to_depth": args.space_to_depth,
           # wire-format receipt (r8): the host→device format this row
           # shipped and what one image costs through device_put — the u8
           # rows must show <= 0.5x the bf16 wire's bytes/img
           "wire": args.wire,
           "wire_bytes_per_image": wire_bytes_per_pixel(
               args.wire, args.image_dtype) * args.image_size
               * args.image_size,
           "scaled_kind": native_jpeg.scaled_kind(),
           "partial_supported": native_jpeg.partial_supported(),
           "restart_kind": native_jpeg.restart_kind(),
           "out_buffer_ring": 3, **s}
    if args.model:
        # zoo row (r13): the per-model basis key the regression sentinel
        # gates on — the host work is identical across zoo models on the
        # u8 wire (the whole point of the shared contract), the label is
        # what routes the row to its own pin
        row["model"] = args.model
        row["ingest"] = _model_descriptor(args.model).describe()
    if args.augment == "on":
        # augment-on receipt: device-side augmentation armed, host flips
        # DELETED from the decode (the loader's ABI v9 switch) — wire
        # bytes/img above must be unchanged vs the augment-off row
        row["augment"] = bench_augment_cfg(args).describe()
    meta = source_meta(data_dir)
    if meta:
        row["source"] = meta
    if prof0 is not None and prof1 is not None:
        imgs = prof1["images"] - prof0["images"]
        jpeg_s = prof1["jpeg_s"] - prof0["jpeg_s"]
        res_s = prof1["resample_s"] - prof0["resample_s"]
        if imgs > 0 and jpeg_s + res_s > 0:
            row["profile"] = {
                "images": imgs,
                "jpeg_us_per_image": round(jpeg_s / imgs * 1e6, 1),
                "resample_us_per_image": round(res_s / imgs * 1e6, 1),
                "jpeg_fraction": round(jpeg_s / (jpeg_s + res_s), 4),
            }
    if st0 is not None and st1 is not None:
        imgs = st1["images"] - st0["images"]
        hits = st1["pool_hits"] - st0["pool_hits"]
        misses = st1["pool_misses"] - st0["pool_misses"]
        if imgs > 0:
            row["decode_receipt"] = {
                "scale_histogram": {
                    m: st1["scale_histogram"].get(m, 0)
                       - st0["scale_histogram"].get(m, 0)
                    for m in sorted(set(st0["scale_histogram"])
                                    | set(st1["scale_histogram"]))},
                "rows_skipped_per_image": round(
                    (st1["rows_skipped"] - st0["rows_skipped"]) / imgs, 1),
                "rows_truncated_per_image": round(
                    (st1["rows_truncated"] - st0["rows_truncated"]) / imgs,
                    1),
                "pool_hit_rate": (round(hits / (hits + misses), 4)
                                  if hits + misses else None),
                "partial_images": st1["partial_images"]
                                  - st0["partial_images"],
                "full_fallbacks": st1["full_fallbacks"]
                                  - st0["full_fallbacks"],
            }
    if rst0 is not None and rst1 is not None:
        # restart-path engagement receipt (r9): how many images rode the
        # excerpt path, how much entropy work was skipped, and why the rest
        # fell back — a column whose sources never engage is diagnosable
        # from the artifact alone
        d = {k: rst1[k] - rst0[k] for k in rst0}
        total = d["images"] + d["marker_absent"] + d["unsupported"] \
            + d["misaligned"] + d["scan_failures"] \
            + d["excerpt_fallbacks"] + d["no_gain"]
        row["restart_receipt"] = {
            **{k: d[k] for k in
               ("images", "marker_absent", "unsupported", "misaligned",
                "scan_failures", "excerpt_fallbacks", "no_gain",
                "segments_used", "segments_skipped", "fanout_images")},
            "engaged_fraction": (round(d["images"] / total, 4)
                                 if total else None),
            "segments_skipped_fraction": (
                round(d["segments_skipped"]
                      / (d["segments_used"] + d["segments_skipped"]), 4)
                if d["segments_used"] + d["segments_skipped"] else None),
        }
    printable = dict(row)
    printable["images_per_sec_per_core"] = round(per_core, 2)
    for k in ("median", "spread"):
        if k in printable:
            printable[k] = round(printable[k], 4)
    print(json.dumps(printable))
    row["raw_rates"] = rates  # un-divided window rates, for emit_contract
    return row


def snapshot_bench_layout(layout: str, data_dir: str, args,
                          cold_row: dict) -> dict:
    """Warm-vs-cold snapshot-cache row (r9): build the SAME pipeline config
    with `data.snapshot_cache` enabled over a FRESH cache, run the cold
    fill pass (every item decoded once and captured), then time warm
    windows with the same min-of-N protocol. The warm path assembles
    batches from the store on ONE python thread — its rate is already
    per-core — while the cold column is the plain decode row's per-core
    rate from this same session. Hit/miss/bytes receipts come from the
    prefetch/snapshot_* registry counters the stall attributor reads."""
    import shutil

    from distributed_vgg_f_tpu import telemetry
    from distributed_vgg_f_tpu.config import DataConfig, SnapshotCacheConfig
    from distributed_vgg_f_tpu.data import build_dataset
    from distributed_vgg_f_tpu.data.snapshot_cache import (
        SnapshotCachingTrainIterator)

    cache_dir = os.path.join(data_dir, ".dvggf_snapshot_bench")
    shutil.rmtree(cache_dir, ignore_errors=True)  # cold fill is the protocol
    cfg = DataConfig(name="imagenet", data_dir=data_dir,
                     image_size=args.image_size,
                     global_batch_size=args.batch, shuffle_buffer=512,
                     native_threads=args.threads,
                     image_dtype=args.image_dtype,
                     space_to_depth=args.space_to_depth,
                     wire=args.wire,
                     snapshot_cache=SnapshotCacheConfig(
                         enabled=True, dir=cache_dir))
    ds = build_dataset(cfg, "train", seed=0)
    if not isinstance(ds, SnapshotCachingTrainIterator):
        raise SystemExit("--snapshot-cache: the ingest layer did not wrap "
                         "the native loader — nothing to measure")
    n_items = ds._n
    fill_batches = (n_items + args.batch - 1) // args.batch
    t0 = time.monotonic()
    for _ in range(fill_batches):
        next(ds)
    cold_fill_rate = fill_batches * args.batch / (time.monotonic() - t0)
    ds.enable_output_buffer_reuse(3)
    reg = telemetry.get_registry()
    reg.delta("snapshot_bench")  # baseline the counter window
    rates = time_pipeline(ds, args.batch, args.batches, repeats=args.repeats)
    counters = reg.delta("snapshot_bench")
    ds.close()
    hits = counters.get("prefetch/snapshot_hits", 0)
    misses = counters.get("prefetch/snapshot_misses", 0)
    warm = _raw_stats(rates)
    warm_rate = warm.pop("images_per_sec")
    cold = cold_row.get("images_per_sec_per_core")
    row = {
        "layout": layout, "mode": "decode_bench_snapshot",
        "threads": args.threads, "wire": args.wire,
        "image_dtype": args.image_dtype,
        "space_to_depth": args.space_to_depth,
        # warm assembly runs on one python thread: the rate IS per-core
        "warm_images_per_sec_per_core": warm_rate,
        "cold_images_per_sec_per_core": cold,
        "warm_vs_cold": (round(warm_rate / cold, 3) if cold else None),
        "cold_fill_images_per_sec": round(cold_fill_rate, 2),
        "snapshot": {
            "items": n_items,
            "hits": hits, "misses": misses,
            "hit_rate": (round(hits / (hits + misses), 4)
                         if hits + misses else None),
            "bytes_served": counters.get("prefetch/snapshot_bytes", 0),
        },
        **warm,
    }
    meta = source_meta(data_dir)
    if meta:
        row["source"] = meta
    printable = dict(row)
    printable["warm_images_per_sec_per_core"] = round(warm_rate, 2)
    for k in ("median", "spread"):
        if k in printable:
            printable[k] = round(printable[k], 4)
    print(json.dumps(printable))
    return row


def _autotune_harness_windows(state, args, batches: int, windows: int,
                              tuner=None, classify=None) -> list[dict]:
    """Timed windows over the autotune harness pipeline (host-prefetch
    wrapper, caller-owned batches — the wrapper queues references, so the
    bench output ring stays OFF here). With a `tuner`, each window's
    honestly-measured infeed fraction (the consumer does nothing but
    `next()`, so its wait share IS the verdict input) is classified and fed
    to `observe` — the same verdict → observe loop the trainer runs."""
    log = []
    for w in range(windows):
        wait_s = 0.0
        t0 = time.monotonic()
        for _ in range(batches):
            tb = time.monotonic()
            next(state["hp"])
            wait_s += time.monotonic() - tb
        wall = time.monotonic() - t0
        rate = args.batch * batches / wall
        entry = {"window": w + 1, "images_per_sec": round(rate, 2),
                 "_rate": rate}
        if tuner is not None:
            rec = tuner.observe(classify(wall, infeed_wait_s=wait_s))
            if rec.get("actuations"):
                entry["actuations"] = rec["actuations"]
            if rec.get("blocked"):
                entry["blocked"] = rec["blocked"]
            entry["settled"] = rec["settled"]
        log.append(entry)
    return log


def autotune_convergence_layout(layout: str, data_dir: str, args,
                                pinned_row: dict) -> dict:
    """--autotune on (r11): the closed-loop convergence column. The
    controller starts from DELIBERATELY-BAD settings — 1 decode thread,
    host prefetch depth 1 (and, with --autotune-start-wire host, the
    host-normalize wire instead of the requested u8) — and must tune the
    live pipeline back to within reach of the hand-pinned configuration,
    with every actuation in the receipt. The 'off' column runs the SAME
    harness (host-prefetch wrapper, fresh output arrays) at the hand-pinned
    settings, so the pair isolates the controller, not the wrapper."""
    from distributed_vgg_f_tpu.config import AutotuneConfig, DataConfig
    from distributed_vgg_f_tpu.data import autotune as at
    from distributed_vgg_f_tpu.data import build_dataset
    from distributed_vgg_f_tpu.data.native_jpeg import NativeJpegTrainIterator
    from distributed_vgg_f_tpu.data.prefetch import HostPrefetchIterator
    from distributed_vgg_f_tpu.telemetry.stall import classify

    start_host_wire = (args.autotune_start_wire == "host"
                       and args.wire == "u8")
    host_wire = ("host_bf16" if args.image_dtype == "bfloat16"
                 else "host_f32")
    start_wire = host_wire if start_host_wire else args.wire
    max_threads = args.autotune_max_threads or max(
        args.threads, min(16, os.cpu_count() or 1))
    state: dict = {"hp": None, "ds": None, "wire_u8": 0}

    def open_pipeline(wire: str, threads: int, depth: int) -> None:
        cfg = DataConfig(name="imagenet", data_dir=data_dir,
                         image_size=args.image_size,
                         global_batch_size=args.batch, shuffle_buffer=512,
                         native_threads=threads,
                         image_dtype=args.image_dtype,
                         space_to_depth=args.space_to_depth,
                         wire=wire)
        ds = build_dataset(cfg, "train", seed=0)
        if not isinstance(ds, NativeJpegTrainIterator):
            raise SystemExit(f"--autotune on: native loader unavailable "
                             f"for layout {layout}")
        try:
            hp = HostPrefetchIterator(ds, depth=depth)
        except BaseException:
            ds.close()  # never leak a live decode pool on a failed wrap
            raise
        state["ds"] = ds
        state["hp"] = hp
        state["wire_u8"] = 1 if wire == "u8" else 0

    def close_pipeline() -> None:
        if state["hp"] is not None:
            state["hp"].close()  # closes the inner loader too
            state["hp"] = state["ds"] = None

    def warm(n: int = 2) -> None:
        for _ in range(n):
            next(state["hp"])

    def wire_apply(target):
        # position-exact rebuild is the parity contract's price for a live
        # wire switch; a bench window has no stream position to preserve,
        # so the hook simply rebuilds the pipeline on the target wire with
        # the controller's OTHER knob values carried over
        target = 1 if target else 0
        if target == state["wire_u8"]:
            return target
        prev_wire = args.wire if state["wire_u8"] else host_wire
        threads = state["ds"].num_threads() or 1
        depth = state["hp"].depth
        try:
            close_pipeline()
            open_pipeline(args.wire if target else host_wire, threads,
                          depth)
        except (SystemExit, Exception):  # noqa: BLE001 — degrade, don't die
            # the REBUILD failed: the knob reports unavailable, but the
            # HARNESS must stay alive — rebuild the previous wire so the
            # next window has a pipeline to time (a second failure here is
            # a genuinely dead harness and propagates)
            open_pipeline(prev_wire, threads, depth)
            warm()
            return None
        # rebuild succeeded: the wire HAS switched, so a warm() failure
        # here must propagate (killing the bench honestly), never return
        # None — that would record the knob as unavailable-on-the-old-wire
        # while every later window times the new one
        warm()
        return state["wire_u8"]

    # ---- 'off' column: hand-pinned settings, same harness, no controller
    open_pipeline(args.wire, args.threads, 2)
    warm()
    off_log = _autotune_harness_windows(state, args, args.batches,
                                        max(1, args.repeats))
    close_pipeline()
    pinned_best = max(e["_rate"] for e in off_log)

    # ---- 'on' column: crippled start, controller steers
    open_pipeline(start_wire, 1, 1)
    warm()
    acfg = AutotuneConfig(
        enabled=True, k_windows=args.autotune_k,
        cooldown_windows=args.autotune_cooldown,
        settled_after_windows=args.autotune_settle,
        max_threads=max_threads,
        max_prefetch=args.autotune_max_prefetch)
    knobs = [
        at.Knob("native_threads", lambda: state["ds"].num_threads(),
                lambda n: state["ds"].set_num_threads(n),
                1, max_threads, geometric=True),
        # geometric depth steps here: the bench's synthetic consumer is
        # infeed-bound by construction, so the controller ALWAYS walks to
        # the rails — +1 stepping just burns convergence windows proving it
        at.Knob("host_prefetch", lambda: state["hp"].depth,
                lambda n: state["hp"].set_depth(n),
                1, args.autotune_max_prefetch, geometric=True),
    ]
    if start_host_wire:
        knobs.append(at.wire_knob(lambda: state["wire_u8"], wire_apply))
    tuner = at.IngestAutotuner(acfg, knobs)
    window_log: list[dict] = []
    settled_rates: list[float] = []
    for _ in range(args.autotune_max_windows):
        entry = _autotune_harness_windows(state, args, args.batches, 1,
                                          tuner=tuner,
                                          classify=classify)[0]
        entry["window"] = len(window_log) + 1
        window_log.append(entry)
        if entry.get("settled"):
            settled_rates.append(entry["_rate"])
            if len(settled_rates) >= max(1, args.repeats):
                break
    final_wire = args.wire if state["wire_u8"] else start_wire
    final_threads = state["ds"].num_threads()
    final_depth = state["hp"].depth
    close_pipeline()
    receipt = tuner.describe()
    settled_best = max(settled_rates) if settled_rates else None
    row = {
        "layout": layout, "mode": "decode_bench_autotune",
        "wire": final_wire, "image_dtype": args.image_dtype,
        "space_to_depth": args.space_to_depth,
        "threads": args.threads,
        "start": {"native_threads": 1, "host_prefetch": 1,
                  "wire": start_wire},
        "pinned": {"native_threads": args.threads, "host_prefetch": 2,
                   "wire": args.wire},
        "settled_knobs": {"native_threads": final_threads,
                          "host_prefetch": final_depth,
                          "wire": final_wire},
        "pinned_images_per_sec": round(pinned_best, 2),
        "settled_images_per_sec": (round(settled_best, 2)
                                   if settled_rates else None),
        "vs_pinned": (round(settled_best / pinned_best, 4)
                      if settled_rates else None),
        "windows_run": len(window_log),
        "settled": bool(settled_rates),
        "window_log": [{k: v for k, v in e.items() if k != "_rate"}
                       for e in window_log],
        "autotune": receipt,
        # context: the plain decode row this session measured without the
        # harness wrapper (ring-armed sync loop) — the wrapper's own cost
        # is visible as pinned-vs-this, never folded into vs_pinned
        "decode_row_images_per_sec_per_core":
            pinned_row.get("images_per_sec_per_core"),
        "protocol": f"'off' = hand-pinned ({args.threads} threads, depth "
                    f"2, wire {args.wire}) through the same host-prefetch "
                    f"harness, best of {max(1, args.repeats)} windows; "
                    f"'on' = crippled start (1 thread, depth 1, wire "
                    f"{start_wire}) steered by the controller "
                    f"(k={args.autotune_k}, cooldown="
                    f"{args.autotune_cooldown}, settle="
                    f"{args.autotune_settle}), best of "
                    f"{max(1, args.repeats)} settled windows x "
                    f"{args.batches} batches of {args.batch}",
    }
    printable = dict(row)
    printable.pop("window_log", None)
    printable.pop("autotune", None)
    printable["actuations_total"] = receipt["actuations_total"]
    print(json.dumps(printable))
    return row


def autotune_overhead_receipt(data_dir: str, args) -> dict:
    """Controller-overhead receipt (r11 acceptance: inside the <2%
    telemetry budget, same alternating-window protocol as host_r8/
    host_r11): the 'on' column attaches a LIVE controller whose rails are
    pinned to the current settings — it pays the full per-window observe
    path (verdict fold, hysteresis/cooldown/escalation scan, counters,
    gauges, blocked-rail receipts) but can never move a knob, so the
    columns time identical pipelines."""
    from distributed_vgg_f_tpu.config import AutotuneConfig
    from distributed_vgg_f_tpu.data import autotune as at
    from distributed_vgg_f_tpu.telemetry.stall import classify

    batches = args.telemetry_batches

    def one_window(with_controller: bool) -> float:
        ds = _receipt_loader(data_dir, args, "autotune")
        hook = None
        if with_controller:
            acfg = AutotuneConfig(enabled=True, k_windows=2,
                                  cooldown_windows=1,
                                  settled_after_windows=4,
                                  max_threads=max(1, args.threads))
            tuner = at.IngestAutotuner(acfg, [
                at.thread_knob(ds, min_value=args.threads,
                               max_value=args.threads)])

            def hook():
                # a permanently infeed-bound verdict is the controller's
                # WORST case: the full escalation scan runs (and blocks on
                # the pinned rails) every single window
                tuner.observe(classify(1.0, infeed_wait_s=1.0))
        try:
            return time_pipeline(ds, args.batch, batches,
                                 window_hook=hook)[0]
        finally:
            ds.close()

    columns = _alternating_overhead(args, one_window)
    receipt = {
        "mode": "autotune_overhead",
        "autotune_on_images_per_sec_per_core": columns.pop("on_best"),
        "autotune_off_images_per_sec_per_core": columns.pop("off_best"),
        **columns,
        "protocol": f"min-of-{args.repeats} ALTERNATING no-controller/"
                    f"controller windows x {batches} batches of "
                    f"{args.batch}; 'on' runs a live IngestAutotuner with "
                    f"rails pinned to the current settings (full observe "
                    f"path per window, zero actuations possible)",
    }
    print(json.dumps(receipt))
    return receipt


def _receipt_loader(data_dir: str, args, label: str):
    """The instrumented-loop loader both overhead receipts time: the
    production pipeline config, native loader required, bench output ring
    armed — ONE implementation so a protocol fix (ring depth, config
    field) can never diverge between the telemetry and exporter columns."""
    from distributed_vgg_f_tpu.config import DataConfig
    from distributed_vgg_f_tpu.data import build_dataset
    from distributed_vgg_f_tpu.data.native_jpeg import NativeJpegTrainIterator

    cfg = DataConfig(name="imagenet", data_dir=data_dir,
                     image_size=args.image_size,
                     global_batch_size=args.batch, shuffle_buffer=512,
                     native_threads=args.threads,
                     image_dtype=args.image_dtype,
                     space_to_depth=args.space_to_depth,
                     wire=args.wire,
                     augment=bench_augment_cfg(args))
    ds = build_dataset(cfg, "train", seed=0)
    if not isinstance(ds, NativeJpegTrainIterator):
        raise SystemExit(f"{label} receipt needs the native loader")
    ds.enable_output_buffer_reuse(3)
    return ds


def _alternating_overhead(args, one_window) -> dict:
    """min-of-N ALTERNATING off/on windows (fresh loader each; never
    concurrent — two live native loaders would contend for cores): both
    columns sample the same box drift, so the min-of-N difference isolates
    the instrumentation instead of the frequency ramp (the same-session
    control-column lesson from r7). Returns the shared receipt fragment;
    the caller adds its column labels and protocol line."""
    off, on = [], []
    for _ in range(max(1, args.repeats)):
        off.append(one_window(False))
        on.append(one_window(True))
    per_core = max(1, args.threads)
    on_best, off_best = max(on) / per_core, max(off) / per_core
    return {
        "on_best": round(on_best, 2), "off_best": round(off_best, 2),
        "overhead_pct": round((1.0 - on_best / off_best) * 100.0, 2),
        "on": _stats([r / per_core for r in on]),
        "off": _stats([r / per_core for r in off]),
    }


def exporter_overhead_receipt(data_dir: str, args) -> dict:
    """Exporter-scrape-under-load receipt (ISSUE 8): the live /metrics
    endpoint polled at 1 Hz WHILE the flagship decode config runs, vs the
    identical instrumented loop with no exporter — min-of-N ALTERNATING
    windows, the same drift-controlled protocol as the telemetry receipt.
    The 'on' column pays the exporter server thread, the scrape handler's
    full registry sweep (pollers included) per poll, and the GIL the
    handler takes from the decode loop — the whole cost of being
    observable live. Windows are longer than the decode rows
    (--exporter-batches) so a 1 Hz cadence lands multiple scrapes per
    window; the realized scrape count is in the receipt."""
    import threading
    import urllib.request

    from distributed_vgg_f_tpu import telemetry
    from distributed_vgg_f_tpu.telemetry.exporter import TelemetryExporter

    batches = args.exporter_batches
    scrapes = {"n": 0, "errors": 0}

    def one_window(with_exporter: bool) -> float:
        telemetry.configure(enabled=True)
        ds = _receipt_loader(data_dir, args, "exporter")
        it = telemetry.instrument_iterator(ds, counter="bench/batches")
        exporter = None
        stop = threading.Event()
        scraper = None
        if with_exporter:
            exporter = TelemetryExporter()
            port = exporter.start()

            def scrape_loop():
                url = f"http://127.0.0.1:{port}/metrics"
                while not stop.wait(1.0):  # 1 Hz
                    try:
                        with urllib.request.urlopen(url, timeout=5) as r:
                            r.read()
                        scrapes["n"] += 1
                    except Exception:
                        scrapes["errors"] += 1

            scraper = threading.Thread(target=scrape_loop, daemon=True)
            scraper.start()
        try:
            return time_pipeline(it, args.batch, batches)[0]
        finally:
            stop.set()
            if scraper is not None:
                scraper.join(timeout=5)
            if exporter is not None:
                exporter.stop()
            ds.close()

    columns = _alternating_overhead(args, one_window)
    receipt = {
        "mode": "exporter_overhead",
        "exporter_on_images_per_sec_per_core": columns.pop("on_best"),
        "exporter_off_images_per_sec_per_core": columns.pop("off_best"),
        "scrapes": scrapes["n"], "scrape_errors": scrapes["errors"],
        **columns,
        "protocol": f"min-of-{args.repeats} ALTERNATING no-exporter/"
                    f"exporter windows x {batches} batches of "
                    f"{args.batch}; telemetry ON in both columns "
                    f"(instrumented full feed path); 'on' adds the live "
                    f"HTTP exporter + a 1 Hz /metrics scrape (full "
                    f"registry sweep per poll)",
    }
    print(json.dumps(receipt))
    return receipt


def augment_overhead_receipt(data_dir: str, args) -> dict:
    """Fused-augmentation HOST-cost receipt (r13 acceptance): the same
    native decode config with device-side augmentation armed (host flips
    DELETED — the loader's ABI v9 switch) vs the augment-off pipeline,
    min-of-N ALTERNATING windows. The claim under test is 'diversity at
    zero host cost': host img/s/core and wire bytes/image must be
    UNCHANGED within noise with augmentation on (the flip moved into the
    jitted step; everything else — mixup/jitter/photometric — never
    touched the host to begin with). A negative overhead is expected
    noise-floor behavior: the augment-on decode does strictly LESS host
    work (no flipped-destination resample writes)."""
    from distributed_vgg_f_tpu.config import AugmentConfig, DataConfig
    from distributed_vgg_f_tpu.data import build_dataset
    from distributed_vgg_f_tpu.data.native_jpeg import NativeJpegTrainIterator

    # measured per column from the loader each window ACTUALLY constructed
    # (not re-derived from flags): if a future change made the augment-on
    # pipeline fall back to a different wire, the receipt must show it
    shipped = {}

    def one_window(with_augment: bool) -> float:
        cfg = DataConfig(
            name="imagenet", data_dir=data_dir,
            image_size=args.image_size, global_batch_size=args.batch,
            shuffle_buffer=512, native_threads=args.threads,
            image_dtype=args.image_dtype,
            space_to_depth=args.space_to_depth, wire=args.wire,
            augment=(flagship_augment_cfg() if with_augment
                     else AugmentConfig()))
        ds = build_dataset(cfg, "train", seed=0)
        if not isinstance(ds, NativeJpegTrainIterator):
            raise SystemExit("augment receipt needs the native loader")
        if with_augment and ds.hflip:
            raise SystemExit("augment-on window did not disable the "
                             "loader's host flip — the receipt would "
                             "measure the wrong ownership split")
        item_bytes = np.empty(
            (), ds._np_dtype).itemsize * int(np.prod(ds._out_shape))
        shipped[with_augment] = {"image_dtype": ds.image_dtype,
                                 "bytes_per_image": item_bytes}
        ds.enable_output_buffer_reuse(3)
        try:
            return time_pipeline(ds, args.batch, args.batches)[0]
        finally:
            ds.close()

    columns = _alternating_overhead(args, one_window)
    receipt = {
        "mode": "augment_overhead",
        "augment_on_images_per_sec_per_core": columns.pop("on_best"),
        "augment_off_images_per_sec_per_core": columns.pop("off_best"),
        # the wire claim, measured from each column's live loader:
        # byte-identical format either way (flips are a pixel permutation,
        # not a format change; mixup lives on device)
        "wire_bytes_per_image_on": shipped[True]["bytes_per_image"],
        "wire_bytes_per_image_off": shipped[False]["bytes_per_image"],
        "shipped_dtype_on": shipped[True]["image_dtype"],
        "shipped_dtype_off": shipped[False]["image_dtype"],
        **columns,
        "protocol": f"min-of-{args.repeats} ALTERNATING augment-off/"
                    f"augment-on windows x {args.batches} batches of "
                    f"{args.batch}; 'on' = flagship augment recipe "
                    f"(flips+mixup) with host flips deleted via the "
                    f"ABI v9 per-loader switch; wire format identical "
                    f"in both columns",
    }
    print(json.dumps(receipt))
    return receipt


def telemetry_overhead_receipt(data_dir: str, args) -> dict:
    """Telemetry-on vs telemetry-off decode throughput, same min-of-N
    protocol as the decode rows (r7 methodology) — the receipt that backs
    'always-on spans+registry are cheap enough to leave on'.

    The 'on' column pays what the trainer's FULL feed path pays per batch
    (telemetry.instrument_iterator: prefetch worker + consumer + trainer
    loop + step-dispatch wrapper op-for-op — 5 span records, 4 counter
    increments, 2 gauge sets) plus one registry delta pull per window —
    the log-cadence cost, poller included. The 'off' column runs the identical wrapper with
    telemetry disabled (the kill-switch path: attribute-check-and-return),
    so the difference isolates the recording cost, not the wrapper. Windows
    ALTERNATE between the modes so both sample the same box drift; on a
    noisy host the overhead still resolves below the window spread (read
    the spread next to the overhead before believing either sign)."""
    from distributed_vgg_f_tpu import telemetry

    batches = args.telemetry_batches

    def one_window(enabled: bool) -> float:
        telemetry.configure(enabled=enabled)
        ds = _receipt_loader(data_dir, args, "telemetry")
        hook = ((lambda: telemetry.get_registry().delta("bench_receipt"))
                if enabled else None)
        it = telemetry.instrument_iterator(ds, counter="bench/batches")
        try:
            return time_pipeline(it, args.batch, batches,
                                 window_hook=hook)[0]
        finally:
            ds.close()

    try:
        columns = _alternating_overhead(args, one_window)
    finally:
        telemetry.configure(enabled=True)
    receipt = {
        "mode": "telemetry_overhead",
        "telemetry_on_images_per_sec_per_core": columns.pop("on_best"),
        "telemetry_off_images_per_sec_per_core": columns.pop("off_best"),
        **columns,
        "protocol": f"min-of-{args.repeats} ALTERNATING off/on windows x "
                    f"{batches} batches of {args.batch}; per-batch 5 spans"
                    f"+4 counters+2 gauges (full trainer feed path, "
                    f"op-for-op) + one registry delta per on-window",
    }
    print(json.dumps(receipt))
    return receipt


def bench_layout(layout: str, data_dir: str, args) -> list[float]:
    from distributed_vgg_f_tpu.config import DataConfig
    from distributed_vgg_f_tpu.data import build_dataset
    from distributed_vgg_f_tpu.data.native_jpeg import NativeJpegTrainIterator

    # the PRODUCTION iterator, thread count set through the config field the
    # trainer itself uses (native_threads) — no hand-rolled rebuild
    cfg = DataConfig(name="imagenet", data_dir=data_dir,
                     image_size=args.image_size,
                     global_batch_size=args.batch, shuffle_buffer=512,
                     native_threads=args.threads,
                     wire=args.wire)
    native_ds = build_dataset(cfg, "train", seed=0)
    if not isinstance(native_ds, NativeJpegTrainIterator):
        raise SystemExit(
            f"native loader unavailable for layout {layout} — nothing to "
            "compare")
    native_rates = time_pipeline(native_ds, args.batch, args.batches,
                                 repeats=args.repeats)
    native_ds.close()

    tf_ds = build_dataset(dataclasses.replace(cfg, native_jpeg=False),
                          "train", seed=0)
    tf_rates = time_pipeline(tf_ds, args.batch, args.batches,
                             repeats=args.repeats)

    grain_rates = None
    try:
        from distributed_vgg_f_tpu.data.grain_imagenet import (
            GrainTrainIterator)
        grain_ds = build_dataset(
            dataclasses.replace(cfg, backend="grain",
                                grain_workers=args.grain_workers),
            "train", seed=0)
        if isinstance(grain_ds, GrainTrainIterator):
            grain_rates = time_pipeline(grain_ds, args.batch, args.batches,
                                        repeats=args.repeats)
            grain_ds.close()  # reap workers before the next timed phase
        else:
            # build_imagenet fell back internally (grain unavailable) — say
            # so instead of silently dropping the row, and don't leak the
            # fallback iterator's decode threads into the remaining phases
            print(json.dumps({"layout": layout, "pipeline": "grain",
                              "error": "fell back to non-grain backend"}))
            if hasattr(grain_ds, "close"):
                grain_ds.close()
    except Exception as e:  # grain absent — bench the other two anyway
        print(json.dumps({"layout": layout, "pipeline": "grain",
                          "error": repr(e)}))

    print(json.dumps({"layout": layout, "pipeline": "native_libjpeg",
                      "threads": args.threads, **_stats(native_rates)}))
    print(json.dumps({"layout": layout, "pipeline": "tf.data",
                      "threads": "AUTOTUNE", **_stats(tf_rates)}))
    if grain_rates is not None:
        print(json.dumps({"layout": layout, "pipeline": "grain+native_decode",
                          "workers": args.grain_workers,
                          **_stats(grain_rates)}))
    print(json.dumps({"layout": layout,
                      "native_vs_tfdata": round(max(native_rates)
                                                / max(tf_rates), 3),
                      "host_vcpus": os.cpu_count()}))
    return native_rates


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--data-dir", default="/tmp/dvggf_host_bench")
    parser.add_argument("--layout", choices=("imagefolder", "tfrecord",
                                             "both"), default="both")
    parser.add_argument("--batch", type=int, default=64)
    parser.add_argument("--batches", type=int, default=12)
    parser.add_argument("--image-size", type=int, default=224)
    parser.add_argument("--threads", type=int, default=1,
                        help="native worker threads (tf.data AUTOTUNE decides "
                             "its own parallelism; on a 1-vCPU host both are "
                             "effectively single-core)")
    parser.add_argument("--grain-workers", type=int, default=0,
                        help="grain decode worker PROCESSES (0 = in-process)")
    parser.add_argument("--classes", type=int, default=8)
    parser.add_argument("--per-class", type=int, default=64)
    parser.add_argument("--num-files", type=int, default=8)
    parser.add_argument("--per-file", type=int, default=64)
    parser.add_argument("--repeats", type=int, default=3,
                        help="independent timed windows per pipeline; best "
                             "window reported, median/spread recorded")
    parser.add_argument("--update-baseline", action="store_true",
                        help="freeze the tfrecord-layout native per-core "
                             "rate (with median/spread) into "
                             "benchmarks/baseline.json")
    parser.add_argument("--decode-bench", action="store_true",
                        help="native-only per-core decode-rate mode: "
                             "min-of-N windows + simd-dispatch receipt + "
                             "libjpeg/resample phase split")
    parser.add_argument("--json-out", default=None,
                        help="decode-bench: write the full artifact (all "
                             "layout rows + contract value) to this path")
    parser.add_argument("--force-scalar", action="store_true",
                        help="decode-bench: pin the scalar resample kernels "
                             "(the 'before' half of a before/after pair)")
    parser.add_argument("--decode-scaled", choices=("auto", "on", "off"),
                        default="auto",
                        help="decode-bench: pin the libjpeg decode strategy "
                             "— 'on' = DCT-scaled + partial (fails fast on "
                             "a -DDVGGF_NO_SCALED build), 'off' = "
                             "full-resolution (the 'before' column), "
                             "'auto' = library default incl. the "
                             "DVGGF_DECODE_SCALED env kill-switch")
    parser.add_argument("--source-hw", default="320x256", metavar="HxW",
                        help="generated source image size (r4-r6 protocol: "
                             "320x256; the r7 scaled-decode rows use >=448 "
                             "— where DCT scaling has pixels to discard)")
    parser.add_argument("--source-kind", choices=("noise", "textured"),
                        default="noise",
                        help="source content: 'noise' (r4-r6 protocol; "
                             "adversarial ~0.9 B/px entropy) or 'textured' "
                             "(gaussian-filtered, ~0.4 B/px — the natural-"
                             "image-class density; see _source_image)")
    parser.add_argument("--restart-interval", type=int, default=-1,
                        metavar="MCUS",
                        help="losslessly transcode the generated sources to "
                             "carry RSTn restart markers every N MCUs (0 = "
                             "one marker per MCU row — the row-trimmable "
                             "layout; -1 = plain sources, the pre-r9 "
                             "protocol). Keyed into the source cache dir "
                             "and recorded in the sentinel")
    parser.add_argument("--decode-restart", choices=("auto", "on", "off"),
                        default="auto",
                        help="decode-bench: pin the entropy-decode strategy "
                             "— 'on' = restart-marker excerpt decode (fails "
                             "fast on a -DDVGGF_NO_RESTART build, or when "
                             "the sources carry no markers), 'off' = "
                             "sequential (the 'before' column), 'auto' = "
                             "library default incl. the "
                             "DVGGF_DECODE_RESTART env kill-switch")
    parser.add_argument("--restart-fanout", type=int, default=1,
                        help="intra-image fan-out width for the restart "
                             "path (latency lever; per-core throughput "
                             "columns keep the default 1)")
    parser.add_argument("--model", default=None,
                        choices=("vggf", "vgg16", "resnet50", "vit_s16"),
                        help="zoo row (r13): derive wire/space-to-depth "
                             "from the model's ingest descriptor "
                             "(models/ingest.py) and label the row with "
                             "the per-model basis key the regression "
                             "sentinel gates on")
    parser.add_argument("--augment", choices=("off", "on"), default="off",
                        help="r13: run the decode columns with device-side "
                             "augmentation armed — host flips deleted via "
                             "the ABI v9 per-loader switch; the row "
                             "carries the augment receipt and gates "
                             "against the augment-on pin")
    parser.add_argument("--augment-receipt", action="store_true",
                        help="r13 acceptance receipt: min-of-N ALTERNATING "
                             "augment-off/on windows proving host "
                             "img/s/core and wire bytes/image are "
                             "unchanged with augmentation on")
    parser.add_argument("--snapshot-cache", action="store_true",
                        help="decode-bench: additionally run the snapshot-"
                             "cache warm-vs-cold protocol (cold fill pass "
                             "over a fresh cache, then min-of-N warm "
                             "windows; hit/miss receipts from the "
                             "prefetch/snapshot_* counters)")
    parser.add_argument("--autotune", choices=("off", "on"), default="off",
                        help="decode-bench: append the closed-loop "
                             "convergence column pair (r11) — 'off' = "
                             "hand-pinned settings through the harness, "
                             "'on' = crippled start (1 thread, depth 1) "
                             "steered by the IngestAutotuner, actuation "
                             "log + settled rate in the artifact")
    parser.add_argument("--autotune-max-windows", type=int, default=48,
                        help="convergence column: hard window budget "
                             "before giving up unsettled (the artifact "
                             "then refuses sentinel gating)")
    parser.add_argument("--autotune-k", type=int, default=2,
                        help="controller hysteresis: consecutive verdicts "
                             "before an actuation (bench default 2; the "
                             "trainer default is 3)")
    parser.add_argument("--autotune-cooldown", type=int, default=1,
                        help="controller cooldown windows after an "
                             "actuation")
    parser.add_argument("--autotune-settle", type=int, default=4,
                        help="actuation-free windows before the "
                             "controller reports settled")
    parser.add_argument("--autotune-max-threads", type=int, default=0,
                        help="thread-knob rail (0 = max(--threads, "
                             "min(16, vCPUs)))")
    parser.add_argument("--autotune-max-prefetch", type=int, default=8,
                        help="host-prefetch-depth knob rail")
    parser.add_argument("--autotune-start-wire", choices=("same", "host"),
                        default="same",
                        help="convergence start wire: 'same' keeps --wire; "
                             "'host' (with --wire u8) starts on the "
                             "host-normalize wire and lets the controller "
                             "actuate the u8 downgrade (the wire knob's "
                             "receipt run)")
    parser.add_argument("--autotune-receipt", action="store_true",
                        help="decode-bench: additionally run the "
                             "controller-overhead receipt (alternating "
                             "no-controller/controller windows, rails "
                             "pinned — the <2%% budget proof)")
    parser.add_argument("--telemetry-batches", type=int, default=8,
                        help="decode-bench: batches per telemetry-overhead "
                             "receipt window (telemetry-on vs -off, same "
                             "min-of-N protocol)")
    parser.add_argument("--no-telemetry-receipt", action="store_true",
                        help="decode-bench: skip the telemetry-overhead "
                             "receipt")
    parser.add_argument("--exporter-receipt", action="store_true",
                        help="decode-bench: additionally run the exporter "
                             "scrape-under-load receipt (live /metrics "
                             "polled at 1 Hz during alternating windows)")
    parser.add_argument("--exporter-batches", type=int, default=48,
                        help="batches per exporter-receipt window (longer "
                             "than the decode rows so a 1 Hz scrape "
                             "cadence lands several polls per window)")
    parser.add_argument("--image-dtype", choices=("float32", "bfloat16"),
                        default="float32",
                        help="decode-bench output dtype; the flagship's "
                             "judged e2e path feeds bfloat16 (bench.py)")
    parser.add_argument("--wire", choices=("auto", "host_f32", "host_bf16",
                                           "u8"),
                        default="auto",
                        help="host→device ingest wire (r8): host_f32/"
                             "host_bf16 = host-normalized batches (implies "
                             "--image-dtype), u8 = raw resampled uint8 "
                             "pixels (1 B/px; normalize/cast/space-to-depth "
                             "move to the device-finish prologue — fails "
                             "fast when the native u8 wire is compiled out "
                             "or kill-switched). 'auto' derives the host "
                             "wire from --image-dtype (pre-r8 behavior)")
    parser.add_argument("--space-to-depth", action="store_true",
                        help="decode-bench: emit the VGG-F stem's packed "
                             "4x4 space-to-depth layout (the flagship "
                             "ingest contract)")
    args = parser.parse_args()
    try:
        h, w = (int(v) for v in args.source_hw.lower().split("x"))
        if h < 16 or w < 16:
            raise ValueError
        args.source_hw = (h, w)
    except ValueError:
        raise SystemExit(f"--source-hw wants HxW (e.g. 448x448), got "
                         f"{args.source_hw!r}")
    apply_model_descriptor(args)
    resolve_wire(args)

    def _src_dir(layout: str) -> str:
        # cache keyed by the full source config: a 448px textured run must
        # never silently reuse a 320x256 noise cache, and a restart-marked
        # run must never reuse plain sources (the sentinel's meta is the
        # receipt, the dir name is the key)
        h, w = args.source_hw
        tag = "" if (args.source_hw == (320, 256)
                     and args.source_kind == "noise") \
            else f"_{args.source_kind}_{h}x{w}"
        if args.restart_interval >= 0:
            tag += f"_rst{args.restart_interval}"
        return os.path.join(args.data_dir, layout + tag)

    if args.decode_bench:
        rows = []
        receipt_dir = None
        autotune_receipt_obj = None
        if args.layout in ("imagefolder", "both"):
            d = _src_dir("imagefolder")
            ensure_imagefolder(d, classes=args.classes,
                               per_class=args.per_class,
                               source_hw=args.source_hw,
                               source_kind=args.source_kind,
                               restart_interval=args.restart_interval)
            row = decode_bench_layout("imagefolder", d, args)
            rows.append(row)
            if args.snapshot_cache:
                rows.append(snapshot_bench_layout("imagefolder", d, args,
                                                  row))
            if args.autotune == "on":
                at_row = autotune_convergence_layout("imagefolder", d,
                                                     args, row)
                rows.append(at_row)
                autotune_receipt_obj = at_row["autotune"]
            receipt_dir = d
        if args.layout in ("tfrecord", "both"):
            d = _src_dir("tfrecord")
            ensure_tfrecords(d, num_files=args.num_files,
                             per_file=args.per_file,
                             source_hw=args.source_hw,
                             source_kind=args.source_kind,
                             restart_interval=args.restart_interval)
            row = decode_bench_layout("tfrecord", d, args)
            rows.append(row)
            if args.snapshot_cache:
                rows.append(snapshot_bench_layout("tfrecord", d, args, row))
            if args.autotune == "on":
                at_row = autotune_convergence_layout("tfrecord", d, args,
                                                     row)
                rows.append(at_row)
                autotune_receipt_obj = at_row["autotune"]
            receipt_dir = d  # prefer the contract layout's sources
            # the frozen contract metric is defined on the f32-unpacked
            # config over 320x256 noise sources (what r4/r5 froze): a
            # bf16/space-to-depth/other-source run must not print a
            # config-mismatched vs_baseline — and must NEVER re-freeze
            # the baseline from a different basis
            baseline_config = (args.image_dtype == "float32"
                               and args.wire == "host_f32"
                               and not args.space_to_depth
                               and args.source_hw == (320, 256)
                               and args.source_kind == "noise"
                               and args.restart_interval < 0)
            if baseline_config:
                emit_contract(row["raw_rates"], args.threads,
                              args.update_baseline)
            elif args.update_baseline:
                raise SystemExit(
                    "--update-baseline refuses a non-baseline config: the "
                    f"frozen {HOST_METRIC} baseline is defined on float32 "
                    "without space_to_depth over 320x256 noise sources")
        receipt = None
        if receipt_dir is not None and not args.no_telemetry_receipt:
            receipt = telemetry_overhead_receipt(receipt_dir, args)
        exporter_receipt = None
        if receipt_dir is not None and args.exporter_receipt:
            exporter_receipt = exporter_overhead_receipt(receipt_dir, args)
        autotune_overhead = None
        if receipt_dir is not None and args.autotune_receipt:
            autotune_overhead = autotune_overhead_receipt(receipt_dir, args)
        augment_overhead = None
        if receipt_dir is not None and args.augment_receipt:
            augment_overhead = augment_overhead_receipt(receipt_dir, args)
        if args.json_out:
            # provisioning reads the LOWER committed per-layout value (the
            # conservative convention HOST_DECODE_RATE_R5 set)
            from distributed_vgg_f_tpu.telemetry.schema import (
                SCHEMA_VERSION)
            artifact = {
                "schema_version": SCHEMA_VERSION,
                "metric": HOST_METRIC,
                "value": round(min(r["images_per_sec_per_core"]
                                   for r in rows
                                   if r.get("mode") == "decode_bench"), 2),
                "unit": "images/sec/core",
                "protocol": f"min-of-{args.repeats} windows, "
                            f"{args.batches} batches of {args.batch} at "
                            f"image_size {args.image_size}, "
                            f"threads {args.threads}, wire {args.wire}, "
                            f"sources {args.source_kind} "
                            f"{args.source_hw[0]}x{args.source_hw[1]}",
                "host_vcpus": os.cpu_count(),
                "layouts": [{k: v for k, v in r.items()
                             if k != "raw_rates"} for r in rows],
            }
            if receipt is not None:
                artifact["telemetry_overhead"] = receipt
            if exporter_receipt is not None:
                artifact["exporter_overhead"] = exporter_receipt
            if autotune_receipt_obj is not None:
                # artifact-level settled-state receipt: the regression
                # sentinel REFUSES to gate this artifact unless the
                # controller had settled (telemetry/regress.py)
                artifact["autotune"] = autotune_receipt_obj
            if autotune_overhead is not None:
                artifact["autotune_overhead"] = autotune_overhead
            if augment_overhead is not None:
                artifact["augment_overhead"] = augment_overhead
            os.makedirs(os.path.dirname(args.json_out) or ".",
                        exist_ok=True)
            with open(args.json_out, "w") as f:
                json.dump(artifact, f, indent=1)
        return

    # full-pipeline mode honors the same dispatch pins (--force-scalar,
    # --decode-scaled) with the same fail-fast contract as decode-bench —
    # a rate printed under a silently-ignored pin is a wrong number
    # wearing a right label
    apply_decode_dispatch(args)
    # ... and the same frozen-basis gate: the contract line/baseline are
    # defined on the host_f32 wire over 320x256 noise only
    baseline_config = (args.source_hw == (320, 256)
                       and args.source_kind == "noise"
                       and args.wire == "host_f32"
                       and args.restart_interval < 0)
    if args.update_baseline and not baseline_config:
        raise SystemExit(
            f"--update-baseline refuses a non-baseline source config: the "
            f"frozen {HOST_METRIC} baseline is defined on the host_f32 "
            "wire over 320x256 noise sources")
    if args.layout in ("imagefolder", "both"):
        d = _src_dir("imagefolder")
        ensure_imagefolder(d, classes=args.classes, per_class=args.per_class,
                           source_hw=args.source_hw,
                           source_kind=args.source_kind)
        bench_layout("imagefolder", d, args)
    if args.layout in ("tfrecord", "both"):
        d = _src_dir("tfrecord")
        ensure_tfrecords(d, num_files=args.num_files, per_file=args.per_file,
                         source_hw=args.source_hw,
                         source_kind=args.source_kind)
        native_rates = bench_layout("tfrecord", d, args)
        if baseline_config:
            emit_contract(native_rates, args.threads, args.update_baseline)


if __name__ == "__main__":
    main()
