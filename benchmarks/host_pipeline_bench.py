"""Host input-path shootout: native loader vs tf.data, on both ImageNet
layouts (raw-JPEG imagefolder and TFRecord shards).

Generates local fake sources once, then times the train pipelines (same
sources, same crop distribution, same normalize) at a fixed thread count.
The host path bounds end-to-end training (README: the measured infeed
stall), so per-core decode rate is the number that matters.

Usage: python benchmarks/host_pipeline_bench.py [--layout both]
       [--threads 1] [--batches 12]
Prints one JSON line per (layout, pipeline) plus a ratio line per layout.

--decode-bench runs the native-loader-only per-core decode-rate protocol
(min-of-N windows, the r5 quiet-host methodology) and — with --json-out —
writes the committed artifact the provisioning model's measured constant is
re-derived from (utils/scaling_model.py HOST_DECODE_RATE_*): per-core rate
with median/spread, WHICH resample path ran (simd_kind — the runtime-
dispatch receipt), and the libjpeg-vs-resample phase split that says where
the remaining time goes. --force-scalar pins the scalar kernels for the
before/after pair.

The tfrecord-layout native per-core rate is also emitted as a contract line
(`host_native_decode_images_per_sec_per_core`, with `vs_baseline` against
benchmarks/baseline.json; freeze with --update-baseline). This is the frozen
e2e-tracking metric (VERDICT r2 #6): on this 1-vCPU host the full-path e2e
bench is ~entirely host-bound (infeed stall ≈ 0.99), so its ratio tracks
host noise; the per-core decode rate is the signal-bearing number that
transfers to real many-core TPU-VM hosts.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def _generated(root: str) -> bool:
    # generation writes a sentinel LAST: a dir without one is a partial
    # (interrupted) generation and must be rebuilt, not silently reused
    return os.path.exists(os.path.join(root, ".complete"))


def _finish(root: str) -> None:
    with open(os.path.join(root, ".complete"), "w") as f:
        f.write("ok\n")


def ensure_imagefolder(root: str, *, classes: int = 8, per_class: int = 64,
                       source_hw=(320, 256)) -> None:
    if _generated(root):
        return
    import tensorflow as tf
    rng = np.random.default_rng(0)
    h, w = source_hw
    for c in range(classes):
        d = os.path.join(root, "train", f"n{c:08d}")
        os.makedirs(d, exist_ok=True)
        for i in range(per_class):
            img = rng.integers(0, 256, size=(h, w, 3)).astype(np.uint8)
            with open(os.path.join(d, f"{c}_{i}.JPEG"), "wb") as f:
                f.write(tf.io.encode_jpeg(img, quality=90).numpy())
    _finish(root)


def ensure_tfrecords(root: str, *, num_files: int = 8, per_file: int = 64,
                     source_hw=(320, 256)) -> None:
    if _generated(root):
        return
    import tensorflow as tf
    rng = np.random.default_rng(0)
    h, w = source_hw
    os.makedirs(root, exist_ok=True)
    for i in range(num_files):
        path = os.path.join(root, f"train-{i:05d}-of-{num_files:05d}")
        with tf.io.TFRecordWriter(path) as writer:
            for _ in range(per_file):
                img = rng.integers(0, 256, size=(h, w, 3)).astype(np.uint8)
                jpeg = tf.io.encode_jpeg(img, quality=90).numpy()
                ex = tf.train.Example(features=tf.train.Features(feature={
                    "image/encoded": tf.train.Feature(
                        bytes_list=tf.train.BytesList(value=[jpeg])),
                    "image/class/label": tf.train.Feature(
                        int64_list=tf.train.Int64List(
                            value=[int(rng.integers(1, 1001))])),
                }))
                writer.write(ex.SerializeToString())
    _finish(root)


def time_pipeline(ds, batch: int, batches: int, warmup: int = 2,
                  repeats: int = 1) -> list[float]:
    """N independent timed windows (min-of-N-time methodology, VERDICT r3
    #4): on a shared 1-vCPU host the best window is the least-contaminated
    sample and the spread is the error bar."""
    for _ in range(warmup):
        next(ds)
    rates = []
    for _ in range(max(1, repeats)):
        t0 = time.monotonic()
        for _ in range(batches):
            next(ds)
        rates.append(batch * batches / (time.monotonic() - t0))
    return rates


def _raw_stats(rates: list[float]) -> dict:
    """Full-precision min-of-N statistics — the ONE implementation every
    consumer (display lines, frozen baseline, contract line) derives from;
    rounding is a presentation decision at each call site."""
    import statistics
    out = {"images_per_sec": max(rates)}
    if len(rates) > 1:
        med = statistics.median(rates)
        out["repeats"] = len(rates)
        out["median"] = med
        out["spread"] = (max(rates) - min(rates)) / med
    return out


def _stats(rates: list[float]) -> dict:
    """Display-rounded form of _raw_stats for the per-pipeline lines."""
    s = _raw_stats(rates)
    for k, nd in (("images_per_sec", 1), ("median", 1), ("spread", 4)):
        if k in s:
            s[k] = round(s[k], nd)
    return s


REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
HOST_METRIC = "host_native_decode_images_per_sec_per_core"


def emit_contract(native_rates: list[float], threads: int,
                  update_baseline: bool) -> None:
    """The judged-style contract line for the frozen host metric — best of
    N windows, with median/spread recorded (and frozen alongside the value
    on --update-baseline, so later ratios have an error bar to read).
    Statistics come from the same _raw_stats used for the per-pipeline
    lines — one methodology, one implementation; the FROZEN value keeps
    full precision (rounding it would make re-runs of identical rates read
    vs_baseline != 1.0 — code-review r4)."""
    s = _raw_stats([r / max(1, threads) for r in native_rates])  # per-core
    per_core = s.pop("images_per_sec")
    path = os.path.join(REPO, "benchmarks", "baseline.json")
    baselines = {}
    if os.path.exists(path):
        with open(path) as f:
            baselines = json.load(f)
    vs = 1.0
    if update_baseline:
        baselines[HOST_METRIC] = {
            "metric": HOST_METRIC, "value": per_core,
            **{k: (round(v, 4) if isinstance(v, float) else v)
               for k, v in s.items()},
            "platform": "host-cpu", "host_vcpus": os.cpu_count(),
            "threads": threads}
        with open(path, "w") as f:
            json.dump(baselines, f)
    elif baselines.get(HOST_METRIC, {}).get("value"):
        vs = per_core / baselines[HOST_METRIC]["value"]
    try:  # the dispatch receipt: which resample path produced this number
        from distributed_vgg_f_tpu.data.native_jpeg import simd_kind
        kind = simd_kind()
    except Exception:
        kind = None
    print(json.dumps({"metric": HOST_METRIC, "value": round(per_core, 2),
                      "unit": "images/sec/core",
                      "vs_baseline": round(vs, 4),
                      "simd_kind": kind,
                      **{k: (round(v, 4) if isinstance(v, float) else v)
                         for k, v in s.items()}}))


def decode_bench_layout(layout: str, data_dir: str, args) -> dict:
    """Native-loader-only per-core decode rate for one layout: min-of-N
    independent windows (the r5 quiet-host protocol), plus the runtime-
    dispatch receipt (which resample path actually ran) and the per-image
    libjpeg-vs-resample phase split over the timed windows — the committed
    'where does the remaining time go' profile."""
    from distributed_vgg_f_tpu.config import DataConfig
    from distributed_vgg_f_tpu.data import build_dataset
    from distributed_vgg_f_tpu.data import native_jpeg
    from distributed_vgg_f_tpu.data.native_jpeg import NativeJpegTrainIterator

    if args.force_scalar:
        native_jpeg.set_simd(False)
    cfg = DataConfig(name="imagenet", data_dir=data_dir,
                     image_size=args.image_size,
                     global_batch_size=args.batch, shuffle_buffer=512,
                     native_threads=args.threads,
                     image_dtype=args.image_dtype,
                     space_to_depth=args.space_to_depth)
    ds = build_dataset(cfg, "train", seed=0)
    if not isinstance(ds, NativeJpegTrainIterator):
        raise SystemExit(f"native loader unavailable for layout {layout} — "
                         "decode bench needs it")
    prof0 = native_jpeg.decode_profile()
    rates = time_pipeline(ds, args.batch, args.batches, repeats=args.repeats)
    prof1 = native_jpeg.decode_profile()
    kind = native_jpeg.simd_kind()
    ds.close()
    s = _raw_stats([r / max(1, args.threads) for r in rates])
    per_core = s.pop("images_per_sec")
    row = {"layout": layout, "mode": "decode_bench",
           "images_per_sec_per_core": per_core, "threads": args.threads,
           "simd_kind": kind, "image_dtype": args.image_dtype,
           "space_to_depth": args.space_to_depth, **s}
    if prof0 is not None and prof1 is not None:
        imgs = prof1["images"] - prof0["images"]
        jpeg_s = prof1["jpeg_s"] - prof0["jpeg_s"]
        res_s = prof1["resample_s"] - prof0["resample_s"]
        if imgs > 0 and jpeg_s + res_s > 0:
            row["profile"] = {
                "images": imgs,
                "jpeg_us_per_image": round(jpeg_s / imgs * 1e6, 1),
                "resample_us_per_image": round(res_s / imgs * 1e6, 1),
                "jpeg_fraction": round(jpeg_s / (jpeg_s + res_s), 4),
            }
    printable = dict(row)
    printable["images_per_sec_per_core"] = round(per_core, 2)
    for k in ("median", "spread"):
        if k in printable:
            printable[k] = round(printable[k], 4)
    print(json.dumps(printable))
    row["raw_rates"] = rates  # un-divided window rates, for emit_contract
    return row


def bench_layout(layout: str, data_dir: str, args) -> list[float]:
    from distributed_vgg_f_tpu.config import DataConfig
    from distributed_vgg_f_tpu.data import build_dataset
    from distributed_vgg_f_tpu.data.native_jpeg import NativeJpegTrainIterator

    # the PRODUCTION iterator, thread count set through the config field the
    # trainer itself uses (native_threads) — no hand-rolled rebuild
    cfg = DataConfig(name="imagenet", data_dir=data_dir,
                     image_size=args.image_size,
                     global_batch_size=args.batch, shuffle_buffer=512,
                     native_threads=args.threads)
    native_ds = build_dataset(cfg, "train", seed=0)
    if not isinstance(native_ds, NativeJpegTrainIterator):
        raise SystemExit(
            f"native loader unavailable for layout {layout} — nothing to "
            "compare")
    native_rates = time_pipeline(native_ds, args.batch, args.batches,
                                 repeats=args.repeats)
    native_ds.close()

    tf_ds = build_dataset(dataclasses.replace(cfg, native_jpeg=False),
                          "train", seed=0)
    tf_rates = time_pipeline(tf_ds, args.batch, args.batches,
                             repeats=args.repeats)

    grain_rates = None
    try:
        from distributed_vgg_f_tpu.data.grain_imagenet import (
            GrainTrainIterator)
        grain_ds = build_dataset(
            dataclasses.replace(cfg, backend="grain",
                                grain_workers=args.grain_workers),
            "train", seed=0)
        if isinstance(grain_ds, GrainTrainIterator):
            grain_rates = time_pipeline(grain_ds, args.batch, args.batches,
                                        repeats=args.repeats)
            grain_ds.close()  # reap workers before the next timed phase
        else:
            # build_imagenet fell back internally (grain unavailable) — say
            # so instead of silently dropping the row, and don't leak the
            # fallback iterator's decode threads into the remaining phases
            print(json.dumps({"layout": layout, "pipeline": "grain",
                              "error": "fell back to non-grain backend"}))
            if hasattr(grain_ds, "close"):
                grain_ds.close()
    except Exception as e:  # grain absent — bench the other two anyway
        print(json.dumps({"layout": layout, "pipeline": "grain",
                          "error": repr(e)}))

    print(json.dumps({"layout": layout, "pipeline": "native_libjpeg",
                      "threads": args.threads, **_stats(native_rates)}))
    print(json.dumps({"layout": layout, "pipeline": "tf.data",
                      "threads": "AUTOTUNE", **_stats(tf_rates)}))
    if grain_rates is not None:
        print(json.dumps({"layout": layout, "pipeline": "grain+native_decode",
                          "workers": args.grain_workers,
                          **_stats(grain_rates)}))
    print(json.dumps({"layout": layout,
                      "native_vs_tfdata": round(max(native_rates)
                                                / max(tf_rates), 3),
                      "host_vcpus": os.cpu_count()}))
    return native_rates


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--data-dir", default="/tmp/dvggf_host_bench")
    parser.add_argument("--layout", choices=("imagefolder", "tfrecord",
                                             "both"), default="both")
    parser.add_argument("--batch", type=int, default=64)
    parser.add_argument("--batches", type=int, default=12)
    parser.add_argument("--image-size", type=int, default=224)
    parser.add_argument("--threads", type=int, default=1,
                        help="native worker threads (tf.data AUTOTUNE decides "
                             "its own parallelism; on a 1-vCPU host both are "
                             "effectively single-core)")
    parser.add_argument("--grain-workers", type=int, default=0,
                        help="grain decode worker PROCESSES (0 = in-process)")
    parser.add_argument("--classes", type=int, default=8)
    parser.add_argument("--per-class", type=int, default=64)
    parser.add_argument("--num-files", type=int, default=8)
    parser.add_argument("--per-file", type=int, default=64)
    parser.add_argument("--repeats", type=int, default=3,
                        help="independent timed windows per pipeline; best "
                             "window reported, median/spread recorded")
    parser.add_argument("--update-baseline", action="store_true",
                        help="freeze the tfrecord-layout native per-core "
                             "rate (with median/spread) into "
                             "benchmarks/baseline.json")
    parser.add_argument("--decode-bench", action="store_true",
                        help="native-only per-core decode-rate mode: "
                             "min-of-N windows + simd-dispatch receipt + "
                             "libjpeg/resample phase split")
    parser.add_argument("--json-out", default=None,
                        help="decode-bench: write the full artifact (all "
                             "layout rows + contract value) to this path")
    parser.add_argument("--force-scalar", action="store_true",
                        help="decode-bench: pin the scalar resample kernels "
                             "(the 'before' half of a before/after pair)")
    parser.add_argument("--image-dtype", choices=("float32", "bfloat16"),
                        default="float32",
                        help="decode-bench output dtype; the flagship's "
                             "judged e2e path feeds bfloat16 (bench.py)")
    parser.add_argument("--space-to-depth", action="store_true",
                        help="decode-bench: emit the VGG-F stem's packed "
                             "4x4 space-to-depth layout (the flagship "
                             "ingest contract)")
    args = parser.parse_args()

    if args.decode_bench:
        rows = []
        if args.layout in ("imagefolder", "both"):
            d = os.path.join(args.data_dir, "imagefolder")
            ensure_imagefolder(d, classes=args.classes,
                               per_class=args.per_class)
            rows.append(decode_bench_layout("imagefolder", d, args))
        if args.layout in ("tfrecord", "both"):
            d = os.path.join(args.data_dir, "tfrecord")
            ensure_tfrecords(d, num_files=args.num_files,
                             per_file=args.per_file)
            row = decode_bench_layout("tfrecord", d, args)
            rows.append(row)
            # the frozen contract metric is defined on the f32-unpacked
            # config (what r4/r5 froze): a bf16/space-to-depth run must
            # not print a config-mismatched vs_baseline — and must NEVER
            # re-freeze the baseline from a different basis
            if args.image_dtype == "float32" and not args.space_to_depth:
                emit_contract(row["raw_rates"], args.threads,
                              args.update_baseline)
            elif args.update_baseline:
                raise SystemExit(
                    "--update-baseline refuses a non-f32-unpacked config: "
                    f"the frozen {HOST_METRIC} baseline is defined on "
                    "float32 without space_to_depth")
        if args.json_out:
            # provisioning reads the LOWER committed per-layout value (the
            # conservative convention HOST_DECODE_RATE_R5 set)
            artifact = {
                "metric": HOST_METRIC,
                "value": round(min(r["images_per_sec_per_core"]
                                   for r in rows), 2),
                "unit": "images/sec/core",
                "protocol": f"min-of-{args.repeats} windows, "
                            f"{args.batches} batches of {args.batch} at "
                            f"image_size {args.image_size}, "
                            f"threads {args.threads}",
                "host_vcpus": os.cpu_count(),
                "layouts": [{k: v for k, v in r.items()
                             if k != "raw_rates"} for r in rows],
            }
            os.makedirs(os.path.dirname(args.json_out) or ".",
                        exist_ok=True)
            with open(args.json_out, "w") as f:
                json.dump(artifact, f, indent=1)
        return

    if args.layout in ("imagefolder", "both"):
        d = os.path.join(args.data_dir, "imagefolder")
        ensure_imagefolder(d, classes=args.classes, per_class=args.per_class)
        bench_layout("imagefolder", d, args)
    if args.layout in ("tfrecord", "both"):
        d = os.path.join(args.data_dir, "tfrecord")
        ensure_tfrecords(d, num_files=args.num_files, per_file=args.per_file)
        native_rates = bench_layout("tfrecord", d, args)
        emit_contract(native_rates, args.threads, args.update_baseline)


if __name__ == "__main__":
    main()
