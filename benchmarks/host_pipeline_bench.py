"""Host input-path shootout: native libjpeg loader vs tf.data JPEG pipeline.

Generates a local fake raw-JPEG imagefolder once, then times both train
pipelines (same sources, same crop distribution, same normalize) at a fixed
thread count. The host path bounds end-to-end training (README: the measured
infeed stall), so per-core decode rate is the number that matters.

Usage: python benchmarks/host_pipeline_bench.py [--threads 1] [--batches 12]
Prints one JSON line per pipeline plus a ratio line.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def ensure_imagefolder(root: str, *, classes: int = 8, per_class: int = 64,
                       source_hw=(320, 256)) -> None:
    if os.path.isdir(os.path.join(root, "train")):
        return
    import tensorflow as tf
    rng = np.random.default_rng(0)
    h, w = source_hw
    for c in range(classes):
        d = os.path.join(root, "train", f"n{c:08d}")
        os.makedirs(d)
        for i in range(per_class):
            img = rng.integers(0, 256, size=(h, w, 3)).astype(np.uint8)
            with open(os.path.join(d, f"{c}_{i}.JPEG"), "wb") as f:
                f.write(tf.io.encode_jpeg(img, quality=90).numpy())


def time_pipeline(ds, batch: int, batches: int, warmup: int = 2) -> float:
    for _ in range(warmup):
        next(ds)
    t0 = time.monotonic()
    for _ in range(batches):
        next(ds)
    return batch * batches / (time.monotonic() - t0)


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--data-dir", default="/tmp/dvggf_host_bench")
    parser.add_argument("--batch", type=int, default=64)
    parser.add_argument("--batches", type=int, default=12)
    parser.add_argument("--image-size", type=int, default=224)
    parser.add_argument("--threads", type=int, default=1,
                        help="native worker threads (tf.data AUTOTUNE decides "
                             "its own parallelism; on a 1-vCPU host both are "
                             "effectively single-core)")
    args = parser.parse_args()

    ensure_imagefolder(args.data_dir)

    import dataclasses

    from distributed_vgg_f_tpu.config import DataConfig
    from distributed_vgg_f_tpu.data import build_dataset

    cfg = DataConfig(name="imagenet", data_dir=args.data_dir,
                     image_size=args.image_size,
                     global_batch_size=args.batch, shuffle_buffer=512)

    native_ds = build_dataset(cfg, "train", seed=0)
    from distributed_vgg_f_tpu.data.native_jpeg import NativeJpegTrainIterator
    if not isinstance(native_ds, NativeJpegTrainIterator):
        raise SystemExit("native jpeg loader unavailable — nothing to compare")
    # rebuild pinned to the requested thread count for a fair per-core number
    native_ds.close()
    files, labels = [], []
    troot = os.path.join(args.data_dir, "train")
    for idx, cls in enumerate(sorted(os.listdir(troot))):
        for fn in sorted(os.listdir(os.path.join(troot, cls))):
            files.append(os.path.join(troot, cls, fn))
            labels.append(idx)
    native_ds = NativeJpegTrainIterator(
        files, labels, args.batch, args.image_size, seed=0,
        mean=np.asarray(cfg.mean_rgb, np.float32),
        std=np.asarray(cfg.stddev_rgb, np.float32),
        num_threads=args.threads)
    native_rate = time_pipeline(native_ds, args.batch, args.batches)
    native_ds.close()

    tf_ds = build_dataset(dataclasses.replace(cfg, native_jpeg=False),
                          "train", seed=0)
    tf_rate = time_pipeline(tf_ds, args.batch, args.batches)

    print(json.dumps({"pipeline": "native_libjpeg", "threads": args.threads,
                      "images_per_sec": round(native_rate, 1)}))
    print(json.dumps({"pipeline": "tf.data", "threads": "AUTOTUNE",
                      "images_per_sec": round(tf_rate, 1)}))
    print(json.dumps({"native_vs_tfdata": round(native_rate / tf_rate, 3),
                      "host_vcpus": os.cpu_count()}))


if __name__ == "__main__":
    main()
