"""Host input-path shootout: native loader vs tf.data, on both ImageNet
layouts (raw-JPEG imagefolder and TFRecord shards).

Generates local fake sources once, then times the train pipelines (same
sources, same crop distribution, same normalize) at a fixed thread count.
The host path bounds end-to-end training (README: the measured infeed
stall), so per-core decode rate is the number that matters.

Usage: python benchmarks/host_pipeline_bench.py [--layout both]
       [--threads 1] [--batches 12]
Prints one JSON line per (layout, pipeline) plus a ratio line per layout.

The tfrecord-layout native per-core rate is also emitted as a contract line
(`host_native_decode_images_per_sec_per_core`, with `vs_baseline` against
benchmarks/baseline.json; freeze with --update-baseline). This is the frozen
e2e-tracking metric (VERDICT r2 #6): on this 1-vCPU host the full-path e2e
bench is ~entirely host-bound (infeed stall ≈ 0.99), so its ratio tracks
host noise; the per-core decode rate is the signal-bearing number that
transfers to real many-core TPU-VM hosts.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def _generated(root: str) -> bool:
    # generation writes a sentinel LAST: a dir without one is a partial
    # (interrupted) generation and must be rebuilt, not silently reused
    return os.path.exists(os.path.join(root, ".complete"))


def _finish(root: str) -> None:
    with open(os.path.join(root, ".complete"), "w") as f:
        f.write("ok\n")


def ensure_imagefolder(root: str, *, classes: int = 8, per_class: int = 64,
                       source_hw=(320, 256)) -> None:
    if _generated(root):
        return
    import tensorflow as tf
    rng = np.random.default_rng(0)
    h, w = source_hw
    for c in range(classes):
        d = os.path.join(root, "train", f"n{c:08d}")
        os.makedirs(d, exist_ok=True)
        for i in range(per_class):
            img = rng.integers(0, 256, size=(h, w, 3)).astype(np.uint8)
            with open(os.path.join(d, f"{c}_{i}.JPEG"), "wb") as f:
                f.write(tf.io.encode_jpeg(img, quality=90).numpy())
    _finish(root)


def ensure_tfrecords(root: str, *, num_files: int = 8, per_file: int = 64,
                     source_hw=(320, 256)) -> None:
    if _generated(root):
        return
    import tensorflow as tf
    rng = np.random.default_rng(0)
    h, w = source_hw
    os.makedirs(root, exist_ok=True)
    for i in range(num_files):
        path = os.path.join(root, f"train-{i:05d}-of-{num_files:05d}")
        with tf.io.TFRecordWriter(path) as writer:
            for _ in range(per_file):
                img = rng.integers(0, 256, size=(h, w, 3)).astype(np.uint8)
                jpeg = tf.io.encode_jpeg(img, quality=90).numpy()
                ex = tf.train.Example(features=tf.train.Features(feature={
                    "image/encoded": tf.train.Feature(
                        bytes_list=tf.train.BytesList(value=[jpeg])),
                    "image/class/label": tf.train.Feature(
                        int64_list=tf.train.Int64List(
                            value=[int(rng.integers(1, 1001))])),
                }))
                writer.write(ex.SerializeToString())
    _finish(root)


def time_pipeline(ds, batch: int, batches: int, warmup: int = 2,
                  repeats: int = 1) -> list[float]:
    """N independent timed windows (min-of-N-time methodology, VERDICT r3
    #4): on a shared 1-vCPU host the best window is the least-contaminated
    sample and the spread is the error bar."""
    for _ in range(warmup):
        next(ds)
    rates = []
    for _ in range(max(1, repeats)):
        t0 = time.monotonic()
        for _ in range(batches):
            next(ds)
        rates.append(batch * batches / (time.monotonic() - t0))
    return rates


def _raw_stats(rates: list[float]) -> dict:
    """Full-precision min-of-N statistics — the ONE implementation every
    consumer (display lines, frozen baseline, contract line) derives from;
    rounding is a presentation decision at each call site."""
    import statistics
    out = {"images_per_sec": max(rates)}
    if len(rates) > 1:
        med = statistics.median(rates)
        out["repeats"] = len(rates)
        out["median"] = med
        out["spread"] = (max(rates) - min(rates)) / med
    return out


def _stats(rates: list[float]) -> dict:
    """Display-rounded form of _raw_stats for the per-pipeline lines."""
    s = _raw_stats(rates)
    for k, nd in (("images_per_sec", 1), ("median", 1), ("spread", 4)):
        if k in s:
            s[k] = round(s[k], nd)
    return s


REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
HOST_METRIC = "host_native_decode_images_per_sec_per_core"


def emit_contract(native_rates: list[float], threads: int,
                  update_baseline: bool) -> None:
    """The judged-style contract line for the frozen host metric — best of
    N windows, with median/spread recorded (and frozen alongside the value
    on --update-baseline, so later ratios have an error bar to read).
    Statistics come from the same _raw_stats used for the per-pipeline
    lines — one methodology, one implementation; the FROZEN value keeps
    full precision (rounding it would make re-runs of identical rates read
    vs_baseline != 1.0 — code-review r4)."""
    s = _raw_stats([r / max(1, threads) for r in native_rates])  # per-core
    per_core = s.pop("images_per_sec")
    path = os.path.join(REPO, "benchmarks", "baseline.json")
    baselines = {}
    if os.path.exists(path):
        with open(path) as f:
            baselines = json.load(f)
    vs = 1.0
    if update_baseline:
        baselines[HOST_METRIC] = {
            "metric": HOST_METRIC, "value": per_core,
            **{k: (round(v, 4) if isinstance(v, float) else v)
               for k, v in s.items()},
            "platform": "host-cpu", "host_vcpus": os.cpu_count(),
            "threads": threads}
        with open(path, "w") as f:
            json.dump(baselines, f)
    elif baselines.get(HOST_METRIC, {}).get("value"):
        vs = per_core / baselines[HOST_METRIC]["value"]
    print(json.dumps({"metric": HOST_METRIC, "value": round(per_core, 2),
                      "unit": "images/sec/core",
                      "vs_baseline": round(vs, 4),
                      **{k: (round(v, 4) if isinstance(v, float) else v)
                         for k, v in s.items()}}))


def bench_layout(layout: str, data_dir: str, args) -> list[float]:
    from distributed_vgg_f_tpu.config import DataConfig
    from distributed_vgg_f_tpu.data import build_dataset
    from distributed_vgg_f_tpu.data.native_jpeg import NativeJpegTrainIterator

    # the PRODUCTION iterator, thread count set through the config field the
    # trainer itself uses (native_threads) — no hand-rolled rebuild
    cfg = DataConfig(name="imagenet", data_dir=data_dir,
                     image_size=args.image_size,
                     global_batch_size=args.batch, shuffle_buffer=512,
                     native_threads=args.threads)
    native_ds = build_dataset(cfg, "train", seed=0)
    if not isinstance(native_ds, NativeJpegTrainIterator):
        raise SystemExit(
            f"native loader unavailable for layout {layout} — nothing to "
            "compare")
    native_rates = time_pipeline(native_ds, args.batch, args.batches,
                                 repeats=args.repeats)
    native_ds.close()

    tf_ds = build_dataset(dataclasses.replace(cfg, native_jpeg=False),
                          "train", seed=0)
    tf_rates = time_pipeline(tf_ds, args.batch, args.batches,
                             repeats=args.repeats)

    grain_rates = None
    try:
        from distributed_vgg_f_tpu.data.grain_imagenet import (
            GrainTrainIterator)
        grain_ds = build_dataset(
            dataclasses.replace(cfg, backend="grain",
                                grain_workers=args.grain_workers),
            "train", seed=0)
        if isinstance(grain_ds, GrainTrainIterator):
            grain_rates = time_pipeline(grain_ds, args.batch, args.batches,
                                        repeats=args.repeats)
            grain_ds.close()  # reap workers before the next timed phase
        else:
            # build_imagenet fell back internally (grain unavailable) — say
            # so instead of silently dropping the row, and don't leak the
            # fallback iterator's decode threads into the remaining phases
            print(json.dumps({"layout": layout, "pipeline": "grain",
                              "error": "fell back to non-grain backend"}))
            if hasattr(grain_ds, "close"):
                grain_ds.close()
    except Exception as e:  # grain absent — bench the other two anyway
        print(json.dumps({"layout": layout, "pipeline": "grain",
                          "error": repr(e)}))

    print(json.dumps({"layout": layout, "pipeline": "native_libjpeg",
                      "threads": args.threads, **_stats(native_rates)}))
    print(json.dumps({"layout": layout, "pipeline": "tf.data",
                      "threads": "AUTOTUNE", **_stats(tf_rates)}))
    if grain_rates is not None:
        print(json.dumps({"layout": layout, "pipeline": "grain+native_decode",
                          "workers": args.grain_workers,
                          **_stats(grain_rates)}))
    print(json.dumps({"layout": layout,
                      "native_vs_tfdata": round(max(native_rates)
                                                / max(tf_rates), 3),
                      "host_vcpus": os.cpu_count()}))
    return native_rates


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--data-dir", default="/tmp/dvggf_host_bench")
    parser.add_argument("--layout", choices=("imagefolder", "tfrecord",
                                             "both"), default="both")
    parser.add_argument("--batch", type=int, default=64)
    parser.add_argument("--batches", type=int, default=12)
    parser.add_argument("--image-size", type=int, default=224)
    parser.add_argument("--threads", type=int, default=1,
                        help="native worker threads (tf.data AUTOTUNE decides "
                             "its own parallelism; on a 1-vCPU host both are "
                             "effectively single-core)")
    parser.add_argument("--grain-workers", type=int, default=0,
                        help="grain decode worker PROCESSES (0 = in-process)")
    parser.add_argument("--classes", type=int, default=8)
    parser.add_argument("--per-class", type=int, default=64)
    parser.add_argument("--num-files", type=int, default=8)
    parser.add_argument("--per-file", type=int, default=64)
    parser.add_argument("--repeats", type=int, default=3,
                        help="independent timed windows per pipeline; best "
                             "window reported, median/spread recorded")
    parser.add_argument("--update-baseline", action="store_true",
                        help="freeze the tfrecord-layout native per-core "
                             "rate (with median/spread) into "
                             "benchmarks/baseline.json")
    args = parser.parse_args()

    if args.layout in ("imagefolder", "both"):
        d = os.path.join(args.data_dir, "imagefolder")
        ensure_imagefolder(d, classes=args.classes, per_class=args.per_class)
        bench_layout("imagefolder", d, args)
    if args.layout in ("tfrecord", "both"):
        d = os.path.join(args.data_dir, "tfrecord")
        ensure_tfrecords(d, num_files=args.num_files, per_file=args.per_file)
        native_rates = bench_layout("tfrecord", d, args)
        emit_contract(native_rates, args.threads, args.update_baseline)


if __name__ == "__main__":
    main()
