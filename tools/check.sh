#!/bin/sh
# The repo's static correctness gate (r15) — one entry point, three passes:
#
#   1. unified invariant linter   (tools/lint: counter-table drift, pins
#      isolation, schema_version stamping, kill-switch completeness —
#      native DVGGF_* triples AND the declared config-plane switches
#      (r18: data.iterator_state.enabled; off = epoch-boundary replay,
#      byte-identical to r17, stream identity pinned in tier-1) —
#      config-field docs, telemetry import isolation)
#   2. ctypes<->ABI contract      (tools/abi_check.py: every extern "C"
#      export declared, arity/width-matched, ABI constants consistent)
#   3. committed-receipt check    (benchmarks/regression_sentinel.py
#      --check-committed: pins == artifacts, trajectory provenance)
#
# All three are stdlib-only static passes — no toolchain, no jax, no
# native build — so the gate runs anywhere in ~seconds. Exercised on
# every default test loop (tests/test_check_gate.py) and at the top of
# the TPU session scripts (benchmarks/tpu_session_r12.sh): a session on
# scarce hardware must not start on a tree that fails its own invariants.
#
# Exit: 0 all green; the first failing pass's exit code otherwise (every
# pass still runs, so one invocation reports everything).

set -u
REPO=$(cd "$(dirname "$0")/.." && pwd)
cd "$REPO" || exit 2
PY=${PYTHON:-python}

rc=0

echo "== tools/check.sh: invariant linter =="
"$PY" -m tools.lint
r=$?
if [ "$r" -ne 0 ] && [ "$rc" -eq 0 ]; then rc=$r; fi

echo "== tools/check.sh: ABI contract checker =="
"$PY" tools/abi_check.py
r=$?
if [ "$r" -ne 0 ] && [ "$rc" -eq 0 ]; then rc=$r; fi

echo "== tools/check.sh: regression sentinel (committed receipts) =="
"$PY" benchmarks/regression_sentinel.py --check-committed
r=$?
if [ "$r" -ne 0 ] && [ "$rc" -eq 0 ]; then rc=$r; fi

if [ "$rc" -eq 0 ]; then
    echo "== tools/check.sh: ALL GREEN =="
else
    echo "== tools/check.sh: FAILED (rc=$rc) ==" >&2
fi
exit "$rc"
