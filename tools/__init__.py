"""Repo tooling namespace — makes `python -m tools.lint` and
`from tools.lint import run_rules` work from a checkout root. Nothing here
ships at runtime; the package boundary (distributed_vgg_f_tpu) never
imports tools."""
