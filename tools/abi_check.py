#!/usr/bin/env python3
"""ctypes <-> C-ABI contract checker (r15 correctness tooling plane).

The native ingest layer's C ABI churned v3->v9 in eleven PRs, and the
failure mode of an argtypes mismatch is the worst kind: cdecl silently
absorbs a wrong arity, a 32-bit int where the C side reads 64 truncates a
pointer, and the result is corrupt training batches, not a crash. This
checker makes that class of drift impossible to land:

  1. every `extern "C"` export in native/{jpeg_loader,dataloader,
     tfrecord_index}.cc is parsed out of the SOURCE (signature, arity,
     parameter types),
  2. every ctypes declaration (`lib.<sym>.argtypes` / `.restype`) in the
     binding modules (data/native_jpeg.py, data/native_loader.py,
     data/native_tfrecord.py) is read out of their ASTs,
  3. the two surfaces are cross-checked: every export declared, no stale
     declarations, arity equal, every parameter and return type
     width-compatible, and every declaration EXPLICIT about both restype
     and argtypes (ctypes' int-sized restype default is exactly the trap
     this tool exists to remove),
  4. the ABI version constant is checked end to end: the literal returned
     by the C `*_abi_version()` export must equal the module-level
     `*_ABI_VERSION` constant in the binding, which must be the value the
     binding passes to its load gate.

Stdlib-only, no compilation, no imports of the checked modules — it runs
on any box in <100 ms as part of tools/check.sh. Exit 0 green, 1 with one
violation per line on stderr otherwise.

Parsing is deliberately structural, not a C grammar: the exports live in a
single `extern "C" { ... }` block per file and use plain C types by
convention (pointers, fixed-width ints, float/double). A new export using
an exotic type fails loudly as "unknown C type" rather than being guessed
at — extend _C_TO_CTYPES when that happens.
"""

from __future__ import annotations

import argparse
import ast
import os
import re
import sys
from typing import Dict, List, Optional, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: The checked surface: one entry per native library.
#:   src           — C++ source under native/
#:   binding       — ctypes binding module (repo-relative)
#:   abi_symbol    — the version export, declared by the loader harness
#:                   (load_abi_checked) or the binding itself
#:   abi_constant  — module-level constant in the binding that must equal
#:                   the C literal (None = the C side has no versioned
#:                   constant to mirror)
LIBRARIES = (
    {
        "src": "native/jpeg_loader.cc",
        "binding": "distributed_vgg_f_tpu/data/native_jpeg.py",
        "abi_symbol": "dvgg_jpeg_loader_abi_version",
        "abi_constant": "JPEG_ABI_VERSION",
    },
    {
        "src": "native/dataloader.cc",
        "binding": "distributed_vgg_f_tpu/data/native_loader.py",
        "abi_symbol": "dvgg_abi_version",
        "abi_constant": "DATA_ABI_VERSION",
    },
    {
        "src": "native/tfrecord_index.cc",
        "binding": "distributed_vgg_f_tpu/data/native_tfrecord.py",
        "abi_symbol": "dvgg_tfrecord_index_abi_version",
        "abi_constant": "TFRECORD_ABI_VERSION",
    },
)

# ---------------------------------------------------------------- C side

_LINE_COMMENT = re.compile(r"//[^\n]*")
_BLOCK_COMMENT = re.compile(r"/\*.*?\*/", re.S)


def _strip_comments(text: str) -> str:
    return _LINE_COMMENT.sub("", _BLOCK_COMMENT.sub("", text))


def _extern_c_block(text: str, path: str) -> str:
    """The contents of the (single, by repo convention) extern "C" block."""
    m = re.search(r'extern\s+"C"\s*\{', text)
    if not m:
        raise SystemExit(f"{path}: no extern \"C\" block found")
    depth, i = 1, m.end()
    while i < len(text) and depth:
        if text[i] == "{":
            depth += 1
        elif text[i] == "}":
            depth -= 1
        i += 1
    return text[m.end():i - 1]


def _norm_c_type(raw: str) -> str:
    """'const uint8_t *' -> 'uint8_t*'; const and spacing are ABI-neutral."""
    t = raw.replace("const", " ").replace("struct", " ")
    t = t.replace("*", " * ")
    parts = t.split()
    stars = parts.count("*")
    base = " ".join(p for p in parts if p != "*")
    return base + "*" * stars


_SIG = re.compile(
    r"(?:^|\n)\s*([A-Za-z_][\w ]*?[\w*])\s*\**\s*"   # return type
    r"(dvgg_\w+)\s*\(([^)]*)\)\s*\{", re.S)


def parse_c_exports(path: str) -> Dict[str, dict]:
    """{symbol: {ret, params: [type, ...], abi_literal}} for one source."""
    with open(path) as f:
        text = _strip_comments(f.read())
    block = _extern_c_block(text, path)
    exports: Dict[str, dict] = {}
    for m in _SIG.finditer(block):
        ret_raw, name, params_raw = m.groups()
        # the regex's return group can't see a '*' consumed by \**; re-read it
        ret = _norm_c_type(block[m.start(1):m.start(2)])
        params: List[str] = []
        params_raw = params_raw.strip()
        if params_raw and params_raw != "void":
            for p in params_raw.split(","):
                p = p.strip()
                # drop the parameter name (last identifier not part of type)
                p = re.sub(r"\b[A-Za-z_]\w*$", "", p).strip()
                params.append(_norm_c_type(p))
        abi_literal = None
        if name.endswith("_abi_version"):
            body = block[m.end():block.index("}", m.end())]
            lit = re.search(r"return\s+(\d+)\s*;", body)
            if lit:
                abi_literal = int(lit.group(1))
        exports[name] = {"ret": ret, "params": params,
                         "abi_literal": abi_literal}
    if not exports:
        raise SystemExit(f"{path}: extern \"C\" block parsed to 0 exports")
    return exports


# ------------------------------------------------------------- Python side

def _ctype_token(node: ast.AST, aliases: Dict[str, str]) -> str:
    """Canonical token for a ctypes expression node.

    ctypes.c_int -> 'c_int'; module alias _I64P -> its resolved value;
    ctypes.POINTER(ctypes.c_float) -> 'POINTER(c_float)'; None -> 'None'.
    Unresolvable expressions return '<unknown>' and fail the check loudly.
    """
    if isinstance(node, ast.Constant) and node.value is None:
        return "None"
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return aliases.get(node.id, f"<unknown:{node.id}>")
    if isinstance(node, ast.Call):
        fn = node.func
        fn_name = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else "<unknown>")
        if fn_name == "POINTER" and node.args:
            return f"POINTER({_ctype_token(node.args[0], aliases)})"
    return "<unknown>"


def parse_py_declarations(path: str) -> Tuple[Dict[str, dict], Dict[str, int]]:
    """({symbol: {argtypes: [...]|None, restype: str|None}},
        {constant_name: int}) from one binding module's AST.

    Only `<anything>.<symbol>.argtypes = [...]` / `.restype = <expr>`
    assignments count — the symbol is the attribute one level below the
    argtypes/restype attribute, so `lib` vs `self._lib` both resolve.
    """
    with open(path) as f:
        tree = ast.parse(f.read(), filename=path)
    aliases: Dict[str, str] = {}
    constants: Dict[str, int] = {}
    decls: Dict[str, dict] = {}

    for node in ast.walk(tree):
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        targets = node.targets if isinstance(node, ast.Assign) else \
            [node.target]
        value = node.value
        if value is None:
            continue
        for target in targets:
            # module-level aliases (_I64P = ctypes.POINTER(ctypes.c_int64))
            # and ABI constants (JPEG_ABI_VERSION = 9)
            if isinstance(target, ast.Name):
                if isinstance(value, ast.Constant) \
                        and isinstance(value.value, int):
                    constants[target.id] = value.value
                else:
                    token = _ctype_token(value, aliases)
                    if not token.startswith("<unknown"):
                        aliases[target.id] = token
                continue
            if not isinstance(target, ast.Attribute):
                continue
            if target.attr not in ("argtypes", "restype"):
                continue
            if not isinstance(target.value, ast.Attribute):
                continue
            symbol = target.value.attr
            if not symbol.startswith("dvgg_"):
                continue
            entry = decls.setdefault(symbol,
                                     {"argtypes": None, "restype": None})
            if target.attr == "restype":
                entry["restype"] = _ctype_token(value, aliases)
            elif isinstance(value, (ast.List, ast.Tuple)):
                entry["argtypes"] = [_ctype_token(e, aliases)
                                     for e in value.elts]
    return decls, constants


def _find_load_gate(binding_path: str, abi_symbol: str,
                    const_name: str) -> str:
    """How the binding gates the loaded library's ABI version:
    'constant' — the gate consumes `const_name` (a `load_abi_checked(...,
    CONST)` call or a direct `lib.<abi_symbol>() != CONST` comparison);
    'literal' — the gate exists but hardcodes a number (frozen copy that
    a future bump would leave stale); 'missing' — no gate found."""
    with open(binding_path) as f:
        tree = ast.parse(f.read(), filename=binding_path)

    def classify(node: ast.AST) -> Optional[str]:
        if isinstance(node, ast.Name):
            return "constant" if node.id == const_name else "literal"
        if isinstance(node, ast.Constant):
            return "literal"
        return None

    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            fn = node.func
            fn_name = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else None)
            if fn_name != "load_abi_checked":
                continue
            # expected_abi is the 4th positional arg, or the keyword
            arg: Optional[ast.AST] = None
            if len(node.args) >= 4:
                arg = node.args[3]
            for kw in node.keywords:
                if kw.arg == "expected_abi":
                    arg = kw.value
            got = classify(arg) if arg is not None else None
            if got:
                return got
        elif isinstance(node, ast.Compare) and len(node.comparators) == 1:
            # direct gate: lib.<abi_symbol>() != CONST (either side)
            sides = (node.left, node.comparators[0])
            for call, other in (sides, sides[::-1]):
                if isinstance(call, ast.Call) \
                        and isinstance(call.func, ast.Attribute) \
                        and call.func.attr == abi_symbol:
                    got = classify(other)
                    if got:
                        return got
    return "missing"


# ------------------------------------------------------------ cross-check

#: Normalized C type -> ctypes tokens that are width- and kind-compatible
#: on every platform this runs on (LP64). A C type absent from this table
#: fails loudly rather than being guessed.
_C_TO_CTYPES = {
    "int": {"c_int"},
    "unsigned": {"c_uint"},
    "unsigned int": {"c_uint"},
    "int32_t": {"c_int32", "c_int"},
    "int64_t": {"c_int64"},
    "uint64_t": {"c_uint64"},
    "float": {"c_float"},
    "double": {"c_double"},
    "void*": {"c_void_p"},
    "char*": {"c_char_p"},
    # byte buffers: c_char_p (python bytes in), c_void_p (numpy .ctypes
    # out), POINTER(c_uint8) are all the same 8-bit-pointee width
    "uint8_t*": {"c_char_p", "c_void_p", "POINTER(c_uint8)"},
    "int32_t*": {"POINTER(c_int32)"},
    "int64_t*": {"POINTER(c_int64)"},
    "float*": {"POINTER(c_float)"},
}

_RET_VOID = {"None"}


def _check_type(c_type: str, token: str, where: str,
                errors: List[str]) -> None:
    allowed = _C_TO_CTYPES.get(c_type)
    if allowed is None:
        errors.append(f"{where}: C type {c_type!r} not in the compatibility "
                      f"table (tools/abi_check.py _C_TO_CTYPES) — extend it "
                      f"deliberately, don't let ctypes guess")
        return
    if token not in allowed:
        errors.append(f"{where}: ctypes {token} incompatible with C "
                      f"{c_type!r} (allowed: {sorted(allowed)})")


def check_library(repo: str, lib_cfg: dict) -> List[str]:
    errors: List[str] = []
    src = os.path.join(repo, lib_cfg["src"])
    binding = os.path.join(repo, lib_cfg["binding"])
    exports = parse_c_exports(src)
    decls, constants = parse_py_declarations(binding)
    src_name = lib_cfg["src"]
    abi_symbol = lib_cfg["abi_symbol"]

    # the version export is declared generically by load_abi_checked
    # (restype c_int64, argtypes []) or explicitly by the binding; either
    # way its C shape is pinned here
    abi = exports.get(abi_symbol)
    if abi is None:
        errors.append(f"{src_name}: ABI version export {abi_symbol} missing")
    else:
        if abi["params"]:
            errors.append(f"{src_name}: {abi_symbol} must take no arguments")
        if abi["abi_literal"] is None:
            errors.append(f"{src_name}: {abi_symbol} does not return an "
                          f"integer literal — the checker (and the stale-.so "
                          f"gate) need the version to be a compile-time "
                          f"constant")

    # C constant == binding constant
    const_name = lib_cfg["abi_constant"]
    if const_name not in constants:
        errors.append(f"{lib_cfg['binding']}: module constant {const_name} "
                      f"missing (the binding's single ABI-version source)")
    elif abi is not None and abi["abi_literal"] is not None \
            and constants[const_name] != abi["abi_literal"]:
        errors.append(
            f"ABI version drift: {src_name} {abi_symbol}() returns "
            f"{abi['abi_literal']} but {lib_cfg['binding']} {const_name} = "
            f"{constants[const_name]}")

    # the load GATE must consume the constant, not a frozen literal: a
    # literal gate + a bumped constant keeps this checker green while the
    # runtime gate mismatches and silently disables the native path
    gate = _find_load_gate(binding, abi_symbol, const_name)
    if gate == "missing":
        errors.append(f"{lib_cfg['binding']}: no load gate found for "
                      f"{abi_symbol} (load_abi_checked call or direct "
                      f"version comparison)")
    elif gate == "literal":
        errors.append(f"{lib_cfg['binding']}: the {abi_symbol} load gate "
                      f"uses a literal version instead of {const_name} — "
                      f"a future bump would update the constant and leave "
                      f"the gate stale")

    # every export declared; every declaration matches
    for symbol, sig in sorted(exports.items()):
        if symbol == abi_symbol and symbol not in decls:
            continue  # declared inside load_abi_checked, checked above
        decl = decls.get(symbol)
        if decl is None:
            errors.append(f"{lib_cfg['binding']}: export {symbol} has no "
                          f"ctypes declaration (argtypes/restype) — cdecl "
                          f"would default its restype to int")
            continue
        where = f"{lib_cfg['binding']}:{symbol}"
        if decl["restype"] is None:
            errors.append(f"{where}: restype never assigned (ctypes "
                          f"defaults to c_int — declare None for void)")
        else:
            if sig["ret"] == "void":
                if decl["restype"] not in _RET_VOID:
                    errors.append(f"{where}: restype {decl['restype']} but "
                                  f"C returns void (declare None)")
            else:
                _check_type(sig["ret"], decl["restype"], where + " restype",
                            errors)
        if decl["argtypes"] is None:
            errors.append(f"{where}: argtypes never assigned (ctypes would "
                          f"accept any arity — declare [] for no-arg "
                          f"exports)")
        else:
            if len(decl["argtypes"]) != len(sig["params"]):
                errors.append(
                    f"{where}: arity mismatch — C takes "
                    f"{len(sig['params'])} args, argtypes declares "
                    f"{len(decl['argtypes'])}")
            else:
                for i, (c_t, token) in enumerate(
                        zip(sig["params"], decl["argtypes"])):
                    _check_type(c_t, token, f"{where} arg[{i}]", errors)

    # no stale declarations for symbols the C side no longer exports
    for symbol in sorted(decls):
        if symbol not in exports:
            errors.append(f"{lib_cfg['binding']}: declares {symbol} which "
                          f"{src_name} does not export (stale binding)")
    return errors


def run(repo: str = REPO) -> List[str]:
    errors: List[str] = []
    for lib_cfg in LIBRARIES:
        errors.extend(check_library(repo, lib_cfg))
    return errors


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--repo", default=REPO,
                        help="repository root (default: this checkout)")
    args = parser.parse_args(argv)
    errors = run(args.repo)
    if errors:
        for e in errors:
            print(f"abi_check: {e}", file=sys.stderr)
        print(f"abi_check: {len(errors)} violation(s)", file=sys.stderr)
        return 1
    n = sum(len(parse_c_exports(os.path.join(args.repo, c["src"])))
            for c in LIBRARIES)
    print(f"abi_check: OK ({n} exports across {len(LIBRARIES)} libraries)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
