"""CLI for the project-invariant linter: `python -m tools.lint` from the
checkout root (tools/check.sh runs it as part of the static gate).

Exit 0 green, 1 with one violation per line on stderr, 2 on usage errors.
"""

from __future__ import annotations

import argparse
import os
import sys

from tools.lint import all_rules, run_rules

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="tools.lint", description=__doc__.splitlines()[0])
    parser.add_argument("--repo", default=REPO,
                        help="repository root (default: this checkout)")
    parser.add_argument("--rule", action="append", default=None,
                        metavar="NAME",
                        help="run only this rule (repeatable)")
    parser.add_argument("--list", action="store_true",
                        help="list the registered rules and exit")
    args = parser.parse_args(argv)
    if args.list:
        for rule in all_rules():
            print(f"{rule.name}: {rule.description}")
        return 0
    violations = run_rules(args.repo, args.rule)
    if violations:
        for v in violations:
            print(f"lint: {v}", file=sys.stderr)
        print(f"lint: {len(violations)} violation(s)", file=sys.stderr)
        return 1
    print(f"lint: OK ({len(all_rules())} rules)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
