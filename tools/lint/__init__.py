"""Unified project-invariant linter (r15 correctness tooling plane).

The repo enforces a growing set of cross-cutting contracts — counters
documented in the README table, bench pins never read at runtime, every
artifact stamped with the schema version, every native kill-switch shipped
as a complete env/setter/compile-out triple, telemetry importable without
heavy deps. Until r15 each contract lived as its own ad-hoc tier-1 test
with its own parsing; this package turns them into NAMED RULES over one
shared repo snapshot, so the next PR extends a rule table instead of
re-inventing a scanner, and `tools/check.sh` runs the whole set as the
repo's static gate.

Design rules for rules:
  * stdlib only (ast / tokenize / re) — the gate must run on a box with no
    jax, no numpy, no native toolchain, in well under a second;
  * rules read the RepoContext's cached sources, never the filesystem
    directly, so one lint pass parses each file at most once;
  * every rule must be mutation-tested: tests/test_lint.py seeds one
    violation per rule into a fixture tree and asserts the rule catches it
    — a rule that cannot fail is not a rule.

`run_rules(repo)` returns [] on a clean tree; the CLI (`python -m
tools.lint`) exits 1 and prints one violation per line otherwise.
"""

from __future__ import annotations

import ast
import io
import os
import tokenize
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

#: Package directory every rule treats as "the runtime" (repo-relative).
PACKAGE = "distributed_vgg_f_tpu"


@dataclass(frozen=True)
class Violation:
    """One broken invariant, pointing at the offending file/line."""
    rule: str
    path: str       # repo-relative
    line: int       # 1-based; 0 = file-level
    message: str

    def __str__(self) -> str:
        loc = f"{self.path}:{self.line}" if self.line else self.path
        return f"{self.rule}: {loc}: {self.message}"


class RepoContext:
    """Cached view of the checkout a lint pass runs over: file text, ASTs
    and comment/string-stripped token streams are each computed once and
    shared by every rule."""

    def __init__(self, repo: str):
        self.repo = os.path.abspath(repo)
        self._text: Dict[str, Optional[str]] = {}
        self._ast: Dict[str, Optional[ast.Module]] = {}
        self._code_tokens: Dict[str, str] = {}

    def exists(self, rel: str) -> bool:
        return os.path.exists(os.path.join(self.repo, rel))

    def text(self, rel: str) -> Optional[str]:
        """File contents, or None when absent (rules decide whether a
        missing file is itself a violation)."""
        if rel not in self._text:
            path = os.path.join(self.repo, rel)
            try:
                with open(path, encoding="utf-8") as f:
                    self._text[rel] = f.read()
            except OSError:
                self._text[rel] = None
        return self._text[rel]

    def parse(self, rel: str) -> Optional[ast.Module]:
        if rel not in self._ast:
            text = self.text(rel)
            try:
                self._ast[rel] = None if text is None else \
                    ast.parse(text, filename=rel)
            except SyntaxError:
                self._ast[rel] = None
        return self._ast[rel]

    def code_tokens(self, rel: str) -> str:
        """Source minus comments and string literals — prose citing a
        forbidden name (docstrings do, by design) is not a runtime read.
        Same tokenizer trick the original ad-hoc guards used."""
        if rel not in self._code_tokens:
            text = self.text(rel) or ""
            try:
                toks = tokenize.generate_tokens(io.StringIO(text).readline)
                self._code_tokens[rel] = " ".join(
                    t.string for t in toks
                    if t.type not in (tokenize.COMMENT, tokenize.STRING))
            except (tokenize.TokenError, IndentationError, SyntaxError):
                self._code_tokens[rel] = text
        return self._code_tokens[rel]

    def py_files(self, rel_dir: str) -> List[str]:
        """Repo-relative paths of every .py under rel_dir (sorted; skips
        __pycache__)."""
        root = os.path.join(self.repo, rel_dir)
        out: List[str] = []
        for dirpath, dirnames, files in os.walk(root):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for f in sorted(files):
                if f.endswith(".py"):
                    out.append(os.path.relpath(os.path.join(dirpath, f),
                                               self.repo))
        return sorted(out)


@dataclass(frozen=True)
class Rule:
    """A named invariant. `check` returns every violation it can prove
    from the RepoContext — rules never raise on malformed input, they
    report it."""
    name: str
    description: str
    check: Callable[[RepoContext], List[Violation]] = field(compare=False)


_REGISTRY: Dict[str, Rule] = {}


def register(name: str, description: str):
    """Decorator: `@register("rule-name", "what it guards")` over a
    `check(ctx) -> list[Violation]` function."""
    def wrap(fn: Callable[[RepoContext], List[Violation]]) -> Rule:
        if name in _REGISTRY:
            raise ValueError(f"duplicate lint rule {name!r}")
        rule = Rule(name=name, description=description, check=fn)
        _REGISTRY[name] = rule
        return rule
    return wrap


def all_rules() -> List[Rule]:
    from tools.lint import rules as _rules  # noqa: F401  (registration)
    return [_REGISTRY[k] for k in sorted(_REGISTRY)]


def get_rule(name: str) -> Rule:
    from tools.lint import rules as _rules  # noqa: F401  (registration)
    try:
        return _REGISTRY[name]
    except KeyError:
        raise KeyError(f"unknown lint rule {name!r} "
                       f"(known: {sorted(_REGISTRY)})") from None


def run_rules(repo: str, names: Optional[List[str]] = None) -> \
        List[Violation]:
    """Run the named rules (default: all) over one shared RepoContext."""
    ctx = RepoContext(repo)
    rules = [get_rule(n) for n in names] if names else all_rules()
    out: List[Violation] = []
    for rule in rules:
        out.extend(rule.check(ctx))
    return out
