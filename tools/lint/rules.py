"""The project-invariant rule set. Each rule is the mechanical form of a
contract the repo already enforces in prose or enforced ad hoc in a
scattered tier-1 test; tests/test_lint.py proves every rule catches a
seeded violation (mutation-style), and the migrated drift-guard tests call
these rules so the original coverage survives the consolidation.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Set, Tuple

from tools.lint import PACKAGE, RepoContext, Violation, register

# --------------------------------------------------------------------------
# counter-namespace-drift
# --------------------------------------------------------------------------

#: Namespaces whose names are registered DYNAMICALLY (poller dict keys, not
#: string literals) — the static stale-direction check exempts them; the
#: runtime half of the guard (tests/test_telemetry.py) closes the loop by
#: running the actual poller.
DYNAMIC_NAMESPACES = {"decode"}

#: Registration sites: telemetry.inc / counter / set_gauge with a literal
#: first argument.
_COUNTER_CALL = re.compile(
    r"(?:inc|counter|set_gauge)\(\s*\"([a-z0-9_]+/[a-z0-9_/]+)\"")


def readme_documented_counters(ctx: RepoContext) -> \
        Tuple[Set[str], Set[str], List[Violation]]:
    """Parse the README 'Counter namespace' table: (namespaces, documented
    fully-qualified names, violations-so-far). Same tokenization as the
    original guard: backticked tokens per names cell; a '/'-carrying token
    whose first segment is itself a table namespace is a fully-qualified
    cross-citation."""
    violations: List[Violation] = []
    text = ctx.text("README.md")
    if text is None or "### Counter namespace" not in text:
        violations.append(Violation(
            "counter-namespace-drift", "README.md", 0,
            "README 'Counter namespace' section missing — the counter "
            "table is the documented contract this rule checks against"))
        return set(), set(), violations
    section = text.split("### Counter namespace", 1)[1].split("\n### ", 1)[0]
    rows = [ln for ln in section.splitlines()
            if ln.startswith("| `") and ln.endswith(" |")]
    namespaces: List[str] = []
    cells: List[Tuple[str, str]] = []
    for row in rows:
        parts = [c.strip() for c in row.strip("|").split("|")]
        m = re.match(r"`([a-z_]+)/`", parts[0])
        if not m or len(parts) < 3:
            continue
        namespaces.append(m.group(1))
        cells.append((m.group(1), parts[2]))
    documented: Set[str] = set()
    for ns, cell in cells:
        for token in re.findall(r"`([a-z0-9_/<>]+)`", cell):
            first = token.split("/", 1)[0]
            if "/" in token and first in namespaces:
                documented.add(token)
            else:
                documented.add(f"{ns}/{token}")
    return set(namespaces), documented, violations


def package_counter_literals(ctx: RepoContext) -> Dict[str, str]:
    """{counter name literal: repo-relative file} across the package's
    registration sites."""
    out: Dict[str, str] = {}
    for rel in ctx.py_files(PACKAGE):
        for name in _COUNTER_CALL.findall(ctx.text(rel) or ""):
            out.setdefault(name, rel)
    return out


@register(
    "counter-namespace-drift",
    "every counter/gauge literal registered by the package is documented "
    "in the README 'Counter namespace' table, and no static table entry "
    "is stale (dynamic poller namespaces are closed by the runtime half "
    "in tests/test_telemetry.py)")
def check_counter_namespace(ctx: RepoContext) -> List[Violation]:
    namespaces, documented, violations = readme_documented_counters(ctx)
    if not namespaces:
        return violations
    literals = package_counter_literals(ctx)
    for name, rel in sorted(literals.items()):
        ns = name.split("/", 1)[0]
        if ns not in namespaces:
            violations.append(Violation(
                "counter-namespace-drift", rel, 0,
                f"counter {name!r} registered under namespace {ns!r} which "
                f"has no README table row"))
        elif name not in documented:
            violations.append(Violation(
                "counter-namespace-drift", rel, 0,
                f"counter {name!r} registered but missing from the README "
                f"table"))
    for name in sorted(documented):
        ns = name.split("/", 1)[0]
        if ns in DYNAMIC_NAMESPACES:
            continue  # closed by the runtime poller cross-check
        if name not in literals:
            violations.append(Violation(
                "counter-namespace-drift", "README.md", 0,
                f"README table documents {name!r} but nothing registers it "
                f"(stale entry)"))
    violations.extend(_check_namespace_help(ctx, namespaces))
    return violations


#: Namespaces excluded from the help-table equality on BOTH sides:
#: `bench/` counters are bench-process-only (never in a training run's
#: exposition, so a HELP line would document nothing scrapeable).
_HELP_EXEMPT_NAMESPACES = {"bench"}

_HELP_MODULE = f"{PACKAGE}/telemetry/metric_help.py"


def _namespace_help_keys(ctx: RepoContext) -> Set[str]:
    """AST-extract the NAMESPACE_HELP literal's keys from metric_help.py —
    parsed, not imported, so the lint stays runnable on a tree whose
    package doesn't import (the same discipline as every other rule)."""
    tree = ctx.parse(_HELP_MODULE)
    if tree is None:
        return set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "NAMESPACE_HELP"
                for t in node.targets):
            if isinstance(node.value, ast.Dict):
                return {k.value for k in node.value.keys
                        if isinstance(k, ast.Constant)
                        and isinstance(k.value, str)}
    return set()


def _check_namespace_help(ctx: RepoContext,
                          readme_namespaces: Set[str]) -> List[Violation]:
    """The r22 half of the contract: the Prometheus help registry
    (telemetry/metric_help.py NAMESPACE_HELP) must cover EXACTLY the
    README counter-table namespaces — a namespace shipping without a
    `# HELP` line, or help text for a namespace nothing documents, is the
    same drift as an undocumented counter."""
    violations: List[Violation] = []
    if not ctx.exists(_HELP_MODULE):
        violations.append(Violation(
            "counter-namespace-drift", _HELP_MODULE, 0,
            "telemetry/metric_help.py missing — every Prometheus family "
            "needs a HELP line sourced from its NAMESPACE_HELP table"))
        return violations
    help_keys = _namespace_help_keys(ctx)
    if not help_keys:
        violations.append(Violation(
            "counter-namespace-drift", _HELP_MODULE, 0,
            "NAMESPACE_HELP dict literal not found/empty in "
            "telemetry/metric_help.py"))
        return violations
    readme = set(readme_namespaces) - _HELP_EXEMPT_NAMESPACES
    helped = help_keys - _HELP_EXEMPT_NAMESPACES
    for ns in sorted(readme - helped):
        violations.append(Violation(
            "counter-namespace-drift", _HELP_MODULE, 0,
            f"README counter-table namespace {ns!r} has no NAMESPACE_HELP "
            f"entry — its Prometheus families would ship without # HELP"))
    for ns in sorted(helped - readme):
        violations.append(Violation(
            "counter-namespace-drift", _HELP_MODULE, 0,
            f"NAMESPACE_HELP documents namespace {ns!r} which has no "
            f"README counter-table row (stale help entry)"))
    return violations


# --------------------------------------------------------------------------
# scaling-model-isolation
# --------------------------------------------------------------------------

#: Runtime subsystems that must not read provisioning pins. The pins may
#: live in utils/scaling_model.py (the analytic model) and be read by
#: telemetry/regress.py (the sentinel over committed receipts) — nothing
#: that executes during training/serving may consult them.
RUNTIME_DIRS = ("data", "train", "parallel", "resilience", "checkpoint",
                "models", "ops", "serving")
RUNTIME_ROOT_FILES = ("cli.py", "config.py")


@register(
    "scaling-model-isolation",
    "HOST_DECODE_RATE_* pins and utils/scaling_model stay bench artifacts: "
    "no runtime subsystem (data/train/parallel/resilience/checkpoint/"
    "models/ops, cli.py, config.py) names the pins or imports the scaling "
    "model")
def check_scaling_model_isolation(ctx: RepoContext) -> List[Violation]:
    violations: List[Violation] = []
    targets: List[str] = []
    for sub in RUNTIME_DIRS:
        targets.extend(ctx.py_files(f"{PACKAGE}/{sub}"))
    targets.extend(f"{PACKAGE}/{f}" for f in RUNTIME_ROOT_FILES
                   if ctx.exists(f"{PACKAGE}/{f}"))
    for rel in targets:
        src = ctx.code_tokens(rel)
        if re.search(r"HOST_DECODE_RATE", src):
            violations.append(Violation(
                "scaling-model-isolation", rel, 0,
                "runtime module names a HOST_DECODE_RATE_* bench pin — "
                "provisioning constants are receipts, not config inputs "
                "(the autotuner is the runtime mechanism)"))
        if re.search(r"\bscaling_model\b", src):
            violations.append(Violation(
                "scaling-model-isolation", rel, 0,
                "runtime module imports/names utils.scaling_model — the "
                "analytic model is a bench artifact, not a runtime input"))
    return violations


# --------------------------------------------------------------------------
# schema-version-stamping
# --------------------------------------------------------------------------

#: Modules that write versioned records/artifacts; each must stamp
#: schema_version FROM the shared constant — a writer that stops stamping
#: (or inlines a frozen copy of the version) breaks every reader's
#: compatibility gate silently.
SCHEMA_WRITERS = (
    f"{PACKAGE}/utils/logging.py",      # MetricLogger JSONL records
    f"{PACKAGE}/telemetry/flight.py",   # crash flight-recorder black boxes
    f"{PACKAGE}/telemetry/regress.py",  # committed trajectory artifact
)


def _dict_key_values(tree: ast.Module) -> List[Tuple[str, ast.AST, int]]:
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Dict):
            for k, v in zip(node.keys, node.values):
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    out.append((k.value, v, k.lineno))
    return out


@register(
    "schema-version-stamping",
    "every schema_version stamp in the package and benchmarks comes from "
    "the shared SCHEMA_VERSION constant (never a string literal), and "
    "every known record/artifact writer actually stamps it")
def check_schema_version_stamping(ctx: RepoContext) -> List[Violation]:
    violations: List[Violation] = []
    scan = ctx.py_files(PACKAGE) + ctx.py_files("benchmarks")
    for rel in scan:
        tree = ctx.parse(rel)
        if tree is None:
            continue
        for key, value, line in _dict_key_values(tree):
            if key != "schema_version":
                continue
            if isinstance(value, ast.Constant):
                violations.append(Violation(
                    "schema-version-stamping", rel, line,
                    f"schema_version stamped with literal "
                    f"{value.value!r} — use the shared SCHEMA_VERSION "
                    f"constant (telemetry/schema.py) so version bumps "
                    f"reach every writer"))
    for rel in SCHEMA_WRITERS:
        tree = ctx.parse(rel)
        if tree is None:
            violations.append(Violation(
                "schema-version-stamping", rel, 0,
                "known record writer missing (moved? update "
                "tools/lint/rules.py SCHEMA_WRITERS)"))
            continue
        stamped = False
        for key, value, _ in _dict_key_values(tree):
            if key != "schema_version":
                continue
            name = value.attr if isinstance(value, ast.Attribute) else (
                value.id if isinstance(value, ast.Name) else None)
            if name == "SCHEMA_VERSION":
                stamped = True
        if not stamped:
            violations.append(Violation(
                "schema-version-stamping", rel, 0,
                "record writer no longer stamps 'schema_version' from "
                "SCHEMA_VERSION — readers lose their compatibility gate"))
    return violations


# --------------------------------------------------------------------------
# kill-switch-completeness
# --------------------------------------------------------------------------

_CC_LINE_COMMENT = re.compile(r"//[^\n]*")
_CC_BLOCK_COMMENT = re.compile(r"/\*.*?\*/", re.S)

#: env-name prefixes that name the mechanism, not the switch: the canonical
#: switch key for DVGGF_DECODE_SIMD / DVGGF_THREAD_RESIZE is SIMD / RESIZE.
_ENV_PREFIXES = ("DECODE_", "THREAD_")


def _kill_switch_sets(text: str) -> Tuple[Dict[str, str], Set[str],
                                          Set[str]]:
    """(env kills {key: env name}, compile-out keys, runtime-setter keys)
    for one comment-stripped C++ source. An env read counts as a KILL
    (not a tuning knob) when the value is compared against '0' nearby —
    the repo's sticky-dispatch idiom."""
    env_kills: Dict[str, str] = {}
    for m in re.finditer(r"getenv\s*\(\s*\"DVGGF_(\w+)\"\s*\)", text):
        tail = text[m.end():m.end() + 200]
        if "'0'" in tail:
            key = m.group(1)
            for p in _ENV_PREFIXES:
                if key.startswith(p):
                    key = key[len(p):]
            env_kills[key] = f"DVGGF_{m.group(1)}"
    macros = {m.group(1)
              for m in re.finditer(r"defined\s*\(\s*DVGGF_NO_(\w+)\s*\)",
                                   text)}
    setters = {m.group(1)
               for m in re.finditer(r"\bint\s+dvgg_\w*?set_(\w+)\s*\(",
                                    text)}
    return env_kills, macros, setters


#: Config-plane kill-switches (r18): dotted config fields that gate whole
#: PYTHON subsystems the way the DVGGF_* env triples gate native ones.
#: Each entry is (dotted switch, dataclass, field); the rule requires the
#: boolean field to exist in config.py AND at least one tier-1 test to
#: name the dotted switch — the off-identity pin (off must be
#: byte-identical to the subsystem-absent behavior) cannot exist without
#: a test that spells the switch out.
CONFIG_KILL_SWITCHES = (
    ("data.iterator_state.enabled", "IteratorStateConfig", "enabled"),
    ("mesh.elastic.enabled", "ElasticConfig", "enabled"),
    ("mesh.shard_params", "MeshConfig", "shard_params"),
    ("serving.tiers.enabled", "ServingTiersConfig", "enabled"),
)


def _config_bool_field(ctx: RepoContext, cls_name: str,
                       field_name: str) -> bool:
    tree = ctx.parse(f"{PACKAGE}/config.py")
    if tree is None:
        return False
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == cls_name:
            for stmt in node.body:
                if isinstance(stmt, ast.AnnAssign) \
                        and isinstance(stmt.target, ast.Name) \
                        and stmt.target.id == field_name \
                        and isinstance(stmt.annotation, ast.Name) \
                        and stmt.annotation.id == "bool":
                    return True
    return False


@register(
    "kill-switch-completeness",
    "every DVGGF_* env kill-switch in the native sources ships as a "
    "complete triple: env kill + -DDVGGF_NO_* compile-out + runtime "
    "setter export, and vice versa (a compile-out without an env kill, or "
    "either without a setter, leaves an untestable half-switch); and "
    "every declared config-plane kill-switch (CONFIG_KILL_SWITCHES, e.g. "
    "data.iterator_state.enabled) exists as a boolean config field with a "
    "tier-1 test naming it — the off-identity pin")
def check_kill_switch_completeness(ctx: RepoContext) -> List[Violation]:
    import os
    violations: List[Violation] = []
    # the config-plane half only applies to trees that HAVE the config
    # surface (the mutation fixtures exercise the native half alone)
    config_switches = CONFIG_KILL_SWITCHES \
        if ctx.exists(f"{PACKAGE}/config.py") else ()
    for dotted, cls_name, field_name in config_switches:
        if not _config_bool_field(ctx, cls_name, field_name):
            violations.append(Violation(
                "kill-switch-completeness", f"{PACKAGE}/config.py", 0,
                f"declared config kill-switch {dotted!r} has no boolean "
                f"field {cls_name}.{field_name} in config.py"))
        if not any(dotted in (ctx.text(rel) or "")
                   for rel in ctx.py_files("tests")):
            violations.append(Violation(
                "kill-switch-completeness", "tests", 0,
                f"config kill-switch {dotted!r} is named by no tier-1 "
                f"test — the off-identity pin (off == subsystem-absent, "
                f"byte-identical) is unenforced"))
    root = os.path.join(ctx.repo, "native")
    if not os.path.isdir(root):
        return violations
    for f in sorted(f for f in os.listdir(root) if f.endswith(".cc")):
        rel = f"native/{f}"
        text = ctx.text(rel)
        if text is None:
            continue
        text = _CC_LINE_COMMENT.sub("", _CC_BLOCK_COMMENT.sub("", text))
        env_kills, macros, setters = _kill_switch_sets(text)
        for key in sorted(set(env_kills) | macros):
            if key not in env_kills:
                violations.append(Violation(
                    "kill-switch-completeness", rel, 0,
                    f"-DDVGGF_NO_{key} compile-out has no matching env "
                    f"kill-switch (the '0'-comparison getenv idiom) — the "
                    f"switch can't be exercised without a rebuild"))
            if key not in macros:
                violations.append(Violation(
                    "kill-switch-completeness", rel, 0,
                    f"env kill-switch {env_kills[key]} has no "
                    f"-DDVGGF_NO_{key} compile-out — the smoke tests "
                    f"can't prove the fallback stands alone"))
            if key.lower() not in setters:
                violations.append(Violation(
                    "kill-switch-completeness", rel, 0,
                    f"kill-switch {key} has no runtime setter export "
                    f"(dvgg_*_set_{key.lower()}) — parity tests can't "
                    f"drive both paths in one process"))
    return violations


# --------------------------------------------------------------------------
# config-field-docs
# --------------------------------------------------------------------------

@register(
    "config-field-docs",
    "every dataclass field in config.py carries documentation: an inline "
    "comment, a comment block immediately above, or a dataclass docstring "
    "naming the field — the config surface is user-facing API and "
    "undocumented knobs rot into folklore")
def check_config_field_docs(ctx: RepoContext) -> List[Violation]:
    rel = f"{PACKAGE}/config.py"
    tree = ctx.parse(rel)
    text = ctx.text(rel)
    if tree is None or text is None:
        return [Violation("config-field-docs", rel, 0,
                          "config.py missing or unparseable")]
    lines = text.splitlines()
    violations: List[Violation] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        is_dataclass = any(
            (isinstance(d, ast.Name) and d.id == "dataclass")
            or (isinstance(d, ast.Call) and (
                (isinstance(d.func, ast.Name) and d.func.id == "dataclass")
                or (isinstance(d.func, ast.Attribute)
                    and d.func.attr == "dataclass")))
            for d in node.decorator_list)
        if not is_dataclass:
            continue
        docstring = ast.get_docstring(node) or ""
        for stmt in node.body:
            if not isinstance(stmt, ast.AnnAssign) \
                    or not isinstance(stmt.target, ast.Name):
                continue
            name = stmt.target.id
            line = stmt.lineno  # 1-based
            src_line = lines[line - 1] if line <= len(lines) else ""
            inline = "#" in src_line.split("=")[-1] or \
                re.search(r"#", src_line.partition(name)[2]) is not None
            above = line - 2 >= 0 and \
                lines[line - 2].lstrip().startswith("#")
            in_doc = re.search(rf"\b{re.escape(name)}\b", docstring) \
                is not None
            if not (inline or above or in_doc):
                violations.append(Violation(
                    "config-field-docs", rel, line,
                    f"{node.name}.{name} has no documentation (inline "
                    f"comment, comment block above, or mention in the "
                    f"class docstring)"))
    return violations


# --------------------------------------------------------------------------
# telemetry-import-isolation
# --------------------------------------------------------------------------

#: Top-level modules the telemetry package must not import at MODULE level
#: (function-local lazy imports are the sanctioned pattern). Heavy deps
#: make telemetry a correctness dependency of the thing it observes; the
#: data package reaches the native .so.
_FORBIDDEN_TELEMETRY_IMPORTS = {
    "jax", "jaxlib", "numpy", "tensorflow", "ml_dtypes", "scipy", "PIL",
}
_FORBIDDEN_TELEMETRY_SUBPACKAGES = (
    f"{PACKAGE}.data", f"{PACKAGE}.train", f"{PACKAGE}.models",
    f"{PACKAGE}.ops", f"{PACKAGE}.parallel",
)


@register(
    "telemetry-import-isolation",
    "telemetry modules import neither heavy numeric deps (jax/numpy/"
    "tensorflow/...) nor the data package at module level — importing "
    "telemetry must never trigger a native build (the runtime half: "
    "tests/test_telemetry.py test_import_pulls_no_heavy_deps)")
def check_telemetry_import_isolation(ctx: RepoContext) -> List[Violation]:
    violations: List[Violation] = []
    for rel in ctx.py_files(f"{PACKAGE}/telemetry"):
        tree = ctx.parse(rel)
        if tree is None:
            continue
        # module level = statements not nested inside a def/lambda; class
        # bodies and module-level try/if blocks DO execute at import
        module_level: List[ast.stmt] = []

        def collect(body: List[ast.stmt]) -> None:
            for stmt in body:
                if isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    continue
                module_level.append(stmt)
                for attr in ("body", "orelse", "finalbody"):
                    collect(getattr(stmt, attr, []) or [])
                for handler in getattr(stmt, "handlers", []) or []:
                    collect(handler.body)

        collect(tree.body)
        for stmt in module_level:
            names: List[Tuple[str, int]] = []
            if isinstance(stmt, ast.Import):
                names = [(a.name, stmt.lineno) for a in stmt.names]
            elif isinstance(stmt, ast.ImportFrom) and stmt.module:
                names = [(stmt.module, stmt.lineno)]
            for mod, line in names:
                top = mod.split(".", 1)[0]
                if top in _FORBIDDEN_TELEMETRY_IMPORTS:
                    violations.append(Violation(
                        "telemetry-import-isolation", rel, line,
                        f"module-level import of {mod!r} — telemetry must "
                        f"stay importable with no heavy deps (lazy-import "
                        f"inside the function that needs it)"))
                elif any(mod == p or mod.startswith(p + ".")
                         for p in _FORBIDDEN_TELEMETRY_SUBPACKAGES):
                    violations.append(Violation(
                        "telemetry-import-isolation", rel, line,
                        f"module-level import of {mod!r} — telemetry "
                        f"observes the data/train layers, it must never "
                        f"import them (native-build trigger)"))
    return violations
